"""Source-level adversarial attack driver: rename variables / insert
dead code in real Java source, verified end-to-end through the extractor.

Reference parity target: the `noamyft/code2vec` fork delta (SURVEY.md §0
item 2; "Adversarial Examples for Models of Code", Yefet, Alon & Yahav
2020). The tensor-space search lives in attacks/gradient_attack.py; this
module closes the loop to actual source code:

  extract -> tensorize -> gradient attack -> rewrite the source ->
  RE-extract -> RE-predict  (the reported outcome is always the model's
  output on the rewritten source, never the tensor-space estimate).

Two manipulations, per the paper:
- **variable rename**: replace every occurrence of one declared
  variable (local/param/field, found by a declaration heuristic) with
  the adversarially-chosen name — semantics-preserving.
- **dead-code insertion** (`--attack_deadcode`): insert an unused local
  declaration `int <advName>;` at the top of the method body and let the
  gradient attack choose `<advName>` — the program's behavior is
  untouched, only the name of a dead variable changes the prediction.

Validity guards: candidate new names exclude every identifier already
present in the source (no shadowing/duplicate-declaration collisions),
and the rename targets are restricted to identifiers that appear in a
declaration position (`Type name`), so called methods and type names are
not rewritten. Since round 4 every Java-source scan and rewrite is
COMMENT/STRING-AWARE: a lexical mask (`code_char_mask` — line/block
comments, string and char literals with escapes) restricts the regexes
to code regions, so `// int fake;` declares nothing, an identifier
inside "a string literal" is neither renamed nor counted as occupied,
and comment-heavy corpora no longer shrink the measured attack surface
(round-3 weak #6). The identifier mapping is still heuristic — the
extractor normalizes leaf tokens (`common.split_to_subtokens`), so
distinct identifiers can collapse to one vocab token. Acceptable for
the attack setting either way: the rewritten file is re-extracted, so
the reported prediction is always truthful.
"""

from __future__ import annotations

import dataclasses
import os
import re
import tempfile
from typing import Dict, List, Optional, Tuple

from code2vec_tpu.attacks.gradient_attack import (AttackResult,
                                                  GradientRenameAttack,
                                                  render_identifier)
from code2vec_tpu.common import split_to_subtokens
from code2vec_tpu.data.reader import parse_c2v_rows
from code2vec_tpu.serving.extractor import Extractor

from code2vec_tpu.attacks.gradient_attack import JAVA_KEYWORDS

_IDENT_RE = re.compile(r"\b[A-Za-z_][A-Za-z0-9_]*\b")
# one keyword list (gradient_attack.JAVA_KEYWORDS, lowercase) + the
# exact-case type name the identifier scanner must also skip
_JAVA_KEYWORDS = JAVA_KEYWORDS | {"String"}
# keywords that may legally precede an identifier but are NOT types —
# `return index;` must not read as a declaration of `index`
_NOT_A_TYPE = frozenset(
    "return new case throw else do instanceof class interface enum "
    "extends implements throws package import goto break continue "
    "assert".split())
_DECL_RE = re.compile(
    r"\b([A-Za-z_][A-Za-z0-9_]*)"          # base type identifier
    r"(?:\s*<[^<>;(){}]*>)?(?:\s*\[\s*\])*"  # generics / array suffix
    r"\s+([a-z_][A-Za-z0-9_]*)\s*(?=[=;,):])")  # variable name


def code_char_mask(source: str) -> List[bool]:
    """True where source[i] is CODE — False inside // and /* */
    comments, "string" / 'char' literals (backslash escapes honored),
    and Java 15 text blocks (\"\"\"...\"\"\", which legally contain
    unescaped double quotes — handled as their own state so an
    embedded quote neither exposes the block's content nor inverts
    the scanner for the code after it). A lexical scanner, not a
    parser: enough to keep the attack's regexes out of text the
    compiler ignores."""
    mask = [True] * len(source)
    i, n = 0, len(source)
    state = "code"
    while i < n:
        c = source[i]
        if state == "code":
            two = source[i:i + 2]
            if two == "//":
                state = "line"
                mask[i] = mask[i + 1] = False
                i += 2
                continue
            if two == "/*":
                state = "block"
                mask[i] = mask[i + 1] = False
                i += 2
                continue
            if source[i:i + 3] == '"""':
                state = "text"
                mask[i] = mask[i + 1] = mask[i + 2] = False
                i += 3
                continue
            if c == '"':
                state = "str"
                mask[i] = False
            elif c == "'":
                state = "char"
                mask[i] = False
            i += 1
            continue
        mask[i] = False
        if state == "line":
            if c == "\n":
                mask[i] = True  # the newline itself is code structure
                state = "code"
            i += 1
        elif state == "block":
            if source[i:i + 2] == "*/":
                mask[i + 1] = False
                i += 2
                state = "code"
            else:
                i += 1
        elif state == "text":
            if c == "\\" and i + 1 < n:
                mask[i + 1] = False
                i += 2
            elif source[i:i + 3] == '"""':
                mask[i + 1] = mask[i + 2] = False
                i += 3
                state = "code"
            else:
                i += 1
        else:  # str / char
            quote = '"' if state == "str" else "'"
            if c == "\\" and i + 1 < n:
                mask[i + 1] = False
                i += 2
            else:
                if c == quote:
                    state = "code"
                i += 1
    return mask


def mask_non_code(source: str) -> str:
    """The source with every non-code character blanked to a space —
    offsets (and therefore every regex match position) are preserved,
    so scans on the masked text map 1:1 onto the original."""
    mask = code_char_mask(source)
    return "".join(c if m or c == "\n" else " "
                   for c, m in zip(source, mask))


def normalize_identifier(ident: str) -> str:
    return "|".join(split_to_subtokens(ident))


def normalize_target_name(name: Optional[str]) -> Optional[str]:
    """CLI/REPL attack targets arrive as camelCase (`sortArray`) or
    already in stored subtoken form (`sort|array`); normalize the
    former. Shared by code2vec.py --attack_target and the REPL's
    `attack <name>` command."""
    if name and "|" not in name:
        return normalize_identifier(name)
    return name


def declared_variables(source: str) -> List[str]:
    """Identifiers in declaration position (`Type name` followed by
    `= ; , ) :`): params, locals, fields. Heuristic — a regex, not a
    parser — but it excludes called methods and type names, which is
    what keeps the rewrite semantics-preserving."""
    out, seen = [], set()
    for m in _DECL_RE.finditer(mask_non_code(source)):
        type_word, name = m.group(1), m.group(2)
        if type_word in _NOT_A_TYPE or name in _JAVA_KEYWORDS:
            continue
        if name not in seen:
            seen.add(name)
            out.append(name)
    return out


def declared_variables_python(source: str) -> List[str]:
    """Python counterpart of declared_variables, via the real parser
    (the python frontend itself uses CPython `ast` — SURVEY.md §8.3
    step 8): function params plus assignment / for / with / comprehension
    binding targets. Called functions and attribute names never bind
    here; together with rename_in_source_python's AST-precise rewrite
    the Python rename path stays semantics-preserving."""
    import ast
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return []
    # names bound by constructs whose binder the renamer cannot rewrite
    # as a positioned node (`except E as x`, `import m as x`) are
    # excluded — renaming their uses but not the binder would break the
    # program. global/nonlocal names stay eligible: the renamer
    # rewrites those statements too.
    hazards = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ExceptHandler) and node.name:
            hazards.add(node.name)
        elif isinstance(node, ast.alias):
            # `import os.path` binds the FIRST segment (`os`)
            hazards.add((node.asname or node.name).split(".")[0])
        elif isinstance(node, (ast.MatchAs, ast.MatchStar)) \
                and node.name:
            hazards.add(node.name)  # match-pattern capture binders
        elif isinstance(node, ast.MatchMapping) and node.rest:
            hazards.add(node.rest)
    out, seen = [], set()

    def add(name: str) -> None:
        if (name not in seen and name not in hazards
                and not name.startswith("__")):
            seen.add(name)
            out.append(name)

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            a = node.args
            for arg in (a.posonlyargs + a.args + a.kwonlyargs
                        + ([a.vararg] if a.vararg else [])
                        + ([a.kwarg] if a.kwarg else [])):
                add(arg.arg)
        elif isinstance(node, ast.Name) and isinstance(node.ctx,
                                                       ast.Store):
            add(node.id)
    return out


def declared_for(source: str, language: str) -> List[str]:
    """Declaration-position identifiers, per source language."""
    return (declared_variables_python(source) if language == "python"
            else declared_variables(source))


def identifiers_for_token(source: str, token_word: str,
                          declared_only: bool = True,
                          language: str = "java") -> List[str]:
    """Source identifiers that normalize to the stored vocab token."""
    pool = (declared_for(source, language) if declared_only else
            [m.group(0)
             for m in _IDENT_RE.finditer(mask_non_code(source))
             if m.group(0) not in _JAVA_KEYWORDS])
    found, seen = [], set()
    for ident in pool:
        if ident not in seen and normalize_identifier(ident) == token_word:
            seen.add(ident)
            found.append(ident)
    return found


def rename_in_source(source: str, old_ident: str, new_ident: str) -> str:
    """Word-boundary rename restricted to CODE regions: occurrences
    inside comments or string literals are untouched (they are not the
    program's identifiers — and rewriting a string would change
    behavior)."""
    pat = re.compile(rf"\b{re.escape(old_ident)}\b")
    masked = mask_non_code(source)
    out, last = [], 0
    for m in pat.finditer(masked):
        out.append(source[last:m.start()])
        out.append(new_ident)
        last = m.end()
    out.append(source[last:])
    return "".join(out)


def rename_in_source_python(source: str, old_ident: str,
                            new_ident: str) -> str:
    """AST-precise Python rename: rewrites only `Name` nodes and
    function-parameter `arg` nodes whose identifier matches — never
    keyword-argument NAMES in calls (`fetch(timeout=x)` keeps its
    `timeout=`, which belongs to the callee), attribute names, or
    string contents. This is what keeps Python renames
    semantics-preserving where a word-boundary regex is not."""
    import ast
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return rename_in_source(source, old_ident, new_ident)
    lines = source.splitlines(keepends=True)
    spots = []
    for node in ast.walk(tree):
        if (isinstance(node, ast.Name) and node.id == old_ident) or \
                (isinstance(node, ast.arg) and node.arg == old_ident):
            spots.append((node.lineno, node.col_offset))
        elif isinstance(node, (ast.Global, ast.Nonlocal)) \
                and old_ident in node.names:
            # names here are bare strings without node positions; the
            # statement span contains only keywords/names/commas, so a
            # word-boundary scan inside it locates them exactly
            for ln in range(node.lineno, node.end_lineno + 1):
                text = lines[ln - 1]
                lo = node.col_offset if ln == node.lineno else 0
                hi = (node.end_col_offset if ln == node.end_lineno
                      else len(text))
                for m in re.finditer(
                        rf"\b{re.escape(old_ident)}\b", text[lo:hi]):
                    spots.append((ln, lo + m.start()))
    for ln, col in sorted(spots, reverse=True):
        line = lines[ln - 1]
        if line[col:col + len(old_ident)] == old_ident:
            lines[ln - 1] = (line[:col] + new_ident
                             + line[col + len(old_ident):])
    return "".join(lines)


def insert_dead_declaration(source: str, method_name_word: str,
                            var_name: str, ordinal: int = 0
                            ) -> Optional[str]:
    """Insert `int <var_name>;` right after the opening brace of the
    (ordinal-th) method whose extractor-normalized name is
    `method_name_word`. Returns the modified source, or None if the
    method isn't found."""
    skip = ordinal
    masked = mask_non_code(source)
    for m in _IDENT_RE.finditer(masked):
        if normalize_identifier(m.group(0)) != method_name_word:
            continue
        # require a parameter list then a brace: it's a method, not a
        # use. The `[^{;)]*` between `)` and `{` rejects call sites in
        # conditions — `if (check()) {` leaves a stray `)` after the
        # matched parens that a declaration never has. Scanned on the
        # code-masked text so a mention in a comment or string never
        # matches (offsets are identical to the original).
        rest = masked[m.end():]
        sig = re.match(r"\s*\([^)]*\)[^{;)]*\{", rest, re.S)
        if not sig:
            continue
        if skip > 0:
            skip -= 1
            continue
        pos = m.end() + sig.end()
        return source[:pos] + f" int {var_name}; " + source[pos:]
    return None


@dataclasses.dataclass
class SourceAttackResult:
    attack: AttackResult              # the tensor-space trajectory
    renames: Dict[str, str]           # source-identifier rewrites applied
    adversarial_source: Optional[str]
    # predictions on the REWRITTEN source, re-extracted (ground truth):
    verified_prediction: Optional[str]
    verified_success: Optional[bool]

    def __str__(self) -> str:
        lines = [str(self.attack)]
        if self.renames:
            lines.append("source rewrites: " + ", ".join(
                f"{a} -> {b}" for a, b in self.renames.items()))
        if self.verified_prediction is not None:
            lines.append(
                f"re-extracted prediction: '{self.verified_prediction}' "
                f"({'SUCCESS' if self.verified_success else 'failed'} "
                f"end-to-end)")
        return "\n".join(lines)


class SourceAttack:
    """Attacks one method of one source file against a loaded model."""

    def __init__(self, config, model, *, top_k_candidates: int = 32,
                 max_iters: int = 4):
        self.config = config
        self.model = model
        self.extractor = Extractor(config)  # re-created per attack_file
        #                                     to match the source language
        self.attack = GradientRenameAttack(
            model.dims, model.vocabs.token_vocab,
            model.vocabs.target_vocab,
            top_k_candidates=top_k_candidates, max_iters=max_iters,
            compute_dtype=model.compute_dtype)

    def _tensorize(self, line: str):
        labels, src, pth, dst, mask, _, _ = parse_c2v_rows(
            [line], self.model.vocabs, self.config.MAX_CONTEXTS,
            keep_strings=True)
        return int(labels[0]), (src[0], pth[0], dst[0], mask[0])

    def _predict_word(self, method) -> str:
        import jax.numpy as jnp
        ids = tuple(jnp.asarray(a) for a in method)
        top1 = self.attack.predict_fn(self.model.params, ids)
        return self.model.vocabs.target_vocab.lookup_word(int(top1))

    def _forbidden_ids(self, source: str) -> frozenset:
        """Vocab ids of every identifier already in the source — never
        valid as a NEW name (duplicate declarations / symbol capture)."""
        tv = self.attack.token_vocab
        ids = set()
        # code regions only: a name that appears solely in a comment
        # or string binds nothing, so it stays usable as a new name
        for m in _IDENT_RE.finditer(mask_non_code(source)):
            idx = tv.lookup_index(normalize_identifier(m.group(0)))
            if idx != tv.oov_index:
                ids.add(idx)
        return frozenset(ids)

    def attack_file(self, path: str, *, method_index: int = 0,
                    targeted: bool = False,
                    target_name: Optional[str] = None,
                    max_renames: int = 1,
                    deadcode: bool = False) -> SourceAttackResult:
        language = "python" if path.endswith(".py") else "java"
        if self.extractor.language != language:
            self.extractor = Extractor(self.config, language=language)
        if deadcode and language == "python":
            raise ValueError(
                "--attack_deadcode supports Java sources only (the "
                "python insertion heuristic is not implemented); use "
                "the rename attack for .py inputs")
        with open(path, encoding="utf-8") as f:
            source = f.read()
        names, lines = self.extractor.extract_paths(path)
        if method_index >= len(names):
            raise ValueError(
                f"file has {len(names)} methods, asked for "
                f"#{method_index}")
        method_name = names[method_index]
        # overloads share a normalized name; track WHICH occurrence
        ordinal = names[:method_index].count(method_name)

        if deadcode:
            # baseline: the PRISTINE file's prediction — success must
            # mean "differs from the original program", and inserting
            # the placeholder alone can already move the prediction
            _, pristine = self._tensorize(lines[method_index])
            import jax.numpy as jnp
            p_ids = tuple(jnp.asarray(a) for a in pristine)
            p_top1 = self.attack.predict_fn(self.model.params, p_ids)
            var0 = self._fresh_variable_name(source)
            mod = insert_dead_declaration(source, method_name, var0,
                                          ordinal)
            if mod is None:
                raise ValueError(
                    f"could not locate method '{method_name}' in {path} "
                    f"to insert dead code")
            return self._run(mod, method_name, ordinal, targeted,
                             target_name, token_ids_from=var0,
                             max_renames=1, baseline_top1=int(p_top1))
        return self._run(source, method_name, ordinal, targeted,
                         target_name, token_ids_from=None,
                         max_renames=max_renames,
                         extraction=(names, lines))

    # ----------------------------------------------------------------
    def _fresh_variable_name(self, source: str) -> str:
        """An initial dead-variable name: in-vocab, identifier-renderable,
        not already present in the source (so its occurrence slots are
        exactly the inserted declaration's)."""
        used = {normalize_identifier(m.group(0))
                for m in _IDENT_RE.finditer(mask_non_code(source))}
        tv = self.attack.token_vocab
        for idx in range(tv.size - 1, 1, -1):
            word = tv.lookup_word(idx)
            ident = render_identifier(word)
            if ident and word not in used:
                return ident
        raise ValueError("no unused in-vocab identifier available")

    def _extract_lines_of(self, source: str) -> Tuple[List[str],
                                                      List[str]]:
        suffix = ".py" if self.extractor.language == "python" else ".java"
        fd, tmp = tempfile.mkstemp(suffix=suffix, prefix="c2v_attack_")
        try:
            with os.fdopen(fd, "w", encoding="utf-8") as f:
                f.write(source)
            return self.extractor.extract_paths(tmp)
        finally:
            os.unlink(tmp)

    @staticmethod
    def _method_row(names: List[str], method_name: str,
                    ordinal: int) -> int:
        """Row of the (ordinal-th) method named `method_name`."""
        matches = [i for i, n in enumerate(names) if n == method_name]
        if not matches:
            raise ValueError(f"method '{method_name}' not found after "
                             f"re-extraction")
        return matches[min(ordinal, len(matches) - 1)]

    def _run(self, source: str, method_name: str, ordinal: int,
             targeted: bool, target_name: Optional[str],
             token_ids_from: Optional[str], max_renames: int,
             extraction: Optional[Tuple[List[str], List[str]]] = None,
             baseline_top1: Optional[int] = None) -> SourceAttackResult:
        names, lines = (extraction if extraction is not None
                        else self._extract_lines_of(source))
        idx = self._method_row(names, method_name, ordinal)
        _, method = self._tensorize(lines[idx])
        if token_ids_from is not None:
            # dead-code mode: attack exactly the inserted variable
            tid = self.attack.token_vocab.lookup_index(
                normalize_identifier(token_ids_from))
            if not ((method[0] == tid).any()
                    or (method[2] == tid).any()):
                raise ValueError(
                    "the inserted dead declaration's contexts were all "
                    "dropped by MAX_CONTEXTS downsampling (method has "
                    "more contexts than fit); raise --max_contexts to "
                    "attack this method with dead code")
            token_ids = [tid]
        else:
            # rename mode: only tokens that map to a DECLARED variable
            # in this source are legitimate rename targets
            declared = {normalize_identifier(d) for d in
                        declared_for(source,
                                     self.extractor.language)}
            token_ids = [t for t, _ in self.attack.attackable_tokens(
                method[0], method[2], method[3])
                if self.attack.token_vocab.lookup_word(t) in declared]
        result = self.attack.attack_method(
            self.model.params, method, targeted=targeted,
            target_name=target_name, max_renames=max_renames,
            token_ids=token_ids,
            forbidden=self._forbidden_ids(source),
            baseline_top1=baseline_top1)

        renames: Dict[str, str] = {}
        adv_source = source
        for orig_tok, final_tok in result.renames:
            new_ident = render_identifier(final_tok)
            if new_ident is None:
                continue
            if token_ids_from is not None and \
                    normalize_identifier(token_ids_from) == orig_tok:
                idents = [token_ids_from]
            else:
                idents = identifiers_for_token(
                    source, orig_tok,
                    language=self.extractor.language)
            rename = (rename_in_source_python
                      if self.extractor.language == "python"
                      else rename_in_source)
            for ident in idents:
                adv_source = rename(adv_source, ident, new_ident)
                renames[ident] = new_ident

        verified_pred = verified_ok = None
        if not renames and token_ids_from is not None and result.success:
            # The placeholder insertion ALONE flipped the prediction —
            # the inserted-declaration source is itself the adversarial
            # example. It was already extracted and predicted in this
            # run (that is where `result` came from), so the verified
            # outcome is exactly the final prediction on it.
            verified_pred = result.final_prediction
            verified_ok = (verified_pred == target_name if targeted
                           else verified_pred
                           != result.original_prediction)
            return SourceAttackResult(
                attack=result, renames={}, adversarial_source=source,
                verified_prediction=verified_pred,
                verified_success=verified_ok)
        if renames:
            try:
                v_names, v_lines = self._extract_lines_of(adv_source)
                v_idx = self._method_row(v_names, method_name, ordinal)
                _, v_method = self._tensorize(v_lines[v_idx])
                verified_pred = self._predict_word(v_method)
                if targeted:
                    verified_ok = verified_pred == target_name
                else:
                    verified_ok = (verified_pred
                                   != result.original_prediction)
            except Exception as e:  # honest failure, not a crash
                verified_pred = f"<re-extraction failed: {e}>"
                verified_ok = False
        return SourceAttackResult(
            attack=result, renames=renames,
            adversarial_source=adv_source if renames else None,
            verified_prediction=verified_pred,
            verified_success=verified_ok)
