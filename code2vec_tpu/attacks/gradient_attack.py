"""Gradient-guided discrete adversarial attacks: variable renaming.

Reference parity target: the `noamyft/code2vec` fork delta (SURVEY.md §0
item 2). The fork's owner co-authored "Adversarial Examples for Models of
Code" (Yefet, Alon & Yahav, 2020), whose artifact attacks code2vec by
**renaming one variable** so the model predicts an attacker-chosen method
name (targeted) or any wrong name (untargeted), and by **inserting dead
code** (an unused variable declaration whose adversarially-chosen name
flips the prediction; see attacks/source_attack.py for that driver). The
reference mount was empty (SURVEY.md §0), so the published attack
semantics are implemented here from the paper's method, TPU-first.

TPU-first design — the discrete search is dense linear algebra, not a
per-candidate loop:

1. one backward pass yields the gradient g [E] of the attack loss w.r.t.
   a shared free embedding placed at every occurrence slot of the
   attacked variable (the occurrence slots are remapped to a spare vocab
   row so the gradient is exact for ANY encoder — bag or transformer —
   without reimplementing its forward);
2. first-order loss deltas for renaming to EVERY token in the vocabulary
   at once are a single [V,E] @ [E] matvec on the MXU (HotFlip-style
   linearization);
3. the top-K shortlisted candidates are re-scored EXACTLY in one jitted
   forward over a [K, C] variant batch — the linearization alone
   mis-ranks, so success is always decided on true model outputs.

The outer loop (iterations × variables) stays on the host: it is O(5),
data-dependent, and each trip is one jit call (SURVEY.md "XLA
semantics" — no data-dependent control flow inside jit).
"""

from __future__ import annotations

import dataclasses
import re
from typing import Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from code2vec_tpu.common import SpecialVocabWords
from code2vec_tpu.models.encoder import (ModelDims, full_logits,
                                         get_encode_fn)
from code2vec_tpu.vocab.vocabularies import Vocab

_LETTERS_RE = re.compile(r"^[a-z]+$")
# Java's reserved words (+ `var`/`string`, which would shadow). Used to
# filter Java DECLARATIONS — words like `match`/`value` are legal Java
# identifiers and must stay attackable, so Python's keywords are NOT in
# this set.
JAVA_KEYWORDS = frozenset(
    "abstract assert boolean break byte case catch char class const "
    "continue default do double else enum extends final finally float "
    "for goto if implements import instanceof int interface long native "
    "new package private protected public return short static strictfp "
    "super switch synchronized this throw throws transient try void "
    "volatile while true false null var string".split())
PYTHON_KEYWORDS = frozenset(
    "and as assert async await break class continue def del elif else "
    "except finally for from global if import in is lambda nonlocal "
    "not or pass raise return try while with yield none true false "
    "match self".split())
# The NEW-name candidate pool is shared by both frontends, so a
# replacement must be a valid identifier in either language. Keywords
# are lowercase single words — camelCase renders never collide.
RESERVED_WORDS = JAVA_KEYWORDS | PYTHON_KEYWORDS


def render_identifier(token_word: str) -> Optional[str]:
    """Stored vocab token -> Java identifier, or None if not renderable.

    Vocab tokens are normalized subtoken strings (`array|index`); the
    source-level rename needs a real identifier (`arrayIndex`). Only
    all-letter subtokens render, and reserved words are rejected —
    anything else could not be a plain identifier and is excluded from
    the candidate pool."""
    subs = token_word.split("|")
    if not subs or any(not _LETTERS_RE.match(s) for s in subs):
        return None
    ident = subs[0] + "".join(s.capitalize() for s in subs[1:])
    if ident.lower() in RESERVED_WORDS:
        return None
    return ident


def spare_row(padded_rows: int, *arrays: np.ndarray) -> int:
    """A vocab row not used by any of `arrays` (the occurrence-isolation
    remap target for the gradient trick)."""
    used = set(np.concatenate([np.asarray(a).ravel()
                               for a in arrays]).tolist())
    for cand in range(padded_rows - 1, -1, -1):
        if cand not in used:
            return cand
    raise ValueError("no spare vocab row (vocab smaller than the ids?)")


def attack_succeeded(targeted: bool, pred: int, label: int,
                     original: int) -> bool:
    """Shared success predicate: targeted hits the label; untargeted
    departs from the clean prediction."""
    return pred == label if targeted else pred != original


def build_shortlist(scores: np.ndarray, legal: np.ndarray, tried: set,
                    top_k: int, cur_id: int) -> np.ndarray:
    """First-order scores -> [top_k] candidate ids. Illegal and
    already-tried rows are inf-masked before selection; the LAST slot
    re-evaluates the current id so the caller's acceptance test costs
    no extra jit call. Masked rows can still leak into a short
    selection (vocab barely above top_k) — guard_leaked handles them
    after exact evaluation."""
    scores[~legal] = np.inf
    for t in tried:
        scores[t] = np.inf
    cand = np.empty((top_k,), np.int32)
    # argpartition: O(V) selection beats a full argsort (~8x at the
    # java-large 1.3M-row vocab); order within the shortlist does not
    # matter — every entry is exactly re-scored anyway. Both attack
    # constructors clamp top_k <= vocab rows, making kth valid.
    k = top_k - 1
    assert k < len(scores), "top_k exceeds the vocabulary"
    cand[:-1] = np.argpartition(scores, k)[:k]
    cand[-1] = cur_id
    return cand


def guard_leaked(att_losses: np.ndarray, scores: np.ndarray,
                 shortlist: np.ndarray) -> np.ndarray:
    """Never accept a shortlist row whose first-order score was
    inf-masked (illegal/tried rows that leaked through a short
    argsort)."""
    att_losses[:-1] = np.where(np.isinf(scores[shortlist[:-1]]),
                               np.inf, att_losses[:-1])
    return att_losses


def candidate_mask(token_vocab: Vocab, padded_rows: int) -> np.ndarray:
    """[padded_rows] bool: True where a vocab row is a legal rename
    candidate — a real, identifier-renderable token (no PAD/OOV, no
    padding rows, no tokens with non-letter subtokens)."""
    mask = np.zeros((padded_rows,), dtype=bool)
    for idx, word in enumerate(token_vocab.to_word_list()):
        if word in (SpecialVocabWords.PAD, SpecialVocabWords.OOV):
            continue
        if render_identifier(word) is not None:
            mask[idx] = True
    return mask


@dataclasses.dataclass
class RenameStep:
    """One accepted rename in an attack trajectory."""
    from_token: str
    to_token: str
    loss_before: float
    loss_after: float


@dataclasses.dataclass
class AttackResult:
    success: bool
    targeted: bool
    original_prediction: str
    final_prediction: str
    target_name: Optional[str]
    # per-variable (original_token, final_token) pairs, in rename order
    renames: List[Tuple[str, str]]
    steps: List[RenameStep]       # full accepted-step trajectory
    iterations: int
    # the post-attack tensors (src, pth, dst, mask) — what detectors
    # and further analysis should score (None until attack_method ran)
    final_method: Optional[tuple] = None

    def __str__(self) -> str:
        kind = "targeted" if self.targeted else "untargeted"
        status = "SUCCESS" if self.success else "failed"
        rename = (", ".join(f"{a} -> {b}" for a, b in self.renames)
                  if self.renames else "(no rename)")
        line = (f"[{kind} {status}] rename {rename}: prediction "
                f"'{self.original_prediction}' -> "
                f"'{self.final_prediction}'")
        if self.targeted:
            line += f" (target '{self.target_name}')"
        return line


def make_attack_steps(dims: ModelDims, *,
                      compute_dtype=jnp.float32) -> Tuple[Callable,
                                                          Callable,
                                                          Callable]:
    """Builds the three jitted pieces of the attack.

    Returns (score_fn, eval_fn, predict_fn):
      score_fn(params, ids, occ, spare, label, sign) -> [Vt] f32
        first-order loss delta of renaming the occurrence slots to each
        token row (lower = better for the attacker).
      eval_fn(params, ids, occ, cand_ids [K], label) ->
        (loss [K], top1 [K]) — exact model outputs for each candidate
        rename.
      predict_fn(params, ids) -> top1 on the clean input.

    `ids` is (src [C], pth [C], dst [C], mask [C]) for ONE method;
    `occ` is (occ_src [C], occ_dst [C]) bool occurrence slots;
    `sign` is +1.0 to minimize CE(label) (targeted) or -1.0 to maximize
    it (untargeted). K is cand_ids' static shape."""
    raw_score, raw_eval, raw_predict = _raw_attack_steps(
        dims, compute_dtype=compute_dtype)
    return (jax.jit(raw_score), jax.jit(raw_eval), jax.jit(raw_predict))


def make_batched_attack_steps(dims: ModelDims, *,
                              compute_dtype=jnp.float32,
                              topk_transfer: Optional[int] = None
                              ) -> Tuple[Callable, ...]:
    """vmapped-over-methods variants of make_attack_steps: every array
    argument gains a leading method dim [M, ...] (params stay shared);
    `sign` stays scalar. One dispatch attacks M methods in lockstep —
    on the tunneled platform dispatch overhead dominates the serial
    sweep, so batching is what makes test-set-scale sweeps fast.

    Returns (eval_b, predict_b[, score_topk_b]); there is deliberately
    NO batched raw-score function — vmapping the spare-row trick
    materializes M functionally-updated token-table copies (64 x
    333 MB at java-large -> OOM); the lax.map'd top-k form below is the
    only safe batched score path:
      score_topk_b(params, ids, occ, spare, label, sign, legal)
        -> (scores [M, T], token_ids [M, T]), ascending
    — the first-order scores are legality-masked and top-T-selected ON
    DEVICE, so only [M, T] crosses the wire instead of [M, V] (166 MB
    per iteration for a 32-method java-large chunk — the device->host
    transfer, not dispatch, dominates once the batch is formed)."""
    raw_score, raw_eval, raw_predict = _raw_attack_steps(
        dims, compute_dtype=compute_dtype)
    out = [
        jax.jit(jax.vmap(raw_eval, in_axes=(None, 0, 0, 0, 0))),
        jax.jit(jax.vmap(raw_predict, in_axes=(None, 0))),
    ]
    if topk_transfer is not None:
        @jax.jit
        def score_topk_b(params, ids, occ, spare, label, sign, legal):
            def one(args):
                ids_i, occ_i, spare_i, label_i = args
                s = raw_score(params, ids_i, occ_i, spare_i, label_i,
                              sign)
                s = jnp.where(legal, s, jnp.inf)
                neg, idx = jax.lax.top_k(-s, topk_transfer)
                return -neg, idx

            return jax.lax.map(one, (ids, occ, spare, label))

        out.append(score_topk_b)
    return tuple(out)


def _raw_attack_steps(dims: ModelDims, *, compute_dtype=jnp.float32):
    """The un-jitted per-method step functions (see make_attack_steps
    for the contracts); jitted directly for the serial path and under
    vmap for the batched path."""
    encode = get_encode_fn(dims)

    def _loss_from_params(params, src, pth, dst, mask, label):
        code, _ = encode(params, src[None], pth[None], dst[None],
                         mask[None], compute_dtype=compute_dtype)
        logits = full_logits(params, code, dims.target_vocab_size)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, label[None])[0]

    def score_fn(params, ids, occ, spare, label, sign):
        src, pth, dst, mask = ids
        occ_src, occ_dst = occ
        table = params["token_emb"]
        # Remap occurrence slots to the spare (unused-in-this-method)
        # row and make that row a free variable: its gradient is exactly
        # the sum of the attack loss's slot gradients, for any encoder.
        src2 = jnp.where(occ_src, spare, src)
        dst2 = jnp.where(occ_dst, spare, dst)
        # occurrences all carry the same id (the attacked variable)
        cur_id = jnp.max(jnp.where(occ_src, src,
                                   jnp.where(occ_dst, dst, -1)))
        e_var = table[cur_id].astype(jnp.float32)

        def loss_of(e):
            t2 = table.at[spare].set(e.astype(table.dtype))
            p2 = dict(params, token_emb=t2)
            return sign * _loss_from_params(p2, src2, pth, dst2, mask,
                                            label)

        g = jax.grad(loss_of)(e_var)
        # First-order delta of moving the shared embedding to row v:
        # (table[v] - e_var) @ g; the -e_var @ g term is constant and
        # kept only so the scores are true deltas (sign-interpretable).
        scores = (table.astype(jnp.float32) @ g) - (e_var @ g)
        return scores

    def eval_fn(params, ids, occ, cand_ids, label):
        src, pth, dst, mask = ids
        occ_src, occ_dst = occ
        K = cand_ids.shape[0]
        srcK = jnp.where(occ_src[None, :], cand_ids[:, None], src[None, :])
        dstK = jnp.where(occ_dst[None, :], cand_ids[:, None], dst[None, :])
        pthK = jnp.broadcast_to(pth[None, :], (K, pth.shape[0]))
        maskK = jnp.broadcast_to(mask[None, :], (K, mask.shape[0]))
        code, _ = encode(params, srcK, pthK, dstK, maskK,
                         compute_dtype=compute_dtype)
        logits = full_logits(params, code, dims.target_vocab_size)
        labels = jnp.full((K,), label, dtype=jnp.int32)
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, labels)
        top1 = jnp.argmax(logits, axis=-1)
        return loss, top1

    def predict_fn(params, ids):
        src, pth, dst, mask = ids
        code, _ = encode(params, src[None], pth[None], dst[None],
                         mask[None], compute_dtype=compute_dtype)
        logits = full_logits(params, code, dims.target_vocab_size)
        return jnp.argmax(logits[0])

    return score_fn, eval_fn, predict_fn


class GradientRenameAttack:
    """Host orchestration of the iterative rename attack on tensorized
    methods. Works against any trained Code2VecModel-compatible params
    pytree; construct once per model, reuse across methods (the jitted
    pieces compile once)."""

    def __init__(self, dims: ModelDims, token_vocab: Vocab,
                 target_vocab: Vocab, *, top_k_candidates: int = 32,
                 max_iters: int = 4, compute_dtype=jnp.float32):
        self.dims = dims
        self.token_vocab = token_vocab
        self.target_vocab = target_vocab
        self.compute_dtype = compute_dtype
        # the shortlist cannot exceed the vocab itself (tiny test vocabs)
        top_k_candidates = min(top_k_candidates,
                               dims.padded(dims.token_vocab_size))
        self.top_k = top_k_candidates
        self.max_iters = max_iters
        self.score_fn, self.eval_fn, self.predict_fn = make_attack_steps(
            dims, compute_dtype=compute_dtype)
        self._batched = None  # built lazily by attack_batch
        self.legal = candidate_mask(token_vocab,
                                    dims.padded(dims.token_vocab_size))

    # -- helpers ---------------------------------------------------------
    def attackable_tokens(self, src: np.ndarray, dst: np.ndarray,
                          mask: np.ndarray) -> List[Tuple[int, int]]:
        """[(token_id, n_occurrences)] of rename-candidate variables in
        one method, most frequent first. A 'variable' at tensor level is
        a token id occurring in valid src/dst slots (the extractor's
        normalized leaf tokens do not distinguish symbol kinds, so every
        leaf identifier is attackable — same granularity the paper's
        tensor-space search uses before source-level validation)."""
        valid = mask > 0
        ids, counts = np.unique(
            np.concatenate([src[valid], dst[valid]]), return_counts=True)
        out = [(int(i), int(c)) for i, c in zip(ids, counts)
               if i < len(self.legal) and self.legal[i]]
        out.sort(key=lambda ic: -ic[1])
        return out

    # -- single-variable attack -----------------------------------------
    def attack_token(self, params, method: Tuple[np.ndarray, np.ndarray,
                                                 np.ndarray, np.ndarray],
                     token_id: int, *, targeted: bool,
                     label: int, original_top1: int,
                     forbidden: frozenset = frozenset()
                     ) -> Tuple[bool, int, List[RenameStep], int]:
        """Iteratively rename `token_id`'s occurrences in one method.

        `label` is the target name id (targeted) or the clean top-1 id
        (untargeted: maximize its CE, succeed when top-1 changes).
        `forbidden` token ids are never chosen as the new name; tokens
        already PRESENT in the method are always forbidden — renaming a
        variable to an identifier the method already uses would merge
        distinct symbols in the representation (and collide with
        params/locals in real source). Returns (success, final_token_id,
        steps, iters_used)."""
        src, pth, dst, mask = (np.asarray(a) for a in method)
        occ_src = src == token_id
        occ_dst = dst == token_id
        occ = (jnp.asarray(occ_src), jnp.asarray(occ_dst))
        spare = spare_row(self.dims.padded(self.dims.token_vocab_size),
                          src, dst)
        sign = 1.0 if targeted else -1.0
        cur_id = token_id
        steps: List[RenameStep] = []
        tried = ({token_id} | set(forbidden)
                 | set(np.unique(np.concatenate([src, dst])).tolist()))
        cur_src, cur_dst = src.copy(), dst.copy()

        for it in range(1, self.max_iters + 1):
            ids = (jnp.asarray(cur_src), jnp.asarray(pth),
                   jnp.asarray(cur_dst), jnp.asarray(mask))
            scores = np.array(self.score_fn(
                params, ids, occ, jnp.int32(spare), jnp.int32(label),
                sign))
            cand = build_shortlist(scores, self.legal, tried,
                                   self.top_k, cur_id)
            loss_k, top1_k = self.eval_fn(
                params, ids, occ, jnp.asarray(cand), jnp.int32(label))
            att_loss_k = guard_leaked(sign * np.asarray(loss_k),
                                      scores, cand)
            top1_k = np.asarray(top1_k)
            cur_attack_loss = float(att_loss_k[-1])
            best = int(np.argmin(att_loss_k[:-1]))
            tried.update(int(c) for c in cand)
            if att_loss_k[best] >= cur_attack_loss:
                return (attack_succeeded(targeted, int(top1_k[-1]),
                                         label, original_top1),
                        cur_id, steps, it)
            new_id = int(cand[best])
            steps.append(RenameStep(
                from_token=self.token_vocab.lookup_word(cur_id),
                to_token=self.token_vocab.lookup_word(new_id),
                loss_before=cur_attack_loss,
                loss_after=float(att_loss_k[best])))
            cur_src = np.where(occ_src, new_id, cur_src)
            cur_dst = np.where(occ_dst, new_id, cur_dst)
            cur_id = new_id
            if attack_succeeded(targeted, int(top1_k[best]), label,
                                original_top1):
                return True, cur_id, steps, it
        return False, cur_id, steps, self.max_iters

    # -- whole-method attack --------------------------------------------
    def attack_method(self, params, method, *, targeted: bool = False,
                      target_name: Optional[str] = None,
                      max_renames: int = 1,
                      token_ids: Optional[Sequence[int]] = None,
                      forbidden: frozenset = frozenset(),
                      baseline_top1: Optional[int] = None
                      ) -> AttackResult:
        """Attack one tensorized method: greedily rename up to
        `max_renames` variables (most-frequent first, or the explicit
        `token_ids`), carrying successful renames forward. `forbidden`
        ids are never used as new names (the source driver passes every
        identifier already present in the file). `baseline_top1`
        overrides the untargeted reference prediction — the dead-code
        driver passes the PRISTINE file's top-1 so 'flipped' means
        'differs from the original program', not 'differs from the
        placeholder-inserted variant'."""
        src, pth, dst, mask = (np.asarray(a) for a in method)
        ids0 = (jnp.asarray(src), jnp.asarray(pth), jnp.asarray(dst),
                jnp.asarray(mask))
        if baseline_top1 is None:
            original_top1 = int(self.predict_fn(params, ids0))
        else:
            original_top1 = int(baseline_top1)
        if targeted:
            if target_name is None:
                raise ValueError("targeted attack needs a target name")
            label = self.target_vocab.lookup_index(target_name)
            if label == self.target_vocab.oov_index:
                raise ValueError(
                    f"target name '{target_name}' is out of vocabulary")
        else:
            label = original_top1

        if token_ids is None:
            token_ids = [t for t, _ in
                         self.attackable_tokens(src, dst, mask)]
        token_ids = list(token_ids)[:max_renames]

        cur = (src.copy(), pth, dst.copy(), mask)
        all_steps: List[RenameStep] = []
        renamed: List[Tuple[int, int]] = []  # (orig_id, final_id)/var
        iters = 0
        success = False
        for tid in token_ids:
            # a requested token can be absent from the tensorized
            # method (dead-code driver after MAX_CONTEXTS downsampling
            # dropped the inserted declaration's contexts): with no
            # occurrence slots the gradient is identically zero, so
            # skip instead of burning iterations on a no-op
            if not ((cur[0] == tid).any() or (cur[2] == tid).any()):
                continue
            ok, final_id, steps, used = self.attack_token(
                params, cur, tid, targeted=targeted, label=label,
                original_top1=original_top1, forbidden=forbidden)
            iters += used
            if steps:
                all_steps.extend(steps)
                renamed.append((tid, final_id))
                occ_s, occ_d = cur[0] == tid, cur[2] == tid
                cur = (np.where(occ_s, final_id, cur[0]), cur[1],
                       np.where(occ_d, final_id, cur[2]), cur[3])
            if ok:
                success = True
                break

        idsF = (jnp.asarray(cur[0]), jnp.asarray(cur[1]),
                jnp.asarray(cur[2]), jnp.asarray(cur[3]))
        top1_f = self.predict_fn(params, idsF)
        tv = self.target_vocab
        look = self.token_vocab.lookup_word
        return AttackResult(
            success=success, targeted=targeted,
            original_prediction=tv.lookup_word(original_top1),
            final_prediction=tv.lookup_word(int(top1_f)),
            target_name=target_name,
            renames=[(look(a), look(b)) for a, b in renamed],
            steps=all_steps, iterations=iters, final_method=cur)

    # -- lockstep batch attack ------------------------------------------
    def attack_batch(self, params, methods: Sequence[Tuple]
                     ) -> List[AttackResult]:
        """Untargeted single-rename attack on M methods at once —
        semantically identical to `attack_method(m, targeted=False,
        max_renames=1)` per method (same scores, same selections, same
        acceptance), but each of the ~max_iters+2 jit dispatches covers
        the WHOLE batch. On the tunneled platform, where fixed dispatch
        cost dominates the serial sweep, this is what makes
        test-set-scale robustness sweeps fast. Methods must each have
        at least one attackable token (the sweep filters first).

        Equivalence caveat: the serial path shortlists via argpartition
        (arbitrary order within the partition) while this path uses a
        sorted device top_k, so an EXACT float tie in first-order scores
        at the shortlist boundary can admit different candidate sets —
        and, since acceptance re-scores exactly, potentially a different
        accepted rename. Ties at f32 gradient-score precision do not
        occur on the tested corpora (the equivalence test passes
        bit-for-bit), but the guarantee is "identical absent score
        ties", not unconditional."""
        rows = self.dims.padded(self.dims.token_vocab_size)
        if self._batched is None:
            # top-T transfer bound: the host drops tried ids from the
            # device top list, so T must cover the K-1 picks plus every
            # id that can be in `tried` (initial method tokens <= 2C+1,
            # plus K per prior iteration)
            T = min(rows, (self.top_k - 1)
                    + 2 * self.dims.max_contexts + 1
                    + self.top_k * self.max_iters)
            self._batched = make_batched_attack_steps(
                self.dims, compute_dtype=self.compute_dtype,
                topk_transfer=T)
        eval_b, predict_b, score_topk_b = self._batched
        legal_dev = jnp.asarray(self.legal)
        M = len(methods)
        src = np.stack([np.asarray(m[0]) for m in methods])
        pth = np.stack([np.asarray(m[1]) for m in methods])
        dst = np.stack([np.asarray(m[2]) for m in methods])
        mask = np.stack([np.asarray(m[3]) for m in methods])
        tok_lists = [self.attackable_tokens(src[i], dst[i], mask[i])
                     for i in range(M)]
        for i, tl in enumerate(tok_lists):
            if len(tl) == 0:
                raise ValueError(
                    f"method {i} has no attackable tokens; filter with "
                    "attackable_tokens first (robustness.py's sweep "
                    "does this)")
        tok = np.array([tl[0][0] for tl in tok_lists], np.int32)
        occ_src = src == tok[:, None]
        occ_dst = dst == tok[:, None]
        occ = (jnp.asarray(occ_src), jnp.asarray(occ_dst))
        spare = np.array([spare_row(rows, src[i], dst[i])
                          for i in range(M)], np.int32)
        labels = np.asarray(predict_b(
            params, (jnp.asarray(src), jnp.asarray(pth),
                     jnp.asarray(dst), jnp.asarray(mask)))).astype(
                         np.int32)
        original = labels.copy()

        cur_src, cur_dst = src.copy(), dst.copy()
        cur_id = tok.copy()
        tried = [({int(tok[i])}
                  | set(np.unique(np.concatenate(
                      [src[i], dst[i]])).tolist()))
                 for i in range(M)]
        steps: List[List[RenameStep]] = [[] for _ in range(M)]
        success = np.zeros((M,), bool)
        done = np.zeros((M,), bool)
        iters = np.zeros((M,), np.int32)
        look = self.token_vocab.lookup_word

        for _ in range(self.max_iters):
            ids = (jnp.asarray(cur_src), jnp.asarray(pth),
                   jnp.asarray(cur_dst), jnp.asarray(mask))
            top_scores, top_ids = score_topk_b(
                params, ids, occ, jnp.asarray(spare),
                jnp.asarray(labels), -1.0, legal_dev)
            top_scores = np.asarray(top_scores)
            top_ids = np.asarray(top_ids)
            cand = np.empty((M, self.top_k), np.int32)
            for i in range(M):
                # host-side: first K-1 untried, finite entries of the
                # device top list (legality was masked on device); pad
                # with cur_id when the list runs dry — those re-evaluate
                # the current loss and can never be accepted (>= test)
                cand[i, :] = cur_id[i]
                if done[i]:
                    continue
                w = 0
                for t, s in zip(top_ids[i], top_scores[i]):
                    if w == self.top_k - 1 or np.isinf(s):
                        break
                    if int(t) not in tried[i]:
                        cand[i, w] = int(t)
                        w += 1
            loss_k, top1_k = eval_b(params, ids, occ,
                                    jnp.asarray(cand),
                                    jnp.asarray(labels))
            loss_k = np.asarray(loss_k)
            top1_k = np.asarray(top1_k)
            for i in range(M):
                if done[i]:
                    continue
                att = -loss_k[i]
                iters[i] += 1
                best = int(np.argmin(att[:-1]))
                tried[i].update(int(c) for c in cand[i])
                if att[best] >= float(att[-1]):
                    success[i] = attack_succeeded(
                        False, int(top1_k[i, -1]), int(labels[i]),
                        int(original[i]))
                    done[i] = True
                    continue
                new_id = int(cand[i, best])
                steps[i].append(RenameStep(
                    from_token=look(int(cur_id[i])),
                    to_token=look(new_id),
                    loss_before=float(att[-1]),
                    loss_after=float(att[best])))
                cur_src[i] = np.where(occ_src[i], new_id, cur_src[i])
                cur_dst[i] = np.where(occ_dst[i], new_id, cur_dst[i])
                cur_id[i] = new_id
                if attack_succeeded(False, int(top1_k[i, best]),
                                    int(labels[i]), int(original[i])):
                    success[i] = True
                    done[i] = True
            if done.all():
                break

        final_top1 = np.asarray(predict_b(
            params, (jnp.asarray(cur_src), jnp.asarray(pth),
                     jnp.asarray(cur_dst), jnp.asarray(mask))))
        tv = self.target_vocab
        return [AttackResult(
            success=bool(success[i]), targeted=False,
            original_prediction=tv.lookup_word(int(original[i])),
            final_prediction=tv.lookup_word(int(final_top1[i])),
            target_name=None,
            renames=([(look(int(tok[i])), look(int(cur_id[i])))]
                     if steps[i] else []),
            steps=steps[i], iterations=int(iters[i]),
            final_method=(cur_src[i], pth[i], cur_dst[i], mask[i]))
            for i in range(M)]
