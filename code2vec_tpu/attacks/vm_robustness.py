"""VarMisuse-head robustness sweep: untargeted rename attacks over a
`.vm.c2v` split (the VM counterpart of attacks/robustness.py — same
protocol from "Adversarial Examples for Models of Code", which attacked
its VarMisuse model the same way).

CLI:
  python -m code2vec_tpu.attacks.vm_robustness --load <vm_ckpt> \
      --test <file.vm.c2v> [--n 200] [--max_renames 1] [--iters 4]
      [--out report.json]

Prints one JSON line: mislocalization (attack success) rate, clean and
under-attack localization accuracy.

The sweep is serial (one attack_method per row): VM corpora in this
environment are synthetic and small, so the code2vec sweep's lockstep
batch optimization (GradientRenameAttack.attack_batch) has not been
ported here — port it before sweeping production-scale VM splits.
"""

from __future__ import annotations

import itertools
import json
import time
from typing import Optional

import numpy as np

from code2vec_tpu.attacks.vm_attack import VMGradientRenameAttack
from code2vec_tpu.data.vm_reader import parse_vm_rows


def evaluate_vm_robustness(model, test_path: str, *,
                           n_methods: int = 200, max_renames: int = 1,
                           max_iters: int = 4,
                           top_k_candidates: int = 32,
                           log=print) -> dict:
    """Attacks up to `n_methods` valid rows of `test_path` with the
    untargeted VM rename attack and aggregates."""
    attack = VMGradientRenameAttack(
        model.dims, model.vocabs.token_vocab,
        top_k_candidates=top_k_candidates, max_iters=max_iters,
        compute_dtype=model.compute_dtype)
    cfg = model.config
    with open(test_path, encoding="utf-8") as f:
        lines = list(itertools.islice(
            (ln for ln in f if ln.strip()), n_methods))
    labels, src, pth, dst, mask, cand, cmask, valid, _ = parse_vm_rows(
        lines, model.vocabs, cfg.MAX_CONTEXTS, cfg.MAX_CANDIDATES)

    n = moved = clean_correct = attacked_correct = 0
    iters_on_success = []
    t0 = time.time()
    for i in range(len(lines)):
        if valid[i] == 0 or mask[i].sum() == 0:
            continue
        # protocol parity with robustness.py: rows with no attackable
        # candidate are excluded, not counted as robust
        if not attack.attackable_slots(cand[i], cmask[i]):
            continue
        row = (src[i], pth[i], dst[i], mask[i], cand[i], cmask[i])
        res = attack.attack_method(model.params, row, targeted=False,
                                   max_renames=max_renames)
        n += 1
        clean_correct += res.original_slot == int(labels[i])
        attacked_correct += res.final_slot == int(labels[i])
        if res.success:
            moved += 1
            iters_on_success.append(res.iterations)
        if n % 25 == 0:
            log(f"vm robustness: {n} rows, "
                f"{moved / n:.3f} mislocalization rate so far")
    dt = time.time() - t0
    return {
        "metric": "vm_untargeted_rename_mislocalization_rate",
        "n_methods": n,
        "attack_success_rate": round(moved / max(n, 1), 4),
        "robustness": round(1.0 - moved / max(n, 1), 4),
        "clean_localization_acc": round(clean_correct / max(n, 1), 4),
        "attacked_localization_acc": round(
            attacked_correct / max(n, 1), 4),
        "mean_iterations_on_success": round(
            float(np.mean(iters_on_success)), 2) if iters_on_success
        else None,
        "max_renames": max_renames,
        "max_iters": max_iters,
        "seconds": round(dt, 1),
    }


def main(argv: Optional[list] = None) -> int:
    import argparse

    from code2vec_tpu.config import Config
    from code2vec_tpu.models.vm_model import VarMisuseModel

    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--load", required=True, help="varmisuse checkpoint")
    p.add_argument("--test", required=True, help=".vm.c2v file")
    p.add_argument("--n", type=int, default=200)
    p.add_argument("--max_renames", type=int, default=1)
    p.add_argument("--iters", type=int, default=4)
    p.add_argument("--topk", type=int, default=32)
    p.add_argument("--out", default=None, help="also write JSON here")
    a = p.parse_args(argv)

    cfg = Config(HEAD="varmisuse")
    cfg.load_path = a.load
    model = VarMisuseModel(cfg)
    report = evaluate_vm_robustness(
        model, a.test, n_methods=a.n, max_renames=a.max_renames,
        max_iters=a.iters, top_k_candidates=a.topk, log=cfg.log)
    line = json.dumps(report)
    print(line)
    if a.out:
        with open(a.out, "w", encoding="utf-8") as f:
            f.write(line + "\n")
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
