"""Configuration for code2vec-tpu.

Mirrors the reference's flat `Config` namespace and CLI flag names
(SURVEY.md §3 "Config/flags": `config.py` in the reference exposes every
hyperparameter as an UPPERCASE class attribute plus an argparse overlay and
derived path properties) so `train.sh`-style invocations run unchanged.

TPU-specific knobs (mesh shape, sampled softmax, binary shards, bf16) are
additive — absent flags keep reference defaults.
"""

from __future__ import annotations

import argparse
import dataclasses
import logging
import os
import sys
from typing import Optional


# Config attrs with NO CLI flag by design: capacity/architecture
# constants (reference parity values a flag would invite mis-tuning
# of) and loop bookkeeping. graftlint's config-drift rule enforces
# that every OTHER UPPERCASE attr is assigned from a --flag in
# load_from_args — adding a new attr forces a conscious choice: wire
# a flag (and document it in README.md) or register it here.
CONFIG_CONSTANTS = frozenset({
    "MAX_TOKEN_VOCAB_SIZE",      # reference java-large capacities
    "MAX_TARGET_VOCAB_SIZE",
    "MAX_PATH_VOCAB_SIZE",
    "DEFAULT_EMBEDDINGS_SIZE",   # model dims are checkpoint-manifest-
    "TARGET_EMBEDDINGS_SIZE",    #   owned, not flag-owned
    "DROPOUT_KEEP_RATE",
    "TEST_BATCH_SIZE",
    "SAVE_EVERY_EPOCHS",
    "MAX_TO_KEEP",
    "NUM_BATCHES_TO_LOG_PROGRESS",
    "TOP_K_WORDS_CONSIDERED_DURING_PREDICTION",
    "PROFILE_START_STEP",        # --profile_steps is the user knob
    "HEALTH_EVERY_S",            # monitor cadence; tests inject tiny
    #                              values directly, production default
    #                              is deliberately not a tuning knob
    "HBM_CEILING_GBPS",          # measured streaming ceiling (bench.py
    #                              re-measures every round; this is the
    #                              denominator for the LIVE analytic
    #                              floor gauges only, not a tuning knob
})


@dataclasses.dataclass
class Config:
    # ---- capacities (reference defaults, SURVEY.md §3 config row) ----
    MAX_CONTEXTS: int = 200
    MAX_TOKEN_VOCAB_SIZE: int = 1301136
    MAX_TARGET_VOCAB_SIZE: int = 261245
    MAX_PATH_VOCAB_SIZE: int = 911417

    # ---- model dims ----
    DEFAULT_EMBEDDINGS_SIZE: int = 128
    # Reference: TARGET_EMBEDDINGS_SIZE == code_vector_size == 3 * 128.
    TARGET_EMBEDDINGS_SIZE: Optional[int] = None  # derived: code_vector_size

    # ---- training hyperparameters ----
    DROPOUT_KEEP_RATE: float = 0.75
    TRAIN_BATCH_SIZE: int = 1024
    TEST_BATCH_SIZE: int = 1024
    NUM_TRAIN_EPOCHS: int = 20
    SAVE_EVERY_EPOCHS: int = 1
    MAX_TO_KEEP: int = 10
    NUM_BATCHES_TO_LOG_PROGRESS: int = 100
    TOP_K_WORDS_CONSIDERED_DURING_PREDICTION: int = 10
    LEARNING_RATE: float = 0.001  # tf.train.AdamOptimizer default (parity)
    # "cosine" (default) | "linear" | "constant" (reference parity).
    # A decaying schedule fixes the sampled-softmax head-class
    # late-training decay (full-LR negative-sampling overshoot; see
    # BASELINE.md round-3 decay study and training/optimizers.make_lr)
    # and lifted EVERY variant's F1 in the 50K-corpus study — the
    # shipped default (sampled+bf16+adafactor+cosine, 0.9273) beats the
    # reference-style constant-LR full softmax (0.9252).
    LR_SCHEDULE: str = "cosine"
    # "warmup_cosine" warmup length; 0 = auto (5% of total steps).
    # Only meaningful with --lr_schedule warmup_cosine (the
    # large-global-batch recipe; BASELINE.md round-4 study).
    LR_WARMUP_STEPS: int = 0
    # LAMB-style per-array trust-ratio rescale on every optimizer
    # branch (training/optimizers.make_optimizer). Changes opt_state
    # structure -> recorded in the checkpoint manifest.
    TRUST_RATIO: bool = False
    # "all" (round-4 behavior; measured harmful on tables) | "dense"
    # (LAMB standard for embedding-dominated models: trust-scale
    # TRANSFORM/ATTENTION/heads only — VERDICT r4 item 8)
    TRUST_RATIO_SCOPE: str = "all"
    SEED: int = 239

    # ---- softmax strategy (TPU addition; SURVEY.md §3.3 requires sampled
    # softmax for the java-large 261K-target config) ----
    USE_SAMPLED_SOFTMAX: bool = False
    NUM_SAMPLED_CLASSES: int = 4096

    # ---- TPU / parallelism (additive) ----
    BACKEND: str = "tpu"  # 'tpu' | 'cpu' — selects jax platform expectations
    MESH_DATA_AXIS: int = 0   # 0 → use all devices on the data axis
    MESH_MODEL_AXIS: int = 1  # model-parallel degree for sharded vocab tables
    MESH_CONTEXT_AXIS: int = 1  # context-parallel degree (transformer)
    MESH_DCN_AXIS: int = 1    # multi-slice data axis (batch shards over
    #                           dcn x data; cross-slice psum rides DCN)
    USE_BF16: bool = True     # compute in bfloat16 on the MXU, params f32
    # Touched-rows-only (lazy) Adam for the vocab tables
    # (training/sparse_steps.py + the round-13 sparse_update facade:
    # gathered-row differentiation, dedup + segment-sum into a compact
    # [U, E] gradient, live-rows-only apply — no dense [V, E] carrier).
    # BENCH_r05 pins the dense path at optimizer efficiency 0.786
    # against its 8.48M pc/s fwd/bwd floor; this is the lever that
    # closes the gap (SPARSE_UPDATE_PALLAS selects the fused kernel).
    # Default off until a TPU driver round lands the measured win:
    # flags-off numerics are the shipped trajectory. Supports
    # float32/bfloat16/int8 tables, adam embedding optimizer,
    # constant LR, bag encoder (verify() gates the rest).
    SPARSE_EMBEDDING_UPDATES: bool = False
    # Storage dtype for the three vocab tables. bf16 halves the
    # gather/scatter/optimizer HBM traffic dominating java-large steps
    # (+~40% throughput measured on v5e-lite) and matched (slightly
    # beat) f32 subtoken-F1 in the 50K-vocab quality study — both in
    # BASELINE.md — so it is the default; --tables_dtype float32
    # restores exact reference numerics.
    # "int8" (ops/quant.py) additionally stores the token/path tables
    # as int8 rows + per-row scales — the sub-bf16 lever BASELINE.md's
    # structural-bound analysis names; single-device bag-encoder
    # training only (verify() gates the unsupported combinations).
    TABLES_DTYPE: str = "bfloat16"  # "float32" | "bfloat16" | "int8"
    # Optimizer for the vocab tables: "adafactor" (factored second
    # moment, no momentum — the standard large-embedding-table practice)
    # or "adam" (reference parity). Adafactor is the default since
    # round 3: it is both the fastest step (26.0 vs 33-35 ms at
    # java-large B=1024) AND the highest-F1 sampled variant on the
    # 50K-corpus study (0.9145 vs 0.9042; BASELINE.md round-3 quality
    # table). `--embedding_optimizer adam` restores reference parity.
    EMBEDDING_OPTIMIZER: str = "adafactor"
    # Fused Pallas attention-pool kernel (ops/pallas_attention.py):
    # ~1.5x faster than the XLA pool in isolation on v5e (4.9 vs 7.7 ms
    # at B=1024). Default on; it only takes effect on a TPU backend
    # (the model silently falls back to the XLA pool elsewhere).
    USE_PALLAS: bool = True
    # int8 requantize implementation (only meaningful with
    # --tables_dtype int8): "auto" = the fused Pallas row-pass
    # (ops/pallas_requant.py) on TPU, the multi-pass XLA reference
    # elsewhere; "fused" forces the kernel (interpret mode off-TPU —
    # the CPU test path); "reference" forces the multi-pass form
    # (the round-5 baseline, kept for A/B attribution).
    REQUANT_PALLAS: str = "auto"  # "auto" | "fused" | "reference"
    # Sparse table-update implementation (only meaningful with
    # --sparse_embeddings): "auto" = the fused
    # Pallas live-row kernel (ops/pallas_sparse_update.py) on a
    # single-device TPU backend, the XLA segment-sum reference on CPU;
    # "fused" forces the kernel (interpret mode off-TPU — the CPU test
    # path); "reference" forces the XLA form (the A/B numerics
    # baseline). Honored under a MESH too (round 14): the compact
    # dedup/segment-sum/live-row apply runs per device inside
    # shard_map (sparse_update.mesh_sparse_apply) — no dense [V, E]
    # carrier on the data-parallel path.
    SPARSE_UPDATE_PALLAS: str = "auto"  # "auto" | "fused" | "reference"
    # Measured single-chip HBM streaming ceiling (GB/s) — bench.py
    # re-measures the real value every round; this constant only feeds
    # the LIVE analytic-floor gauges (train/step_floor_ms and the
    # health opt_efficiency monitor) where running the 1-GiB membench
    # mid-train would perturb the run being observed.
    HBM_CEILING_GBPS: float = 637.0
    # Double-buffered device infeed (data/prefetch.py; SURVEY.md §3.3
    # infeed row): how many batches ahead a daemon thread runs the host
    # parse + host->device transfer. 2 = classic double buffering
    # (default); 0 = synchronous transfers in the step loop (the
    # round-3 behavior, kept for A/B measurement).
    INFEED_PREFETCH: int = 2
    # Latency-amortizing chunked infeed (prefetch.py
    # ChunkedDevicePrefetcher): group this many batches into ONE
    # host->device transfer and slice on-device. 1 = off (default).
    # For high-latency links (the tunneled dev platform: ~200 ms per
    # transfer round trip); single-device only — ignored with a mesh.
    INFEED_CHUNK: int = 1
    # Async epoch checkpointing (training/checkpoint.py
    # AsyncCheckpointWriter): the train loop snapshots params/opt_state
    # with a cheap on-device copy and a background thread does the
    # device fetch + orbax write + pruning, so the loop's blocked time
    # per checkpoint is a small constant instead of the save wall time
    # (eval overlaps the writer tail; hard commit barrier at end of
    # training). `--async_checkpoint off` restores the synchronous save
    # (identical checkpoint directory layout) for A/B measurement —
    # tools/epoch_overhead.py drives the comparison.
    ASYNC_CHECKPOINT: bool = True

    # ---- batched serving (serving/server.py + serving/batcher.py):
    # a thread-safe request queue feeding a dynamic micro-batcher that
    # coalesces concurrent predict requests into the power-of-two
    # buckets the jitted predict step compiles, an LRU prediction
    # cache, and bounded-queue admission control. ----
    # Max methods per coalesced device batch. Must be a power of two:
    # it is the largest warmed shape bucket, so steady-state serving
    # never triggers a new jit compilation.
    SERVE_BATCH_MAX: int = 64
    # Coalescing window: after the first queued request, wait at most
    # this long for more before flushing (Clipper-style deadline batch).
    # 0 = greedy drain-and-flush (batches still form while the device
    # is busy). Small values keep the idle REPL's latency unchanged.
    SERVE_BATCH_TIMEOUT_MS: float = 2.0
    # Admission control: bounded request queue; submissions beyond this
    # depth are refused immediately with ServerOverloaded.
    SERVE_QUEUE_DEPTH: int = 128
    # Per-request deadline: a request still queued past this is shed
    # with ServerOverloaded instead of growing the tail. 0 = none.
    SERVE_DEADLINE_MS: float = 2000.0
    # LRU prediction cache entries (one per normalized path-context
    # bag); hits skip encode + device entirely. 0 disables.
    SERVE_CACHE_SIZE: int = 1024
    # Persistent extractor worker pool size (serving/extractor.py):
    # in-process libc2v when built, else one subprocess per file but
    # never a fresh pool spawn per request.
    SERVE_EXTRACT_WORKERS: int = 2

    # ---- external serving plane (ISSUE 18, serving/frontend.py +
    # replicas.py + reload.py + autoscale.py): HTTP front-end over a
    # replica fleet with hot weight reload and SLO autoscaling. ----
    # HTTP front-end port (POST /predict, GET /healthz /metrics
    # /pool). 0 = no socket (the in-process surface still works).
    SERVE_PORT: int = 0
    # Initial replica count: N PredictionServers (one model each)
    # behind one shared prediction cache.
    SERVE_REPLICAS: int = 1
    # Autoscaler bounds: the pool never shrinks below min or grows
    # past max, whatever the SLO rules say.
    SERVE_MIN_REPLICAS: int = 1
    SERVE_MAX_REPLICAS: int = 4
    # p99 latency SLO in ms: the autoscaler's serving_p99_slo alert
    # rule threshold (serve/request_ms:p99 > slo -> grow the pool).
    SERVE_SLO_MS: float = 250.0
    # Checkpoint-dir poll cadence for hot weight reload: committed
    # steps are sha256-verified then rolled one replica at a time.
    # 0 = reload off.
    SERVE_RELOAD_POLL_S: float = 0.0
    # Run the SLO autoscaling policy loop (off = fixed-size pool;
    # death/refill still applies either way).
    SERVE_AUTOSCALE: bool = False

    # ---- encoder architecture: "bag" (reference parity) or
    # "transformer" (set transformer over the contexts,
    # models/transformer_encoder.py; BASELINE.json configs[4]). ----
    ENCODER_TYPE: str = "bag"
    XF_LAYERS: int = 2
    # 3 heads -> head_dim = 384/3 = 128 = one MXU lane width: measured
    # 9% faster through the fused attention kernels at IDENTICAL
    # 12-epoch quality vs 4 heads (F1 0.9277 both; BASELINE.md round-4
    # transformer story). TPU-first default; --xf_heads 4 remains valid.
    XF_HEADS: int = 3
    # Per-layer rematerialization (jax.checkpoint) for deep encoders —
    # required at CodeBERT depth (12 layers) to keep activations O(1).
    XF_REMAT: bool = False
    # Ring attention over the ctx mesh axis (K/V rotate via ppermute;
    # O(C/s) per-device attention memory). Only takes effect with
    # --encoder transformer and --mesh_context > 1.
    RING_ATTENTION: bool = False

    # ---- task head: "code2vec" (method-name prediction, reference
    # parity) or "varmisuse" (pointer-style variable-misuse repair,
    # BASELINE.json configs[3]; models/varmisuse.py). ----
    HEAD: str = "code2vec"
    HEAD_EXPLICIT: bool = False  # True when --head was given on the CLI
    MAX_CANDIDATES: int = 8   # varmisuse pointer-candidate slots

    # ---- multi-host (SURVEY.md §3.3 comm-backend row): explicit
    # coordination flags; auto-detection (Cloud TPU pod / Slurm env)
    # needs no flags. ----
    DIST_COORDINATOR: Optional[str] = None   # host:port of process 0
    DIST_NUM_PROCESSES: Optional[int] = None
    DIST_PROCESS_ID: Optional[int] = None

    # ---- CLI surface (reference flag names, SURVEY.md §2 L6) ----
    train_data_path: Optional[str] = None   # --data <prefix>
    test_data_path: Optional[str] = None    # --test <file>
    save_path: Optional[str] = None         # --save <ckpt>
    load_path: Optional[str] = None         # --load <ckpt>
    is_predict: bool = False                # --predict
    release: bool = False                   # --release
    # --auto_resume: if --save already contains a checkpoint, load it
    # and continue training (preemption-friendly pod runs: the same
    # command line resumes after a restart instead of starting over).
    AUTO_RESUME: bool = False
    export_code_vectors: bool = False       # --export_code_vectors
    save_w2v: Optional[str] = None          # --save_w2v <path>
    save_t2v: Optional[str] = None          # --save_t2v <path>
    # --framework: the reference selects between its two implementations
    # (tensorflow|keras) here. This framework has exactly one
    # implementation (JAX/TPU), so the reference's values are accepted as
    # ALIASES of it — verify() logs a notice so a ported train.sh is
    # never silently ambiguous about what ran.
    DL_FRAMEWORK: str = "jax"
    VERBOSE_MODE: int = 1

    # ---- logging ----
    LOG_PATH: Optional[str] = None

    # ---- profiling (SURVEY.md §6 tracing row): --profile <dir> wraps
    # PROFILE_STEPS training steps in jax.profiler.start_trace /
    # stop_trace; the trace opens in tensorboard-plugin-profile. ----
    PROFILE_DIR: Optional[str] = None
    PROFILE_STEPS: int = 10
    PROFILE_START_STEP: int = 5  # skip compile + warmup steps

    # ---- optional TensorBoard scalars (SURVEY.md §6 metrics row):
    # --tensorboard <dir> streams train loss/throughput + eval metrics
    # as tf.summary scalars (host-side; TF is imported only when set).
    TENSORBOARD_DIR: Optional[str] = None

    # ---- unified run telemetry (code2vec_tpu/obs/): --telemetry_dir
    # <dir> opens a per-run JSONL event log + manifest and turns on
    # per-step step_ms / infeed_wait_ms / loss records, device-memory
    # gauges, and serving latency histograms. Unset (default): the
    # per-step path is a single boolean check, nothing is allocated or
    # written. NOTE: per-step records are device-sync-aware — enabling
    # telemetry serializes step dispatch against the loss transfer
    # (accurate attribution in exchange for pipelining; --profile stays
    # the non-intrusive tool).
    TELEMETRY_DIR: Optional[str] = None

    # ---- request-scoped tracing + stall watchdog (code2vec_tpu/obs/
    # trace.py + watchdog.py, ISSUE 6; both need --telemetry_dir — the
    # spans and stall dumps live in the run dir). ----
    # --trace: per-request span trees (queue -> batch -> device ->
    # decode share one trace id through the serving threads) and
    # per-step span trees (infeed_wait / step, linking the infeed batch
    # consumed and the async save triggered). Export with
    # tools/trace_report.py (--chrome for Perfetto / chrome://tracing).
    # Off (default): one boolean check on every traced path.
    TRACE: bool = False
    # --watchdog_stall_s: per-component progress deadline in seconds
    # for the heartbeating components (train loop, infeed producer,
    # checkpoint writer, serving batcher). A missed deadline emits a
    # `stall` telemetry event and dumps live spans + all thread stacks
    # + a registry snapshot to the run dir. 0 (default) = off. Size it
    # above the slowest legitimate gap (first-step jit compile, epoch
    # eval).
    WATCHDOG_STALL_S: float = 0.0
    # --watchdog_mode: "warn" records the stall and keeps running;
    # "raise" additionally makes it sticky — StallError at the stalled
    # component's next beat / the end-of-run poll (loud death over a
    # silent wedge).
    WATCHDOG_MODE: str = "warn"

    # ---- live metrics plane (code2vec_tpu/obs/exposition.py +
    # health.py + alerts.py, ISSUE 7): pull-based exposition, derived
    # health monitors, and an SLO alert engine. ----
    # --metrics_port: serve /metrics (Prometheus text format),
    # /healthz (watchdog-liveness readiness) and /vars (raw JSON
    # snapshot) from a stdlib daemon-thread HTTP server on this port.
    # 0 (default) = off. Works without --telemetry_dir (the registry
    # then lives in memory only — live scrape, no JSONL persistence).
    METRICS_PORT: int = 0
    # --alerts_mode: "off" (default) | "warn" | "raise". warn/raise
    # start the health monitors (non-finite loss, loss-spike z-score,
    # throughput regression, infeed starvation; serving adds cache-hit
    # collapse + shed burn-rate) and evaluate alert rules on a cadence
    # off the hot path, emitting edge-triggered `alert` JSONL events +
    # stdout lines. "raise" additionally makes a firing alert sticky —
    # AlertError at the training loop's next beat (the watchdog's
    # sticky-error discipline; never raised from the monitor thread).
    ALERTS_MODE: str = "off"
    # --alerts_rules: JSON file replacing the built-in rule set (see
    # README "Live metrics & alerts" for the syntax); None = defaults.
    ALERTS_RULES: Optional[str] = None
    # health-monitor / alert-rule evaluation cadence in seconds (no
    # CLI flag by design: tests inject tiny values, production runs
    # are fine at 1 Hz — the monitors read dict snapshots, so the
    # sweep never touches the hot path either way).
    HEALTH_EVERY_S: float = 1.0

    # ---- sampled phase attribution (code2vec_tpu/obs/phases.py,
    # ISSUE 15): --phase_profile on dispatches one step in every
    # --phase_sample_every through a phase-split path (each phase its
    # own synced dispatch over the training/phase_probes.py prefixes;
    # the state update stays the fused dispatch, so the trajectory is
    # bit-identical to an unprofiled run) and publishes per-phase
    # `train/phase/<p>_ms` timers + live `health/phase_*` roofline
    # gauges. Off (default): one boolean check per step. Needs a live
    # registry: --telemetry_dir or --metrics_port.
    PHASE_PROFILE: str = "off"   # "off" | "on"
    PHASE_SAMPLE_EVERY: int = 64

    # ---- deterministic fault injection (code2vec_tpu/resilience/,
    # ISSUE 10): --faults <file-or-inline-json> arms the seeded
    # failpoint registry (sites: ckpt/write, infeed/produce,
    # train/nan_loss, train/kill, serve/extract, serve/kill,
    # dist/init).
    # Unset (default): every site is one attribute/None check, no
    # thread, no allocation. tools/chaos.py drives the scenarios.
    FAULTS: Optional[str] = None

    # ---- adversarial attacks (the noamyft fork delta, SURVEY.md §0
    # item 2; attacks/): --attack {targeted,untargeted} runs the
    # gradient-guided rename attack on --attack_input's source and
    # reports the re-extracted, re-predicted outcome. ----
    ATTACK: Optional[str] = None          # "targeted" | "untargeted"
    ATTACK_TARGET: Optional[str] = None   # target method name (targeted)
    ATTACK_INPUT: str = "Input.java"      # source file to attack
    ATTACK_METHOD_INDEX: int = 0          # which method in the file
    ATTACK_MAX_RENAMES: int = 1           # variables to rename (greedy)
    ATTACK_DEADCODE: bool = False         # insert `int <adv>;` instead
    ATTACK_TOPK: int = 32                 # exact-rescore shortlist size
    ATTACK_ITERS: int = 4                 # rename iterations / variable
    # Adversarial-training defense (attacks/defense.py): with this
    # probability each training example has one variable renamed to a
    # random legal token (occurrences replaced consistently) inside the
    # jitted train step. 0 disables (reference parity).
    ADV_RENAME_PROB: float = 0.0
    # Replacement distribution for the defense: "uniform" (random legal
    # token, round-3 behavior) or "batch" (another example's variable —
    # simulates the attack's wrong-class cue injection; the measured
    # positive-control defense, BASELINE.md round 4).
    ADV_RENAME_MODE: str = "uniform"

    def __post_init__(self) -> None:
        if self.TARGET_EMBEDDINGS_SIZE is None:
            self.TARGET_EMBEDDINGS_SIZE = self.code_vector_size
        self._logger: Optional[logging.Logger] = None

    # ---- derived properties (reference parity) ----
    @property
    def context_vector_size(self) -> int:
        # token + path + token embeddings concatenated
        return 3 * self.DEFAULT_EMBEDDINGS_SIZE

    @property
    def code_vector_size(self) -> int:
        return self.context_vector_size

    @property
    def is_training(self) -> bool:
        return bool(self.train_data_path)

    @property
    def is_testing(self) -> bool:
        return bool(self.test_data_path)

    @property
    def is_loading(self) -> bool:
        return bool(self.load_path)

    @property
    def is_saving(self) -> bool:
        return bool(self.save_path)

    @property
    def train_data_path_prefix(self) -> Optional[str]:
        return self.train_data_path

    def data_path(self, split: str) -> str:
        """Path of one split's `.c2v` file: `<prefix>.<split>.c2v`."""
        assert self.train_data_path is not None
        return f"{self.train_data_path}.{split}.c2v"

    @property
    def word_freq_dict_path(self) -> Optional[str]:
        """The `.dict.c2v` pickle written by preprocessing (SURVEY.md §3.2)."""
        if not self.train_data_path:
            return None
        return f"{self.train_data_path}.dict.c2v"

    @property
    def model_load_dir(self) -> Optional[str]:
        return self.load_path

    @property
    def entire_model_load_path(self) -> Optional[str]:
        return self.load_path

    @property
    def entire_model_save_path(self) -> Optional[str]:
        return self.save_path

    # ---- argparse ingestion (reference flag spelling) ----
    @classmethod
    def arguments_parser(cls) -> argparse.ArgumentParser:
        p = argparse.ArgumentParser(description="code2vec-tpu")
        p.add_argument("--data", dest="data_path", default=None,
                       help="path prefix of {train,val,test}.c2v data")
        p.add_argument("--test", dest="test_path", default=None,
                       help="path to a .c2v test file")
        p.add_argument("--save", dest="save_path", default=None)
        p.add_argument("--load", dest="load_path", default=None)
        p.add_argument("--predict", action="store_true")
        p.add_argument("--release", action="store_true")
        p.add_argument("--auto_resume", action="store_true",
                       help="resume from --save's latest checkpoint "
                            "when one exists (preemption recovery)")
        p.add_argument("--export_code_vectors", action="store_true")
        p.add_argument("--save_w2v", dest="save_w2v", default=None)
        p.add_argument("--save_t2v", dest="save_t2v", default=None)
        p.add_argument("--framework", dest="dl_framework", default="jax",
                       choices=["jax", "tensorflow", "keras"],
                       help="accepted for CLI compatibility; always runs the "
                            "JAX/TPU implementation")
        p.add_argument("--backend", dest="backend", default=None,
                       choices=["tpu", "cpu", "gpu"])
        p.add_argument("--max_contexts", dest="max_contexts", type=int, default=None)
        p.add_argument("--batch_size", dest="batch_size", type=int, default=None)
        p.add_argument("--epochs", dest="epochs", type=int, default=None)
        p.add_argument("--lr", dest="lr", type=float, default=None)
        p.add_argument("--lr_schedule", dest="lr_schedule", default=None,
                       choices=["constant", "cosine", "linear",
                                "warmup_cosine"])
        p.add_argument("--warmup_steps", dest="warmup_steps", type=int,
                       default=None,
                       help="warmup_cosine warmup length "
                            "(0 = auto, 5%% of total steps)")
        p.add_argument("--trust_ratio_scope", dest="trust_ratio_scope",
                       default=None, choices=["all", "dense"])
        p.add_argument("--trust_ratio", dest="trust_ratio",
                       action="store_true",
                       help="LAMB-style per-array trust-ratio rescale "
                            "(large-global-batch recipe)")
        p.add_argument("--infeed_prefetch", dest="infeed_prefetch",
                       type=int, default=None,
                       help="batches of host->device transfer to run "
                            "ahead of the step loop (0 = synchronous)")
        p.add_argument("--infeed_chunk", dest="infeed_chunk",
                       type=int, default=None,
                       help="batches per host->device transfer "
                            "(latency amortization; 1 = off)")
        p.add_argument("--async_checkpoint", dest="async_checkpoint",
                       default=None, choices=["on", "off"],
                       help="background checkpoint writer (default on):"
                            " epoch saves block the train loop only for"
                            " an on-device snapshot; 'off' restores the"
                            " synchronous save for A/B measurement")
        p.add_argument("--sampled_softmax", dest="sampled_softmax",
                       action="store_true")
        p.add_argument("--num_sampled", dest="num_sampled", type=int, default=None)
        p.add_argument("--encoder", dest="encoder", default=None,
                       choices=["bag", "transformer"])
        p.add_argument("--xf_layers", dest="xf_layers", type=int,
                       default=None)
        p.add_argument("--xf_heads", dest="xf_heads", type=int,
                       default=None)
        p.add_argument("--xf_remat", dest="xf_remat",
                       action="store_true")
        p.add_argument("--ring_attention", dest="ring_attention",
                       action="store_true")
        p.add_argument("--head", dest="head", default=None,
                       choices=["code2vec", "varmisuse"])
        p.add_argument("--max_candidates", dest="max_candidates",
                       type=int, default=None)
        p.add_argument("--tables_dtype", dest="tables_dtype", default=None,
                       choices=["float32", "bfloat16", "int8"])
        p.add_argument("--no_bf16", dest="no_bf16", action="store_true",
                       help="compute in float32 on the MXU instead of "
                            "the bfloat16 default (A/B numerics "
                            "control; tables_dtype governs storage)")
        p.add_argument("--no_pallas", dest="no_pallas",
                       action="store_true",
                       help="disable the fused Pallas kernels (XLA "
                            "fallback everywhere; the A/B control for "
                            "the attention-pool and MHA kernels)")
        p.add_argument("--sparse_embeddings", dest="sparse_embeddings",
                       action="store_true",
                       help="touched-rows-only (lazy) Adam for the "
                            "vocab tables via the dedup + segment-sum "
                            "+ live-row sparse-update path — no dense "
                            "[V, E] gradient carrier (requires "
                            "--embedding_optimizer adam "
                            "--lr_schedule constant; float32/bfloat16/"
                            "int8 tables; see --sparse_update_pallas)")
        p.add_argument("--embedding_optimizer", dest="embedding_optimizer",
                       default=None, choices=["adam", "adafactor"])
        p.add_argument("--requant_pallas", dest="requant_pallas",
                       default=None,
                       choices=["auto", "fused", "reference"],
                       help="int8 requantize implementation: fused "
                            "Pallas row-pass (auto on TPU) or the "
                            "multi-pass XLA reference")
        p.add_argument("--sparse_update_pallas",
                       dest="sparse_update_pallas", default=None,
                       choices=["auto", "fused", "reference"],
                       help="sparse table-update implementation under "
                            "--sparse_embeddings: fused Pallas "
                            "live-row kernel (auto on single-device "
                            "TPU) or the XLA segment-sum reference; "
                            "honored under a mesh too (the kernel "
                            "runs per device inside shard_map)")
        p.add_argument("--mesh_data", dest="mesh_data", type=int, default=None)
        p.add_argument("--mesh_model", dest="mesh_model", type=int, default=None)
        p.add_argument("--mesh_context", dest="mesh_context", type=int,
                       default=None)
        p.add_argument("--mesh_dcn", dest="mesh_dcn", type=int,
                       default=None)
        p.add_argument("--seed", dest="seed", type=int, default=None)
        p.add_argument("--dist_coordinator", dest="dist_coordinator",
                       default=None,
                       help="host:port of process 0 for multi-host runs")
        p.add_argument("--dist_num_processes", dest="dist_num_processes",
                       type=int, default=None)
        p.add_argument("--dist_process_id", dest="dist_process_id",
                       type=int, default=None)
        p.add_argument("--logs-path", dest="logs_path", default=None)
        p.add_argument("--profile", dest="profile_dir", default=None,
                       help="write a jax.profiler trace of a few "
                            "training steps to this directory")
        p.add_argument("--profile_steps", dest="profile_steps", type=int,
                       default=None)
        p.add_argument("--tensorboard", dest="tensorboard_dir",
                       default=None,
                       help="write loss/throughput/eval scalars as "
                            "TensorBoard summaries to this directory")
        p.add_argument("--telemetry_dir", dest="telemetry_dir",
                       default=None,
                       help="unified run telemetry: per-run manifest + "
                            "JSONL event log (per-step step_ms / "
                            "infeed_wait_ms / loss, device-memory "
                            "gauges, serving latency); summarize with "
                            "tools/telemetry_report.py")
        p.add_argument("--trace", dest="trace", action="store_true",
                       help="request-scoped tracing: span trees for "
                            "serving requests and train steps in the "
                            "telemetry event log (requires "
                            "--telemetry_dir); render with "
                            "tools/trace_report.py")
        p.add_argument("--watchdog_stall_s", dest="watchdog_stall_s",
                       type=float, default=None,
                       help="stall watchdog progress deadline in "
                            "seconds for the train loop / infeed "
                            "producer / checkpoint writer / serving "
                            "batcher (0 = off; requires "
                            "--telemetry_dir)")
        p.add_argument("--watchdog_mode", dest="watchdog_mode",
                       default=None, choices=["warn", "raise"],
                       help="on a missed deadline: warn (record + "
                            "dump diagnostics, keep running) or raise "
                            "(sticky StallError)")
        p.add_argument("--metrics_port", dest="metrics_port",
                       type=int, default=None,
                       help="serve /metrics (Prometheus text), "
                            "/healthz (watchdog liveness) and /vars "
                            "(JSON snapshot) on this port from a "
                            "daemon-thread HTTP server (0 = off; "
                            "works with or without --telemetry_dir)")
        p.add_argument("--alerts_mode", dest="alerts_mode",
                       default=None, choices=["off", "warn", "raise"],
                       help="training-health monitors + SLO alert "
                            "rules evaluated off the hot path: warn "
                            "records edge-triggered alert events, "
                            "raise additionally surfaces a sticky "
                            "AlertError at the train loop's next beat "
                            "(requires --telemetry_dir)")
        p.add_argument("--alerts_rules", dest="alerts_rules",
                       default=None,
                       help="JSON rule file replacing the built-in "
                            "alert rules (threshold + multi-window "
                            "burn-rate; see README)")
        p.add_argument("--phase_profile", dest="phase_profile",
                       default=None, choices=["off", "on"],
                       help="sampled per-phase device timing: every "
                            "--phase_sample_every steps one step runs "
                            "phase-split (synced per-phase dispatches; "
                            "the state update stays the fused step) "
                            "and publishes train/phase/* timers + "
                            "health_phase_* roofline gauges (needs "
                            "--telemetry_dir or --metrics_port)")
        p.add_argument("--phase_sample_every",
                       dest="phase_sample_every", type=int,
                       default=None,
                       help="steps between phase-split samples "
                            "(default 64; the non-sampled hot path is "
                            "untouched)")
        p.add_argument("--serve_batch_max", dest="serve_batch_max",
                       type=int, default=None,
                       help="max methods per coalesced serving batch "
                            "(power of two; the largest warmed predict "
                            "bucket)")
        p.add_argument("--serve_batch_timeout_ms",
                       dest="serve_batch_timeout_ms", type=float,
                       default=None,
                       help="micro-batcher coalescing window in ms "
                            "(0 = greedy flush)")
        p.add_argument("--serve_queue_depth", dest="serve_queue_depth",
                       type=int, default=None,
                       help="bounded request queue depth; beyond it "
                            "submissions shed with ServerOverloaded")
        p.add_argument("--serve_deadline_ms", dest="serve_deadline_ms",
                       type=float, default=None,
                       help="per-request deadline in ms; queued past it "
                            "the request is shed (0 = none)")
        p.add_argument("--serve_cache_size", dest="serve_cache_size",
                       type=int, default=None,
                       help="LRU prediction cache entries keyed by the "
                            "normalized path-context bag (0 = off)")
        p.add_argument("--serve_extract_workers",
                       dest="serve_extract_workers", type=int,
                       default=None,
                       help="persistent extractor worker pool size")
        p.add_argument("--serve_port", dest="serve_port", type=int,
                       default=None,
                       help="HTTP front-end port (POST /predict, GET "
                            "/healthz /metrics /pool); 0 = no socket")
        p.add_argument("--serve_replicas", dest="serve_replicas",
                       type=int, default=None,
                       help="initial replica count behind the serving "
                            "front-end (one model per replica, one "
                            "shared prediction cache)")
        p.add_argument("--serve_min_replicas",
                       dest="serve_min_replicas", type=int,
                       default=None,
                       help="autoscaler floor: the pool never shrinks "
                            "below this")
        p.add_argument("--serve_max_replicas",
                       dest="serve_max_replicas", type=int,
                       default=None,
                       help="autoscaler ceiling: the pool never grows "
                            "past this")
        p.add_argument("--serve_slo_ms", dest="serve_slo_ms",
                       type=float, default=None,
                       help="p99 latency SLO in ms (the autoscaler's "
                            "serving_p99_slo rule threshold)")
        p.add_argument("--serve_reload_poll_s",
                       dest="serve_reload_poll_s", type=float,
                       default=None,
                       help="checkpoint-dir poll cadence for hot "
                            "weight reload (sha256-verified, one "
                            "replica at a time); 0 = off")
        p.add_argument("--serve_autoscale", dest="serve_autoscale",
                       action="store_true",
                       help="run the SLO autoscaling policy loop "
                            "(grow on burn-rate/p99 pages, shrink "
                            "after a sustained quiet window)")
        p.add_argument("--faults", dest="faults", default=None,
                       help="deterministic fault injection: a JSON "
                            "file (or inline JSON) arming named "
                            "failpoints — see README 'Fault "
                            "tolerance' and tools/chaos.py (unset = "
                            "all sites disarmed, zero overhead)")
        p.add_argument("--attack", dest="attack", default=None,
                       choices=["targeted", "untargeted"],
                       help="gradient-guided variable-rename attack on "
                            "--attack_input (needs --load)")
        p.add_argument("--attack_target", dest="attack_target",
                       default=None,
                       help="target method name for --attack targeted "
                            "(camelCase or subtoken|form)")
        p.add_argument("--attack_input", dest="attack_input",
                       default=None, help="source file (default "
                                          "Input.java)")
        p.add_argument("--attack_method_index", dest="attack_method_index",
                       type=int, default=None)
        p.add_argument("--attack_max_renames", dest="attack_max_renames",
                       type=int, default=None)
        p.add_argument("--attack_deadcode", dest="attack_deadcode",
                       action="store_true",
                       help="insert a dead `int <adv>;` declaration and "
                            "adversarially choose its name instead of "
                            "renaming an existing variable")
        p.add_argument("--attack_topk", dest="attack_topk", type=int,
                       default=None)
        p.add_argument("--attack_iters", dest="attack_iters", type=int,
                       default=None)
        p.add_argument("--adv_rename_prob", dest="adv_rename_prob",
                       type=float, default=None,
                       help="adversarial-training defense: probability "
                            "of randomly renaming one variable per "
                            "training example")
        p.add_argument("--adv_rename_mode", dest="adv_rename_mode",
                       default=None, choices=["uniform", "batch"],
                       help="defense replacement distribution: uniform "
                            "legal token, or another batch example's "
                            "variable (wrong-class cue training)")
        p.add_argument("-v", "--verbose", dest="verbose_mode", type=int, default=None)
        return p

    @classmethod
    def load_from_args(cls, args: Optional[list] = None) -> "Config":
        ns = cls.arguments_parser().parse_args(
            args if args is not None else sys.argv[1:])
        cfg = cls()
        cfg.train_data_path = ns.data_path
        cfg.test_data_path = ns.test_path
        cfg.save_path = ns.save_path
        cfg.load_path = ns.load_path
        cfg.is_predict = ns.predict
        cfg.release = ns.release
        cfg.AUTO_RESUME = ns.auto_resume
        cfg.export_code_vectors = ns.export_code_vectors
        cfg.save_w2v = ns.save_w2v
        cfg.save_t2v = ns.save_t2v
        cfg.DL_FRAMEWORK = ns.dl_framework
        if ns.backend is not None:
            cfg.BACKEND = ns.backend
        if ns.max_contexts is not None:
            cfg.MAX_CONTEXTS = ns.max_contexts
        if ns.batch_size is not None:
            cfg.TRAIN_BATCH_SIZE = ns.batch_size
        if ns.epochs is not None:
            cfg.NUM_TRAIN_EPOCHS = ns.epochs
        if ns.lr is not None:
            cfg.LEARNING_RATE = ns.lr
        if ns.lr_schedule is not None:
            cfg.LR_SCHEDULE = ns.lr_schedule
        if ns.warmup_steps is not None:
            cfg.LR_WARMUP_STEPS = ns.warmup_steps
        if ns.trust_ratio:
            cfg.TRUST_RATIO = True
        if ns.trust_ratio_scope is not None:
            cfg.TRUST_RATIO_SCOPE = ns.trust_ratio_scope
        if ns.infeed_prefetch is not None:
            cfg.INFEED_PREFETCH = ns.infeed_prefetch
        if ns.infeed_chunk is not None:
            cfg.INFEED_CHUNK = ns.infeed_chunk
        if ns.async_checkpoint is not None:
            cfg.ASYNC_CHECKPOINT = ns.async_checkpoint == "on"
        if ns.sampled_softmax:
            cfg.USE_SAMPLED_SOFTMAX = True
        if ns.num_sampled is not None:
            cfg.NUM_SAMPLED_CLASSES = ns.num_sampled
        if ns.encoder is not None:
            cfg.ENCODER_TYPE = ns.encoder
        if ns.xf_layers is not None:
            cfg.XF_LAYERS = ns.xf_layers
        if ns.xf_heads is not None:
            cfg.XF_HEADS = ns.xf_heads
        if ns.xf_remat:
            cfg.XF_REMAT = True
        if ns.ring_attention:
            cfg.RING_ATTENTION = True
        if ns.head is not None:
            cfg.HEAD = ns.head
        cfg.HEAD_EXPLICIT = ns.head is not None
        if ns.max_candidates is not None:
            cfg.MAX_CANDIDATES = ns.max_candidates
        if ns.tables_dtype is not None:
            cfg.TABLES_DTYPE = ns.tables_dtype
        if ns.no_bf16:
            cfg.USE_BF16 = False
        if ns.no_pallas:
            cfg.USE_PALLAS = False
        if ns.sparse_embeddings:
            cfg.SPARSE_EMBEDDING_UPDATES = True
        if ns.embedding_optimizer is not None:
            cfg.EMBEDDING_OPTIMIZER = ns.embedding_optimizer
        if ns.requant_pallas is not None:
            cfg.REQUANT_PALLAS = ns.requant_pallas
        if ns.sparse_update_pallas is not None:
            cfg.SPARSE_UPDATE_PALLAS = ns.sparse_update_pallas
        if ns.mesh_data is not None:
            cfg.MESH_DATA_AXIS = ns.mesh_data
        if ns.mesh_model is not None:
            cfg.MESH_MODEL_AXIS = ns.mesh_model
        if ns.mesh_context is not None:
            cfg.MESH_CONTEXT_AXIS = ns.mesh_context
        if ns.mesh_dcn is not None:
            cfg.MESH_DCN_AXIS = ns.mesh_dcn
        if ns.seed is not None:
            cfg.SEED = ns.seed
        cfg.DIST_COORDINATOR = ns.dist_coordinator
        cfg.DIST_NUM_PROCESSES = ns.dist_num_processes
        cfg.DIST_PROCESS_ID = ns.dist_process_id
        if ns.logs_path is not None:
            cfg.LOG_PATH = ns.logs_path
        if ns.profile_dir is not None:
            cfg.PROFILE_DIR = ns.profile_dir
        if ns.profile_steps is not None:
            cfg.PROFILE_STEPS = ns.profile_steps
        if ns.tensorboard_dir is not None:
            cfg.TENSORBOARD_DIR = ns.tensorboard_dir
        if ns.telemetry_dir is not None:
            cfg.TELEMETRY_DIR = ns.telemetry_dir
        if ns.trace:
            cfg.TRACE = True
        if ns.watchdog_stall_s is not None:
            cfg.WATCHDOG_STALL_S = ns.watchdog_stall_s
        if ns.watchdog_mode is not None:
            cfg.WATCHDOG_MODE = ns.watchdog_mode
        if ns.metrics_port is not None:
            cfg.METRICS_PORT = ns.metrics_port
        if ns.alerts_mode is not None:
            cfg.ALERTS_MODE = ns.alerts_mode
        if ns.alerts_rules is not None:
            cfg.ALERTS_RULES = ns.alerts_rules
        if ns.phase_profile is not None:
            cfg.PHASE_PROFILE = ns.phase_profile
        if ns.phase_sample_every is not None:
            cfg.PHASE_SAMPLE_EVERY = ns.phase_sample_every
        if ns.serve_batch_max is not None:
            cfg.SERVE_BATCH_MAX = ns.serve_batch_max
        if ns.serve_batch_timeout_ms is not None:
            cfg.SERVE_BATCH_TIMEOUT_MS = ns.serve_batch_timeout_ms
        if ns.serve_queue_depth is not None:
            cfg.SERVE_QUEUE_DEPTH = ns.serve_queue_depth
        if ns.serve_deadline_ms is not None:
            cfg.SERVE_DEADLINE_MS = ns.serve_deadline_ms
        if ns.serve_cache_size is not None:
            cfg.SERVE_CACHE_SIZE = ns.serve_cache_size
        if ns.serve_extract_workers is not None:
            cfg.SERVE_EXTRACT_WORKERS = ns.serve_extract_workers
        if ns.serve_port is not None:
            cfg.SERVE_PORT = ns.serve_port
        if ns.serve_replicas is not None:
            cfg.SERVE_REPLICAS = ns.serve_replicas
        if ns.serve_min_replicas is not None:
            cfg.SERVE_MIN_REPLICAS = ns.serve_min_replicas
        if ns.serve_max_replicas is not None:
            cfg.SERVE_MAX_REPLICAS = ns.serve_max_replicas
        if ns.serve_slo_ms is not None:
            cfg.SERVE_SLO_MS = ns.serve_slo_ms
        if ns.serve_reload_poll_s is not None:
            cfg.SERVE_RELOAD_POLL_S = ns.serve_reload_poll_s
        if ns.serve_autoscale:
            cfg.SERVE_AUTOSCALE = True
        if ns.faults is not None:
            cfg.FAULTS = ns.faults
        if ns.attack is not None:
            cfg.ATTACK = ns.attack
        if ns.attack_target is not None:
            cfg.ATTACK_TARGET = ns.attack_target
        if ns.attack_input is not None:
            cfg.ATTACK_INPUT = ns.attack_input
        if ns.attack_method_index is not None:
            cfg.ATTACK_METHOD_INDEX = ns.attack_method_index
        if ns.attack_max_renames is not None:
            cfg.ATTACK_MAX_RENAMES = ns.attack_max_renames
        if ns.attack_deadcode:
            cfg.ATTACK_DEADCODE = True
        if ns.attack_topk is not None:
            cfg.ATTACK_TOPK = ns.attack_topk
        if ns.attack_iters is not None:
            cfg.ATTACK_ITERS = ns.attack_iters
        if ns.adv_rename_prob is not None:
            cfg.ADV_RENAME_PROB = ns.adv_rename_prob
        if ns.adv_rename_mode is not None:
            cfg.ADV_RENAME_MODE = ns.adv_rename_mode
        if ns.verbose_mode is not None:
            cfg.VERBOSE_MODE = ns.verbose_mode
        cfg.verify()
        return cfg

    def verify(self) -> None:
        """Validate flag combinations (reference `Config.verify`)."""
        if self.DL_FRAMEWORK not in ("jax", "tensorflow", "keras"):
            raise ValueError(
                f"--framework {self.DL_FRAMEWORK!r} unknown (expected "
                "jax, or the reference aliases tensorflow/keras).")
        if self.DL_FRAMEWORK != "jax":
            # reference CLI compatibility: both of the reference's
            # framework choices map onto the one JAX/TPU implementation
            self.log(f"--framework {self.DL_FRAMEWORK}: running the "
                     "JAX/TPU implementation (this framework's only "
                     "backend; the flag is accepted as an alias for "
                     "reference train.sh compatibility)")
        if not (self.is_training or self.is_loading):
            raise ValueError(
                "Must train (--data) or load a trained model (--load).")
        if self.is_predict and not self.is_loading:
            raise ValueError("--predict requires --load.")
        if self.release and not self.is_loading:
            raise ValueError("--release requires --load.")
        if self.MAX_CONTEXTS <= 0:
            raise ValueError("MAX_CONTEXTS must be positive.")
        if self.USE_SAMPLED_SOFTMAX and self.NUM_SAMPLED_CLASSES <= 0:
            raise ValueError("NUM_SAMPLED_CLASSES must be positive.")
        if self.HEAD == "varmisuse" and (self.is_predict or self.release
                                         or self.save_w2v
                                         or self.save_t2v
                                         or self.export_code_vectors):
            raise ValueError(
                "--predict/--release/--save_w2v/--save_t2v/"
                "--export_code_vectors apply to the code2vec head only.")
        if self.SPARSE_EMBEDDING_UPDATES and \
                self.EMBEDDING_OPTIMIZER != "adam":
            # the live-row update IS row-Adam; adafactor's factored
            # column stats are global over V and cannot be updated at
            # row granularity without a full-table walk
            raise ValueError(
                "SPARSE_EMBEDDING_UPDATES requires the adam embedding "
                "optimizer (the live-row kernel applies row-Adam; "
                "float32/bfloat16/int8 tables are all supported).")
        if self.REQUANT_PALLAS not in ("auto", "fused", "reference"):
            raise ValueError(
                "--requant_pallas must be auto, fused or reference "
                f"(got {self.REQUANT_PALLAS!r}).")
        if self.SPARSE_UPDATE_PALLAS not in ("auto", "fused",
                                             "reference"):
            raise ValueError(
                "--sparse_update_pallas must be auto, fused or "
                f"reference (got {self.SPARSE_UPDATE_PALLAS!r}).")
        if self.TABLES_DTYPE == "int8":
            # the int8 path covers the shipped per-chip training config
            # (bag encoder, single device); the gated combinations read
            # the token/path tables as plain arrays (transformer/vm
            # gathers, attack matvec, LAMB's ||param||) or shard by flat
            # key (mesh rules) and would need the dequantized view.
            if self.ENCODER_TYPE != "bag":
                raise ValueError(
                    "--tables_dtype int8 supports the bag encoder only "
                    "(transformer_encoder gathers the tables directly).")
            if self.HEAD != "code2vec":
                raise ValueError(
                    "--tables_dtype int8 supports the code2vec head "
                    "only.")
            if self.MESH_MODEL_AXIS > 1 or self.MESH_CONTEXT_AXIS > 1:
                raise ValueError(
                    "--tables_dtype int8 supports data-parallel meshes "
                    "only (model/ctx sharding of {q, s} subtrees is "
                    "untested; tables replicate under DP).")
            if self.TRUST_RATIO:
                raise ValueError(
                    "--tables_dtype int8 is incompatible with "
                    "--trust_ratio (the trust rescale needs ||param|| "
                    "of the flat table the quantized step never "
                    "materializes).")
            if self.ATTACK:
                raise ValueError(
                    "--attack needs float/bf16 tables (the gradient "
                    "attack's candidate matvec reads the table as one "
                    "array); rerun with a bf16 checkpoint.")
        if self.SERVE_BATCH_MAX < 1 or (
                self.SERVE_BATCH_MAX & (self.SERVE_BATCH_MAX - 1)):
            # power of two so the batcher's flush cap IS the largest
            # warmed predict bucket — otherwise steady-state serving
            # would jit-compile an unwarmed shape under load
            raise ValueError(
                "--serve_batch_max must be a power of two "
                f"(got {self.SERVE_BATCH_MAX}).")
        if self.SERVE_BATCH_TIMEOUT_MS < 0:
            raise ValueError("--serve_batch_timeout_ms must be >= 0.")
        if self.SERVE_QUEUE_DEPTH < 1:
            raise ValueError("--serve_queue_depth must be >= 1.")
        if self.SERVE_DEADLINE_MS < 0:
            raise ValueError("--serve_deadline_ms must be >= 0.")
        if self.SERVE_CACHE_SIZE < 0:
            raise ValueError("--serve_cache_size must be >= 0.")
        if self.SERVE_EXTRACT_WORKERS < 1:
            raise ValueError("--serve_extract_workers must be >= 1.")
        if not 0 <= self.SERVE_PORT <= 65535:
            raise ValueError("--serve_port must be in [0, 65535].")
        if self.SERVE_MIN_REPLICAS < 1:
            raise ValueError("--serve_min_replicas must be >= 1.")
        if self.SERVE_MAX_REPLICAS < self.SERVE_MIN_REPLICAS:
            raise ValueError(
                "--serve_max_replicas must be >= --serve_min_replicas "
                f"(got {self.SERVE_MAX_REPLICAS} < "
                f"{self.SERVE_MIN_REPLICAS}).")
        if not (self.SERVE_MIN_REPLICAS <= self.SERVE_REPLICAS
                <= self.SERVE_MAX_REPLICAS):
            raise ValueError(
                "--serve_replicas must sit inside "
                "[--serve_min_replicas, --serve_max_replicas] "
                f"(got {self.SERVE_REPLICAS} outside "
                f"[{self.SERVE_MIN_REPLICAS}, "
                f"{self.SERVE_MAX_REPLICAS}]).")
        if self.SERVE_SLO_MS <= 0:
            raise ValueError("--serve_slo_ms must be > 0.")
        if self.SERVE_RELOAD_POLL_S < 0:
            raise ValueError("--serve_reload_poll_s must be >= 0.")
        if self.TRACE and not self.TELEMETRY_DIR:
            raise ValueError(
                "--trace requires --telemetry_dir (spans are recorded "
                "through the run's JSONL event log).")
        if self.WATCHDOG_STALL_S < 0:
            raise ValueError("--watchdog_stall_s must be >= 0.")
        if self.WATCHDOG_STALL_S > 0 and not self.TELEMETRY_DIR:
            raise ValueError(
                "--watchdog_stall_s requires --telemetry_dir (stall "
                "events and diagnostic dumps live in the run dir).")
        if self.WATCHDOG_MODE not in ("warn", "raise"):
            raise ValueError(
                "--watchdog_mode must be warn or raise "
                f"(got {self.WATCHDOG_MODE!r}).")
        if not 0 <= self.METRICS_PORT <= 65535:
            raise ValueError(
                f"--metrics_port must be in [0, 65535] "
                f"(got {self.METRICS_PORT}).")
        if self.ALERTS_MODE not in ("off", "warn", "raise"):
            raise ValueError(
                "--alerts_mode must be off, warn or raise "
                f"(got {self.ALERTS_MODE!r}).")
        if self.ALERTS_MODE != "off" and not self.TELEMETRY_DIR:
            raise ValueError(
                "--alerts_mode warn/raise requires --telemetry_dir "
                "(alert events are recorded through the run's JSONL "
                "event log; --metrics_port alone works without it).")
        if self.ALERTS_RULES and self.ALERTS_MODE == "off":
            raise ValueError(
                "--alerts_rules without --alerts_mode warn|raise "
                "would be silently ignored.")
        if self.HEALTH_EVERY_S <= 0:
            raise ValueError("HEALTH_EVERY_S must be positive.")
        if self.PHASE_PROFILE not in ("off", "on"):
            raise ValueError(
                "--phase_profile must be off or on "
                f"(got {self.PHASE_PROFILE!r}).")
        if self.PHASE_SAMPLE_EVERY < 1:
            raise ValueError("--phase_sample_every must be >= 1.")
        if self.PHASE_PROFILE == "on" and not self.TELEMETRY_DIR \
                and self.METRICS_PORT <= 0:
            raise ValueError(
                "--phase_profile on needs a live registry: pass "
                "--telemetry_dir (persisted phase events) or "
                "--metrics_port (in-memory, scrape-only).")
        if self.LR_WARMUP_STEPS < 0:
            raise ValueError("--warmup_steps must be >= 0.")
        if self.INFEED_PREFETCH < 0:
            raise ValueError("--infeed_prefetch must be >= 0.")
        if self.INFEED_CHUNK < 1:
            raise ValueError("--infeed_chunk must be >= 1.")
        if self.INFEED_CHUNK > 1 and self.INFEED_PREFETCH == 0:
            # chunking is inherently threaded (the producer stacks
            # ahead); silently running a thread under the synchronous
            # A/B control flag would confound the measurement
            raise ValueError(
                "--infeed_chunk > 1 requires --infeed_prefetch >= 1 "
                "(chunked infeed always uses the producer thread).")
        if self.LR_WARMUP_STEPS > 0 and self.LR_SCHEDULE != "warmup_cosine":
            raise ValueError(
                "--warmup_steps applies only to "
                "--lr_schedule warmup_cosine (other schedules have no "
                "warmup phase and would silently ignore it).")
        if (self.TRUST_RATIO and self.TRUST_RATIO_SCOPE == "dense"
                and self.EMBEDDING_OPTIMIZER != "adafactor"):
            raise ValueError(
                "--trust_ratio_scope dense requires "
                "--embedding_optimizer adafactor (adam runs one "
                "transform over all params; no table/dense split).")
        if self.TRUST_RATIO and self.SPARSE_EMBEDDING_UPDATES:
            raise ValueError(
                "--trust_ratio is not supported with "
                "SPARSE_EMBEDDING_UPDATES (the sparse row-update kernel "
                "bypasses the optax chain for the tables).")
        if self.SPARSE_EMBEDDING_UPDATES and self.LR_SCHEDULE != "constant":
            # the sparse row-update kernel applies a constant LR; a
            # schedule would be silently ignored
            raise ValueError(
                "SPARSE_EMBEDDING_UPDATES supports constant LR only "
                "(sparse_steps.py applies a fixed per-row learning "
                "rate).")
        if self.SPARSE_EMBEDDING_UPDATES and self.ENCODER_TYPE != "bag":
            # sparse_steps hard-codes the bag attention pool and would
            # silently leave transformer params untrained while eval runs
            # them — a train/eval architecture mismatch.
            raise ValueError(
                "SPARSE_EMBEDDING_UPDATES supports the bag encoder only "
                "(sparse_steps.py trains no transformer params).")
        if not 0.0 <= self.ADV_RENAME_PROB <= 1.0:
            raise ValueError("--adv_rename_prob must be in [0, 1].")
        if self.ADV_RENAME_PROB > 0 and self.SPARSE_EMBEDDING_UPDATES:
            raise ValueError(
                "--adv_rename_prob is not supported with "
                "SPARSE_EMBEDDING_UPDATES (the sparse step has no "
                "augmentation hook).")
        if self.ADV_RENAME_PROB > 0 and self.HEAD == "varmisuse":
            raise ValueError(
                "--adv_rename_prob applies to the code2vec head only "
                "(the varmisuse train step has no augmentation hook).")
        if self.ATTACK and not self.is_loading:
            raise ValueError("--attack requires --load.")
        if self.ATTACK == "targeted" and not self.ATTACK_TARGET:
            raise ValueError(
                "--attack targeted requires --attack_target <name>.")
        if self.ATTACK and self.HEAD == "varmisuse":
            raise ValueError(
                "--attack applies to the code2vec head only.")
        if self.HEAD == "varmisuse" and (self.ENCODER_TYPE != "bag"
                                         or self.MESH_CONTEXT_AXIS > 1):
            # vm_scores calls the bag encode() directly; accepting
            # --encoder transformer here would silently train the wrong
            # architecture.
            raise ValueError(
                "--head varmisuse supports the bag encoder only "
                "(no --encoder transformer / --mesh_context > 1).")

    def get_logger(self) -> logging.Logger:
        if self._logger is None:
            logger = logging.getLogger("code2vec-tpu")
            logger.setLevel(logging.INFO if self.VERBOSE_MODE >= 1
                            else logging.WARNING)
            if not logger.handlers:
                sh = logging.StreamHandler(sys.stdout)
                sh.setFormatter(logging.Formatter(
                    "%(asctime)s %(levelname)s %(message)s"))
                logger.addHandler(sh)
                if self.LOG_PATH:
                    os.makedirs(os.path.dirname(self.LOG_PATH) or ".",
                                exist_ok=True)
                    fh = logging.FileHandler(self.LOG_PATH)
                    fh.setFormatter(logging.Formatter(
                        "%(asctime)s %(levelname)s %(message)s"))
                    logger.addHandler(fh)
            self._logger = logger
        return self._logger

    def log(self, msg: str) -> None:
        self.get_logger().info(msg)
