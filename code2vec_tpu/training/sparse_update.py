"""Fused sparse table-update facade: dedup + segment-sum + live-row
optimizer update (ROADMAP item 1, round 13).

BENCH_r05 pins the per-chip step at 6.66M pc/s against an 8.48M fwd/bwd
floor (optimizer efficiency 0.786) with HBM at 15.7% of the 637 GB/s
ceiling: the step is backward-scatter-bound. A batch touches far fewer
than V unique token/path ids, yet the dense-path gradients flow through
a dense [V, E] carrier (the VJP of a gather) and the optimizer/requant
apply walks far more rows than it needs. This module removes the dense
carrier from the sparse path entirely:

  1. `dedup_segment_sum`: sort-dedup the step's gathered ids
     (jnp.unique with a static slot count) and scatter-add their
     cotangents into a COMPACT [S, E] gradient — S ~ the id count, not
     V, so the scatter target is batch-sized. Bit-parity property:
     accumulation order per duplicate group matches the dense-carrier
     scatter-add (same updates array, same per-index order), so the
     compact sums equal `zeros([V, E]).at[ids].add(g)` gathered at the
     unique ids bit-for-bit in f32 (tests/test_sparse_update.py).
  2. A live-row apply touching ONLY the unique rows: row-Adam on
     float/bf16 tables, a requantize-aware row-Adam on int8 {q, s}
     tables (same per-row absmax rescale + counter-hash dither stream
     as ops/quant.requantize — `dither_from_index` is the shared
     primitive, so a live-row pass and a full-table pass draw identical
     dither for the same element index and salt).

Dispatch follows the ops/quant.requantize pattern: the fused Pallas
kernel (ops/pallas_sparse_update.py — one pass over the live rows,
per-row DMA gather/scatter, no [V, E] materialization) on a
single-device TPU backend, the XLA gather/scatter reference on CPU;
`Config.SPARSE_UPDATE_PALLAS` ("auto" | "fused" | "reference") maps
onto the `fused` argument via `resolve_sparse_update_mode`. Under a
MESH (round 14) `mesh_sparse_apply` runs the SAME compact path per
device inside `shard_map` — the GSPMD partitioner never sees the
dedup composition it miscompiles, and the flag is honored everywhere.
The reference and the kernel share the row-math helpers below (single
source of truth), so fused-vs-reference parity is bit-exact on
float/bf16 tables and q-exact on int8 under a shared salt.

Consumed by training/sparse_steps.py (code2vec head: cotangents arrive
at gathered-row granularity, no dense carrier anywhere) and
training/vm_steps.py (varmisuse head: autodiff still emits the dense
table cotangent, but the optimizer walk is live-rows-only via
`rows_from_dense`). bench.py attributes the phase every round
(`sparse_update_*`) against the analytic traffic model here.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from code2vec_tpu.ops.quant import (_SCALE_FLOOR, QuantTable,
                                    dither_from_index, is_quantized)
from code2vec_tpu.training.sparse_adam import RowAdamState

# Unique-row slots per kernel program. 512 rows x E=128 keeps the
# per-block VMEM working set (p/m/v or q/s/m/v row blocks + f32 temps)
# small while amortizing the grid; tools/sparse_update_sweep.py is the
# tuning driver for this knob.
_BLOCK_ROWS = 512


def resolve_sparse_update_mode(mode: str):
    """Config.SPARSE_UPDATE_PALLAS -> the `fused` argument below
    (ops/quant.resolve_tristate_mode is the shared mapping)."""
    from code2vec_tpu.ops.quant import resolve_tristate_mode
    return resolve_tristate_mode(mode, "SPARSE_UPDATE_PALLAS")


def _num_slots(n_ids: int, block_rows: int) -> int:
    """Static unique-id capacity: n_ids rounded up to a whole number of
    kernel blocks (>= any possible unique count; the kernel never sees
    Pallas-introduced padding, whose contents are undefined)."""
    return -(-n_ids // block_rows) * block_rows


def dedup_segment_sum(ids: jax.Array, grads: jax.Array, num_rows: int,
                      *, block_rows: int = _BLOCK_ROWS
                      ) -> Tuple[jax.Array, jax.Array]:
    """[N] ids + [N, E] cotangents -> ([S] unique ids padded with the
    out-of-range sentinel `num_rows`, [S, E] f32 per-unique-row sums).

    S is static (= N rounded up to block_rows), so the whole step jits
    once; `num_rows` doubles as the padding sentinel because real ids
    are always < the table's row count. Accumulates in f32 regardless
    of the cotangent dtype (bf16 sums over hundreds of duplicates would
    lose the low bits the optimizer needs)."""
    ids = ids.reshape(-1)
    grads = grads.reshape(ids.shape[0], -1)
    slots = _num_slots(ids.shape[0], block_rows)
    uids, inv = jnp.unique(ids, size=slots, fill_value=num_rows,
                           return_inverse=True)
    seg = jnp.zeros((slots, grads.shape[1]), jnp.float32
                    ).at[inv].add(grads.astype(jnp.float32))
    return uids, seg


# ---- shared row math (the kernel calls EXACTLY these helpers on its
# VMEM blocks — one definition, so fused-vs-reference parity cannot
# drift) ----

def row_adam_math(p, m, v, g, count, lr: float, b1: float, b2: float,
                  eps: float):
    """One Adam step for a block of rows, all f32. `count` is the
    (already incremented) global step shared with the dense-parameter
    optimizer so bias correction matches."""
    m_new = b1 * m + (1.0 - b1) * g
    v_new = b2 * v + (1.0 - b2) * jnp.square(g)
    c = count.astype(jnp.float32)
    lr_t = lr * jnp.sqrt(1.0 - b2 ** c) / (1.0 - b1 ** c)
    p_new = p - lr_t * m_new / (jnp.sqrt(v_new) + eps)
    return p_new, m_new, v_new


def requant_row_math(q, s, m, v, g, row_ids, salt, count, lr: float,
                     b1: float, b2: float, eps: float):
    """Row-Adam + requantize for a block of int8 rows: dequantize,
    Adam in f32, per-row absmax rescale, counter-hash dither over the
    ABSOLUTE [V, E] element index (row id * E + col — the same stream a
    full-table pass draws at those rows), round/clip back to int8.
    `row_ids` are the rows' table indices (int32 [R]); padded sentinel
    rows produce garbage that the caller discards."""
    f = q.astype(jnp.float32) * s
    p_new, m_new, v_new = row_adam_math(f, m, v, g, count, lr, b1, b2,
                                        eps)
    absmax = jnp.max(jnp.abs(p_new), axis=1, keepdims=True)
    s_new = jnp.maximum(absmax, _SCALE_FLOOR) / 127.0
    x = p_new / s_new
    emb = q.shape[-1]
    cols = jax.lax.broadcasted_iota(jnp.uint32, x.shape, 1)
    idx = row_ids.astype(jnp.uint32)[:, None] * jnp.uint32(emb) + cols
    q_new = jnp.clip(jnp.round(x + dither_from_index(idx, salt)),
                     -127, 127).astype(jnp.int8)
    return q_new, s_new, m_new, v_new


# ---- reference (XLA gather/scatter) live-row applies ----

def _apply_rows_reference(table, state: RowAdamState, uids, seg, count,
                          lr, b1, b2, eps):
    # sentinel uids gather a clipped garbage row and compute a garbage
    # update; the mode="drop" scatters discard exactly those rows
    p = jnp.take(table, uids, axis=0, mode="clip").astype(jnp.float32)
    m = jnp.take(state.m, uids, axis=0, mode="clip")
    v = jnp.take(state.v, uids, axis=0, mode="clip")
    p_new, m_new, v_new = row_adam_math(p, m, v, seg, count, lr, b1,
                                        b2, eps)
    table = table.at[uids].set(p_new.astype(table.dtype), mode="drop")
    m = state.m.at[uids].set(m_new, mode="drop")
    v = state.v.at[uids].set(v_new, mode="drop")
    return table, RowAdamState(m=m, v=v)


def _apply_quant_rows_reference(qt: QuantTable, state: RowAdamState,
                                uids, seg, salt, count, lr, b1, b2,
                                eps, dither_ids=None):
    # `dither_ids` (default: uids) are the rows' GLOBAL table indices
    # for the counter-hash dither stream — they differ from the gather
    # indices only when `qt` is a model-axis-sharded block of a larger
    # table (mesh_sparse_apply), where the dither must still draw from
    # the absolute [V, E] element index a full-table pass would use.
    q = jnp.take(qt["q"], uids, axis=0, mode="clip")
    s = jnp.take(qt["s"], uids, axis=0, mode="clip")
    m = jnp.take(state.m, uids, axis=0, mode="clip")
    v = jnp.take(state.v, uids, axis=0, mode="clip")
    q_new, s_new, m_new, v_new = requant_row_math(
        q, s, m, v, seg, uids if dither_ids is None else dither_ids,
        salt, count, lr, b1, b2, eps)
    new_q = qt["q"].at[uids].set(q_new, mode="drop")
    new_s = qt["s"].at[uids].set(s_new, mode="drop")
    new_m = state.m.at[uids].set(m_new, mode="drop")
    new_v = state.v.at[uids].set(v_new, mode="drop")
    return {"q": new_q, "s": new_s}, RowAdamState(m=new_m, v=new_v)


# ---- dispatch ----

def _resolve_fused(fused) -> bool:
    if fused is None:
        return jax.default_backend() == "tpu"
    return bool(fused)


def sparse_row_adam(table: jax.Array, state: RowAdamState,
                    ids: jax.Array, grads: jax.Array, *,
                    count: jax.Array, lr: float, b1: float = 0.9,
                    b2: float = 0.999, eps: float = 1e-8,
                    fused=None, block_rows: int | None = None):
    """Dedup + segment-sum + live-row Adam for a float/bf16 table.

    `ids` [N] (any shape, flattened) with per-occurrence cotangents
    `grads` [N, E]; only the unique rows are read or written — no dense
    [V, E] carrier. `fused=None` auto-selects the Pallas kernel on a
    TPU backend. Single-device entry: mesh steps route through
    `mesh_sparse_apply`, which runs the same dedup + apply per device
    inside shard_map. Returns (new_table, new_state)."""
    block_rows = block_rows or _BLOCK_ROWS
    uids, seg = dedup_segment_sum(ids, grads, table.shape[0],
                                  block_rows=block_rows)
    if _resolve_fused(fused):
        from code2vec_tpu.ops.pallas_sparse_update import \
            sparse_row_adam_fused
        return sparse_row_adam_fused(table, state, uids, seg,
                                     count=count, lr=lr, b1=b1, b2=b2,
                                     eps=eps, block_rows=block_rows)
    return _apply_rows_reference(table, state, uids, seg, count, lr,
                                 b1, b2, eps)


def sparse_requant_adam(qt: QuantTable, state: RowAdamState,
                        ids: jax.Array, grads: jax.Array,
                        rng: jax.Array, *, count: jax.Array, lr: float,
                        b1: float = 0.9, b2: float = 0.999,
                        eps: float = 1e-8, fused=None,
                        block_rows: int | None = None):
    """Dedup + segment-sum + live-row requantize-aware Adam for an int8
    {q, s} table. ONE tiny threefry draw per call (the same salt
    derivation as ops/quant._dither), shared by the fused and reference
    paths so q parity is bit-exact under a fixed rng. Returns
    (new_qt, new_state)."""
    block_rows = block_rows or _BLOCK_ROWS
    salt = jax.random.bits(rng, dtype=jnp.uint32)
    uids, seg = dedup_segment_sum(ids, grads, qt["q"].shape[0],
                                  block_rows=block_rows)
    if _resolve_fused(fused):
        from code2vec_tpu.ops.pallas_sparse_update import \
            sparse_requant_adam_fused
        return sparse_requant_adam_fused(qt, state, uids, seg, salt,
                                         count=count, lr=lr, b1=b1,
                                         b2=b2, eps=eps,
                                         block_rows=block_rows)
    return _apply_quant_rows_reference(qt, state, uids, seg, salt,
                                       count, lr, b1, b2, eps)


def mesh_sparse_apply(mesh, table, state: RowAdamState, parts, *,
                      count: jax.Array, lr: float, b1: float = 0.9,
                      b2: float = 0.999, eps: float = 1e-8, fused=None,
                      block_rows: int | None = None, rng=None):
    """The compact sparse update under a mesh (ROADMAP item 2): no
    dense [V, E] carrier, bit-identical to the single-device compact
    path.

    Why not just run sparse_row_adam under GSPMD: the dedup composition
    (jnp.unique at a static slot count + segment scatter) MISCOMPILES
    when the partitioner shards its inputs (measured, round 13 — wrong
    segment sums). So the whole dedup/segment-sum/apply runs INSIDE
    `shard_map` (manual SPMD — the partitioner never sees it):

      1. all-gather each sharded part's per-occurrence ids and
         cotangents over the composite batch axes ('dcn', 'data'),
         tiled, so every device holds the GLOBAL occurrence list in
         batch order; replicated parts (the shared sampled-softmax
         sample) pass through.
      2. concatenate parts in caller order and run the SAME
         `dedup_segment_sum` a single device would — identical input
         order means identical f32 additions in identical order, which
         is what makes the mesh path bit-exact vs the single-device
         compact path (and, transitively, vs the dense-carrier
         scatter-add in f32 — the round-13 property).
      3. apply live rows on the LOCAL table block: with the vocab dim
         sharded over 'model' each shard translates global unique ids
         into its row window (out-of-window rows become the local
         sentinel and are dropped by the scatter); data/dcn shards hold
         identical replicas and compute the identical update. int8
         blocks draw dither from the GLOBAL row index, so a sharded
         pass and a full-table pass emit identical bits.

    `parts` is a sequence of `(ids, grads, sharded)` triples holding
    GLOBAL-shape arrays ([N] / [N, E]); `sharded=True` marks arrays
    whose leading dim rides the ('dcn', 'data') batch axes (per-example
    gathers), False marks replicated arrays (the shared sample).
    ICI cost: one [N] + [N, E] all-gather per sharded part — the
    per-occurrence cotangents, NOT the [V, E] table; HBM cost per
    device: the single-device compact apply (∝ U live rows).
    `fused` follows resolve_sparse_update_mode exactly like the
    single-device path — SPARSE_UPDATE_PALLAS is honored under the
    mesh (the kernel runs per device inside the manual region).
    Returns (new_table, new_state)."""
    from code2vec_tpu.parallel.compat import shard_map
    from code2vec_tpu.parallel.mesh import (CONTEXT_AXIS, DATA_AXIS,
                                            DCN_AXIS, MODEL_AXIS)

    quant = is_quantized(table)
    block_rows = block_rows or _BLOCK_ROWS
    mesh_shape = dict(zip(mesh.axis_names, mesh.devices.shape))
    if mesh_shape.get(CONTEXT_AXIS, 1) != 1:
        raise ValueError(
            "mesh sparse updates require ctx=1 (the bag encoder's "
            f"batch never shards over 'ctx'; got mesh {mesh_shape})")
    model_shards = mesh_shape.get(MODEL_AXIS, 1)
    num_rows = (table["q"] if quant else table).shape[0]
    if num_rows % model_shards:
        raise ValueError(
            f"table rows {num_rows} not divisible by model axis "
            f"{model_shards} (ModelDims.vocab_pad_multiple)")
    salt = jnp.uint32(0)
    if quant:
        if rng is None:
            raise ValueError("int8 mesh sparse update needs `rng` for "
                             "the requantize dither salt")
        salt = jax.random.bits(rng, dtype=jnp.uint32)

    ids_list = [ids.reshape(-1) for ids, _g, _sh in parts]
    grads_list = [g.reshape(ids.shape[0], -1)
                  for ids, (_i, g, _sh) in zip(ids_list, parts)]
    flags = [bool(sh) for _i, _g, sh in parts]

    batch_axes = (DCN_AXIS, DATA_AXIS)
    P = jax.sharding.PartitionSpec
    row_spec = P(MODEL_AXIS, None)
    table_spec = {"q": row_spec, "s": row_spec} if quant else row_spec
    in_specs = (table_spec, row_spec, row_spec, P(), P(),
                *[P(batch_axes) if sh else P(None) for sh in flags],
                *[P(batch_axes, None) if sh else P(None, None)
                  for sh in flags])
    out_specs = (table_spec, row_spec, row_spec)

    def body(tbl, m, v, count_, salt_, *flat):
        k = len(flags)
        g_ids, g_grads = [], []
        for i in range(k):
            ids_i, grads_i = flat[i], flat[k + i]
            if flags[i]:
                ids_i = jax.lax.all_gather(ids_i, batch_axes, axis=0,
                                           tiled=True)
                grads_i = jax.lax.all_gather(grads_i, batch_axes,
                                             axis=0, tiled=True)
            g_ids.append(ids_i)
            g_grads.append(grads_i)
        ids = jnp.concatenate(g_ids) if k > 1 else g_ids[0]
        grads = jnp.concatenate(g_grads) if k > 1 else g_grads[0]
        uids, seg = dedup_segment_sum(ids, grads, num_rows,
                                      block_rows=block_rows)
        r_local = (tbl["q"] if quant else tbl).shape[0]
        if model_shards > 1:
            lo = jax.lax.axis_index(MODEL_AXIS) * r_local
            in_win = (uids >= lo) & (uids < lo + r_local)
            luids = jnp.where(in_win, uids - lo, r_local)
        else:
            luids = uids
        st = RowAdamState(m=m, v=v)
        if quant:
            if model_shards > 1 or not _resolve_fused(fused):
                # the fused kernel derives dither from its gather ids;
                # a model-sharded block needs the GLOBAL ids for that
                # stream, which only the reference threads through
                new_t, new_st = _apply_quant_rows_reference(
                    tbl, st, luids, seg, salt_, count_, lr, b1, b2,
                    eps, dither_ids=uids)
            else:
                from code2vec_tpu.ops.pallas_sparse_update import \
                    sparse_requant_adam_fused
                new_t, new_st = sparse_requant_adam_fused(
                    tbl, st, luids, seg, salt_, count=count_, lr=lr,
                    b1=b1, b2=b2, eps=eps, block_rows=block_rows)
        elif _resolve_fused(fused):
            from code2vec_tpu.ops.pallas_sparse_update import \
                sparse_row_adam_fused
            new_t, new_st = sparse_row_adam_fused(
                tbl, st, luids, seg, count=count_, lr=lr, b1=b1,
                b2=b2, eps=eps, block_rows=block_rows)
        else:
            new_t, new_st = _apply_rows_reference(
                tbl, st, luids, seg, count_, lr, b1, b2, eps)
        return new_t, new_st.m, new_st.v

    fn = shard_map(body, mesh=mesh, in_specs=in_specs,
                   out_specs=out_specs)
    new_t, new_m, new_v = fn(table, state.m, state.v, count, salt,
                             *ids_list, *grads_list)
    return new_t, RowAdamState(m=new_m, v=new_v)


def rows_from_dense(table, state: RowAdamState, dense_grad: jax.Array,
                    ids: jax.Array, *, count: jax.Array, lr: float,
                    b1: float = 0.9, b2: float = 0.999,
                    eps: float = 1e-8, fused=None,
                    block_rows: int | None = None):
    """Live-row Adam fed by a DENSE [V, E] cotangent (the varmisuse
    head: its loss gathers inside the differentiated function, so
    autodiff already emits the dense scatter-added carrier). The dense
    rows at the unique ids ARE the segment sums — gathering per
    occurrence and re-summing would multiply each row by its duplicate
    count — so this skips the segment-sum and pays only the [U, E]
    gather out of the carrier. Half the win of the carrier-free path
    (the backward scatter remains dense), all of the optimizer-walk
    win."""
    block_rows = block_rows or _BLOCK_ROWS
    ids = ids.reshape(-1)
    slots = _num_slots(ids.shape[0], block_rows)
    num_rows = table.shape[0]
    uids = jnp.unique(ids, size=slots, fill_value=num_rows)
    seg = jnp.take(dense_grad, uids, axis=0,
                   mode="clip").astype(jnp.float32)
    if _resolve_fused(fused):
        from code2vec_tpu.ops.pallas_sparse_update import \
            sparse_row_adam_fused
        return sparse_row_adam_fused(table, state, uids, seg,
                                     count=count, lr=lr, b1=b1, b2=b2,
                                     eps=eps, block_rows=block_rows)
    return _apply_rows_reference(table, state, uids, seg, count, lr,
                                 b1, b2, eps)


# ---- analytic traffic model (bench.py attribution + the live
# opt_efficiency gauge) ----

def sparse_update_traffic_bytes(table, n_ids: int, unique_rows: int,
                                *, grad_itemsize: int = 4,
                                block_rows: int = _BLOCK_ROWS) -> int:
    """Analytic HBM bytes of ONE sparse apply at U live rows: ids read
    once (the sort's log-factor passes are excluded — ids are ~0.1% of
    the row traffic), per-occurrence cotangents read once, the compact
    segment buffer written + read once, and per LIVE row: table rows
    read + written (int8: q AND s) plus both f32 moment rows read +
    written. The [U, E]-aware floor comparator for bench.py's
    `sparse_update_*` attribution — the dense path this replaces moves
    table+moment traffic proportional to V, not U."""
    n_slots = _num_slots(n_ids, block_rows)
    emb = (table["q"] if is_quantized(table) else table).shape[-1]
    total = n_ids * 4                       # ids read
    total += n_ids * emb * grad_itemsize    # cotangent rows read
    total += n_slots * emb * 4 * 2          # segment buffer w + r
    if is_quantized(table):
        total += unique_rows * emb * 1 * 2  # q rows r + w
        total += unique_rows * 4 * 2        # s rows r + w
    else:
        itemsize = table.dtype.itemsize
        total += unique_rows * emb * itemsize * 2   # param rows r + w
    total += unique_rows * emb * 4 * 2 * 2          # m and v rows r + w
    return int(total)


def table_id_counts(batch_size: int, max_contexts: int,
                    num_sampled: int = 0) -> dict:
    """Per-table gathered-id counts of one sparse train step (the
    code2vec head): token rows are gathered for src AND dst, target
    rows (sampled softmax) for the labels plus the shared sample."""
    counts = {"token_emb": 2 * batch_size * max_contexts,
              "path_emb": batch_size * max_contexts}
    if num_sampled:
        counts["target_emb"] = batch_size + num_sampled
    return counts


def sparse_update_phase_bytes(params, batch_size: int,
                              max_contexts: int, *,
                              num_sampled: int = 0,
                              block_rows: int = _BLOCK_ROWS,
                              processes: int = 1) -> int:
    """Analytic PER-DEVICE HBM bytes of the dedup/segment-sum/apply
    phase alone for one step over the three tables — the same
    per-table expected-unique-rows and grad-itemsize rules as
    sparse_step_floor_bytes (single source: bench.py's
    `sparse_update_bytes` attribution and the train loop's live
    `train/sparse_update_bytes` gauge must agree for the same config).
    Under a mesh every device runs the phase over the all-gathered
    GLOBAL occurrence list (mesh_sparse_apply), so `processes` scales
    the per-process `batch_size` up to the global count; the data-axis
    shard count does not appear (the phase is replicated, not
    sharded). Row-sharded tables are not described — see
    sparse_step_floor_bytes."""
    total = 0
    for key, n in table_id_counts(batch_size, max_contexts,
                                  num_sampled).items():
        table = params.get(key)
        if table is None:
            continue
        n_global = n * processes
        if is_quantized(table):
            num_rows, grad_itemsize = table["q"].shape[0], 2
        else:
            num_rows = table.shape[0]
            grad_itemsize = table.dtype.itemsize
        total += sparse_update_traffic_bytes(
            table, n_global, expected_unique_rows(n_global, num_rows),
            grad_itemsize=grad_itemsize, block_rows=block_rows)
    return int(total)


def sparse_step_floor_bytes(params, batch_size: int, max_contexts: int,
                            *, num_sampled: int = 0,
                            block_rows: int = _BLOCK_ROWS,
                            data_shards: int = 1,
                            processes: int = 1) -> int:
    """Analytic PER-DEVICE per-step HBM bytes of the FULL sparse-update
    step — the [U, E]-aware replacement for bench.py's dense
    `_step_hbm_bytes` (which counts a dense [V, E] carrier write+read
    and a table-proportional optimizer walk this path does not
    perform): forward row gathers (per occurrence), backward cotangent
    writes, and the dedup/segment-sum/live-row apply traffic
    (sparse_update_traffic_bytes at the uniform-ids E[U] — the bench
    worst case; real corpora are Zipfian, so this over-counts and the
    derived floor stays conservative). Dense non-table params add their
    usual grad/param/moment sweeps (negligible at java-large). Shared
    by bench.py's sparse floor attribution and the train loops' live
    `train/step_floor_ms` gauge (the health opt_efficiency monitor).

    Mesh model (round 14): `batch_size` stays the PER-PROCESS batch
    and `processes`/`data_shards` describe the topology — per device,
    the forward gathers and backward cotangent writes cover only the
    device's batch shard (global occurrences / data_shards), while the
    dedup/segment-sum/apply phase runs over the all-gathered GLOBAL
    occurrence list on every device (mesh_sparse_apply replicates that
    work rather than paying a second collective round). The defaults
    (1, 1) are the single-device identity. Row-sharded tables
    (model axis > 1) are NOT described — callers skip the gauges
    there (the window-masked apply needs its own model)."""
    counts = table_id_counts(batch_size, max_contexts, num_sampled)
    total = 0
    for key, n in counts.items():
        table = params.get(key)
        if table is None:
            continue
        n_global = n * processes
        n_local = n_global / data_shards
        if is_quantized(table):
            num_rows, emb = table["q"].shape
            row_bytes, grad_itemsize = emb * 1 + 4, 2  # q row + scale
        else:
            num_rows, emb = table.shape
            row_bytes = emb * table.dtype.itemsize
            grad_itemsize = table.dtype.itemsize
        u = expected_unique_rows(n_global, num_rows)
        total += int(n_local * row_bytes)  # forward row gathers
        total += int(n_local * emb * grad_itemsize)  # bwd cotangents
        total += sparse_update_traffic_bytes(
            table, n_global, u, grad_itemsize=grad_itemsize,
            block_rows=block_rows)
    for key, p in params.items():
        if key in counts or is_quantized(p):
            continue  # row-gathered tables: handled above
        for leaf in jax.tree_util.tree_leaves(p):
            b = leaf.size * leaf.dtype.itemsize
            total += b * 4 + b * 4  # grad w+r, param r+w, m/v r+w
    return int(total)


def phase_traffic_bytes(params, batch_size: int, max_contexts: int, *,
                        num_sampled: int = 0, sparse: bool = False,
                        compute_itemsize: int = 2,
                        block_rows: int = _BLOCK_ROWS,
                        data_shards: int = 1,
                        processes: int = 1) -> dict:
    """Analytic PER-DEVICE HBM bytes of each step phase (ISSUE 15):
    the per-phase generalization of sparse_step_floor_bytes, keyed by
    the phase names obs/phases.py publishes, so the live
    `health/phase_*` roofline gauges and bench.py's `phase_*`
    attribution divide measured ms by the SAME comparator. Coarse by
    design — streaming lower bounds (gathers run at random-access,
    not streaming, bandwidth; activations that stay resident are
    still counted once), so derived utilizations are conservative:

      embed_gather — forward row gathers per occurrence (row read +
        gathered-activation write at the compute dtype). The sampled-
        softmax target gathers are counted here for both paths (the
        dense step performs them inside the loss; one coarse rule).
      concat_dense — concat write + read of the [B, C, 3E] context
        tensor, the TRANSFORM weights, the transformed-tensor write.
      forward_pool — transformed read, attention-weighted reduction,
        code write, sampled logits.
      backward — activation re-read + context-cotangent write, plus
        per-occurrence table cotangents (at gathered-row granularity
        when `sparse`, the dense [V, E] carrier write + read
        otherwise — the asymmetry IS the sparse path's win).
      table_apply — `sparse`: sparse_update_phase_bytes (the [U, E]
        live-row model); dense: grad read + param read/write + two
        f32 moment sweeps per leaf (the Adam-shaped comparator
        _step_hbm_bytes uses).

    Mesh model follows sparse_step_floor_bytes: `batch_size` is the
    per-process batch; forward/backward cover the device's batch
    shard, the sparse apply covers the all-gathered GLOBAL list."""
    counts = table_id_counts(batch_size, max_contexts, num_sampled)
    gather = 0
    cot = 0
    carrier = 0
    emb_any = 0
    for key, n in counts.items():
        table = params.get(key)
        if table is None:
            continue
        n_local = n * processes / data_shards
        if is_quantized(table):
            rows, emb = table["q"].shape
            row_bytes, grad_itemsize = emb * 1 + 4, 2
            table_elems = table["q"].size
        else:
            rows, emb = table.shape
            row_bytes = emb * table.dtype.itemsize
            grad_itemsize = table.dtype.itemsize
            table_elems = table.size
        emb_any = emb
        gather += int(n_local * (row_bytes + emb * compute_itemsize))
        cot += int(n_local * emb * grad_itemsize)
        carrier += table_elems * grad_itemsize * 2  # dense w + r
    transform = params.get("transform")
    D = int(transform.shape[0]) if transform is not None else 3 * emb_any
    B_local = batch_size * processes / max(1, data_shards)
    ctx_bytes = int(B_local * max_contexts * D * compute_itemsize)
    out = {"embed_gather": gather}
    out["concat_dense"] = int(
        ctx_bytes * 3 + (D * D * 4 if transform is not None else 0))
    out["forward_pool"] = int(
        ctx_bytes + B_local * D * compute_itemsize
        + B_local * (1 + num_sampled) * 4)
    out["backward"] = int(ctx_bytes * 2
                          + (cot if sparse else cot + carrier))
    if sparse:
        out["table_apply"] = sparse_update_phase_bytes(
            params, batch_size, max_contexts, num_sampled=num_sampled,
            block_rows=block_rows, processes=processes)
    else:
        apply = 0
        for p in params.values():
            if is_quantized(p):
                apply += p["q"].size * 2          # carrier grad read
                apply += p["q"].size * 2          # q r + w
                apply += p["s"].size * 4 * 2      # s r + w
                apply += p["q"].size * 4 * 2 * 2  # Adam-shaped moments
                continue
            for leaf in jax.tree_util.tree_leaves(p):
                b = leaf.size * leaf.dtype.itemsize
                apply += b * 3                    # grad r, param r + w
                apply += leaf.size * 4 * 2 * 2    # two f32 moments r+w
        out["table_apply"] = int(apply)
    return {k: int(v) for k, v in out.items()}


def expected_unique_rows(n_ids: int, num_rows: int) -> int:
    """E[U] for n uniform draws over V rows (the bench worst case):
    V * (1 - (1 - 1/V)^n). Real corpora are Zipfian (fewer uniques),
    so a floor derived from this over-counts live-row traffic and stays
    conservative."""
    import math
    if num_rows <= 0 or n_ids <= 0:
        return 0
    return int(num_rows * (1.0 - math.exp(
        n_ids * math.log1p(-1.0 / num_rows))))
