"""Measurement-only jitted prefixes of the train step (ISSUE 15).

obs/phases.PhaseProfiler dispatches these, each its own synced call,
on a sampled step; bench.py slope-times the same chain for the
per-round `phase_*` breakdown — one probe construction, so the
sampled in-train attribution and the offline bench attribution can
never measure different math. The chain is CUMULATIVE (probe k
re-runs probes 1..k-1 plus one more stage); the profiler differences
consecutive synced times into per-phase device ms
(obs/phases.derive_chain_phases is the shared rule).

Probe outputs are DISCARDED — the sampled step's state update is the
fused dispatch (obs/phases.py module docstring: "sample the split,
trust the fused"). Prefix math comes from the step's own building
blocks: the dense chain re-runs `make_train_loss_fn` (the exact
function the fused step differentiates), the sparse chain re-runs
`sparse_steps.prepare_step_inputs`/`make_gathered_loss` (the exact
helpers `step_impl` calls). The concat/dense prefix stops after the
TRANSFORM matmul (tanh(contexts @ T)) — the last point before the
attention-softmax-pool — mirroring ops/attention.attention_pool's
first stage.

int8 tables: the chain stops at the forward (differentiating the
{q, s} dicts needs the fused step's carrier plumbing), so backward +
apply report as one `backward_apply` remainder — a documented
degradation, not a wrong number.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import optax

from code2vec_tpu.models.encoder import ModelDims, take_rows
from code2vec_tpu.obs.phases import ProbeKit

__all__ = ["make_code2vec_probes", "make_vm_probes"]


def _dropout(contexts, rng, keep_rate: float):
    if keep_rate >= 1.0:
        return contexts
    keep = jax.random.bernoulli(rng, keep_rate, contexts.shape)
    return jnp.where(keep, contexts / keep_rate, 0.0)


def _make_dense_apply(optimizer):
    """Isolated optimizer apply over the fwd_bwd probe's gradients —
    exactly make_train_step's apply section, timed alone."""

    @jax.jit
    def apply_probe(params, opt_state, grads):
        updates, new_state = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), new_state

    def apply_fn(params, opt_state, _batch, _rng, chain_out):
        _loss, grads = chain_out
        return apply_probe(params, opt_state, grads)

    return apply_fn


def _make_allreduce(mesh) -> Optional[Callable]:
    """Isolated grads-shaped all-reduce over the mesh's composite batch
    axes — the comm's fully-exposed cost (obs/phases.py derives the
    exposed-vs-overlapped pair from it). None when the mesh has no
    batch sharding (nothing to reduce)."""
    from code2vec_tpu.parallel.mesh import DATA_AXIS, DCN_AXIS
    shape = dict(zip(mesh.axis_names, mesh.devices.shape))
    if shape.get(DCN_AXIS, 1) * shape.get(DATA_AXIS, 1) <= 1:
        return None
    from code2vec_tpu.parallel.compat import shard_map
    P = jax.sharding.PartitionSpec

    def body(tree):
        return jax.tree_util.tree_map(
            lambda g: jax.lax.psum(g, (DCN_AXIS, DATA_AXIS)), tree)

    # replicated in/out: every device holds the full grads tree, the
    # psum is the allreduce pattern the GSPMD backward inserts (the
    # summed VALUES are n_devices x grads — discarded, only the comm
    # is being timed)
    fn = jax.jit(shard_map(body, mesh=mesh, in_specs=(P(),),
                           out_specs=P()))

    def allreduce_fn(chain_out):
        return fn(chain_out[1])

    return allreduce_fn


def make_code2vec_probes(dims: ModelDims, optimizer, *,
                         use_sampled_softmax: bool = False,
                         num_sampled: int = 4096,
                         compute_dtype=jnp.float32,
                         use_pallas: bool = False, mesh=None,
                         sparse_updates: bool = False) -> ProbeKit:
    """The code2vec head's probe kit, mirroring make_train_step's
    dispatch: the sparse chain when `sparse_updates` (gathered-row
    granularity — its backward emits NO dense carrier, exactly like
    the step), the dense chain otherwise."""
    if sparse_updates:
        return _sparse_kit(dims, use_sampled_softmax=use_sampled_softmax,
                           num_sampled=num_sampled,
                           compute_dtype=compute_dtype)
    return _dense_kit(dims, optimizer,
                      use_sampled_softmax=use_sampled_softmax,
                      num_sampled=num_sampled,
                      compute_dtype=compute_dtype,
                      use_pallas=use_pallas, mesh=mesh)


def _dense_kit(dims, optimizer, *, use_sampled_softmax, num_sampled,
               compute_dtype, use_pallas, mesh) -> ProbeKit:
    from code2vec_tpu.training.steps import make_train_loss_fn
    loss_fn = make_train_loss_fn(
        dims, use_sampled_softmax=use_sampled_softmax,
        num_sampled=num_sampled, compute_dtype=compute_dtype,
        use_pallas=use_pallas, mesh=mesh)

    @jax.jit
    def embed_gather(params, batch, _rng):
        _l, src, pth, dst, _m, _w = batch
        return (take_rows(params, "token_emb", src),
                take_rows(params, "path_emb", pth),
                take_rows(params, "token_emb", dst))

    chain = [("embed_gather", embed_gather)]

    if dims.encoder_type == "bag":
        @jax.jit
        def concat_dense(params, batch, rng):
            _l, src, pth, dst, _m, _w = batch
            contexts = jnp.concatenate(
                [take_rows(params, "token_emb", src),
                 take_rows(params, "path_emb", pth),
                 take_rows(params, "token_emb", dst)],
                axis=-1).astype(compute_dtype)
            drop_rng, _sample_rng = jax.random.split(rng)
            contexts = _dropout(contexts, drop_rng,
                                dims.dropout_keep_rate)
            return jnp.tanh(contexts
                            @ params["transform"].astype(contexts.dtype))

        chain.append(("concat_dense", concat_dense))
    # transformer encoder: no pre-attention seam to stop at — the
    # concat/dense stage folds into forward_pool

    chain.append(("forward_pool", jax.jit(loss_fn)))

    if dims.tables_dtype == "int8":
        # no backward probe (the {q, s} grads need the fused step's
        # straight-through carriers): backward + apply report as one
        # remainder
        return ProbeKit(chain, remainder_name="backward_apply")

    grad_fn = jax.jit(jax.value_and_grad(loss_fn))
    chain.append(("backward", lambda p, b, r: grad_fn(p, b, r)))
    allreduce_fn = _make_allreduce(mesh) if mesh is not None else None
    if allreduce_fn is None:
        # single-device: table_apply is the fused remainder — exact
        # there (fused = chain + apply, nothing else runs), and it
        # keeps the per-sample cost to the chain alone (the ≤2%
        # sampling-overhead budget at --phase_sample_every 64)
        return ProbeKit(chain)
    # mesh: the isolated apply probe is what lets the exposed-comm
    # derivation separate allreduce from apply (obs/phases.py). This
    # kit measures every phase directly, so no derived remainder —
    # table_apply stays the MEASURED apply and the kit publishes the
    # real residual (the in-fused comm the split cannot see) instead
    # of silently absorbing it into table_apply.
    return ProbeKit(chain, apply_fn=_make_dense_apply(optimizer),
                    allreduce_fn=allreduce_fn, derive_remainder=False)


def _sparse_kit(dims, *, use_sampled_softmax, num_sampled,
                compute_dtype) -> ProbeKit:
    """The sparse (--sparse_embeddings) chain over sparse_steps' own
    helpers. No apply probe: the dedup/segment-sum/live-row apply is
    entangled with the step's rng/count threading, so it reports as
    the fused remainder (`table_apply = fused - chain`) — under a mesh
    that remainder also carries mesh_sparse_apply's per-occurrence
    all-gathers."""
    from code2vec_tpu.training.sparse_steps import (make_gathered_loss,
                                                    prepare_step_inputs)
    S = min(num_sampled, dims.target_vocab_size)
    V = dims.target_vocab_size
    prep = functools.partial(prepare_step_inputs,
                             use_sampled_softmax=use_sampled_softmax,
                             num_sampled=S, target_vocab=V)

    @jax.jit
    def embed_gather(params, batch, rng):
        _dense, gathered, _ctx = prep(params, batch, rng)
        return gathered

    @jax.jit
    def concat_dense(params, batch, rng):
        dense, gathered, ctx = prep(params, batch, rng)
        contexts = jnp.concatenate(
            [gathered["src_e"], gathered["pth_e"], gathered["dst_e"]],
            axis=-1).astype(compute_dtype)
        contexts = _dropout(contexts, ctx["drop_rng"],
                            dims.dropout_keep_rate)
        return jnp.tanh(contexts
                        @ dense["transform"].astype(contexts.dtype))

    def _loss(params, batch, rng):
        dense, gathered, ctx = prep(params, batch, rng)
        loss_fn = make_gathered_loss(
            dims, ctx, use_sampled_softmax=use_sampled_softmax,
            compute_dtype=compute_dtype)
        return loss_fn, dense, gathered

    @jax.jit
    def forward_pool(params, batch, rng):
        loss_fn, dense, gathered = _loss(params, batch, rng)
        return loss_fn(dense, gathered)

    @jax.jit
    def backward(params, batch, rng):
        loss_fn, dense, gathered = _loss(params, batch, rng)
        return jax.value_and_grad(loss_fn, argnums=(0, 1))(dense,
                                                           gathered)

    return ProbeKit([("embed_gather", embed_gather),
                     ("concat_dense", concat_dense),
                     ("forward_pool", forward_pool),
                     ("backward", backward)])


def make_vm_probes(dims: ModelDims, *, compute_dtype=jnp.float32,
                   use_pallas: bool = False) -> ProbeKit:
    """The varmisuse head's probe kit (vm_steps.make_vm_train_step's
    shape): gather → forward → backward, with table_apply as the fused
    remainder on BOTH the dense and sparse apply paths (the remainder
    covers whichever apply the fused step runs, so the kit needs
    neither the optimizer nor the sparse flag). The vm loss gathers
    inside the differentiated function (its backward emits the dense
    cotangent), so there is no pre-attention concat/dense seam to
    probe."""
    from code2vec_tpu.models.varmisuse import vm_loss

    def loss_fn(params, batch, rng):
        return vm_loss(params, batch, dropout_rng=rng,
                       dropout_keep_rate=dims.dropout_keep_rate,
                       compute_dtype=compute_dtype,
                       use_pallas=use_pallas)

    @jax.jit
    def embed_gather(params, batch, _rng):
        _l, src, pth, dst, _m, cand, _cm, _w = batch
        return (take_rows(params, "token_emb", src),
                take_rows(params, "path_emb", pth),
                take_rows(params, "token_emb", dst),
                take_rows(params, "token_emb", cand))

    chain = [("embed_gather", embed_gather),
             ("forward_pool", jax.jit(loss_fn))]
    if dims.tables_dtype == "int8":
        return ProbeKit(chain, remainder_name="backward_apply")
    grad_fn = jax.jit(jax.value_and_grad(loss_fn))
    chain.append(("backward", lambda p, b, r: grad_fn(p, b, r)))
    # table_apply = fused remainder on both vm paths (the dense-apply
    # probe exists for the mesh exposed-comm derivation, which the vm
    # head does not wire) — keeps the sampling-overhead budget
    return ProbeKit(chain)
