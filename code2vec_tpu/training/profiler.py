"""--profile support: trace a window of training steps with jax.profiler.

SURVEY.md §6 (tracing row): the reference has no profiler at all — only
throughput log lines. The TPU framework adds a first-class trace hook:
`--profile <dir>` wraps steps [PROFILE_START_STEP, +PROFILE_STEPS) of the
current process's run in `jax.profiler.start_trace`/`stop_trace`; the
result opens in tensorboard-plugin-profile. Shared by every train loop
(code2vec and varmisuse heads).
"""

from __future__ import annotations

from typing import Callable, Optional


class StepProfiler:
    """Drives one bounded jax.profiler trace window over a train loop.

    Call `tick(step, sync_leaf)` once per step BEFORE launching the
    step's device work, with `step` counted from the start of this
    process (so resumed runs still profile), and `finish(sync_leaf)`
    after the loop in case the run was shorter than the window.
    `sync_leaf` is any device array to block on before stop_trace so the
    trace captures complete device timelines.
    """

    def __init__(self, profile_dir: Optional[str], start_step: int,
                 num_steps: int,
                 log: Optional[Callable[[str], None]] = None):
        self.profile_dir = profile_dir
        self.start_step = start_step
        self.num_steps = num_steps
        self.log = log or (lambda _msg: None)
        self._active = False
        self._done = profile_dir is None
        self._stop_at = start_step + num_steps

    def tick(self, step: int, sync_leaf) -> None:
        if self._done:
            return
        import jax
        if not self._active and step >= self.start_step:
            jax.profiler.start_trace(self.profile_dir)
            self._active = True
            self.log(f"profiler: tracing {self.num_steps} steps "
                     f"-> {self.profile_dir}")
        elif self._active and step >= self._stop_at:
            self._stop(sync_leaf)

    def finish(self, sync_leaf) -> None:
        """Close the trace if the run ended inside the window."""
        if self._active:
            self._stop(sync_leaf)
        elif not self._done:
            # --profile was requested but the run ended before
            # start_step — say so instead of leaving an empty directory
            self.log(f"profiler: run ended before step {self.start_step};"
                     f" no trace written (lower --profile start via "
                     f"PROFILE_START_STEP or train longer)")
            self._done = True

    def _stop(self, sync_leaf) -> None:
        import jax

        # Hard sync (host transfer of a tiny reduction, shared with the
        # telemetry spans — obs.device_sync): block_until_ready can
        # return early on the tunneled axon platform (BASELINE.md timing
        # methodology), which would stop the trace while traced steps
        # are still in flight.
        from code2vec_tpu.obs import device_sync
        device_sync(sync_leaf)
        jax.profiler.stop_trace()
        self._active = False
        self._done = True
        self.log(f"profiler: trace written to {self.profile_dir}")
