"""Jitted train/eval steps for the VarMisuse head (models/varmisuse.py).

Same shape discipline as training/steps.py: static shapes, pure
functions, sharding carried by the inputs, donation on the hot path.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
import optax

from code2vec_tpu.models.encoder import ModelDims
from code2vec_tpu.models.varmisuse import vm_loss, vm_scores


_VM_TABLE_KEYS = ("token_emb", "path_emb")


def init_vm_sparse_opt_state(params, dense_opt:
                             optax.GradientTransformation):
    """Sparse-row opt state for the vm head: row-Adam moments for the
    two vocab tables, the dense optimizer for everything else — the
    same {dense, rows, count} layout as sparse_steps so checkpoints
    and telemetry read uniformly."""
    from code2vec_tpu.training.sparse_adam import init_row_adam
    dense_params = {k: v for k, v in params.items()
                    if k not in _VM_TABLE_KEYS}
    rows = {k: init_row_adam(params[k]) for k in _VM_TABLE_KEYS}
    return {"dense": dense_opt.init(dense_params), "rows": rows,
            "count": jnp.zeros((), jnp.int32)}


def make_vm_train_step(dims: ModelDims,
                       optimizer: optax.GradientTransformation, *,
                       compute_dtype=jnp.float32,
                       use_pallas: bool = False,
                       sparse_updates: bool = False,
                       learning_rate: float | None = None,
                       sparse_update_fused=None,
                       sparse_block_rows: int | None = None,
                       mesh=None) -> Callable:
    """step(params, opt_state, batch, rng) -> (params, opt_state, loss);
    batch = (labels, src, pth, dst, mask, cand_ids, cand_mask,
    weights).

    `sparse_updates=True` (Config.SPARSE_EMBEDDING_UPDATES): the two
    vocab tables take a live-rows-only row-Adam step through
    training/sparse_update.rows_from_dense instead of riding the dense
    optax walk; opt_state must then come from init_vm_sparse_opt_state.
    The vm loss gathers INSIDE the differentiated function, so autodiff
    still emits the dense [V, E] cotangent — this buys the
    optimizer-walk half of the sparse win (the backward scatter stays
    dense; the code2vec head's sparse_steps path removes that too).
    Precision caveat: that cotangent is accumulated by autodiff's
    scatter-add in the TABLE dtype, so bf16 tables sum duplicate-row
    occurrences in bf16 — identical to what the vm DENSE path feeds
    optax (parity, not a regression), but weaker than the code2vec
    head's f32 segment-sum guarantee; prefer f32 tables when vm
    gradient fidelity matters."""

    def loss_fn(params, batch, rng):
        return vm_loss(params, batch, dropout_rng=rng,
                       dropout_keep_rate=dims.dropout_keep_rate,
                       compute_dtype=compute_dtype, use_pallas=use_pallas)

    if sparse_updates:
        assert learning_rate is not None, (
            "sparse_updates needs the tables' learning_rate")
        if mesh is not None:
            # the id-dedup composition (concat -> unique) miscompiles
            # under GSPMD on the virtual CPU mesh (measured, round 13
            # — see sparse_steps' dense-carrier mesh rule); the vm
            # head has no carrier fallback worth keeping, so gate.
            raise ValueError(
                "--sparse_embeddings on the varmisuse head is "
                "single-device only; drop the flag for mesh runs")
        from code2vec_tpu.training.sparse_update import rows_from_dense
        fused = sparse_update_fused

        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def sparse_step(params, opt_state, batch, rng):
            loss, grads = jax.value_and_grad(loss_fn)(params, batch,
                                                      rng)
            count = opt_state["count"] + 1
            dense = {k: v for k, v in params.items()
                     if k not in _VM_TABLE_KEYS}
            g_dense = {k: grads[k] for k in dense}
            updates, dense_state = optimizer.update(
                g_dense, opt_state["dense"], dense)
            new_params = dict(params,
                              **optax.apply_updates(dense, updates))
            # table ids gathered by vm_scores: src/dst/candidate token
            # rows, path rows
            _labels, src, pth, dst, _mask, cand_ids, _cm, _w = batch
            table_ids = {
                "token_emb": jnp.concatenate(
                    [src.reshape(-1), dst.reshape(-1),
                     cand_ids.reshape(-1)]),
                "path_emb": pth.reshape(-1)}
            new_rows = {}
            for k in _VM_TABLE_KEYS:
                new_params[k], new_rows[k] = rows_from_dense(
                    params[k], opt_state["rows"][k], grads[k],
                    table_ids[k], count=count, lr=learning_rate,
                    fused=fused, block_rows=sparse_block_rows)
            return new_params, {"dense": dense_state,
                                "rows": new_rows,
                                "count": count}, loss

        return sparse_step

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(params, opt_state, batch, rng):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch, rng)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return step


def make_vm_eval_step(dims: ModelDims, *, compute_dtype=jnp.float32,
                      use_pallas: bool = False) -> Callable:
    """step(params, batch) -> (loss_sum, correct_sum, pred [B]);
    no dropout."""

    @jax.jit
    def step(params, batch):
        labels, src, pth, dst, mask, cand_ids, cand_mask, weights = batch
        scores, _ = vm_scores(params, src, pth, dst, mask, cand_ids,
                              cand_mask, compute_dtype=compute_dtype,
                              use_pallas=use_pallas)
        logp = jax.nn.log_softmax(scores, axis=-1)
        ce = -jnp.take_along_axis(logp, labels[:, None], axis=-1)[:, 0]
        pred = jnp.argmax(scores, axis=-1)
        correct = (pred == labels).astype(jnp.float32)
        return (jnp.sum(ce * weights), jnp.sum(correct * weights), pred)

    return step
