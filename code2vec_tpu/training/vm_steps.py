"""Jitted train/eval steps for the VarMisuse head (models/varmisuse.py).

Same shape discipline as training/steps.py: static shapes, pure
functions, sharding carried by the inputs, donation on the hot path.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
import optax

from code2vec_tpu.models.encoder import ModelDims
from code2vec_tpu.models.varmisuse import vm_loss, vm_scores


def make_vm_train_step(dims: ModelDims,
                       optimizer: optax.GradientTransformation, *,
                       compute_dtype=jnp.float32,
                       use_pallas: bool = False) -> Callable:
    """step(params, opt_state, batch, rng) -> (params, opt_state, loss);
    batch = (labels, src, pth, dst, mask, cand_ids, cand_mask,
    weights)."""

    def loss_fn(params, batch, rng):
        return vm_loss(params, batch, dropout_rng=rng,
                       dropout_keep_rate=dims.dropout_keep_rate,
                       compute_dtype=compute_dtype, use_pallas=use_pallas)

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(params, opt_state, batch, rng):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch, rng)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return step


def make_vm_eval_step(dims: ModelDims, *, compute_dtype=jnp.float32,
                      use_pallas: bool = False) -> Callable:
    """step(params, batch) -> (loss_sum, correct_sum, pred [B]);
    no dropout."""

    @jax.jit
    def step(params, batch):
        labels, src, pth, dst, mask, cand_ids, cand_mask, weights = batch
        scores, _ = vm_scores(params, src, pth, dst, mask, cand_ids,
                              cand_mask, compute_dtype=compute_dtype,
                              use_pallas=use_pallas)
        logp = jax.nn.log_softmax(scores, axis=-1)
        ce = -jnp.take_along_axis(logp, labels[:, None], axis=-1)[:, 0]
        pred = jnp.argmax(scores, axis=-1)
        correct = (pred == labels).astype(jnp.float32)
        return (jnp.sum(ce * weights), jnp.sum(correct * weights), pred)

    return step
