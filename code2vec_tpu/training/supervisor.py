"""Crash-recovery supervisor (ISSUE 10 tentpole, layer 3; elastic
resize ISSUE 13): close the detect -> decide -> recover loop.

The stack could already DETECT trouble (PR 6 stall watchdog, PR 7 alert
engine) and SURVIVE it on disk (PR 5 commit-or-vanish checkpoints) —
but a killed worker ended the run and waited for a human. `Supervisor`
makes restart the ordinary path:

  - it spawns the training run as child process(es) — one, or an
    N-process Gloo cohort with a fresh coordinator port per attempt —
    and watches their exit codes;
  - BEFORE every (re)launch it verifies the checkpoint directory
    (`checkpoint.verify_and_resolve`): a corrupt latest step is
    quarantined and the child auto-resumes from the last VERIFIED
    committed step, never from rotten bytes;
  - any nonzero/signal exit fails the whole attempt: the remaining
    cohort members get a grace window to die on their own (the Gloo
    coordination-service heartbeat tolerance evicts the dead peer's
    partners), then are SIGKILLed, and the cohort relaunches
    COHERENTLY — never a half-old half-new mix of processes;
  - `resize_policy="shrink"` (ISSUE 13) makes peer loss a RESIZE, not
    a do-over: the next coherent launch re-forms the cohort at N−1
    processes (floor `min_procs`) instead of relaunching the world at
    full size, and grows back toward the configured target when a
    replacement is available (`replacement_fn`). The relaunched
    children rebuild the mesh and the per-host infeed split from the
    surviving process set, and the checkpoint layer reshards the
    restored state onto the new topology (its per-file sha256
    manifests are resharding-proof by design). Hangs (attempt
    timeouts) still relaunch at the same size — every member wedging
    is not evidence one of them is bad. `resize_policy="relaunch"`
    (the default) keeps the PR-10 full-size behavior;
  - a child that simply finishes (all exit 0) ends the supervised run;
  - the restart budget is bounded, the pacing is the shared
    `resilience/retry` backoff math, and every decision escalates
    through the EXISTING alert engine (`supervisor/*` gauges drive
    edge-triggered `alert` events: restarted -> ticket, quarantined
    checkpoint -> ticket, cohort resized -> ticket, budget
    exhausted -> page).

Frequent checkpointing (Check-N-Run) only pays off when restart is
automatic and verified; this is the piece that makes it so. The spawn
function is injectable, so the policy logic tests without real
training runs; `tools/train_supervisor.py` is the CLI entry and
`tools/chaos.py` drives the acceptance scenarios (SIGKILL parity,
corrupt-checkpoint fallback, kill-and-resize elastic parity) end to
end. `cohort_topology()` exposes the live process set + target size;
pass a `watchdog=` (tools/train_supervisor.py does behind
`--watchdog_stall_s`) and the supervisor attaches it to stall dumps
and heartbeats its supervise loop — a wedged cohort's postmortem shows
WHO was in the mesh.
"""

from __future__ import annotations

import os
import subprocess
import time
from typing import Callable, List, Optional, Sequence, Tuple

from code2vec_tpu.resilience import retry as retry_mod
from code2vec_tpu.training import checkpoint as ckpt

__all__ = ["RestartBudgetExceeded", "Supervisor", "build_cli_spawn",
           "supervisor_alert_rules"]


class RestartBudgetExceeded(RuntimeError):
    """The cohort kept dying past `max_restarts` relaunches — a human's
    problem now; the page-severity alert already fired."""


def supervisor_alert_rules():
    """Escalation through the EXISTING alert engine (ISSUE 7): the
    supervisor publishes gauges, these rules turn them into
    edge-triggered `alert` events + stdout lines."""
    from code2vec_tpu.obs.alerts import AlertRule
    from code2vec_tpu.obs.fleet import fleet_alert_rules
    return [
        AlertRule("train_process_restarted",
                  metric="supervisor/restarts", op=">=", value=1,
                  severity="ticket"),
        AlertRule("checkpoint_quarantined",
                  metric="resilience/ckpt_quarantined", op=">=",
                  value=1, severity="ticket"),
        # elastic re-form (ISSUE 13): a resized cohort keeps training,
        # but a human should know capacity degraded — warn-tier ticket,
        # not a page
        AlertRule("cohort_resized",
                  metric="supervisor/cohort_resized", op=">=",
                  value=1, severity="ticket"),
        # an explicit 0/1 gauge, not `restarts_remaining <= 0`: a
        # max_restarts=0 supervisor would otherwise page on a run that
        # SUCCEEDED without ever restarting
        AlertRule("restart_budget_exhausted",
                  metric="supervisor/budget_exhausted", op=">=",
                  value=1, severity="page"),
        # fleet plane (ISSUE 17): the cohort collector publishes its
        # gauges into THIS registry, so its straggler/divergence
        # tickets ride the same engine. Installed unconditionally —
        # threshold rules stay quiet while the fleet/* series are
        # absent (fleet plane off).
        *fleet_alert_rules(),
    ]


class Supervisor:
    """Restart supervisor over an injectable spawn function.

    `spawn_fn(attempt, proc_id, port, cohort_size) -> subprocess.Popen`
    launches one cohort member (`port` is a fresh coordinator port per
    attempt, 0 for single-process launches; `cohort_size` is the size
    of THIS attempt's cohort — under `resize_policy="shrink"` it can
    differ from the configured `num_procs`). The supervisor owns
    reaping: no child outlives a failed attempt (the tests/conftest.py
    leak-guard discipline).
    """

    def __init__(self, spawn_fn: Callable[[int, int, int, int],
                                          "subprocess.Popen"], *,
                 num_procs: int = 1, max_restarts: int = 3,
                 resize_policy: str = "relaunch",
                 min_procs: int = 1,
                 replacement_fn: Optional[Callable[[], bool]] = None,
                 ckpt_dir: Optional[str] = None,
                 telemetry=None, watchdog=None,
                 log: Optional[Callable[[str], None]] = None,
                 poll_s: float = 0.2, peer_grace_s: float = 15.0,
                 attempt_timeout_s: Optional[float] = None,
                 backoff: Optional[retry_mod.RetryPolicy] = None,
                 sleep: Callable[[float], None] = time.sleep):
        assert num_procs >= 1 and max_restarts >= 0
        assert resize_policy in ("relaunch", "shrink"), resize_policy
        assert 1 <= min_procs <= num_procs, (min_procs, num_procs)
        self._spawn_fn = spawn_fn
        self.num_procs = num_procs      # configured TARGET cohort size
        self.cur_procs = num_procs      # this attempt's cohort size
        self.resize_policy = resize_policy
        self.min_procs = min_procs
        self.replacement_fn = replacement_fn
        self.max_restarts = max_restarts
        self.ckpt_dir = ckpt_dir
        self._log = log or (lambda m: print(m, flush=True))
        self.poll_s = poll_s
        self.peer_grace_s = peer_grace_s
        self.attempt_timeout_s = attempt_timeout_s
        self._sleep = sleep
        # ONE backoff math for the whole repo: the supervisor's restart
        # pacing is the retry policy's delay curve, not a second
        # implementation
        self.backoff = backoff if backoff is not None else \
            retry_mod.RetryPolicy("supervisor-restart", max_attempts=1,
                                  base_delay_s=1.0, max_delay_s=60.0)
        if telemetry is None:
            from code2vec_tpu.obs import Telemetry
            telemetry = Telemetry.memory("supervisor")
        self.telemetry = telemetry
        retry_mod.set_telemetry(telemetry)
        from code2vec_tpu.obs.alerts import AlertEngine
        self.alerts = AlertEngine.create(
            telemetry, mode="warn", rules=supervisor_alert_rules(),
            log=self._log)
        self.restarts = 0
        self.quarantined: List[str] = []
        self.resumed_from_step: Optional[int] = None
        # elastic bookkeeping (ISSUE 13): every resize decision and the
        # count of same-size do-overs — the chaos kill_resize scenario
        # asserts full_relaunches == 0 when shrink handled a peer death
        self.resizes: List[Tuple[int, int]] = []
        self.full_relaunches = 0
        self.last_launch_ts: Optional[float] = None
        self._procs: List["subprocess.Popen"] = []
        # watchdog (ISSUE 13 satellite): attach the live cohort
        # topology to stall dumps and heartbeat the supervise loop —
        # a supervisor wedged in a hung spawn_fn or a reap that never
        # ends shows up as a stall whose dump says WHO was in the
        # mesh. tools/train_supervisor.py wires this behind
        # --watchdog_stall_s; embedders can also call
        # Watchdog.attach(cohort=sup.cohort_topology) themselves.
        self._watchdog_hb = None
        if watchdog is not None and getattr(watchdog, "enabled", False):
            watchdog.attach(cohort=self.cohort_topology)
            self._watchdog_hb = watchdog.register("supervisor_loop")
        # fleet plane (ISSUE 17): None until attach_fleet — one None
        # check per site is the whole disabled-path cost
        self.fleet = None
        self._fleet_members: List[str] = []

    def attach_fleet(self, collector,
                     member_urls: Sequence[str]) -> None:
        """Host the cohort collector (obs/fleet.py) in the supervisor:
        its gauges land in this registry, its straggler/divergence
        tickets ride `self.alerts`, its members re-point per attempt
        (an elastic resize shrinks the scrape set with the mesh), and
        its cohort snapshot joins stall dumps next to
        `cohort_topology` (which reads it live)."""
        if collector is None or not collector.enabled:
            return
        self.fleet = collector.attach(alerts=self.alerts)
        self._fleet_members = list(member_urls)

    def cohort_topology(self) -> dict:
        """The live cohort, as a stall-dump-attachable snapshot:
        target vs current size, live member pids, the resize history.
        Read from other threads (the watchdog's dump path) — every
        field is rebuilt per call, nothing is mutated."""
        procs = list(self._procs)
        topo = {
            "target_procs": self.num_procs,
            "cohort_size": self.cur_procs,
            "min_procs": self.min_procs,
            "resize_policy": self.resize_policy,
            "attempt": self.restarts,
            "live_pids": [p.pid for p in procs if p.poll() is None],
            "resizes": [list(r) for r in self.resizes],
            "full_relaunches": self.full_relaunches,
        }
        if self.fleet is not None:
            # a wedged cohort's stall dump answers "who was slow"
            # from the latest fleet sweep, right next to who was in
            # the mesh
            topo["fleet"] = self.fleet.brief()
        return topo

    # ---- checkpoint verification (runs before EVERY launch) ----
    def verify_checkpoint(self) -> Optional[int]:
        """Verify + quarantine so the child only ever resumes from a
        VERIFIED committed step; returns that step (None = fresh
        start). Quarantines escalate through the alert engine."""
        if not self.ckpt_dir or not os.path.isdir(self.ckpt_dir):
            return None
        good, quarantined = ckpt.verify_and_resolve(
            self.ckpt_dir, log=self._log)
        if quarantined:
            self.quarantined.extend(quarantined)
            self.telemetry.gauge("resilience/ckpt_quarantined",
                                 len(self.quarantined), emit=False)
            self.telemetry.event(
                "ckpt_quarantine", dirs=quarantined,
                fallback_step=good)
            self.alerts.check_now()
        return good

    # ---- one attempt ----
    def _kill_all(self, procs: Sequence["subprocess.Popen"]) -> None:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for p in procs:
            if p.poll() is None:
                p.wait()

    def _reap_with_grace(self, procs: Sequence["subprocess.Popen"]
                         ) -> None:
        """A peer died: give the rest `peer_grace_s` to notice (the
        coordination-service heartbeat eviction takes them down on
        their own), then SIGKILL the stragglers — the next launch is
        always a COHERENT cohort, whatever size it re-forms at."""
        deadline = time.monotonic() + self.peer_grace_s
        while time.monotonic() < deadline \
                and any(p.poll() is None for p in procs):
            if self._watchdog_hb is not None:
                self._watchdog_hb.beat()  # the grace wait IS progress
            self._sleep(self.poll_s)
        self._kill_all(procs)

    def _run_cohort(self, attempt: int
                    ) -> Tuple[bool, List[int], str]:
        """One coherent attempt at the CURRENT cohort size. Returns
        (ok, exit codes, reason) with reason one of "done",
        "peer_death", "cohort_failure", "timeout" — the resize policy
        shrinks only on peer death. A whole-cohort hang (timeout) or
        EVERY member of a multi-process cohort exiting nonzero
        together (cohort_failure — the same bad --data path killing
        all of them identically) is no evidence any ONE member is bad:
        shrinking would relaunch ever-smaller equally-doomed cohorts,
        so those relaunch at full size."""
        from code2vec_tpu.parallel.compat import free_port
        n = self.cur_procs
        port = free_port() if n > 1 else 0
        self.last_launch_ts = time.time()
        if self.fleet is not None:
            # this attempt's scrape set: the first n member endpoints
            # (a shrunk cohort scrapes the shrunk set; relaunched
            # members re-handshake when their run_id changes)
            self.fleet.set_members(self._fleet_members[:n])
        procs = [self._spawn_fn(attempt, i, port, n) for i in range(n)]
        self._procs = procs
        deadline = (time.monotonic() + self.attempt_timeout_s
                    if self.attempt_timeout_s else None)
        try:
            while True:
                rcs = [p.poll() for p in procs]
                if all(rc is not None for rc in rcs):
                    ok = all(rc == 0 for rc in rcs)
                    if ok:
                        return ok, rcs, "done"
                    # every member of a >1 cohort failed in the same
                    # poll window: systemic, not a lost peer (a single
                    # supervised process dying IS its peer dying)
                    systemic = len(rcs) > 1 \
                        and all(rc != 0 for rc in rcs)
                    return ok, rcs, ("cohort_failure" if systemic
                                     else "peer_death")
                if any(rc is not None and rc != 0 for rc in rcs):
                    # dead peer detected: coherent cohort teardown
                    self._reap_with_grace(procs)
                    return False, [p.poll() for p in procs], \
                        "peer_death"
                if deadline is not None and time.monotonic() > deadline:
                    self._log(f"supervisor: attempt {attempt} exceeded "
                              f"{self.attempt_timeout_s:.0f}s — "
                              "killing cohort")
                    self._kill_all(procs)
                    return False, [p.poll() for p in procs], "timeout"
                if self._watchdog_hb is not None:
                    self._watchdog_hb.beat()  # the loop is alive
                self._sleep(self.poll_s)
        finally:
            self._kill_all(procs)  # no orphan survives any exit path

    def _next_cohort_size(self, reason: str) -> int:
        """The resize decision: shrink by one on peer death (floor
        `min_procs`), then grow back toward the configured target for
        as many replacements as are available — a replacement arriving
        in the same window the peer died re-fills its slot, so the
        cohort re-forms at N, not N−1."""
        size = self.cur_procs
        if self.resize_policy == "shrink" and reason == "peer_death":
            size = max(self.min_procs, size - 1)
        while (self.replacement_fn is not None
               and size < self.num_procs and self.replacement_fn()):
            size += 1
        return size

    # ---- the supervised run ----
    def run(self) -> int:
        if self.fleet is not None:
            self.fleet.start()
        try:
            return self._run()
        finally:
            if self.fleet is not None:
                self.fleet.stop()

    def _run(self) -> int:
        self.telemetry.gauge("supervisor/restarts", 0, emit=False)
        self.telemetry.gauge("supervisor/restarts_remaining",
                             self.max_restarts, emit=False)
        self.telemetry.gauge("supervisor/cohort_target",
                             self.num_procs, emit=False)
        while True:
            if self._watchdog_hb is not None:
                # covers the pre-launch checkpoint-verify sweep; size
                # --watchdog_stall_s above that sweep (the train
                # loops' eval-vs-deadline guidance applies here too)
                self._watchdog_hb.beat()
            step = self.verify_checkpoint()
            if self.restarts > 0 or step is not None:
                self.resumed_from_step = step
            self.telemetry.gauge("supervisor/cohort_size",
                                 self.cur_procs, emit=False)
            self.telemetry.event(
                "supervisor_launch", attempt=self.restarts,
                num_procs=self.cur_procs,
                cohort_target=self.num_procs,
                resume_step=step if step is not None else -1)
            if step is not None:
                self._log(f"supervisor: launching attempt "
                          f"{self.restarts} at {self.cur_procs} "
                          f"process(es) (resume from verified "
                          f"step {step})")
            ok, rcs, reason = self._run_cohort(self.restarts)
            self.telemetry.event("supervisor_attempt",
                                 attempt=self.restarts, ok=ok,
                                 num_procs=self.cur_procs,
                                 reason=reason, exit_codes=rcs)
            if ok:
                self._log(f"supervisor: run completed after "
                          f"{self.restarts} restart(s)")
                self.alerts.check_now()
                if self._watchdog_hb is not None:
                    self._watchdog_hb.idle()  # no deadline after done
                return 0
            self.restarts += 1
            self.telemetry.count("supervisor/attempts_failed")
            self.telemetry.gauge("supervisor/restarts", self.restarts,
                                 emit=False)
            self.telemetry.gauge("supervisor/restarts_remaining",
                                 self.max_restarts - self.restarts,
                                 emit=False)
            # elastic re-form (ISSUE 13): decide the NEXT cohort size
            # before the budget check so the resize escalates in the
            # same alert sweep as the restart itself
            new_size = self._next_cohort_size(reason)
            if new_size != self.cur_procs:
                self.resizes.append((self.cur_procs, new_size))
                self.telemetry.count("resilience/resize")
                self.telemetry.gauge("supervisor/cohort_resized",
                                     len(self.resizes), emit=False)
                self.telemetry.gauge("supervisor/cohort_size",
                                     new_size, emit=False)
                self.telemetry.event("cohort_resized",
                                     from_procs=self.cur_procs,
                                     to_procs=new_size, reason=reason)
                self._log(f"supervisor: re-forming cohort at "
                          f"{new_size} process(es) (was "
                          f"{self.cur_procs}; {reason})")
                self.cur_procs = new_size
            else:
                self.full_relaunches += 1
                self.telemetry.gauge("supervisor/full_relaunches",
                                     self.full_relaunches, emit=False)
            self.alerts.check_now()
            if self.restarts > self.max_restarts:
                self.telemetry.gauge("supervisor/budget_exhausted", 1,
                                     emit=False)
                self.alerts.check_now()  # the page-severity alert
                self._log(f"supervisor: restart budget exhausted "
                          f"({self.max_restarts}); exit codes {rcs}")
                raise RestartBudgetExceeded(
                    f"training cohort died {self.restarts} times "
                    f"(budget {self.max_restarts}); last exit codes "
                    f"{rcs}")
            delay = self.backoff.delay_s(self.restarts)
            self._log(f"supervisor: cohort died (exit codes {rcs}); "
                      f"relaunching in {delay:.2f}s "
                      f"(restart {self.restarts}/{self.max_restarts})")
            if self._watchdog_hb is not None:
                # the backoff sleep is a DELIBERATE wait (up to the
                # policy's max delay), not silence: exempt it from the
                # deadline; the loop-top beat re-arms on relaunch
                self._watchdog_hb.idle()
            self._sleep(delay)


def build_cli_spawn(child_cmd: Sequence[str], *, num_procs: int = 1,
                    out_dir: Optional[str] = None,
                    cpu_devices: Optional[int] = None,
                    metrics_ports: Optional[Sequence[int]] = None,
                    log: Optional[Callable[[str], None]] = None
                    ) -> Callable[[int, int, int, int],
                                  "subprocess.Popen"]:
    """Spawn function over a CLI child command (tools/train_supervisor
    and tools/chaos use this). Multi-process cohorts get the explicit
    `--dist_*` coordination flags appended per member (fresh port per
    attempt, sized to THIS attempt's cohort — a re-formed N−1 cohort
    gets N−1 in its flags, so the children rebuild mesh + infeed split
    from the surviving process set; a cohort re-formed at ONE process
    gets no flags at all and runs plain single-process);
    `cpu_devices` pins the CPU harness's virtual device count via
    `parallel/compat.cpu_worker_env`, BEFORE the child's jax import.
    `metrics_ports` gives member i a fixed `--metrics_port` (the fleet
    collector's scrape set must be knowable BEFORE launch, so members
    can't pick ephemeral ports). Child output streams to
    `attempt<k>.proc<i>.log` under `out_dir` (or inherits the
    supervisor's stdio)."""
    child_cmd = list(child_cmd)

    def spawn(attempt: int, proc_id: int, port: int,
              cohort_size: Optional[int] = None) -> "subprocess.Popen":
        n = num_procs if cohort_size is None else cohort_size
        cmd = list(child_cmd)
        if n > 1:
            cmd += ["--dist_coordinator", f"127.0.0.1:{port}",
                    "--dist_num_processes", str(n),
                    "--dist_process_id", str(proc_id)]
        if metrics_ports is not None and proc_id < len(metrics_ports):
            cmd += ["--metrics_port", str(metrics_ports[proc_id])]
        if cpu_devices is not None:
            from code2vec_tpu.parallel.compat import cpu_worker_env
            env = cpu_worker_env(cpu_devices)
        else:
            env = dict(os.environ)
        stdout = None
        if out_dir is not None:
            os.makedirs(out_dir, exist_ok=True)
            log_path = os.path.join(
                out_dir, f"attempt{attempt}.proc{proc_id}.log")
            stdout = open(log_path, "w", encoding="utf-8")
        if log is not None:
            log(f"supervisor: spawn attempt={attempt} proc={proc_id}: "
                f"{' '.join(cmd)}")
        try:
            return subprocess.Popen(cmd, env=env, stdout=stdout,
                                    stderr=subprocess.STDOUT
                                    if stdout is not None else None)
        finally:
            if stdout is not None:
                stdout.close()  # the child holds its own dup
    return spawn
