"""Crash-recovery supervisor (ISSUE 10 tentpole, layer 3): close the
detect -> decide -> recover loop.

The stack could already DETECT trouble (PR 6 stall watchdog, PR 7 alert
engine) and SURVIVE it on disk (PR 5 commit-or-vanish checkpoints) —
but a killed worker ended the run and waited for a human. `Supervisor`
makes restart the ordinary path:

  - it spawns the training run as child process(es) — one, or an
    N-process Gloo cohort with a fresh coordinator port per attempt —
    and watches their exit codes;
  - BEFORE every (re)launch it verifies the checkpoint directory
    (`checkpoint.verify_and_resolve`): a corrupt latest step is
    quarantined and the child auto-resumes from the last VERIFIED
    committed step, never from rotten bytes;
  - any nonzero/signal exit fails the whole attempt: the remaining
    cohort members get a grace window to die on their own (the Gloo
    coordination-service heartbeat tolerance evicts the dead peer's
    partners), then are SIGKILLed, and the cohort relaunches
    COHERENTLY — never a half-old half-new mix of processes;
  - a child that simply finishes (all exit 0) ends the supervised run;
  - the restart budget is bounded, the pacing is the shared
    `resilience/retry` backoff math, and every decision escalates
    through the EXISTING alert engine (`supervisor/*` gauges drive
    edge-triggered `alert` events: restarted -> ticket, quarantined
    checkpoint -> ticket, budget exhausted -> page).

Frequent checkpointing (Check-N-Run) only pays off when restart is
automatic and verified; this is the piece that makes it so. The spawn
function is injectable, so the policy logic tests without real
training runs; `tools/train_supervisor.py` is the CLI entry and
`tools/chaos.py` drives the acceptance scenarios (SIGKILL parity,
corrupt-checkpoint fallback) end to end.
"""

from __future__ import annotations

import os
import subprocess
import time
from typing import Callable, List, Optional, Sequence, Tuple

from code2vec_tpu.resilience import retry as retry_mod
from code2vec_tpu.training import checkpoint as ckpt

__all__ = ["RestartBudgetExceeded", "Supervisor", "build_cli_spawn",
           "supervisor_alert_rules"]


class RestartBudgetExceeded(RuntimeError):
    """The cohort kept dying past `max_restarts` relaunches — a human's
    problem now; the page-severity alert already fired."""


def supervisor_alert_rules():
    """Escalation through the EXISTING alert engine (ISSUE 7): the
    supervisor publishes gauges, these rules turn them into
    edge-triggered `alert` events + stdout lines."""
    from code2vec_tpu.obs.alerts import AlertRule
    return [
        AlertRule("train_process_restarted",
                  metric="supervisor/restarts", op=">=", value=1,
                  severity="ticket"),
        AlertRule("checkpoint_quarantined",
                  metric="resilience/ckpt_quarantined", op=">=",
                  value=1, severity="ticket"),
        # an explicit 0/1 gauge, not `restarts_remaining <= 0`: a
        # max_restarts=0 supervisor would otherwise page on a run that
        # SUCCEEDED without ever restarting
        AlertRule("restart_budget_exhausted",
                  metric="supervisor/budget_exhausted", op=">=",
                  value=1, severity="page"),
    ]


class Supervisor:
    """Restart supervisor over an injectable spawn function.

    `spawn_fn(attempt, proc_id, port) -> subprocess.Popen` launches one
    cohort member (`port` is a fresh coordinator port per attempt, 0
    for single-process runs). The supervisor owns reaping: no child
    outlives a failed attempt (the tests/conftest.py leak-guard
    discipline).
    """

    def __init__(self, spawn_fn: Callable[[int, int, int],
                                          "subprocess.Popen"], *,
                 num_procs: int = 1, max_restarts: int = 3,
                 ckpt_dir: Optional[str] = None,
                 telemetry=None,
                 log: Optional[Callable[[str], None]] = None,
                 poll_s: float = 0.2, peer_grace_s: float = 15.0,
                 attempt_timeout_s: Optional[float] = None,
                 backoff: Optional[retry_mod.RetryPolicy] = None,
                 sleep: Callable[[float], None] = time.sleep):
        assert num_procs >= 1 and max_restarts >= 0
        self._spawn_fn = spawn_fn
        self.num_procs = num_procs
        self.max_restarts = max_restarts
        self.ckpt_dir = ckpt_dir
        self._log = log or (lambda m: print(m, flush=True))
        self.poll_s = poll_s
        self.peer_grace_s = peer_grace_s
        self.attempt_timeout_s = attempt_timeout_s
        self._sleep = sleep
        # ONE backoff math for the whole repo: the supervisor's restart
        # pacing is the retry policy's delay curve, not a second
        # implementation
        self.backoff = backoff if backoff is not None else \
            retry_mod.RetryPolicy("supervisor-restart", max_attempts=1,
                                  base_delay_s=1.0, max_delay_s=60.0)
        if telemetry is None:
            from code2vec_tpu.obs import Telemetry
            telemetry = Telemetry.memory("supervisor")
        self.telemetry = telemetry
        retry_mod.set_telemetry(telemetry)
        from code2vec_tpu.obs.alerts import AlertEngine
        self.alerts = AlertEngine.create(
            telemetry, mode="warn", rules=supervisor_alert_rules(),
            log=self._log)
        self.restarts = 0
        self.quarantined: List[str] = []
        self.resumed_from_step: Optional[int] = None

    # ---- checkpoint verification (runs before EVERY launch) ----
    def verify_checkpoint(self) -> Optional[int]:
        """Verify + quarantine so the child only ever resumes from a
        VERIFIED committed step; returns that step (None = fresh
        start). Quarantines escalate through the alert engine."""
        if not self.ckpt_dir or not os.path.isdir(self.ckpt_dir):
            return None
        good, quarantined = ckpt.verify_and_resolve(
            self.ckpt_dir, log=self._log)
        if quarantined:
            self.quarantined.extend(quarantined)
            self.telemetry.gauge("resilience/ckpt_quarantined",
                                 len(self.quarantined), emit=False)
            self.telemetry.event(
                "ckpt_quarantine", dirs=quarantined,
                fallback_step=good)
            self.alerts.check_now()
        return good

    # ---- one attempt ----
    def _kill_all(self, procs: Sequence["subprocess.Popen"]) -> None:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for p in procs:
            if p.poll() is None:
                p.wait()

    def _reap_with_grace(self, procs: Sequence["subprocess.Popen"]
                         ) -> None:
        """A peer died: give the rest `peer_grace_s` to notice (the
        coordination-service heartbeat eviction takes them down on
        their own), then SIGKILL the stragglers — the cohort always
        relaunches whole."""
        deadline = time.monotonic() + self.peer_grace_s
        while time.monotonic() < deadline \
                and any(p.poll() is None for p in procs):
            self._sleep(self.poll_s)
        self._kill_all(procs)

    def _run_cohort(self, attempt: int) -> Tuple[bool, List[int]]:
        from code2vec_tpu.parallel.compat import free_port
        port = free_port() if self.num_procs > 1 else 0
        procs = [self._spawn_fn(attempt, i, port)
                 for i in range(self.num_procs)]
        deadline = (time.monotonic() + self.attempt_timeout_s
                    if self.attempt_timeout_s else None)
        try:
            while True:
                rcs = [p.poll() for p in procs]
                if all(rc is not None for rc in rcs):
                    return all(rc == 0 for rc in rcs), rcs
                if any(rc is not None and rc != 0 for rc in rcs):
                    # dead peer detected: coherent cohort teardown
                    self._reap_with_grace(procs)
                    return False, [p.poll() for p in procs]
                if deadline is not None and time.monotonic() > deadline:
                    self._log(f"supervisor: attempt {attempt} exceeded "
                              f"{self.attempt_timeout_s:.0f}s — "
                              "killing cohort")
                    self._kill_all(procs)
                    return False, [p.poll() for p in procs]
                self._sleep(self.poll_s)
        finally:
            self._kill_all(procs)  # no orphan survives any exit path

    # ---- the supervised run ----
    def run(self) -> int:
        self.telemetry.gauge("supervisor/restarts", 0, emit=False)
        self.telemetry.gauge("supervisor/restarts_remaining",
                             self.max_restarts, emit=False)
        while True:
            step = self.verify_checkpoint()
            if self.restarts > 0 or step is not None:
                self.resumed_from_step = step
            self.telemetry.event(
                "supervisor_launch", attempt=self.restarts,
                num_procs=self.num_procs,
                resume_step=step if step is not None else -1)
            if step is not None:
                self._log(f"supervisor: launching attempt "
                          f"{self.restarts} (resume from verified "
                          f"step {step})")
            ok, rcs = self._run_cohort(self.restarts)
            self.telemetry.event("supervisor_attempt",
                                 attempt=self.restarts, ok=ok,
                                 exit_codes=rcs)
            if ok:
                self._log(f"supervisor: run completed after "
                          f"{self.restarts} restart(s)")
                self.alerts.check_now()
                return 0
            self.restarts += 1
            self.telemetry.count("supervisor/attempts_failed")
            self.telemetry.gauge("supervisor/restarts", self.restarts,
                                 emit=False)
            self.telemetry.gauge("supervisor/restarts_remaining",
                                 self.max_restarts - self.restarts,
                                 emit=False)
            self.alerts.check_now()
            if self.restarts > self.max_restarts:
                self.telemetry.gauge("supervisor/budget_exhausted", 1,
                                     emit=False)
                self.alerts.check_now()  # the page-severity alert
                self._log(f"supervisor: restart budget exhausted "
                          f"({self.max_restarts}); exit codes {rcs}")
                raise RestartBudgetExceeded(
                    f"training cohort died {self.restarts} times "
                    f"(budget {self.max_restarts}); last exit codes "
                    f"{rcs}")
            delay = self.backoff.delay_s(self.restarts)
            self._log(f"supervisor: cohort died (exit codes {rcs}); "
                      f"relaunching in {delay:.2f}s "
                      f"(restart {self.restarts}/{self.max_restarts})")
            self._sleep(delay)


def build_cli_spawn(child_cmd: Sequence[str], *, num_procs: int = 1,
                    out_dir: Optional[str] = None,
                    cpu_devices: Optional[int] = None,
                    log: Optional[Callable[[str], None]] = None
                    ) -> Callable[[int, int, int], "subprocess.Popen"]:
    """Spawn function over a CLI child command (tools/train_supervisor
    and tools/chaos use this). Multi-process cohorts get the explicit
    `--dist_*` coordination flags appended per member (fresh port per
    attempt); `cpu_devices` pins the CPU harness's virtual device count
    via `parallel/compat.cpu_worker_env`, BEFORE the child's jax
    import. Child output streams to `attempt<k>.proc<i>.log` under
    `out_dir` (or inherits the supervisor's stdio)."""
    child_cmd = list(child_cmd)

    def spawn(attempt: int, proc_id: int, port: int
              ) -> "subprocess.Popen":
        cmd = list(child_cmd)
        if num_procs > 1:
            cmd += ["--dist_coordinator", f"127.0.0.1:{port}",
                    "--dist_num_processes", str(num_procs),
                    "--dist_process_id", str(proc_id)]
        if cpu_devices is not None:
            from code2vec_tpu.parallel.compat import cpu_worker_env
            env = cpu_worker_env(cpu_devices)
        else:
            env = dict(os.environ)
        stdout = None
        if out_dir is not None:
            os.makedirs(out_dir, exist_ok=True)
            log_path = os.path.join(
                out_dir, f"attempt{attempt}.proc{proc_id}.log")
            stdout = open(log_path, "w", encoding="utf-8")
        if log is not None:
            log(f"supervisor: spawn attempt={attempt} proc={proc_id}: "
                f"{' '.join(cmd)}")
        try:
            return subprocess.Popen(cmd, env=env, stdout=stdout,
                                    stderr=subprocess.STDOUT
                                    if stdout is not None else None)
        finally:
            if stdout is not None:
                stdout.close()  # the child holds its own dup
    return spawn
