"""Sparse-row (lazy) Adam for embedding tables.

SURVEY.md §8.4 item 2: dense embedding gradients dominate java-large step
time — Adam over the full token/path/target tables reads+writes ~9 GB of
HBM per step (measured 45 ms/step on one v5e chip). Only a few hundred
thousand rows are touched per batch, so moments and parameters are
updated for TOUCHED ROWS ONLY:

  scatter-ADD cotangents into a dense [V, E] gradient-sum buffer (the
  VJP of a gather) -> gather the summed gradients, m/v, and params at
  the touched ids -> per-row Adam -> scatter-SET rows back (duplicates
  of a row write identical values, so the sets are idempotent).

Everything is static-shaped (N = number of gathered rows per step), so
the step jits once and XLA maps the gather/scatter onto the TPU.

Semantics note (documented deviation): TF1's AdamOptimizer._apply_sparse
decays m/v over ALL rows each step (which is exactly the dense traffic we
must avoid); this implementation is the LazyAdam variant — untouched rows
keep stale moments. LazyAdam is the standard large-embedding practice and
matches reference quality in our integration tests; set
Config.SPARSE_EMBEDDING_UPDATES=False for strict dense-Adam semantics.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class RowAdamState(NamedTuple):
    m: jax.Array  # [V, E] first moment (same shape as the table)
    v: jax.Array  # [V, E] second moment


def init_row_adam(table: jax.Array) -> RowAdamState:
    return RowAdamState(m=jnp.zeros_like(table), v=jnp.zeros_like(table))


def row_adam_update(table: jax.Array, state: RowAdamState,
                    ids: jax.Array, grads: jax.Array, *, count: jax.Array,
                    lr: float, b1: float = 0.9, b2: float = 0.999,
                    eps: float = 1e-8, vocab_size: int | None = None):
    """Apply one lazy-Adam step to the rows named by `ids`.

    Duplicate handling without any sort: scatter-ADD the cotangents into
    one dense [V, E] gradient-sum buffer (exactly what the VJP of a
    gather would emit), gather the per-row sums back at `ids`, compute
    the Adam row update, and scatter-SET results — duplicates of a row
    all write identical values, so the sets are idempotent. The dense
    buffer costs one zeros+scatter pass (~table-sized write); the win is
    skipping the two full m/v read-modify-write passes of dense Adam.

    `count` is the (already incremented) global step, shared with the
    dense-parameter optimizer so bias correction matches.
    Returns (new_table, new_state).
    """
    del vocab_size  # all ids are in-range here; kept for API stability
    g_rows = grads.astype(table.dtype)
    g_sum_dense = jnp.zeros_like(table).at[ids].add(g_rows)  # [V, E]
    g = jnp.take(g_sum_dense, ids, axis=0)                   # [N, E]

    m_rows = jnp.take(state.m, ids, axis=0)
    v_rows = jnp.take(state.v, ids, axis=0)
    p_rows = jnp.take(table, ids, axis=0)

    m_new = b1 * m_rows + (1.0 - b1) * g
    v_new = b2 * v_rows + (1.0 - b2) * jnp.square(g)
    c = count.astype(jnp.float32)
    lr_t = lr * jnp.sqrt(1.0 - b2 ** c) / (1.0 - b1 ** c)
    p_new = p_rows - lr_t * m_new / (jnp.sqrt(v_new) + eps)

    table = table.at[ids].set(p_new)
    m = state.m.at[ids].set(m_new)
    v = state.v.at[ids].set(v_new)
    return table, RowAdamState(m=m, v=v)
