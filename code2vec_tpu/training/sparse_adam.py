"""Sparse-row (lazy) Adam state + the dense-carrier oracle update.

SURVEY.md §8.4 item 2: table traffic dominates the java-large step, and
a batch touches far fewer than V unique rows — BENCH_r05 puts the
shipped dense path at 6.66M pc/s/chip against an 8.48M fwd/bwd floor
(optimizer efficiency 0.786, HBM at 15.7% of the 637 GB/s ceiling), so
moments and parameters are updated for TOUCHED ROWS ONLY. (The "45 ms
dense / ~9 GB moment traffic" figures previously quoted here were
pre-round-3 Adam-table measurements; adafactor tables + bf16 storage
retired them — BENCH_r*.json is the trajectory of record.)

The production path is training/sparse_update.py (round 13): dedup +
segment-sum into a COMPACT [U, E] gradient, then a live-rows-only
row-Adam / requantize-aware apply — fused into one Pallas pass over the
live rows on TPU (`--sparse_update_pallas`), XLA reference elsewhere.
`row_adam_update` below is the ORIGINAL dense-carrier form (scatter-ADD
cotangents into a dense [V, E] buffer — the VJP of a gather — gather
back at the touched ids, per-row Adam, idempotent scatter-SET): it
survives as the bit-parity oracle the compact path is property-tested
against (tests/test_sparse_update.py) and for A/B attribution of the
carrier's cost.

Semantics note (documented deviation): TF1's AdamOptimizer._apply_sparse
decays m/v over ALL rows each step (which is exactly the dense traffic we
must avoid); this implementation is the LazyAdam variant — untouched rows
keep stale moments. LazyAdam is the standard large-embedding practice and
matches reference quality in our integration tests; set
Config.SPARSE_EMBEDDING_UPDATES=False for strict dense-Adam semantics.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class RowAdamState(NamedTuple):
    m: jax.Array  # [V, E] first moment (same rows as the table)
    v: jax.Array  # [V, E] second moment


def init_row_adam(table) -> RowAdamState:
    """Zero moments for a table — f32 regardless of storage dtype
    (bf16 moments would lose the low accumulation bits Adam needs;
    int8 {q, s} tables get moments shaped like q). Moment rows are
    only ever read/written at touched ids, so the f32 cost is HBM
    capacity, not step traffic."""
    shape = table["q"].shape if isinstance(table, dict) else table.shape
    return RowAdamState(m=jnp.zeros(shape, jnp.float32),
                        v=jnp.zeros(shape, jnp.float32))


def row_adam_update(table: jax.Array, state: RowAdamState,
                    ids: jax.Array, grads: jax.Array, *, count: jax.Array,
                    lr: float, b1: float = 0.9, b2: float = 0.999,
                    eps: float = 1e-8, vocab_size: int | None = None):
    """Apply one lazy-Adam step to the rows named by `ids`.

    Duplicate handling without any sort: scatter-ADD the cotangents into
    one dense [V, E] gradient-sum buffer (exactly what the VJP of a
    gather would emit), gather the per-row sums back at `ids`, compute
    the Adam row update, and scatter-SET results — duplicates of a row
    all write identical values, so the sets are idempotent. The dense
    buffer costs one zeros+scatter pass (~table-sized write); the win is
    skipping the two full m/v read-modify-write passes of dense Adam.

    `count` is the (already incremented) global step, shared with the
    dense-parameter optimizer so bias correction matches.
    Returns (new_table, new_state).
    """
    del vocab_size  # all ids are in-range here; kept for API stability
    g_rows = grads.astype(table.dtype)
    g_sum_dense = jnp.zeros_like(table).at[ids].add(g_rows)  # [V, E]
    g = jnp.take(g_sum_dense, ids, axis=0)                   # [N, E]

    m_rows = jnp.take(state.m, ids, axis=0)
    v_rows = jnp.take(state.v, ids, axis=0)
    p_rows = jnp.take(table, ids, axis=0)

    m_new = b1 * m_rows + (1.0 - b1) * g
    v_new = b2 * v_rows + (1.0 - b2) * jnp.square(g)
    c = count.astype(jnp.float32)
    lr_t = lr * jnp.sqrt(1.0 - b2 ** c) / (1.0 - b1 ** c)
    p_new = p_rows - lr_t * m_new / (jnp.sqrt(v_new) + eps)

    table = table.at[ids].set(p_new)
    m = state.m.at[ids].set(m_new)
    v = state.v.at[ids].set(v_new)
    return table, RowAdamState(m=m, v=v)
