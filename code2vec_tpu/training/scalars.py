"""Optional TensorBoard scalar streaming (--tensorboard <dir>).

SURVEY.md §6 (metrics row): the reference logs loss/throughput lines to
Python logging only; TensorBoard scalars are the optional TPU-build
addition. Host-side and dependency-light: TensorFlow is imported
lazily, only when a directory is given — and when it is missing
entirely the writer degrades to a warn-once no-op instead of raising,
so a TF-free training image keeps the same command line (the JSONL
telemetry under --telemetry_dir stays the durable record).
"""

from __future__ import annotations

import logging
from typing import Mapping, Optional

# warn-once latch for the missing-TF fallback (module-level: one
# warning per process, not one per writer)
_WARNED_MISSING_TF = False


class ScalarWriter:
    """No-op when constructed with dir=None, so call sites stay
    unconditional. Writes one scalar per (tag, step) otherwise."""

    def __init__(self, log_dir: Optional[str]):
        self._writer = None
        if log_dir:
            try:
                import tensorflow as tf  # lazy: only with --tensorboard
            except Exception:  # ImportError, or a broken TF install
                global _WARNED_MISSING_TF
                if not _WARNED_MISSING_TF:
                    _WARNED_MISSING_TF = True
                    logging.getLogger("code2vec-tpu").warning(
                        "--tensorboard %s requested but TensorFlow is "
                        "not importable; scalar streaming disabled "
                        "(install tensorflow, or use --telemetry_dir "
                        "for the TF-free JSONL record)", log_dir)
                return
            self._writer = tf.summary.create_file_writer(log_dir)
            self._tf = tf

    def write(self, step: int, scalars: Mapping[str, float]) -> None:
        if self._writer is None:
            return
        with self._writer.as_default(step=step):
            for tag, value in scalars.items():
                self._tf.summary.scalar(tag, float(value))
        self._writer.flush()

    def close(self) -> None:
        if self._writer is not None:
            self._writer.close()
