"""Optional TensorBoard scalar streaming (--tensorboard <dir>).

SURVEY.md §6 (metrics row): the reference logs loss/throughput lines to
Python logging only; TensorBoard scalars are the optional TPU-build
addition. Host-side and dependency-light: TensorFlow (installed for the
baseline tooling) is imported lazily, only when a directory is given —
the training path never touches TF otherwise.
"""

from __future__ import annotations

from typing import Mapping, Optional


class ScalarWriter:
    """No-op when constructed with dir=None, so call sites stay
    unconditional. Writes one scalar per (tag, step) otherwise."""

    def __init__(self, log_dir: Optional[str]):
        self._writer = None
        if log_dir:
            import tensorflow as tf  # lazy: only with --tensorboard
            self._writer = tf.summary.create_file_writer(log_dir)
            self._tf = tf

    def write(self, step: int, scalars: Mapping[str, float]) -> None:
        if self._writer is None:
            return
        with self._writer.as_default(step=step):
            for tag, value in scalars.items():
                self._tf.summary.scalar(tag, float(value))
        self._writer.flush()

    def close(self) -> None:
        if self._writer is not None:
            self._writer.close()
