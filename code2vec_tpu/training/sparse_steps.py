"""Train step with sparse-row embedding updates (the TPU fast path).

Same math as training/steps.make_train_step, restructured so the three
vocab tables are differentiated at the GATHERED-ROW level: the gathers
happen outside the differentiated function, autodiff produces cotangents
for the gathered [rows, E] arrays directly (no dense-table scatter in the
backward pass), and sparse_adam applies touched-rows-only Adam. Dense
params (TRANSFORM / ATTENTION — and TARGET_WORDS_VOCAB when running full
softmax, whose logits touch every row anyway) keep ordinary optax Adam.

Step time on java-large (1 chip, batch 1024): 45 ms dense -> see bench.py
for the sparse number; the dense-Adam moment traffic (~9 GB/step) is
replaced by ~1 GB of gather/scatter on touched rows.
"""

from __future__ import annotations

from typing import Callable, Dict

import jax
import jax.numpy as jnp
import optax

from code2vec_tpu.models.encoder import ModelDims
from code2vec_tpu.ops.attention import attention_pool
from code2vec_tpu.ops.sampled_softmax import (
    _log_expected_count, log_uniform_sample)
from code2vec_tpu.training.sparse_adam import (init_row_adam,
                                               row_adam_update)


def init_sparse_opt_state(params: Dict[str, jax.Array],
                          dense_opt: optax.GradientTransformation,
                          use_sampled_softmax: bool):
    dense_keys = ["transform", "attention"]
    if not use_sampled_softmax:
        dense_keys.append("target_emb")
    dense_params = {k: params[k] for k in dense_keys}
    rows = {"token_emb": init_row_adam(params["token_emb"]),
            "path_emb": init_row_adam(params["path_emb"])}
    if use_sampled_softmax:
        rows["target_emb"] = init_row_adam(params["target_emb"])
    return {"dense": dense_opt.init(dense_params), "rows": rows,
            "count": jnp.zeros((), jnp.int32)}


def make_sparse_train_step(dims: ModelDims, *, learning_rate: float,
                           dense_optimizer: optax.GradientTransformation
                           | None = None,
                           use_sampled_softmax: bool = False,
                           num_sampled: int = 4096,
                           compute_dtype=jnp.float32,
                           use_pallas: bool = False,
                           b1: float = 0.9, b2: float = 0.999,
                           eps: float = 1e-8) -> Callable:
    """Returns jitted `step(params, opt_state, batch, rng) ->
    (params, opt_state, loss)`; opt_state from init_sparse_opt_state.

    `dense_optimizer` must be the SAME transformation passed to
    init_sparse_opt_state (single source of truth for the dense-param
    hyperparameters); `learning_rate`/`b1`/`b2`/`eps` govern only the
    row-sparse table updates and should match it."""
    dense_opt = dense_optimizer if dense_optimizer is not None else \
        optax.adam(learning_rate, b1=b1, b2=b2, eps=eps)
    S = min(num_sampled, dims.target_vocab_size)
    V = dims.target_vocab_size

    def step_impl(params, opt_state, batch, rng):
        labels, src, pth, dst, mask, weights = batch
        B, C = src.shape
        drop_rng, sample_rng = jax.random.split(rng)

        # ---- non-differentiated preliminaries ----
        if use_sampled_softmax:
            sampled = log_uniform_sample(sample_rng, S, V)          # [S]
            true_corr = _log_expected_count(labels, S, V)           # [B]
            samp_corr = _log_expected_count(sampled, S, V)          # [S]
            accidental = sampled[None, :] == labels[:, None]        # [B,S]

        # ---- gathers OUTSIDE the differentiated function ----
        src_e = jnp.take(params["token_emb"], src, axis=0)
        dst_e = jnp.take(params["token_emb"], dst, axis=0)
        pth_e = jnp.take(params["path_emb"], pth, axis=0)
        gathered = {"src_e": src_e, "pth_e": pth_e, "dst_e": dst_e}
        if use_sampled_softmax:
            gathered["true_w"] = jnp.take(params["target_emb"], labels,
                                          axis=0)
            gathered["samp_w"] = jnp.take(params["target_emb"], sampled,
                                          axis=0)

        dense_keys = ["transform", "attention"]
        if not use_sampled_softmax:
            dense_keys.append("target_emb")
        dense = {k: params[k] for k in dense_keys}

        def loss_fn(dense, gathered):
            contexts = jnp.concatenate(
                [gathered["src_e"], gathered["pth_e"], gathered["dst_e"]],
                axis=-1).astype(compute_dtype)
            if dims.dropout_keep_rate < 1.0:
                keep = jax.random.bernoulli(
                    drop_rng, dims.dropout_keep_rate, contexts.shape)
                contexts = jnp.where(keep,
                                     contexts / dims.dropout_keep_rate,
                                     0.0)
            code, _ = attention_pool(contexts, dense["transform"],
                                     dense["attention"], mask)
            if use_sampled_softmax:
                true_w = gathered["true_w"].astype(code.dtype)
                samp_w = gathered["samp_w"].astype(code.dtype)
                true_logits = jnp.sum(code * true_w, axis=-1).astype(
                    jnp.float32) - true_corr
                samp_logits = (code @ samp_w.T).astype(
                    jnp.float32) - samp_corr[None, :]
                samp_logits = jnp.where(accidental, -1e9, samp_logits)
                logits = jnp.concatenate(
                    [true_logits[:, None], samp_logits], axis=1)
                per_ex = -jax.nn.log_softmax(logits, axis=-1)[:, 0]
            else:
                table = dense["target_emb"].astype(code.dtype)
                logits = (code @ table.T).astype(jnp.float32)
                col = jnp.arange(table.shape[0])
                logits = jnp.where(col[None, :] < V, logits, -1e9)
                per_ex = optax.softmax_cross_entropy_with_integer_labels(
                    logits, labels)
            denom = jnp.maximum(jnp.sum(weights), 1.0)
            return jnp.sum(per_ex * weights) / denom

        loss, (g_dense, g_rows) = jax.value_and_grad(
            loss_fn, argnums=(0, 1))(dense, gathered)

        count = opt_state["count"] + 1

        # ---- dense params: ordinary Adam ----
        updates, dense_state = dense_opt.update(
            g_dense, opt_state["dense"], dense)
        dense = optax.apply_updates(dense, updates)

        # ---- tables: touched-rows-only Adam ----
        E = dims.embeddings_size
        tok_ids = jnp.concatenate([src.reshape(-1), dst.reshape(-1)])
        tok_g = jnp.concatenate([g_rows["src_e"].reshape(-1, E),
                                 g_rows["dst_e"].reshape(-1, E)])
        new_tok, tok_state = row_adam_update(
            params["token_emb"], opt_state["rows"]["token_emb"], tok_ids,
            tok_g, count=count, lr=learning_rate, b1=b1, b2=b2, eps=eps,
            vocab_size=dims.padded(dims.token_vocab_size))
        new_pth, pth_state = row_adam_update(
            params["path_emb"], opt_state["rows"]["path_emb"],
            pth.reshape(-1), g_rows["pth_e"].reshape(-1, E), count=count,
            lr=learning_rate, b1=b1, b2=b2, eps=eps,
            vocab_size=dims.padded(dims.path_vocab_size))

        new_params = dict(params)
        new_params["token_emb"] = new_tok
        new_params["path_emb"] = new_pth
        new_params["transform"] = dense["transform"]
        new_params["attention"] = dense["attention"]
        new_rows = {"token_emb": tok_state, "path_emb": pth_state}
        if use_sampled_softmax:
            D = dims.code_vector_size
            tgt_ids = jnp.concatenate([labels, sampled])
            tgt_g = jnp.concatenate([g_rows["true_w"].reshape(-1, D),
                                     g_rows["samp_w"].reshape(-1, D)])
            new_tgt, tgt_state = row_adam_update(
                params["target_emb"], opt_state["rows"]["target_emb"],
                tgt_ids, tgt_g, count=count, lr=learning_rate, b1=b1,
                b2=b2, eps=eps,
                vocab_size=dims.padded(dims.target_vocab_size))
            new_params["target_emb"] = new_tgt
            new_rows["target_emb"] = tgt_state
        else:
            new_params["target_emb"] = dense["target_emb"]

        new_opt_state = {"dense": dense_state, "rows": new_rows,
                         "count": count}
        return new_params, new_opt_state, loss

    return jax.jit(step_impl, donate_argnums=(0, 1))
