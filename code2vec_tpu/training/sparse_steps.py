"""Train step with sparse-row embedding updates (the TPU fast path).

Same math as training/steps.make_train_step, restructured so the three
vocab tables are differentiated at the GATHERED-ROW level: the gathers
happen outside the differentiated function, autodiff produces cotangents
for the gathered [rows, E] arrays directly (no dense-table scatter in the
backward pass), and the sparse-update facade
(training/sparse_update.py, round 13) dedups + segment-sums those
cotangents into a compact [U, E] gradient and applies touched-rows-only
Adam — no dense [V, E] carrier anywhere, and on int8 {q, s} tables a
requantize-aware row update reusing the ops/pallas_requant dither/absmax
machinery. Dense params (TRANSFORM / ATTENTION — and TARGET_WORDS_VOCAB
when running full softmax, whose logits touch every row anyway) keep
ordinary optax Adam.

Why: BENCH_r05 measures the shipped dense-path step at 6.66M pc/s/chip
against an 8.48M fwd/bwd floor (optimizer efficiency 0.786, HBM at
15.7% of the 637 GB/s ceiling) — the gap IS the dense backward scatter
plus the table-proportional optimizer walk this module avoids. The
round-6 lesson (the fused requantize row-pass turned the int8 +26%
step-time tax into ~0) repeats one level up: `--sparse_update_pallas`
selects the fused Pallas live-row kernel on a single-device TPU and the
XLA segment-sum reference on CPU. Under a mesh (round 14) the SAME
compact path runs inside `shard_map` via
`sparse_update.mesh_sparse_apply` — no dense [V, E] carrier on the
data-parallel path either; bench.py attributes the phase
every round (`sparse_update_*`). The pre-round-6 "45 ms dense" numbers
previously quoted here predate the adafactor default and the bf16
tables — BENCH_r*.json is the trajectory of record.
"""

from __future__ import annotations

from typing import Callable, Dict

import jax
import jax.numpy as jnp
import optax

from code2vec_tpu.models.encoder import ModelDims
from code2vec_tpu.ops.attention import attention_pool
from code2vec_tpu.ops.quant import is_quantized
from code2vec_tpu.ops.sampled_softmax import (
    _log_expected_count, log_uniform_sample)
from code2vec_tpu.training.sparse_adam import init_row_adam
from code2vec_tpu.training.sparse_update import (mesh_sparse_apply,
                                                 sparse_requant_adam,
                                                 sparse_row_adam)


def init_sparse_opt_state(params: Dict[str, jax.Array],
                          dense_opt: optax.GradientTransformation,
                          use_sampled_softmax: bool):
    dense_keys = ["transform", "attention"]
    if not use_sampled_softmax:
        dense_keys.append("target_emb")
    dense_params = {k: params[k] for k in dense_keys}
    rows = {"token_emb": init_row_adam(params["token_emb"]),
            "path_emb": init_row_adam(params["path_emb"])}
    if use_sampled_softmax:
        rows["target_emb"] = init_row_adam(params["target_emb"])
    return {"dense": dense_opt.init(dense_params), "rows": rows,
            "count": jnp.zeros((), jnp.int32)}


def _gather_rows(table, ids):
    """Row gather in the dtype autodiff differentiates: plain tables
    as-is; int8 {q, s} dequantize AFTER the gather to bf16 (q*s carries
    <= 8 significant bits — same rationale as ops/quant.quantized_take,
    but no straight-through carrier: the rows themselves are the
    differentiated leaves here)."""
    if is_quantized(table):
        rows = (jnp.take(table["q"], ids, axis=0).astype(jnp.float32)
                * jnp.take(table["s"], ids, axis=0))
        return rows.astype(jnp.bfloat16)
    return jnp.take(table, ids, axis=0)


def prepare_step_inputs(params, batch, rng, *, use_sampled_softmax:
                        bool, num_sampled: int, target_vocab: int):
    """The sparse step's non-differentiated preliminaries + gathers —
    extracted from `step_impl` so the phase probes
    (training/phase_probes.py, ISSUE 15) measure EXACTLY the gathers
    the step performs, never a drifted copy. Returns
    `(dense, gathered, ctx)`: the dense-param dict, the gathered-row
    dict autodiff differentiates, and a ctx dict carrying everything
    `make_gathered_loss` and the apply section need (drop_rng, qrngs,
    sampled ids + sampled-softmax corrections)."""
    labels, src, pth, dst, mask, weights = batch
    qkeys = sorted(k for k in ("token_emb", "path_emb")
                   if is_quantized(params[k]))
    drop_rng, sample_rng, *qrngs = jax.random.split(
        rng, 2 + len(qkeys))
    ctx = {"drop_rng": drop_rng, "qrngs": dict(zip(qkeys, qrngs)),
           "labels": labels, "mask": mask, "weights": weights}

    if use_sampled_softmax:
        S, V = num_sampled, target_vocab
        sampled = log_uniform_sample(sample_rng, S, V)            # [S]
        ctx["sampled"] = sampled
        ctx["true_corr"] = _log_expected_count(labels, S, V)      # [B]
        ctx["samp_corr"] = _log_expected_count(sampled, S, V)     # [S]
        ctx["accidental"] = sampled[None, :] == labels[:, None]   # [B,S]

    # ---- gathers OUTSIDE the differentiated function ----
    gathered = {"src_e": _gather_rows(params["token_emb"], src),
                "pth_e": _gather_rows(params["path_emb"], pth),
                "dst_e": _gather_rows(params["token_emb"], dst)}
    if use_sampled_softmax:
        gathered["true_w"] = _gather_rows(params["target_emb"], labels)
        gathered["samp_w"] = _gather_rows(params["target_emb"],
                                          ctx["sampled"])

    dense_keys = ["transform", "attention"]
    if not use_sampled_softmax:
        dense_keys.append("target_emb")
    dense = {k: params[k] for k in dense_keys}
    return dense, gathered, ctx


def make_gathered_loss(dims: ModelDims, ctx, *, use_sampled_softmax:
                       bool, compute_dtype):
    """`loss_fn(dense, gathered)` over prepare_step_inputs' outputs —
    the exact function the sparse step differentiates (and the phase
    probes' forward/backward prefixes re-run)."""
    V = dims.target_vocab_size
    mask, weights = ctx["mask"], ctx["weights"]

    def loss_fn(dense, gathered):
        contexts = jnp.concatenate(
            [gathered["src_e"], gathered["pth_e"], gathered["dst_e"]],
            axis=-1).astype(compute_dtype)
        if dims.dropout_keep_rate < 1.0:
            keep = jax.random.bernoulli(
                ctx["drop_rng"], dims.dropout_keep_rate,
                contexts.shape)
            contexts = jnp.where(keep,
                                 contexts / dims.dropout_keep_rate,
                                 0.0)
        code, _ = attention_pool(contexts, dense["transform"],
                                 dense["attention"], mask)
        if use_sampled_softmax:
            true_w = gathered["true_w"].astype(code.dtype)
            samp_w = gathered["samp_w"].astype(code.dtype)
            true_logits = jnp.sum(code * true_w, axis=-1).astype(
                jnp.float32) - ctx["true_corr"]
            samp_logits = (code @ samp_w.T).astype(
                jnp.float32) - ctx["samp_corr"][None, :]
            samp_logits = jnp.where(ctx["accidental"], -1e9,
                                    samp_logits)
            logits = jnp.concatenate(
                [true_logits[:, None], samp_logits], axis=1)
            per_ex = -jax.nn.log_softmax(logits, axis=-1)[:, 0]
        else:
            table = dense["target_emb"].astype(code.dtype)
            logits = (code @ table.T).astype(jnp.float32)
            col = jnp.arange(table.shape[0])
            logits = jnp.where(col[None, :] < V, logits, -1e9)
            per_ex = optax.softmax_cross_entropy_with_integer_labels(
                logits, ctx["labels"])
        denom = jnp.maximum(jnp.sum(weights), 1.0)
        return jnp.sum(per_ex * weights) / denom

    return loss_fn


def make_sparse_train_step(dims: ModelDims, *, learning_rate: float,
                           dense_optimizer: optax.GradientTransformation
                           | None = None,
                           use_sampled_softmax: bool = False,
                           num_sampled: int = 4096,
                           compute_dtype=jnp.float32,
                           use_pallas: bool = False,
                           b1: float = 0.9, b2: float = 0.999,
                           eps: float = 1e-8,
                           sparse_update_fused=None,
                           sparse_block_rows: int | None = None,
                           mesh=None) -> Callable:
    """Returns jitted `step(params, opt_state, batch, rng) ->
    (params, opt_state, loss)`; opt_state from init_sparse_opt_state.

    `dense_optimizer` must be the SAME transformation passed to
    init_sparse_opt_state (single source of truth for the dense-param
    hyperparameters); `learning_rate`/`b1`/`b2`/`eps` govern only the
    row-sparse table updates and should match it. `sparse_update_fused`
    selects the live-row implementation on single-device runs AND
    under a mesh (sparse_update facade: None = Pallas kernel on TPU,
    XLA reference on CPU — the mesh path runs it per device inside
    shard_map's manual region, so SPARSE_UPDATE_PALLAS is honored
    everywhere).

    Mesh runs (round 14) use `mesh_sparse_apply`: the compact
    dedup/segment-sum composition MISCOMPILES when the GSPMD
    partitioner shards its inputs (measured, round 13 — wrong segment
    sums), so the whole dedup + apply runs inside `shard_map` where
    the partitioner never sees it, fed by an all-gather of the
    per-occurrence [N]/[N, E] cotangents (NOT a [V, E] carrier).
    Sharded INPUTS into a step built with mesh=None still hit the
    miscompile: callers must pass the mesh they shard with."""
    dense_opt = dense_optimizer if dense_optimizer is not None else \
        optax.adam(learning_rate, b1=b1, b2=b2, eps=eps)
    S = min(num_sampled, dims.target_vocab_size)
    V = dims.target_vocab_size

    def step_impl(params, opt_state, batch, rng):
        labels, src, pth, dst, mask, weights = batch
        # preliminaries + gathers + the differentiated loss live in
        # module-level helpers shared with the ISSUE-15 phase probes
        # (training/phase_probes.py): ONE definition, so a sampled
        # phase-split prefix can never measure drifted math
        dense, gathered, ctx = prepare_step_inputs(
            params, batch, rng, use_sampled_softmax=use_sampled_softmax,
            num_sampled=S, target_vocab=V)
        qrngs = ctx["qrngs"]
        sampled = ctx.get("sampled")
        loss_fn = make_gathered_loss(
            dims, ctx, use_sampled_softmax=use_sampled_softmax,
            compute_dtype=compute_dtype)

        loss, (g_dense, g_rows) = jax.value_and_grad(
            loss_fn, argnums=(0, 1))(dense, gathered)

        count = opt_state["count"] + 1

        # ---- dense params: ordinary Adam ----
        updates, dense_state = dense_opt.update(
            g_dense, opt_state["dense"], dense)
        dense = optax.apply_updates(dense, updates)

        # ---- tables: dedup + segment-sum + live-rows-only update
        # (training/sparse_update.py — no dense [V, E] carrier) ----
        E = dims.embeddings_size

        def apply_rows(key, parts):
            """`parts` = [(ids, grads, sharded), ...] in the SAME order
            the single-device path concatenates them — mesh_sparse_apply
            all-gathers + concatenates in this order, which is what
            makes mesh-vs-single-device parity bit-exact."""
            table, state = params[key], opt_state["rows"][key]
            kw = dict(count=count, lr=learning_rate, b1=b1, b2=b2,
                      eps=eps, fused=sparse_update_fused,
                      block_rows=sparse_block_rows)
            if mesh is not None:
                return mesh_sparse_apply(mesh, table, state, parts,
                                         rng=qrngs.get(key), **kw)
            ids = jnp.concatenate([i.reshape(-1) for i, _g, _s in parts])
            grads = jnp.concatenate(
                [g.reshape(i.reshape(-1).shape[0], -1)
                 for i, g, _s in parts])
            if is_quantized(table):
                return sparse_requant_adam(table, state, ids, grads,
                                           qrngs[key], **kw)
            return sparse_row_adam(table, state, ids, grads, **kw)

        new_tok, tok_state = apply_rows(
            "token_emb", [(src, g_rows["src_e"].reshape(-1, E), True),
                          (dst, g_rows["dst_e"].reshape(-1, E), True)])
        new_pth, pth_state = apply_rows(
            "path_emb", [(pth, g_rows["pth_e"].reshape(-1, E), True)])

        new_params = dict(params)
        new_params["token_emb"] = new_tok
        new_params["path_emb"] = new_pth
        new_params["transform"] = dense["transform"]
        new_params["attention"] = dense["attention"]
        new_rows = {"token_emb": tok_state, "path_emb": pth_state}
        if use_sampled_softmax:
            D = dims.code_vector_size
            # labels ride the batch axes; the shared sample is
            # replicated on every device (same rng) — no gather needed
            new_tgt, tgt_state = apply_rows(
                "target_emb",
                [(labels, g_rows["true_w"].reshape(-1, D), True),
                 (sampled, g_rows["samp_w"].reshape(-1, D), False)])
            new_params["target_emb"] = new_tgt
            new_rows["target_emb"] = tgt_state
        else:
            new_params["target_emb"] = dense["target_emb"]

        new_opt_state = {"dense": dense_state, "rows": new_rows,
                         "count": count}
        return new_params, new_opt_state, loss

    return jax.jit(step_impl, donate_argnums=(0, 1))
