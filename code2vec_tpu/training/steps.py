"""Jit-compiled train / eval / predict steps.

Reference parity target: the three graphs of `tensorflow_model.py`
(SURVEY.md §3: `_build_tf_training_graph`, `_build_tf_testing_graph`,
`_build_tf_predict_graph`) — here they are three pure functions closed
over static ModelDims and jitted once each. Everything inside is
XLA-friendly: static shapes, no data-dependent control flow
(SURVEY.md "XLA semantics").

The same step functions serve single-chip and mesh runs: SPMD sharding is
carried by the INPUTS (params/batch placed with NamedSharding by
parallel/sharding.py), and jit's "computation follows sharding" does the
partitioning — gradient allreduce over 'data' and table-sharded gathers
over 'model' are inserted by XLA, not hand-written collectives.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
import optax

from code2vec_tpu.models.encoder import (ModelDims, full_logits,
                                         get_encode_fn)
from code2vec_tpu.ops.sampled_softmax import sampled_softmax_loss


def _weighted_mean(values: jax.Array, weights: jax.Array) -> jax.Array:
    denom = jnp.maximum(jnp.sum(weights), 1.0)
    return jnp.sum(values * weights) / denom


def make_train_loss_fn(dims: ModelDims, *,
                       use_sampled_softmax: bool = False,
                       num_sampled: int = 4096,
                       compute_dtype=jnp.float32,
                       use_pallas: bool = False,
                       mesh=None) -> Callable:
    """The training-time loss `loss_fn(params, batch, rng)` (dropout on,
    sampled or full softmax). Single source of truth: make_train_step
    differentiates exactly this, and bench.py's fwd+bwd roofline floor
    measures exactly this — the two MUST share it or the floor silently
    measures different math than the step."""
    encode = get_encode_fn(dims, mesh)

    def loss_fn(params, batch, rng):
        labels, src, pth, dst, mask, weights = batch
        drop_rng, sample_rng = jax.random.split(rng)
        code, _attn = encode(
            params, src, pth, dst, mask, dropout_rng=drop_rng,
            dropout_keep_rate=dims.dropout_keep_rate,
            compute_dtype=compute_dtype, use_pallas=use_pallas)
        if use_sampled_softmax:
            loss, _ = sampled_softmax_loss(
                params["target_emb"], code, labels, sample_rng,
                num_sampled, example_weights=weights,
                vocab_size=dims.target_vocab_size)
        else:
            logits = full_logits(params, code, dims.target_vocab_size)
            ce = optax.softmax_cross_entropy_with_integer_labels(
                logits, labels)
            loss = _weighted_mean(ce, weights)
        return loss

    return loss_fn


def make_train_step(dims: ModelDims, optimizer: optax.GradientTransformation,
                    *, use_sampled_softmax: bool = False,
                    num_sampled: int = 4096,
                    compute_dtype=jnp.float32,
                    use_pallas: bool = False,
                    mesh=None,
                    augment_fn: Callable = None,
                    requant_fused: bool = None,
                    sparse_updates: bool = False,
                    learning_rate: float | None = None,
                    sparse_update_fused=None,
                    sparse_block_rows: int | None = None) -> Callable:
    """Returns jitted `step(params, opt_state, batch, rng) ->
    (params, opt_state, loss)` where batch is a 6-tuple of arrays
    (labels [B], src/path/dst ids [B, C], mask [B, C],
    example_weights [B]). `augment_fn(batch, rng) -> batch` is an
    optional train-only input transform (the --adv_rename_prob
    adversarial-training defense, attacks/defense.py); it runs inside
    the jit, before the loss. `requant_fused` selects the int8 tables'
    requantize implementation (ops/quant.requantize: None = fused
    Pallas row-pass on single-device TPU, XLA reference elsewhere —
    incl. under a mesh, where the kernel-in-GSPMD composition is
    unexercised); ignored for float/bf16 tables.

    `sparse_updates=True` (Config.SPARSE_EMBEDDING_UPDATES) dispatches
    to training/sparse_steps.make_sparse_train_step — gathered-row
    differentiation + the dedup/segment-sum/live-row facade
    (training/sparse_update.py), with `sparse_update_fused` /
    `sparse_block_rows` (Config.SPARSE_UPDATE_PALLAS) selecting the
    Pallas live-row kernel vs the XLA reference; opt_state must then
    come from sparse_steps.init_sparse_opt_state and `learning_rate`
    names the tables' row-Adam LR. This keeps ONE step-construction
    entry point for models/jax_model.py and bench.py."""
    if sparse_updates:
        assert augment_fn is None, (
            "sparse_updates has no augmentation hook "
            "(Config.verify gates --adv_rename_prob)")
        assert learning_rate is not None, (
            "sparse_updates needs the tables' learning_rate")
        from code2vec_tpu.training.sparse_steps import \
            make_sparse_train_step
        return make_sparse_train_step(
            dims, learning_rate=learning_rate,
            dense_optimizer=optimizer,
            use_sampled_softmax=use_sampled_softmax,
            num_sampled=num_sampled, compute_dtype=compute_dtype,
            use_pallas=use_pallas,
            sparse_update_fused=sparse_update_fused,
            sparse_block_rows=sparse_block_rows, mesh=mesh)

    loss_fn = make_train_loss_fn(
        dims, use_sampled_softmax=use_sampled_softmax,
        num_sampled=num_sampled, compute_dtype=compute_dtype,
        use_pallas=use_pallas, mesh=mesh)

    if dims.tables_dtype == "int8":
        return _make_quantized_train_step(optimizer, loss_fn, augment_fn,
                                          requant_fused, mesh)

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(params, opt_state, batch, rng):
        if augment_fn is not None:
            rng, aug_rng = jax.random.split(rng)
            batch = augment_fn(batch, aug_rng)
        loss, grads = jax.value_and_grad(loss_fn)(params, batch, rng)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return step


def _make_quantized_train_step(optimizer, loss_fn, augment_fn,
                               requant_fused=None, mesh=None):
    """The int8-tables train step (ops/quant.py; VERDICT r4 item 3).

    Differs from the float step in exactly three ways:
    1. gradients for the quantized tables flow to zero "carriers"
       created inside the step — the straight-through custom_vjp routes
       each table's dense [V, E] cotangent there, and XLA DCEs the
       zeros in the forward, so the carriers cost no HBM traffic beyond
       the scatter-add every table gradient already pays;
    2. the optimizer sees a FLAT gradient view (one [V, E] array per
       table, same keys/structure as the float path), so opt_state
       structure and the multi_transform labels are unchanged;
    3. the apply requantizes: dequant + update + stochastic-rounding
       int8 round-trip per table (ops/quant.requantize — a fused
       Pallas row-pass on TPU, `requant_fused` forces either form),
       instead of optax.apply_updates' dense add.
    """
    from code2vec_tpu.ops.quant import is_quantized, requantize

    if requant_fused is None and mesh is not None:
        # Auto-select stays on the XLA reference under a mesh: the
        # fused kernel inside a GSPMD-partitioned step is unexercised
        # (int8 supports data-parallel meshes only — the tables and
        # their updates replicate, so the reference is exactly the
        # round-5 dryrun-tested path). `--requant_pallas fused` still
        # forces the kernel for anyone measuring that composition.
        requant_fused = False

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(params, opt_state, batch, rng):
        if augment_fn is not None:
            rng, aug_rng = jax.random.split(rng)
            batch = augment_fn(batch, aug_rng)
        qkeys = sorted(k for k in params if is_quantized(params[k]))
        rng, loss_rng, *qrngs = jax.random.split(rng, 2 + len(qkeys))

        def lf(carriers, params):
            virt = dict(params)
            for k, c in carriers.items():
                virt[k] = dict(params[k], g=c)
            return loss_fn(virt, batch, loss_rng)

        carriers = {k: jnp.zeros(params[k]["q"].shape, jnp.bfloat16)
                    for k in qkeys}
        loss, (g_tables, g_rest) = jax.value_and_grad(
            lf, argnums=(0, 1), allow_int=True)(carriers, params)
        flat_grads = {k: (g_tables[k] if k in g_tables else g_rest[k])
                      for k in params}
        # optax's factored_rms requires a params arg even when
        # multiply_by_parameter_scale=False (shape-only use); give the
        # quantized tables flat zero stand-ins matching the grad view —
        # their VALUES are never read, so XLA drops the zeros
        flat_params = {k: (carriers[k] if k in carriers else params[k])
                       for k in params}
        updates, opt_state = optimizer.update(flat_grads, opt_state,
                                              flat_params)
        new_params = {}
        for k, qrng in zip(qkeys, qrngs):
            new_params[k] = requantize(params[k], updates[k], qrng,
                                       fused=requant_fused)
        for k in params:
            if k not in new_params:
                new_params[k] = optax.apply_updates(params[k], updates[k])
        return new_params, opt_state, loss

    return step


def make_eval_step(dims: ModelDims, *, top_k: int = 10,
                   compute_dtype=jnp.float32,
                   use_pallas: bool = False,
                   mesh=None) -> Callable:
    """Returns jitted `step(params, batch) -> (loss_sum, topk_ids,
    topk_probs)`; no dropout (SURVEY.md §4.3)."""
    encode = get_encode_fn(dims, mesh)

    @jax.jit
    def step(params, batch):
        labels, src, pth, dst, mask, weights = batch
        code, _attn = encode(params, src, pth, dst, mask,
                             compute_dtype=compute_dtype,
                             use_pallas=use_pallas)
        logits = full_logits(params, code, dims.target_vocab_size)
        ce = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
        # CE is mathematically >= 0; on TPU the logsumexp-minus-logit
        # difference can come out a hair negative for near-zero-loss
        # examples (different reduction paths), which makes the REPORTED
        # eval loss print as e.g. -0.019 on overfit tiny runs. Clamp —
        # this is an eval-only metric, no gradients flow through it.
        ce = jnp.maximum(ce, 0.0)
        loss_sum = jnp.sum(ce * weights)
        probs = jax.nn.softmax(logits, axis=-1)
        topk_probs, topk_ids = jax.lax.top_k(probs, top_k)
        return loss_sum, topk_ids, topk_probs

    return step


def make_encode_step(dims: ModelDims, *,
                     compute_dtype=jnp.float32,
                     use_pallas: bool = False,
                     mesh=None) -> Callable:
    """Returns jitted `step(params, batch) -> code_vectors [B, D] f32` —
    encoder only, no [B, V] logits matmul. Used by --export_code_vectors
    over a whole test split, where top-k/softmax would be wasted FLOPs."""
    encode = get_encode_fn(dims, mesh)

    @jax.jit
    def step(params, batch):
        _labels, src, pth, dst, mask, _weights = batch
        code, _attn = encode(params, src, pth, dst, mask,
                             compute_dtype=compute_dtype,
                             use_pallas=use_pallas)
        return code.astype(jnp.float32)

    return step


def make_predict_step(dims: ModelDims, *, top_k: int = 10,
                      compute_dtype=jnp.float32,
                      use_pallas: bool = False,
                      mesh=None) -> Callable:
    """Returns jitted `step(params, batch) -> (topk_ids, topk_probs,
    attention, code_vectors)` — the predict graph additionally surfaces
    per-context attention and the code vector (SURVEY.md §4.4,
    interpretability output + --export_code_vectors)."""
    encode = get_encode_fn(dims, mesh)

    @jax.jit
    def step(params, batch):
        _labels, src, pth, dst, mask, _weights = batch
        code, attn = encode(params, src, pth, dst, mask,
                            compute_dtype=compute_dtype,
                            use_pallas=use_pallas)
        logits = full_logits(params, code, dims.target_vocab_size)
        probs = jax.nn.softmax(logits, axis=-1)
        topk_probs, topk_ids = jax.lax.top_k(probs, top_k)
        return topk_ids, topk_probs, attn, code.astype(jnp.float32)

    return step
