"""Checkpoint / resume via orbax.

Reference parity target (SURVEY.md §6 "Checkpoint / resume"): the reference
saves with tf.train.Saver every SAVE_EVERY_EPOCHS epochs keeping
MAX_TO_KEEP=10, writes a vocab sidecar next to the checkpoint so `--load`
needs no dataset, and `--release` strips optimizer state. Here:

  <ckpt_dir>/
    step_<N>/state/      orbax pytree: params (+ opt_state + step unless released)
    vocab.pkl            Code2VecVocabs sidecar
    manifest.json        ModelDims + softmax config (to rebuild the model
                         without a dataset)

Checkpoints restore with the caller-provided sharding template, so a
checkpoint written on one mesh reloads onto another (or a single chip).

Async path (`--async_checkpoint`, default on): `AsyncCheckpointWriter`
makes the train loop's blocked time per checkpoint a small constant —
`snapshot_state` dispatches on-device copies (the train steps DONATE
params/opt_state, so by the time a background writer serializes, the
originals have been invalidated by the next step; a copy decouples the
snapshot from training for the price of one async device memcpy), and a
single background thread runs the device fetch + orbax write + pruning.
One save in flight at a time; a second submit BLOCKS until the first
commits — never drops or reorders (multi-host: every process runs its
own writer thread, so the collective orbax save keeps the same
per-process call order and write discipline as the sync path). The
torn-write protocol is unchanged: `_step_dirs` counts only step dirs
with a committed (renamed) `state`, so a writer killed mid-save leaves
auto-resume pointing at the last COMMITTED step.

Sidecars are write-once per checkpoint dir: vocabularies never change
within a run, and the manifest only carries structure (its `step` field
is advisory — `--release` derives the true step from the committed step
dirs), so epoch saves skip the re-pickle/rewrite when nothing changed.

Integrity (ISSUE 10): every committed step dir carries a
`checksums.json` per-file sha256 manifest of its `state` tree, written
by process 0 AFTER the commit rename. Restore verifies the files
against it first (`verify_step`); a mismatch — a bit-flipped leaf blob,
a truncated write the rename protocol could not see — quarantines the
step dir under `<ckpt_dir>/quarantine/` and falls back to the previous
committed step instead of feeding corrupt bytes into orbax. Hashing is
file-level rather than pytree-leaf-level on purpose: it is
resharding-proof (a checkpoint written on one mesh reloads onto
another — per-shard leaf digests would not survive that) and catches
exactly the storage-rot failure mode quarantine exists for. A committed
step WITHOUT a checksums file (pre-integrity checkpoints, or a death in
the rename->checksums window) restores as before, unverified.

Elastic resume (ISSUE 13): each committed step also carries a
`topology.json` save-time record ({num_processes, epoch}) written by
process 0 after the commit, so an auto-resume onto a DIFFERENT cohort
size — the supervisor re-forming a mesh at N−1 after peer loss —
converts the restored step into completed epochs under the topology
that counted them (models/setup.resume_epoch_offset), and
`load_checkpoint` reshards the restored tree onto the new mesh via the
caller's template while re-verifying the same per-file checksums.

Transient checkpoint-IO errors retry through the shared
`resilience/retry` policy (single-process only — a multi-host orbax
save is a collective, and one process re-issuing it alone would
deadlock the cohort); ENOSPC is a giveup, surfacing at the commit
barrier immediately, because a full disk does not refill on a backoff
schedule. `faults.fire("ckpt/write")` sits inside the retried write so
chaos scenarios exercise both the retry and the sticky-error path.
"""

from __future__ import annotations

import errno
import hashlib
import json
import os
import re
import shutil
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import orbax.checkpoint as ocp

from code2vec_tpu.models.encoder import ModelDims
from code2vec_tpu.resilience import faults
from code2vec_tpu.resilience import retry as retry_mod
from code2vec_tpu.vocab.vocabularies import Code2VecVocabs

_STEP_RE = re.compile(r"^step_(\d+)$")

CHECKSUMS_NAME = "checksums.json"
TOPOLOGY_NAME = "topology.json"
QUARANTINE_DIRNAME = "quarantine"


class CheckpointCorrupt(RuntimeError):
    """A committed step dir failed checksum verification and no
    quarantine fallback was possible (explicit-step restore, or a
    multi-process load where a unilateral quarantine move would race
    the cohort — the supervisor quarantines before relaunch there)."""


def _step_dirs(ckpt_dir: str):
    """COMMITTED step dirs only: a preemption mid-save leaves a torn
    step_N/ holding an orbax temp dir but no renamed `state` — counting
    it would turn auto-resume (and --load latest) into a crash loop on
    exactly the interruption it exists to survive."""
    out = []
    if os.path.isdir(ckpt_dir):
        for name in os.listdir(ckpt_dir):
            m = _STEP_RE.match(name)
            if m and os.path.exists(os.path.join(ckpt_dir, name,
                                                 "state")):
                out.append((int(m.group(1)), os.path.join(ckpt_dir, name)))
    return sorted(out)


def _build_manifest(step: int, dims: ModelDims,
                    extra_manifest: Optional[Dict[str, Any]]
                    ) -> Dict[str, Any]:
    manifest = {
        "token_vocab_size": dims.token_vocab_size,
        "path_vocab_size": dims.path_vocab_size,
        "target_vocab_size": dims.target_vocab_size,
        "embeddings_size": dims.embeddings_size,
        "max_contexts": dims.max_contexts,
        "dropout_keep_rate": dims.dropout_keep_rate,
        "vocab_pad_multiple": dims.vocab_pad_multiple,
        "tables_dtype": dims.tables_dtype,
        "encoder_type": dims.encoder_type,
        "xf_layers": dims.xf_layers,
        "xf_heads": dims.xf_heads,
        "xf_mlp_ratio": dims.xf_mlp_ratio,
        "xf_remat": dims.xf_remat,
        "ring_attention": dims.ring_attention,
        "step": step,
    }
    if extra_manifest:
        manifest.update(extra_manifest)
    return manifest


# ckpt_dir -> weakref to the vocabs object whose pickle THIS process
# last wrote there: epoch saves with the SAME vocabs skip the re-pickle
# (vocabularies are immutable within a run), while a different vocabs
# object aimed at the same dir (a second model trained into a reused
# directory in one long-lived process) — or a stale sidecar from an
# earlier run — still gets written. Identity via weakref, not id():
# a recycled id after GC must not alias a dead object's skip.
_VOCAB_WRITTEN: Dict[str, Any] = {}


def _write_sidecars(ckpt_dir: str, vocabs: Code2VecVocabs,
                    manifest: Dict[str, Any]) -> None:
    """vocab.pkl + manifest.json, write-once semantics: skip when present
    and unchanged. The manifest's `step` field is advisory (readers that
    need the real step use the committed step dirs — see
    `load_manifest`), so a step-only difference does not force a
    rewrite."""
    import weakref

    vocab_path = os.path.join(ckpt_dir, "vocab.pkl")
    ref = _VOCAB_WRITTEN.get(ckpt_dir)
    if (ref is None or ref() is not vocabs
            or not os.path.exists(vocab_path)):
        vocabs.save(vocab_path)
        _VOCAB_WRITTEN[ckpt_dir] = weakref.ref(vocabs)
    manifest_path = os.path.join(ckpt_dir, "manifest.json")
    if os.path.exists(manifest_path):
        try:
            with open(manifest_path, encoding="utf-8") as f:
                old = json.load(f)
        except (OSError, ValueError):
            old = None
        if old is not None and (
                {k: v for k, v in old.items() if k != "step"}
                == {k: v for k, v in manifest.items() if k != "step"}):
            return
    with open(manifest_path, "w") as f:
        json.dump(manifest, f, indent=1)


# lazily built so importing this module costs nothing extra; one shared
# policy, per-call budgets (retry.py's contract)
_CKPT_IO_RETRY: Optional[retry_mod.RetryPolicy] = None


def _ckpt_io_retry() -> retry_mod.RetryPolicy:
    global _CKPT_IO_RETRY
    if _CKPT_IO_RETRY is None:
        _CKPT_IO_RETRY = retry_mod.RetryPolicy(
            "checkpoint-io", max_attempts=3, base_delay_s=0.05,
            max_delay_s=1.0, retry_on=(OSError,),
            # a full disk is not transient: surface it at the commit
            # barrier NOW instead of burning the backoff budget
            giveup=lambda e: getattr(e, "errno", None) == errno.ENOSPC)
    return _CKPT_IO_RETRY


def save_checkpoint(ckpt_dir: str, state: Dict[str, Any], step: int,
                    vocabs: Code2VecVocabs, dims: ModelDims,
                    extra_manifest: Optional[Dict[str, Any]] = None,
                    max_to_keep: int = 10,
                    topology: Optional[Dict[str, Any]] = None) -> str:
    os.makedirs(ckpt_dir, exist_ok=True)
    step_dir = os.path.join(ckpt_dir, f"step_{step}")
    path = os.path.join(step_dir, "state")

    def _write() -> None:
        # failpoint INSIDE the retried callable: slow disk (sleep),
        # ENOSPC (io_error — a giveup, lands at the commit barrier),
        # transient EIO (retried here), crash-before-rename (kill)
        faults.fire("ckpt/write", path=step_dir, step=step)
        with ocp.StandardCheckpointer() as ckptr:
            ckptr.save(os.path.abspath(path), state, force=True)

    if jax.process_count() == 1:
        _ckpt_io_retry().call(_write)
    else:
        # multi-host orbax saves are collectives: one process retrying
        # alone would deadlock its peers — the supervisor's cohort
        # relaunch is the multi-process retry
        _write()
    if jax.process_index() == 0:
        write_step_checksums(ckpt_dir, step)
        write_step_topology(ckpt_dir, step, topology)
    _write_sidecars(ckpt_dir, vocabs,
                    _build_manifest(step, dims, extra_manifest))
    # Retention: keep the newest `max_to_keep` step dirs (reference
    # MAX_TO_KEEP=10 semantics).
    steps = _step_dirs(ckpt_dir)
    for _s, d in steps[:-max_to_keep]:
        shutil.rmtree(d, ignore_errors=True)
    return path


# ---- integrity: per-file checksums, verify-on-restore, quarantine ----

def _hash_file(path: str, chunk: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk)
            if not block:
                break
            h.update(block)
    return h.hexdigest()


def _state_file_digests(step_dir: str) -> Dict[str, Dict[str, Any]]:
    """{relpath-under-step_dir: {sha256, bytes}} for every file of the
    committed `state` tree, sorted for a stable manifest."""
    state_dir = os.path.join(step_dir, "state")
    out: Dict[str, Dict[str, Any]] = {}
    for base, _dirs, files in os.walk(state_dir):
        for name in sorted(files):
            p = os.path.join(base, name)
            rel = os.path.relpath(p, step_dir).replace(os.sep, "/")
            out[rel] = {"sha256": _hash_file(p),
                        "bytes": os.path.getsize(p)}
    return dict(sorted(out.items()))


def write_step_checksums(ckpt_dir: str, step: int) -> str:
    """Write `step_<N>/checksums.json` over the committed state tree.
    Runs AFTER the commit rename: a death in the rename->checksums
    window leaves a committed-but-unverified step, which restores like
    a pre-integrity checkpoint (verify_step returns None)."""
    step_dir = os.path.join(ckpt_dir, f"step_{step}")
    payload = {"step": step, "files": _state_file_digests(step_dir)}
    dest = os.path.join(step_dir, CHECKSUMS_NAME)
    tmp = dest + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=1)
    os.replace(tmp, dest)
    return dest


def write_step_topology(ckpt_dir: str, step: int,
                        extra: Optional[Dict[str, Any]] = None) -> str:
    """Write `step_<N>/topology.json`: the SAVE-TIME topology of this
    committed step (ISSUE 13 — elastic resume). An auto-resume onto a
    DIFFERENT cohort size must convert the restored step count into
    completed epochs using the topology the steps were counted under,
    not the one restoring; this per-step record is what makes that
    conversion exact across any resize history (the dir-level manifest
    is write-once and can't track per-step topology). `extra` adds
    caller fields — the train loops record the completed `epoch`, which
    makes the conversion a lookup instead of arithmetic. Written by
    process 0 after the commit rename, like the checksums manifest; a
    step WITHOUT one (pre-elastic checkpoints, or a death in the
    rename->sidecar window) resumes via the old steps//spe arithmetic
    under the current topology."""
    step_dir = os.path.join(ckpt_dir, f"step_{step}")
    payload: Dict[str, Any] = {"step": step,
                               "num_processes": jax.process_count()}
    if extra:
        payload.update({k: v for k, v in extra.items()
                        if v is not None})
    dest = os.path.join(step_dir, TOPOLOGY_NAME)
    tmp = dest + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=1)
    os.replace(tmp, dest)
    return dest


def load_step_topology(ckpt_dir: str,
                       step: int) -> Optional[Dict[str, Any]]:
    """The step's save-time topology record, or None for pre-elastic
    checkpoints (and unreadable records — resume then falls back to
    current-topology arithmetic rather than dying on a sidecar)."""
    path = os.path.join(ckpt_dir, f"step_{step}", TOPOLOGY_NAME)
    if not os.path.exists(path):
        return None
    try:
        with open(path, encoding="utf-8") as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def verify_step(ckpt_dir: str, step: int) -> Optional[bool]:
    """True = every state file matches its recorded digest; False = any
    mismatch/missing/extra file (corrupt); None = no checksums manifest
    (pre-integrity checkpoint — nothing to verify against)."""
    step_dir = os.path.join(ckpt_dir, f"step_{step}")
    manifest_path = os.path.join(step_dir, CHECKSUMS_NAME)
    if not os.path.exists(manifest_path):
        return None
    try:
        with open(manifest_path, encoding="utf-8") as f:
            recorded = json.load(f)["files"]
    except (OSError, ValueError, KeyError):
        return False  # an unreadable integrity manifest IS corruption
    actual = _state_file_digests(step_dir)
    if set(actual) != set(recorded):
        return False
    return all(actual[k]["sha256"] == v.get("sha256")
               for k, v in recorded.items())


def quarantine_step(ckpt_dir: str, step: int,
                    log: Optional[Callable[[str], None]] = None) -> str:
    """Move a corrupt step dir under `<ckpt_dir>/quarantine/` (kept for
    the postmortem, invisible to `latest_step`/retention). Returns the
    destination path."""
    qdir = os.path.join(ckpt_dir, QUARANTINE_DIRNAME)
    os.makedirs(qdir, exist_ok=True)
    src = os.path.join(ckpt_dir, f"step_{step}")
    dest = os.path.join(qdir, f"step_{step}")
    n = 0
    while os.path.exists(dest):  # a re-corrupted rewrite of the same step
        n += 1
        dest = os.path.join(qdir, f"step_{step}.{n}")
    os.replace(src, dest)
    if log is not None:
        log(f"checkpoint step {step} failed verification -> "
            f"quarantined at {dest}")
    return dest


def verify_and_resolve(ckpt_dir: str, *, quarantine: bool = True,
                       log: Optional[Callable[[str], None]] = None
                       ) -> Tuple[Optional[int], List[str]]:
    """Walk committed steps newest-first, verifying each; corrupt ones
    are quarantined (when allowed). Returns (first verified-or-
    unverifiable step usable for resume — None when none survive,
    quarantined dir paths). The supervisor runs this before every
    (re)launch so a child only ever resumes from a VERIFIED committed
    step."""
    quarantined: List[str] = []
    for step, _d in reversed(_step_dirs(ckpt_dir)):
        ok = verify_step(ckpt_dir, step)
        if ok is False:
            if not quarantine:
                raise CheckpointCorrupt(
                    f"checkpoint step {step} under {ckpt_dir} failed "
                    f"checksum verification")
            quarantined.append(quarantine_step(ckpt_dir, step, log))
            continue
        if ok is None and log is not None:
            log(f"checkpoint step {step}: no {CHECKSUMS_NAME} "
                "(pre-integrity checkpoint) — restoring unverified")
        return step, quarantined
    return None, quarantined


def snapshot_state(state: Dict[str, Any]) -> Dict[str, Any]:
    """Decouple a state pytree from the train loop: async-dispatched
    on-device copies of every jax.Array leaf. The train steps donate
    their params/opt_state buffers, so handing the ORIGINALS to a
    background writer would read deleted arrays as soon as the next step
    dispatches; the copy costs one device memcpy (dispatch returns
    immediately — the loop does not wait for the bytes) plus transient
    HBM for the duplicate until the writer drains. Non-array leaves
    (the python `step` int) pass through untouched so the saved
    structure is identical to the sync path's."""
    import jax.numpy as jnp

    return jax.tree_util.tree_map(
        lambda x: jnp.copy(x) if isinstance(x, jax.Array) else x, state)


class AsyncCheckpointWriter:
    """Background checkpoint writer: the Check-N-Run / t5x
    AsyncCheckpointer shape. `submit()` returns as soon as the snapshot
    is queued; one daemon thread runs the device fetch + serialization +
    committed-`state` rename + retention pruning. Discipline:

      - ONE save in flight: a second `submit` while the first is still
        writing blocks until it commits (never drops, never reorders —
        the orbax collective needs every process to issue the same save
        sequence).
      - `wait()` is the hard commit barrier (end of training, explicit
        `save(block=True)`, anything about to READ the checkpoint dir).
      - a failed background save is sticky: the error re-raises at the
        next `submit`/`wait`/`close` instead of letting a run train for
        hours past a dead disk.

    `save_fn` is injectable for crash-safety tests (simulate a writer
    killed before the `state` rename commits), and `clock` (default
    `time.perf_counter`) is the duration timebase — the deflaked
    timing tests (tests/test_async_checkpoint.py) drive a fake clock
    through the injected save_fn instead of betting on wall-clock
    ratios under CI contention. `heartbeat` is the obs.watchdog
    liveness hook (--watchdog_stall_s): busy at job pickup, idle after
    commit — a write hung in orbax/disk I/O stops beating and the
    watchdog dumps the writer thread's stack instead of the run going
    silently wedged."""

    def __init__(self, log: Optional[Callable[[str], None]] = None,
                 save_fn: Optional[Callable] = None,
                 heartbeat=None,
                 clock: Callable[[], float] = time.perf_counter):
        self._log = log or (lambda _m: None)
        # None -> module-level save_checkpoint, resolved at WRITE time
        # (tests monkeypatch the module function to inject slow disks
        # and torn writes)
        self._save_fn = save_fn
        self._heartbeat = heartbeat
        self._clock = clock
        self._cond = threading.Condition()
        self._job: Optional[Dict[str, Any]] = None
        self._error: Optional[BaseException] = None
        self._closed = False
        self._thread: Optional[threading.Thread] = None

    def _raise_pending(self) -> None:
        # threading.Condition's default lock is an RLock, so this is
        # safe from call sites already holding _cond
        with self._cond:
            if self._error is not None:
                err, self._error = self._error, None
                raise err

    def submit(self, ckpt_dir: str, state: Dict[str, Any], step: int,
               vocabs: Code2VecVocabs, dims: ModelDims, *,
               extra_manifest: Optional[Dict[str, Any]] = None,
               max_to_keep: int = 10, telemetry=None,
               tracer=None, trace_ctx=None,
               topology: Optional[Dict[str, Any]] = None) -> None:
        """Snapshot `state` and queue the save. Blocks only on the
        snapshot dispatch — unless a previous save is still in flight,
        in which case it blocks until that one commits. `trace_ctx`
        (with its `tracer`) is the cross-thread span handoff: the
        writer parents its `train/save_write` span to the loop-side
        save span that queued this job."""
        snap = snapshot_state(state)
        with self._cond:
            self._raise_pending()
            if self._closed:
                raise RuntimeError("AsyncCheckpointWriter is closed")
            while self._job is not None:
                self._cond.wait()
                self._raise_pending()
            self._job = {
                "ckpt_dir": ckpt_dir, "state": snap, "step": step,
                "vocabs": vocabs, "dims": dims,
                "extra_manifest": extra_manifest,
                "max_to_keep": max_to_keep, "telemetry": telemetry,
                "tracer": tracer, "trace_ctx": trace_ctx,
                "topology": topology,
            }
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._run, daemon=True, name="ckpt-writer")
                self._thread.start()
            self._cond.notify_all()

    def _run(self) -> None:
        while True:
            with self._cond:
                while self._job is None and not self._closed:
                    self._cond.wait()
                if self._job is None:
                    return  # closed and drained
                job = self._job
            hb = self._heartbeat
            try:
                if hb is not None:
                    hb.busy()  # deadline clock runs while writing
                t0 = self._clock()
                tracer = job["tracer"]
                t0_trace = tracer.clock() if tracer is not None else 0.0
                save_fn = self._save_fn or save_checkpoint
                save_fn(job["ckpt_dir"], job["state"], job["step"],
                        job["vocabs"], job["dims"],
                        extra_manifest=job["extra_manifest"],
                        max_to_keep=job["max_to_keep"],
                        topology=job["topology"])
                total_ms = (self._clock() - t0) * 1e3
                if tracer is not None:
                    # writer-side span, parented (cross-thread) to the
                    # loop's save span via the handed-off context
                    tracer.record_span(
                        "train/save_write", t0_trace, tracer.clock(),
                        parent=job["trace_ctx"], step=int(job["step"]))
                tele = job["telemetry"]
                if tele is not None:
                    tele.record_ms("train/save_total_ms", total_ms)
                    tele.event("save_committed", step=int(job["step"]),
                               total_ms=round(total_ms, 3))
                self._log(f"async checkpoint step {job['step']} "
                          f"committed -> {job['ckpt_dir']} "
                          f"({total_ms:.0f} ms in background)")
            except BaseException as e:  # surfaces at next submit/wait
                with self._cond:
                    self._error = e
            finally:
                if hb is not None:
                    hb.idle()
                with self._cond:
                    self._job = None
                    self._cond.notify_all()

    def wait(self) -> None:
        """Hard commit barrier: returns once no save is in flight;
        re-raises a background failure."""
        with self._cond:
            while self._job is not None:
                self._cond.wait()
            self._raise_pending()

    def drain_quiet(self) -> None:
        """Barrier without the re-raise (exception-path teardown: the
        original error must not be masked; a sticky writer error still
        surfaces at the next wait/submit/close)."""
        with self._cond:
            while self._job is not None:
                self._cond.wait()

    def close(self) -> None:
        """Commit barrier + writer-thread shutdown."""
        with self._cond:
            while self._job is not None:
                self._cond.wait()
            self._closed = True
            self._cond.notify_all()
            thread = self._thread
        if thread is not None:
            thread.join()
        with self._cond:
            self._raise_pending()


def latest_step(ckpt_dir: str) -> Optional[int]:
    steps = _step_dirs(ckpt_dir)
    return steps[-1][0] if steps else None


def load_manifest(ckpt_dir: str) -> Dict[str, Any]:
    """Manifest with the EFFECTIVE step: the on-disk `step` field is
    advisory (sidecars are write-once — it freezes at the dir's first
    save), so every consumer that needs the real step — the released
    checkpoint's step, the LR-schedule resume horizon in
    models/setup.py — gets it corrected here from the committed step
    dirs."""
    with open(os.path.join(ckpt_dir, "manifest.json")) as f:
        manifest = json.load(f)
    step = latest_step(ckpt_dir)
    if step is not None:
        manifest["step"] = step
    return manifest


def load_dims(ckpt_dir: str) -> ModelDims:
    m = load_manifest(ckpt_dir)
    return ModelDims(
        token_vocab_size=m["token_vocab_size"],
        path_vocab_size=m["path_vocab_size"],
        target_vocab_size=m["target_vocab_size"],
        embeddings_size=m["embeddings_size"],
        max_contexts=m["max_contexts"],
        dropout_keep_rate=m["dropout_keep_rate"],
        vocab_pad_multiple=m.get("vocab_pad_multiple", 1),
        tables_dtype=m.get("tables_dtype", "float32"),
        encoder_type=m.get("encoder_type", "bag"),
        xf_layers=m.get("xf_layers", 2),
        xf_heads=m.get("xf_heads", 4),
        xf_mlp_ratio=m.get("xf_mlp_ratio", 4),
        xf_remat=m.get("xf_remat", False),
        ring_attention=m.get("ring_attention", False),
    )


def load_checkpoint(ckpt_dir: str, template: Dict[str, Any],
                    step: Optional[int] = None, *,
                    verify: bool = True,
                    log: Optional[Callable[[str], None]] = None
                    ) -> Dict[str, Any]:
    """Restore the pytree at `step` (default: latest) with the dtype /
    sharding layout of `template` (abstract arrays are fine).

    Verify-on-restore (default on): the step's files are checked
    against its `checksums.json` first. An EXPLICITLY requested corrupt
    step raises `CheckpointCorrupt` — the caller asked for those bytes,
    silently substituting others would be worse. A corrupt LATEST step
    is quarantined (single-process only: a multi-process unilateral
    move would race the cohort, so those raise and let the supervisor
    quarantine before relaunch) and the restore falls back to the
    previous committed step. Steps without a checksums manifest restore
    unverified, as before.

    Resharding (ISSUE 13 — the elastic-resume restore path): the
    restore honors the TEMPLATE's shardings, not the saver's, so a
    checkpoint written by an N-process cohort redistributes its
    row-sharded tables and optimizer slots across whatever mesh the
    surviving cohort rebuilt — orbax reads each process's needed byte
    ranges from the per-leaf blobs directly. Integrity survives the
    move because the checksums are per-FILE over the committed state
    tree (deliberately not per-shard — see the module docstring): the
    same `verify_step` sweep above re-verifies every file regardless
    of which topology wrote it or which will read it. A cross-topology
    restore is logged via the step's save-time `topology.json`."""
    explicit = step is not None
    while True:
        if step is None:
            step = latest_step(ckpt_dir)
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {ckpt_dir}")
        if not verify or verify_step(ckpt_dir, step) is not False:
            break
        if explicit or jax.process_count() > 1:
            raise CheckpointCorrupt(
                f"checkpoint step {step} under {ckpt_dir} failed "
                f"checksum verification"
                + ("" if explicit else
                   " (multi-process load: quarantine via the "
                   "supervisor, not unilaterally)"))
        quarantine_step(ckpt_dir, step, log)
        step = None  # fall back to the previous committed step
    saved = load_step_topology(ckpt_dir, step)
    if (log is not None and saved
            and saved.get("num_processes") is not None
            and int(saved["num_processes"]) != jax.process_count()):
        log(f"checkpoint step {step}: saved by "
            f"{saved['num_processes']} process(es), restoring onto "
            f"{jax.process_count()} — resharding onto the new mesh")
    path = os.path.join(ckpt_dir, f"step_{step}", "state")
    abstract = jax.tree_util.tree_map(ocp.utils.to_shape_dtype_struct,
                                      template)
    with ocp.StandardCheckpointer() as ckptr:
        return ckptr.restore(os.path.abspath(path), abstract)


def load_vocabs(ckpt_dir: str) -> Code2VecVocabs:
    return Code2VecVocabs.load(os.path.join(ckpt_dir, "vocab.pkl"))


def release_checkpoint(load_dir: str, dest_dir: str,
                       params: Dict[str, Any]) -> None:
    """Reference `--release` (SURVEY.md §4.5): write a stripped
    inference-only checkpoint (params, no optimizer slots)."""
    os.makedirs(dest_dir, exist_ok=True)
    manifest = load_manifest(load_dir)  # step already effective
    manifest["released"] = True
    step = manifest.get("step", 0)
    path = os.path.join(dest_dir, f"step_{step}", "state")
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(os.path.abspath(path), {"params": params}, force=True)
    shutil.copy(os.path.join(load_dir, "vocab.pkl"),
                os.path.join(dest_dir, "vocab.pkl"))
    with open(os.path.join(dest_dir, "manifest.json"), "w") as f:
        json.dump(manifest, f, indent=1)
