"""Checkpoint / resume via orbax.

Reference parity target (SURVEY.md §6 "Checkpoint / resume"): the reference
saves with tf.train.Saver every SAVE_EVERY_EPOCHS epochs keeping
MAX_TO_KEEP=10, writes a vocab sidecar next to the checkpoint so `--load`
needs no dataset, and `--release` strips optimizer state. Here:

  <ckpt_dir>/
    step_<N>/state/      orbax pytree: params (+ opt_state + step unless released)
    vocab.pkl            Code2VecVocabs sidecar
    manifest.json        ModelDims + softmax config (to rebuild the model
                         without a dataset)

Checkpoints restore with the caller-provided sharding template, so a
checkpoint written on one mesh reloads onto another (or a single chip).
"""

from __future__ import annotations

import json
import os
import re
import shutil
from typing import Any, Dict, Optional

import jax
import orbax.checkpoint as ocp

from code2vec_tpu.models.encoder import ModelDims
from code2vec_tpu.vocab.vocabularies import Code2VecVocabs

_STEP_RE = re.compile(r"^step_(\d+)$")


def _step_dirs(ckpt_dir: str):
    """COMMITTED step dirs only: a preemption mid-save leaves a torn
    step_N/ holding an orbax temp dir but no renamed `state` — counting
    it would turn auto-resume (and --load latest) into a crash loop on
    exactly the interruption it exists to survive."""
    out = []
    if os.path.isdir(ckpt_dir):
        for name in os.listdir(ckpt_dir):
            m = _STEP_RE.match(name)
            if m and os.path.exists(os.path.join(ckpt_dir, name,
                                                 "state")):
                out.append((int(m.group(1)), os.path.join(ckpt_dir, name)))
    return sorted(out)


def save_checkpoint(ckpt_dir: str, state: Dict[str, Any], step: int,
                    vocabs: Code2VecVocabs, dims: ModelDims,
                    extra_manifest: Optional[Dict[str, Any]] = None,
                    max_to_keep: int = 10) -> str:
    os.makedirs(ckpt_dir, exist_ok=True)
    path = os.path.join(ckpt_dir, f"step_{step}", "state")
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(os.path.abspath(path), state, force=True)
    vocabs.save(os.path.join(ckpt_dir, "vocab.pkl"))
    manifest = {
        "token_vocab_size": dims.token_vocab_size,
        "path_vocab_size": dims.path_vocab_size,
        "target_vocab_size": dims.target_vocab_size,
        "embeddings_size": dims.embeddings_size,
        "max_contexts": dims.max_contexts,
        "dropout_keep_rate": dims.dropout_keep_rate,
        "vocab_pad_multiple": dims.vocab_pad_multiple,
        "tables_dtype": dims.tables_dtype,
        "encoder_type": dims.encoder_type,
        "xf_layers": dims.xf_layers,
        "xf_heads": dims.xf_heads,
        "xf_mlp_ratio": dims.xf_mlp_ratio,
        "xf_remat": dims.xf_remat,
        "ring_attention": dims.ring_attention,
        "step": step,
    }
    if extra_manifest:
        manifest.update(extra_manifest)
    with open(os.path.join(ckpt_dir, "manifest.json"), "w") as f:
        json.dump(manifest, f, indent=1)
    # Retention: keep the newest `max_to_keep` step dirs (reference
    # MAX_TO_KEEP=10 semantics).
    steps = _step_dirs(ckpt_dir)
    for _s, d in steps[:-max_to_keep]:
        shutil.rmtree(d, ignore_errors=True)
    return path


def latest_step(ckpt_dir: str) -> Optional[int]:
    steps = _step_dirs(ckpt_dir)
    return steps[-1][0] if steps else None


def load_manifest(ckpt_dir: str) -> Dict[str, Any]:
    with open(os.path.join(ckpt_dir, "manifest.json")) as f:
        return json.load(f)


def load_dims(ckpt_dir: str) -> ModelDims:
    m = load_manifest(ckpt_dir)
    return ModelDims(
        token_vocab_size=m["token_vocab_size"],
        path_vocab_size=m["path_vocab_size"],
        target_vocab_size=m["target_vocab_size"],
        embeddings_size=m["embeddings_size"],
        max_contexts=m["max_contexts"],
        dropout_keep_rate=m["dropout_keep_rate"],
        vocab_pad_multiple=m.get("vocab_pad_multiple", 1),
        tables_dtype=m.get("tables_dtype", "float32"),
        encoder_type=m.get("encoder_type", "bag"),
        xf_layers=m.get("xf_layers", 2),
        xf_heads=m.get("xf_heads", 4),
        xf_mlp_ratio=m.get("xf_mlp_ratio", 4),
        xf_remat=m.get("xf_remat", False),
        ring_attention=m.get("ring_attention", False),
    )


def load_checkpoint(ckpt_dir: str, template: Dict[str, Any],
                    step: Optional[int] = None) -> Dict[str, Any]:
    """Restore the pytree at `step` (default: latest) with the dtype /
    sharding layout of `template` (abstract arrays are fine)."""
    if step is None:
        step = latest_step(ckpt_dir)
    if step is None:
        raise FileNotFoundError(f"no checkpoints under {ckpt_dir}")
    path = os.path.join(ckpt_dir, f"step_{step}", "state")
    abstract = jax.tree_util.tree_map(ocp.utils.to_shape_dtype_struct,
                                      template)
    with ocp.StandardCheckpointer() as ckptr:
        return ckptr.restore(os.path.abspath(path), abstract)


def load_vocabs(ckpt_dir: str) -> Code2VecVocabs:
    return Code2VecVocabs.load(os.path.join(ckpt_dir, "vocab.pkl"))


def release_checkpoint(load_dir: str, dest_dir: str,
                       params: Dict[str, Any]) -> None:
    """Reference `--release` (SURVEY.md §4.5): write a stripped
    inference-only checkpoint (params, no optimizer slots)."""
    os.makedirs(dest_dir, exist_ok=True)
    manifest = load_manifest(load_dir)
    manifest["released"] = True
    step = manifest.get("step", 0)
    path = os.path.join(dest_dir, f"step_{step}", "state")
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(os.path.abspath(path), {"params": params}, force=True)
    shutil.copy(os.path.join(load_dir, "vocab.pkl"),
                os.path.join(dest_dir, "vocab.pkl"))
    with open(os.path.join(dest_dir, "manifest.json"), "w") as f:
        json.dump(manifest, f, indent=1)
