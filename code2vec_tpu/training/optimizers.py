"""Optimizer construction.

The reference trains everything with Adam (SURVEY.md §3,
`tensorflow_model.py` training graph). On TPU the dominant step cost at
java-large scale is the optimizer's full-table HBM traffic (measured
15.6 ms of a 40 ms step for f32 Adam on v5e-lite; BASELINE.md), so the
framework also offers a factored second-moment optimizer for the three
vocab tables:

- "adam": optax.adam on every param — reference-parity default.
- "adafactor": Adafactor (factored v, no momentum) on the vocab tables,
  Adam on TRANSFORM/ATTENTION. Cuts optimizer state for a [V, E] table
  from 2*V*E to ~V+E and the update traffic accordingly — the standard
  large-embedding practice.
"""

from __future__ import annotations

import optax

TABLE_PARAMS = ("token_emb", "path_emb", "target_emb")


def make_optimizer(learning_rate: float,
                   embedding_optimizer: str = "adam"
                   ) -> optax.GradientTransformation:
    if embedding_optimizer == "adam":
        return optax.adam(learning_rate)
    if embedding_optimizer == "adafactor":
        # label by key so extra head params (e.g. vm_pointer) route to
        # adam automatically
        def labels(params):
            return {k: ("table" if k in TABLE_PARAMS else "small")
                    for k in params}

        return optax.multi_transform(
            {"table": optax.adafactor(
                learning_rate, multiply_by_parameter_scale=False,
                momentum=None),
             "small": optax.adam(learning_rate)},
            labels)
    raise ValueError(
        f"unknown embedding_optimizer {embedding_optimizer!r} "
        "(expected 'adam' or 'adafactor')")
