"""Optimizer construction.

The reference trains everything with Adam (SURVEY.md §3,
`tensorflow_model.py` training graph). On TPU the dominant step cost at
java-large scale is the optimizer's full-table HBM traffic (measured
15.6 ms of a 40 ms step for f32 Adam on v5e-lite; BASELINE.md), so the
framework also offers a factored second-moment optimizer for the three
vocab tables:

- "adafactor" (DEFAULT since round 3): Adafactor (factored v, no
  momentum) on the vocab tables, Adam on TRANSFORM/ATTENTION. Cuts
  optimizer state for a [V, E] table from 2*V*E to ~V+E and the update
  traffic accordingly — the standard large-embedding practice. Measured
  both fastest (26.0 vs 33-35 ms/step, java-large B=1024) and
  highest-F1 sampled variant (BASELINE.md round-3 quality table).
- "adam": reference parity — Adam on every param, with mu/nu kept f32
  even for bf16 tables (scale_by_adam_f32_moments below).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax

TABLE_PARAMS = ("token_emb", "path_emb", "target_emb")


def scale_by_adam_f32_moments(b1: float = 0.9, b2: float = 0.999,
                              eps: float = 1e-8
                              ) -> optax.GradientTransformation:
    """scale_by_adam that keeps mu AND nu in float32 regardless of the
    parameter dtype.

    With bf16 vocab tables, stock optax.adam inherits bf16 for both
    moments (mu/nu = zeros_like(param)); the second-moment increment
    (1-b2)*g^2 = 1e-3*g^2 underflows bf16's 8-bit mantissa once it drops
    below ~1/256 of the running value, risking a quiet late-training
    stall at java-large scale (round-2 advisor finding). f32 moments are
    measured perf-neutral on v5e-lite (BASELINE.md phase isolation:
    15.6 ms f32 vs 15.9 ms bf16 moment traffic — the update kernel is
    not moment-traffic-bound), so this is the default for "adam".
    Residual caveat: the *applied update* still rounds to the bf16
    table, which the 50K-corpus quality study validates (BASELINE.md).
    """

    def init_fn(params):
        f32_zeros = lambda p: jnp.zeros(p.shape, jnp.float32)
        return optax.ScaleByAdamState(
            count=jnp.zeros([], jnp.int32),
            mu=jax.tree_util.tree_map(f32_zeros, params),
            nu=jax.tree_util.tree_map(f32_zeros, params))

    def update_fn(updates, state, params=None):
        del params
        g32 = jax.tree_util.tree_map(
            lambda g: g.astype(jnp.float32), updates)
        mu = jax.tree_util.tree_map(
            lambda m, g: b1 * m + (1.0 - b1) * g, state.mu, g32)
        nu = jax.tree_util.tree_map(
            lambda v, g: b2 * v + (1.0 - b2) * (g * g), state.nu, g32)
        count = optax.safe_int32_increment(state.count)
        bc1 = 1.0 - b1 ** count.astype(jnp.float32)
        bc2 = 1.0 - b2 ** count.astype(jnp.float32)
        new_updates = jax.tree_util.tree_map(
            lambda m, v, u: ((m / bc1) / (jnp.sqrt(v / bc2) + eps)
                             ).astype(u.dtype),
            mu, nu, updates)
        return new_updates, optax.ScaleByAdamState(count=count, mu=mu,
                                                   nu=nu)

    return optax.GradientTransformation(init_fn, update_fn)


def make_lr(learning_rate: float, schedule: str = "constant",
            total_steps: int = 0, warmup_steps: int = 0):
    """Returns a float or an optax schedule.

    The reference trains at constant LR (TF AdamOptimizer default —
    parity). "cosine" decays to 10% of peak over total_steps: the decay
    study (tools/sampled_decay_study.py, BASELINE.md round 3) shows the
    sampled-softmax head-class top1 decay is full-LR negative-pressure
    overshoot — head rows keep receiving ~every-step negative updates
    after converging, and at lr=1e-3 they drift off their optimum late
    in training (at lr=5e-4 the decay vanishes, Adam nu stays flat so
    it is not an effective-LR spike). A decaying schedule removes the
    pathology without relying on bf16 rounding noise.

    "warmup_cosine" (round 4, the large-global-batch recipe): linear
    0→peak over `warmup_steps` (default 5% of total_steps), then cosine
    to 10% of peak. At B≥8192 the first steps take scaled-LR updates on
    cold Adam/Adafactor second moments — warmup is the standard cure
    (Goyal et al. 2017), and the large-batch study (BASELINE.md round 4)
    measures what it buys here.
    """
    if schedule == "constant":
        return learning_rate
    assert total_steps > 0, f"--lr_schedule {schedule} needs total_steps"
    if schedule == "cosine":
        return optax.cosine_decay_schedule(learning_rate, total_steps,
                                           alpha=0.1)
    if schedule == "linear":
        return optax.linear_schedule(learning_rate, learning_rate * 0.1,
                                     total_steps)
    if schedule == "warmup_cosine":
        w = warmup_length(total_steps, warmup_steps)
        # optax cosine-decays over (decay_steps - warmup_steps), which
        # must stay positive — eval/predict-only loads build the
        # schedule with horizon 1 just for opt_state STRUCTURE
        # (models/setup.build_optimizer), so clamp rather than assert
        return optax.warmup_cosine_decay_schedule(
            init_value=0.0, peak_value=learning_rate, warmup_steps=w,
            decay_steps=max(total_steps, w + 1),
            end_value=0.1 * learning_rate)
    raise ValueError(f"unknown lr schedule {schedule!r}")


def warmup_length(total_steps: int, warmup_steps: int) -> int:
    """The EFFECTIVE warmup length make_lr uses: explicit if given,
    else 5% of the horizon, clamped inside it. Exposed so
    build_optimizer can resolve auto-warmup to a concrete number at
    first training — the checkpoint manifest must record the effective
    value, or a resume would re-derive a different auto length from
    its extended horizon and follow a different LR trajectory."""
    w = warmup_steps if warmup_steps > 0 else max(1, total_steps // 20)
    return min(w, max(1, total_steps - 1))


def schedule_total_steps(num_examples: int, batch_size: int, epochs: int,
                         num_hosts: int = 1,
                         restored_step: int = 0) -> int:
    """Decay horizon for make_lr: steps this run will take (matching the
    reader's per-host ceil-div batch count) plus the restored optimizer
    step for resumes — the restored count leaf already sits at the
    checkpoint's step, so without the extension a resumed run would
    clamp to the schedule floor immediately."""
    per_host = -(-num_examples // num_hosts)
    return -(-per_host // batch_size) * epochs + restored_step


def resolve_checkpoint_schedule(requested: str, manifest: dict,
                                log) -> str:
    """The LR-schedule a loaded model must use: the checkpoint's (the
    opt_state structure is fixed at first training). Warns when a CLI
    request conflicts instead of silently dropping it."""
    ckpt_schedule = manifest.get("lr_schedule", "constant")
    if requested != ckpt_schedule:
        log(f"--lr_schedule {requested!r} ignored: using the "
            f"checkpoint's {ckpt_schedule!r} (the optimizer state "
            "structure is fixed at first training)")
    return ckpt_schedule


def resolve_checkpoint_warmup(schedule: str, requested: int,
                              manifest: dict, log) -> int:
    """Companion to resolve_checkpoint_schedule, with the same logging
    contract: the checkpoint's EFFECTIVE warmup length wins (the LR
    trajectory is fixed at first training), a conflicting CLI
    --warmup_steps is logged rather than silently dropped, and a
    warmup aimed at a non-warmup schedule is logged+zeroed (the
    combination Config.verify rejects on the fresh-training path)."""
    if schedule != "warmup_cosine":
        if requested > 0:
            log(f"--warmup_steps {requested} ignored: the checkpoint's "
                f"schedule is {schedule!r} (no warmup phase)")
        return 0
    ckpt_warmup = int(manifest.get("lr_warmup_steps", 0))
    if ckpt_warmup > 0 and requested > 0 and requested != ckpt_warmup:
        log(f"--warmup_steps {requested} ignored: using the "
            f"checkpoint's effective warmup {ckpt_warmup} (the LR "
            "trajectory is fixed at first training)")
    return ckpt_warmup if ckpt_warmup > 0 else requested


def make_optimizer(learning_rate,
                   embedding_optimizer: str = "adafactor",
                   trust_ratio: bool = False,
                   trust_ratio_scope: str = "all"
                   ) -> optax.GradientTransformation:
    """`learning_rate` is a float or an optax schedule (see make_lr).

    `trust_ratio=True` (round 4, the large-global-batch recipe) inserts
    a LAMB-style per-array trust-ratio rescale (You et al. 2020:
    update *= ||param|| / ||update||, guarded to 1 when either norm is
    0) between the preconditioner and the LR scaling. Per-array
    granularity means each vocab TABLE is one trust group — the same
    granularity LAMB uses per layer. Changes the opt_state STRUCTURE,
    so it is recorded in the checkpoint manifest like
    embedding_optimizer.

    `trust_ratio_scope` (round 5, VERDICT r4 item 8): "all" applies
    the rescale on every branch — measured HARMFUL on this model
    family (BASELINE.md round 4: the rms-clipped update is rescaled by
    the small norm of fresh embedding tables; effective LR collapses,
    F1 0.11). "dense" is the standard LAMB practice for
    embedding-dominated models: trust-scale only the dense params
    (TRANSFORM/ATTENTION/extra heads), plain adafactor on the tables.
    Requires the adafactor branch (the tables need their own
    transform for the scope split to exist).
    """
    assert trust_ratio_scope in ("all", "dense"), trust_ratio_scope
    if embedding_optimizer == "adam":
        if trust_ratio and trust_ratio_scope != "all":
            raise ValueError(
                "--trust_ratio_scope dense requires the adafactor "
                "embedding optimizer (adam runs one transform over "
                "all params, so there is no table/dense split).")
        if not trust_ratio:
            return optax.chain(
                scale_by_adam_f32_moments(),
                optax.scale_by_learning_rate(learning_rate))
        return optax.chain(scale_by_adam_f32_moments(),
                           optax.scale_by_trust_ratio(),
                           optax.scale_by_learning_rate(learning_rate))
    if embedding_optimizer == "adafactor":
        # label by key so extra head params (e.g. vm_pointer) route to
        # adam automatically
        def labels(params):
            return {k: ("table" if k in TABLE_PARAMS else "small")
                    for k in params}

        if not trust_ratio:
            table_tx = optax.adafactor(
                learning_rate, multiply_by_parameter_scale=False,
                momentum=None)
            small_tx = optax.adam(learning_rate)
        elif trust_ratio_scope == "dense":
            # tables keep the plain (measured-best) adafactor path;
            # only the dense params get the LAMB rescale
            table_tx = optax.adafactor(
                learning_rate, multiply_by_parameter_scale=False,
                momentum=None)
            small_tx = optax.chain(
                optax.scale_by_adam(),
                optax.scale_by_trust_ratio(),
                optax.scale_by_learning_rate(learning_rate))
        else:
            # optax.adafactor(lr, multiply_by_parameter_scale=False,
            # momentum=None) == factored_rms + block-rms clip + lr;
            # rebuilt here explicitly so the trust ratio lands between
            # the clip and the LR (after the LR it would cancel the
            # schedule — ||update|| already contains lr).
            table_tx = optax.chain(
                optax.scale_by_factored_rms(),
                optax.clip_by_block_rms(1.0),
                optax.scale_by_trust_ratio(),
                optax.scale_by_learning_rate(learning_rate))
            small_tx = optax.chain(
                optax.scale_by_adam(),
                optax.scale_by_trust_ratio(),
                optax.scale_by_learning_rate(learning_rate))
        return optax.multi_transform({"table": table_tx,
                                      "small": small_tx}, labels)
    raise ValueError(
        f"unknown embedding_optimizer {embedding_optimizer!r} "
        "(expected 'adam' or 'adafactor')")
