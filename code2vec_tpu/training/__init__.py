from code2vec_tpu.training.steps import (  # noqa: F401
    make_train_step, make_eval_step, make_predict_step)
