from code2vec_tpu.training.steps import (  # noqa: F401
    make_train_step, make_train_loss_fn, make_eval_step,
    make_predict_step)
from code2vec_tpu.training.optimizers import (  # noqa: F401
    make_optimizer, make_lr)
from code2vec_tpu.training.profiler import StepProfiler  # noqa: F401
from code2vec_tpu.training.scalars import ScalarWriter  # noqa: F401
