"""The path-context encoder: the model core as pure functions on a pytree.

Reference parity target: `tensorflow_model.py` forward graph (SURVEY.md §3):
trainable variables WORDS_VOCAB [Vt, 128], PATHS_VOCAB [Vp, 128],
TARGET_WORDS_VOCAB [Vy, 384], TRANSFORM [384, 384], ATTENTION [384, 1];
forward = 3 embedding gathers -> concat(384) -> dropout(keep 0.75) ->
tanh(ctx @ TRANSFORM) -> masked attention softmax over MAX_CONTEXTS ->
weighted sum = code vector -> logits vs TARGET_WORDS_VOCABᵀ.

TPU-first design choices:
- pure-jax param pytree (a flat dict) rather than a framework Module: the
  five arrays are exactly the reference's variables, and explicit pytrees
  make NamedSharding rules trivial (parallel/sharding.py).
- vocab-table row counts are padded up to a multiple of the model-parallel
  mesh axis so tables shard evenly (padding rows are dead: PAD/OOV indices
  are < the true size and the sampler clips to the true vocab size).
- compute dtype is bfloat16 on the MXU (params stay f32; casts at use).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from code2vec_tpu.ops.attention import attention_pool

Params = Dict[str, jax.Array]


@dataclasses.dataclass(frozen=True)
class ModelDims:
    """Static model dimensions (hashable: usable as a jit static arg)."""
    token_vocab_size: int
    path_vocab_size: int
    target_vocab_size: int
    embeddings_size: int = 128
    max_contexts: int = 200
    dropout_keep_rate: float = 0.75
    # Row padding so vocab dims divide the 'model' mesh axis evenly.
    vocab_pad_multiple: int = 1
    # Storage dtype of the three vocab tables
    # ("float32" | "bfloat16" | "int8").
    # bf16 tables halve the gather / scatter / optimizer HBM traffic that
    # dominates the java-large step (~30-40% end-to-end, measured on
    # v5e-lite; see BASELINE.md). "int8" (ops/quant.py, VERDICT r4
    # item 3) halves the token/path-table bytes AGAIN: int8 rows +
    # per-row f32 scales, gather-level dequantization,
    # stochastic-rounding requantize in the apply; target_emb stays
    # bf16 (the sampled-softmax head matmuls against it).
    # TRANSFORM/ATTENTION always stay f32.
    tables_dtype: str = "float32"
    # Encoder architecture: "bag" (the reference's single-query
    # attention pool) or "transformer" (set transformer over the
    # contexts, models/transformer_encoder.py; BASELINE.json configs[4]).
    encoder_type: str = "bag"
    xf_layers: int = 2
    # 3 -> head_dim 384/3 = 128 = one MXU lane width (shipped default,
    # matches Config.XF_HEADS; quality-identical to 4, 9% faster
    # through the fused kernels — BASELINE.md round 4)
    xf_heads: int = 3
    xf_mlp_ratio: int = 4
    # Rematerialize each transformer layer in the backward pass
    # (jax.checkpoint): trades ~30% more FLOPs for O(layers) -> O(1)
    # activation memory — required to fit CodeBERT-depth (12-layer)
    # encoders at B*C activation scale (SURVEY.md "HBM bandwidth" row).
    xf_remat: bool = False
    # Ring attention over the 'ctx' mesh axis (ops/ring_attention.py):
    # K/V stay sharded and rotate via ppermute instead of the XLA
    # all-gather — O(C/s) per-device attention memory for long-context
    # sequence parallelism. Takes effect only when the mesh's ctx axis
    # is > 1 (numerically exact either way).
    ring_attention: bool = False

    @property
    def context_vector_size(self) -> int:
        return 3 * self.embeddings_size

    @property
    def code_vector_size(self) -> int:
        return self.context_vector_size

    def padded(self, n: int) -> int:
        m = self.vocab_pad_multiple
        return ((n + m - 1) // m) * m


def init_params(rng: jax.Array, dims: ModelDims,
                dtype=jnp.float32) -> Params:
    """Variance-scaled init, matching the reference's scheme in spirit
    (TF used glorot-ish initializers on the tables and TRANSFORM).
    The vocab tables are stored in dims.tables_dtype; TRANSFORM and
    ATTENTION stay in `dtype` (f32) for numerics."""
    k_tok, k_path, k_tgt, k_tr, k_at = jax.random.split(rng, 5)
    E = dims.embeddings_size
    D = dims.context_vector_size
    init = jax.nn.initializers.variance_scaling(
        1.0, "fan_avg", "uniform")
    quantized = dims.tables_dtype == "int8"
    t_dtype = jnp.bfloat16 if quantized else jnp.dtype(dims.tables_dtype)
    params = {
        "token_emb": init(k_tok, (dims.padded(dims.token_vocab_size), E),
                          t_dtype),
        "path_emb": init(k_path, (dims.padded(dims.path_vocab_size), E),
                         t_dtype),
        "target_emb": init(k_tgt, (dims.padded(dims.target_vocab_size), D),
                           t_dtype),
        "transform": init(k_tr, (D, D), dtype),
        "attention": init(k_at, (D, 1), dtype)[:, 0],
    }
    if quantized:
        # int8 + per-row scale for the two leaf-token tables;
        # target_emb stays bf16 (ops/quant.py module docstring)
        from code2vec_tpu.ops.quant import (QUANTIZED_TABLE_KEYS,
                                            quantize_table)
        for k in QUANTIZED_TABLE_KEYS:
            params[k] = quantize_table(params[k])
    if dims.encoder_type == "transformer":
        from code2vec_tpu.models.transformer_encoder import init_xf_params
        params["xf"] = init_xf_params(
            jax.random.fold_in(rng, 0x5f), dims)
    return params


def take_rows(params: Params, name: str, ids: jax.Array) -> jax.Array:
    """Embedding-row gather that understands the three table storages:
    plain float arrays, {"q","s"} int8 tables (no-grad dequantizing
    gather — eval/predict/serving), and {"q","s","g"} int8 tables with
    a gradient carrier attached by the quantized train step (the
    straight-through custom_vjp gather; ops/quant.py)."""
    t = params[name]
    if isinstance(t, dict):
        if "g" in t:
            from code2vec_tpu.ops.quant import quantized_take
            return quantized_take(t["g"], t, ids)
        # bf16 output, matching quantized_take (int8 rows carry <= 8
        # significant bits; f32 would double the activation traffic)
        return (jnp.take(t["q"], ids, axis=0).astype(jnp.float32)
                * jnp.take(t["s"], ids, axis=0)).astype(jnp.bfloat16)
    return jnp.take(t, ids, axis=0)


def encode(params: Params, source_ids: jax.Array, path_ids: jax.Array,
           target_ids: jax.Array, mask: jax.Array, *,
           dropout_rng: Optional[jax.Array] = None,
           dropout_keep_rate: float = 1.0,
           compute_dtype=jnp.float32,
           use_pallas: bool = False) -> Tuple[jax.Array, jax.Array]:
    """Forward to the code vector.

    Args: [B, C] int32 ids for source token / path / target token, [B, C]
    f32 mask. Returns (code_vectors [B, D] in compute dtype,
    attention [B, C] f32). use_pallas selects the fused Pallas pooling
    kernel (ops/pallas_attention.py).
    """
    src = take_rows(params, "token_emb", source_ids)
    pth = take_rows(params, "path_emb", path_ids)
    dst = take_rows(params, "token_emb", target_ids)
    contexts = jnp.concatenate([src, pth, dst], axis=-1).astype(compute_dtype)

    if dropout_rng is not None and dropout_keep_rate < 1.0:
        keep = jax.random.bernoulli(dropout_rng, dropout_keep_rate,
                                    contexts.shape)
        contexts = jnp.where(keep, contexts / dropout_keep_rate, 0.0)

    if use_pallas:
        from code2vec_tpu.ops.pallas_attention import attention_pool_fused
        code, attn = attention_pool_fused(
            contexts, params["transform"], params["attention"], mask)
        return code.astype(compute_dtype), attn
    return attention_pool(contexts, params["transform"],
                          params["attention"], mask)


def get_encode_fn(dims: ModelDims, mesh=None):
    """The encode callable for dims.encoder_type (same signature as
    `encode`); the jitted steps in training/steps.py close over it.
    `mesh` is only consumed by the transformer's ring-attention path
    (dims.ring_attention with a ctx axis > 1)."""
    if dims.encoder_type == "transformer":
        import functools

        from code2vec_tpu.models.transformer_encoder import (
            encode_transformer)
        return functools.partial(encode_transformer, dims=dims,
                                 mesh=mesh)
    return encode


def logits_vs_table(table: jax.Array, code_vectors: jax.Array,
                    true_target_vocab_size: Optional[int] = None
                    ) -> jax.Array:
    """[B, V] logits against a (possibly row-padded) target table.
    Padding rows are masked to -inf so they never win top-k."""
    table = table.astype(code_vectors.dtype)
    logits = (code_vectors @ table.T).astype(jnp.float32)
    if (true_target_vocab_size is not None
            and true_target_vocab_size < table.shape[0]):
        col = jnp.arange(table.shape[0])
        logits = jnp.where(col[None, :] < true_target_vocab_size,
                           logits, -1e9)
    return logits


def full_logits(params: Params, code_vectors: jax.Array,
                true_target_vocab_size: Optional[int] = None) -> jax.Array:
    return logits_vs_table(params["target_emb"], code_vectors,
                           true_target_vocab_size)
