"""The concrete JAX/TPU code2vec model.

Reference parity target: `tensorflow_model.Code2VecModel`
(SURVEY.md §3, §4.2–§4.5) — training loop with throughput logging,
evaluation with top-k + subtoken metrics, raw-line prediction with
attention output, checkpoint save/load/release, embedding export. The
compute path is the jitted steps in training/steps.py; this class is host
orchestration only.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Iterable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from code2vec_tpu.common import (EvaluationResults, MethodPredictionResults,
                                 SpecialVocabWords)
from code2vec_tpu.config import Config
from code2vec_tpu.data.reader import (BatchTensors, _pad_batch, open_reader,
                                      parse_c2v_rows)
from code2vec_tpu.models.encoder import ModelDims, init_params
from code2vec_tpu.models.model_base import Code2VecModelBase, MetricAccumulator
from code2vec_tpu.parallel.distributed import fetch_global
from code2vec_tpu.parallel.mesh import DATA_AXIS, DCN_AXIS, MODEL_AXIS
from code2vec_tpu.parallel.sharding import (shard_batch, shard_opt_state,
                                            shard_params)
from code2vec_tpu.training import checkpoint as ckpt
from code2vec_tpu.training.profiler import StepProfiler
from code2vec_tpu.training.steps import (make_encode_step, make_eval_step,
                                         make_predict_step, make_train_step)
from code2vec_tpu.vocab.vocabularies import Code2VecVocabs, VocabType


@dataclasses.dataclass
class PreparedRows:
    """Pre-parsed predict rows (the host half of `predict`): one row per
    method, un-padded leading dim. The serving micro-batcher coalesces
    several requests' rows with `concat` and runs ONE bucketed device
    call (`predict_prepared`), so parsing stays on the client threads
    and the device sees power-of-two batches only."""

    labels: "np.ndarray"
    src: "np.ndarray"
    pth: "np.ndarray"
    dst: "np.ndarray"
    mask: "np.ndarray"
    target_strings: List[str]
    context_strings: List[List[str]]

    @property
    def n(self) -> int:
        return int(self.labels.shape[0])

    def slice(self, start: int, stop: int) -> "PreparedRows":
        """Row slice [start, stop) — numpy views, no copy. Used to
        chunk an oversized request to the batcher's max_batch."""
        if start == 0 and stop >= self.n:
            return self
        return PreparedRows(
            self.labels[start:stop], self.src[start:stop],
            self.pth[start:stop], self.dst[start:stop],
            self.mask[start:stop], self.target_strings[start:stop],
            self.context_strings[start:stop])

    @staticmethod
    def concat(items: Sequence["PreparedRows"]) -> "PreparedRows":
        assert items
        if len(items) == 1:
            return items[0]
        return PreparedRows(
            labels=np.concatenate([p.labels for p in items]),
            src=np.concatenate([p.src for p in items]),
            pth=np.concatenate([p.pth for p in items]),
            dst=np.concatenate([p.dst for p in items]),
            mask=np.concatenate([p.mask for p in items]),
            target_strings=[s for p in items for s in p.target_strings],
            context_strings=[c for p in items for c in p.context_strings])


class Code2VecModel(Code2VecModelBase):
    def __init__(self, config: Config):
        super().__init__(config)
        cfg = config
        self.log = cfg.log
        self.compute_dtype = jnp.bfloat16 if cfg.USE_BF16 else jnp.float32
        # Pallas kernels are TPU-only; fall back to the XLA pool
        # elsewhere (tests run on the virtual CPU mesh).
        self.use_pallas = (cfg.USE_PALLAS
                           and jax.default_backend() == "tpu")

        # ---- mesh (SURVEY.md §3.3): data axis for DP, model axis for
        # sharded vocab tables; single-device runs use no mesh. ----
        from code2vec_tpu.models.setup import build_mesh, build_optimizer
        self.mesh = build_mesh(cfg)
        model_axis = max(1, cfg.MESH_MODEL_AXIS)
        self.shard_contexts = max(1, cfg.MESH_CONTEXT_AXIS) > 1

        if cfg.is_loading:
            # Dims come from the checkpoint manifest, not the CLI: a model
            # trained with different max_contexts / pad multiple must
            # restore bit-exactly regardless of current flags.
            self.dims = ckpt.load_dims(cfg.load_path)
            cfg.MAX_CONTEXTS = self.dims.max_contexts
            manifest = ckpt.load_manifest(cfg.load_path)
            cfg.USE_SAMPLED_SOFTMAX = manifest.get(
                "use_sampled_softmax", cfg.USE_SAMPLED_SOFTMAX)
            cfg.NUM_SAMPLED_CLASSES = manifest.get(
                "num_sampled", cfg.NUM_SAMPLED_CLASSES)
            cfg.SPARSE_EMBEDDING_UPDATES = manifest.get(
                "sparse_embedding_updates", cfg.SPARSE_EMBEDDING_UPDATES)
            cfg.TABLES_DTYPE = self.dims.tables_dtype
            # fallback "adam", NOT the current default: checkpoints
            # predating the manifest key were trained with Adam, and an
            # adafactor template would fail orbax structure matching
            cfg.EMBEDDING_OPTIMIZER = manifest.get(
                "embedding_optimizer", "adam")
            # trust_ratio changes opt_state structure exactly like the
            # optimizer choice does; pre-round-4 checkpoints never had it
            cfg.TRUST_RATIO = manifest.get("trust_ratio", False)
            cfg.TRUST_RATIO_SCOPE = manifest.get("trust_ratio_scope",
                                                 "all")
            from code2vec_tpu.training.optimizers import (
                resolve_checkpoint_schedule, resolve_checkpoint_warmup)
            cfg.LR_SCHEDULE = resolve_checkpoint_schedule(
                cfg.LR_SCHEDULE, manifest, cfg.log)
            cfg.LR_WARMUP_STEPS = resolve_checkpoint_warmup(
                cfg.LR_SCHEDULE, cfg.LR_WARMUP_STEPS, manifest, cfg.log)
        else:
            self.dims = ModelDims(
                token_vocab_size=self.vocabs.token_vocab.size,
                path_vocab_size=self.vocabs.path_vocab.size,
                target_vocab_size=self.vocabs.target_vocab.size,
                embeddings_size=cfg.DEFAULT_EMBEDDINGS_SIZE,
                max_contexts=cfg.MAX_CONTEXTS,
                dropout_keep_rate=cfg.DROPOUT_KEEP_RATE,
                vocab_pad_multiple=model_axis,
                tables_dtype=cfg.TABLES_DTYPE,
                encoder_type=cfg.ENCODER_TYPE,
                xf_layers=cfg.XF_LAYERS,
                xf_heads=cfg.XF_HEADS,
                xf_remat=cfg.XF_REMAT,
                ring_attention=cfg.RING_ATTENTION,
            )
        if self.dims.tables_dtype == "int8" and self.mesh is not None:
            # data-parallel meshes replicate the quantized tables and
            # psum the carrier grads — supported (tested on the virtual
            # 8-device mesh). Model/context sharding of {q, s} subtrees
            # is not: verify() rejects the explicit flags, this catches
            # an implicit multi-axis mesh. Checked against
            # self.dims.tables_dtype AFTER the is_loading block: the
            # manifest overrides cfg.TABLES_DTYPE there, so a
            # programmatic Config loading an int8 checkpoint (bypassing
            # code2vec.py's manifest pre-read) must not slip past the
            # backstop into shard_params' untested row-sharding
            # (ADVICE r5 finding 1).
            shape = dict(zip(self.mesh.axis_names, self.mesh.devices.shape))
            if shape.get("model", 1) > 1 or shape.get("ctx", 1) > 1:
                raise ValueError(
                    "--tables_dtype int8 supports data-parallel meshes "
                    f"only; got mesh {shape}")
        # --sparse_embeddings under a mesh runs the compact
        # dedup/segment-sum/live-row apply inside shard_map
        # (sparse_update.mesh_sparse_apply, round 14) for f32/bf16 AND
        # int8 tables — the round-13 f32-only dense-carrier restriction
        # is gone with the carrier itself. int8 stays fenced to
        # data-parallel meshes by the guard above (shared with the
        # non-sparse quantized step).

        def n_train_examples() -> int:
            # dict pickle already carries the count; rescan the file
            # only for foreign datasets missing it
            n = self.vocabs.num_training_examples
            if not n:
                from code2vec_tpu.data.reader import count_examples
                n = count_examples(cfg.data_path("train"))
            return n

        self._n_train_examples = n_train_examples
        self.optimizer = build_optimizer(
            cfg, n_train_examples,
            manifest if cfg.is_loading else None)
        self.rng = jax.random.PRNGKey(cfg.SEED)

        # ---- params: load (--load) or init ----
        self.step_num = 0
        self.rng, init_rng = jax.random.split(self.rng)
        params = init_params(init_rng, self.dims)
        if cfg.SPARSE_EMBEDDING_UPDATES:
            # Config.verify() enforces this for CLI runs; assert here so
            # programmatic Config users get a clear error instead of an
            # optax chain-state mismatch (adafactor became the default
            # table optimizer in round 3, sparse_steps is adam-only).
            assert cfg.EMBEDDING_OPTIMIZER == "adam", (
                "SPARSE_EMBEDDING_UPDATES requires "
                "EMBEDDING_OPTIMIZER='adam'")
            assert cfg.LR_SCHEDULE == "constant", (
                "SPARSE_EMBEDDING_UPDATES requires "
                "LR_SCHEDULE='constant' (the row-update kernel applies "
                "a fixed per-row learning rate)")
            from code2vec_tpu.training.sparse_steps import (
                init_sparse_opt_state)
            opt_state = init_sparse_opt_state(params, self.optimizer,
                                              cfg.USE_SAMPLED_SOFTMAX)
        else:
            opt_state = self.optimizer.init(self._opt_param_view(params))
        if cfg.is_loading:
            if manifest.get("released"):
                loaded = ckpt.load_checkpoint(cfg.load_path,
                                              {"params": params})
                params = loaded["params"]
                # A released checkpoint carries no optimizer state; keep
                # the freshly-initialized opt_state built above — it
                # already matches the train step's expected structure
                # (sparse dict vs optax Adam, per the manifest override).
                self.step_num = int(manifest.get("step", 0))
            else:
                full = ckpt.load_checkpoint(
                    cfg.load_path, {"params": params,
                                    "opt_state": opt_state,
                                    "step": 0})
                params, opt_state = full["params"], full["opt_state"]
                self.step_num = int(full.get("step", 0))
        if self.mesh is not None:
            params = shard_params(self.mesh, params)
            opt_state = shard_opt_state(self.mesh, opt_state, params)
        self.params, self.opt_state = params, opt_state

        # ---- jitted steps (make_train_step owns the sparse-vs-dense
        # dispatch; Config.verify gates the combinations) ----
        augment_fn = None
        if cfg.ADV_RENAME_PROB > 0:
            # adversarial-training defense (attacks/defense.py)
            from code2vec_tpu.attacks.defense import (
                legal_token_mask, make_rename_augment)
            augment_fn = make_rename_augment(
                legal_token_mask(self.vocabs.token_vocab, self.dims),
                cfg.ADV_RENAME_PROB, mode=cfg.ADV_RENAME_MODE)
        from code2vec_tpu.ops.quant import resolve_requant_mode
        from code2vec_tpu.training.sparse_update import \
            resolve_sparse_update_mode
        self._train_step = make_train_step(
            self.dims, self.optimizer,
            use_sampled_softmax=cfg.USE_SAMPLED_SOFTMAX,
            num_sampled=cfg.NUM_SAMPLED_CLASSES,
            compute_dtype=self.compute_dtype,
            use_pallas=self.use_pallas, mesh=self.mesh,
            augment_fn=augment_fn,
            requant_fused=resolve_requant_mode(cfg.REQUANT_PALLAS),
            sparse_updates=cfg.SPARSE_EMBEDDING_UPDATES,
            learning_rate=cfg.LEARNING_RATE,
            sparse_update_fused=resolve_sparse_update_mode(
                cfg.SPARSE_UPDATE_PALLAS))
        # background checkpoint writer (--async_checkpoint, default on):
        # created lazily at the first save so load/predict-only model
        # instances never start the thread
        self._ckpt_writer: Optional[ckpt.AsyncCheckpointWriter] = None
        top_k = cfg.TOP_K_WORDS_CONSIDERED_DURING_PREDICTION
        self._eval_step = make_eval_step(self.dims, top_k=top_k,
                                         compute_dtype=self.compute_dtype,
                                         use_pallas=self.use_pallas,
                                         mesh=self.mesh)
        self._predict_step = make_predict_step(
            self.dims, top_k=top_k, compute_dtype=self.compute_dtype,
            use_pallas=self.use_pallas, mesh=self.mesh)

    # ---- vocabs: dataset dict when training, checkpoint sidecar when
    # loading (SURVEY.md §3.2 "Model checkpoint") ----
    def _load_or_create_vocabs(self) -> Code2VecVocabs:
        cfg = self.config
        if cfg.is_loading:
            return ckpt.load_vocabs(cfg.load_path)
        assert cfg.word_freq_dict_path is not None, (
            "need --data (for its .dict.c2v) or --load")
        return Code2VecVocabs.load_from_dict_file(
            cfg.word_freq_dict_path, cfg.MAX_TOKEN_VOCAB_SIZE,
            cfg.MAX_PATH_VOCAB_SIZE, cfg.MAX_TARGET_VOCAB_SIZE)

    # ---- helpers ----
    def _host_batch_arrays(self, b: BatchTensors):
        """The 6 numpy arrays of one batch (pre-transfer form — shared
        by the per-batch and chunked infeeds)."""
        weights = np.zeros((b.target_index.shape[0],), dtype=np.float32)
        weights[:b.num_valid_examples] = 1.0
        return (b.target_index, b.path_source_token_indices,
                b.path_indices, b.path_target_token_indices,
                b.context_valid_mask, weights)

    def _device_batch(self, b: BatchTensors, process_local: bool = True):
        """process_local=True for training (each host contributes its own
        shard; global batch scales with host count), False for eval and
        predict (all hosts feed the same batch)."""
        arrays = self._host_batch_arrays(b)
        if self.mesh is not None:
            return shard_batch(self.mesh, arrays,
                               process_local=process_local,
                               shard_contexts=self.shard_contexts)
        # materialize on device HERE (async dispatch) — without this
        # the arrays ride into the jitted step as numpy and the
        # transfer happens on the MAIN thread at call time, making the
        # prefetch thread parse-only (round-4 infeed A/B finding)
        return tuple(jnp.asarray(a) for a in arrays)

    def _train_infeed(self, reader, instrument=None, heartbeat=None):
        from code2vec_tpu.data.prefetch import build_train_infeed
        return build_train_infeed(
            reader, chunk=self.config.INFEED_CHUNK,
            depth=self.config.INFEED_PREFETCH, mesh=self.mesh,
            host_arrays_fn=self._host_batch_arrays,
            device_batch_fn=self._device_batch, log=self.log,
            instrument=instrument, heartbeat=heartbeat)


    def _ids_to_words(self, topk_ids: np.ndarray) -> List[List[str]]:
        tv = self.vocabs.target_vocab
        return [[tv.lookup_word(int(i)) for i in row] for row in topk_ids]

    # ---- train (SURVEY.md §4.2) ----
    def train(self) -> None:
        cfg = self.config
        # auto-resume (ISSUE 10): the ONE shared epoch-offset
        # arithmetic (models/setup.py — the recovery contract both
        # heads must agree on)
        from code2vec_tpu.models.setup import (infeed_split,
                                               resume_epoch_offset)
        completed_epochs = resume_epoch_offset(
            cfg, self.step_num, self._n_train_examples, self.log)
        # per-host infeed split from the LIVE process set (ISSUE 13):
        # a supervisor-re-formed cohort re-deals the same global
        # stream over however many survivors joined this launch
        host_shard, num_host_shards = infeed_split()
        reader = open_reader(
            cfg.data_path("train"), self.vocabs, cfg.MAX_CONTEXTS,
            cfg.TRAIN_BATCH_SIZE, shuffle=True, seed=cfg.SEED,
            host_shard=host_shard, num_host_shards=num_host_shards,
            epoch_offset=completed_epochs)
        self.log(f"starting training: dims={self.dims}, "
                 f"devices={len(jax.devices())}, mesh={self.mesh}")
        window_examples = 0
        window_start = time.time()
        profiler = StepProfiler(cfg.PROFILE_DIR, cfg.PROFILE_START_STEP,
                                cfg.PROFILE_STEPS, self.log)
        from code2vec_tpu.training.scalars import ScalarWriter
        scalars = ScalarWriter(cfg.TENSORBOARD_DIR
                               if jax.process_index() == 0 else None)
        # Unified run telemetry (code2vec_tpu/obs/): per-step
        # step_ms/infeed_wait_ms/loss events + device-memory gauges when
        # --telemetry_dir is set; the disabled path is one boolean check
        # per step (recorder.enabled) and wrap() returns the infeed
        # unchanged.
        from code2vec_tpu.obs import (SpanChannel, Telemetry, Tracer,
                                      TrainStepRecorder, Watchdog,
                                      build_live_plane)
        telemetry = Telemetry.create(
            cfg.TELEMETRY_DIR, config=cfg, mesh=self.mesh,
            component="train", scalar_writer=scalars, log=self.log)
        if cfg.METRICS_PORT > 0 and not telemetry.enabled:
            # --metrics_port without --telemetry_dir: live pull-based
            # exposition over an in-memory registry (scrapeable run,
            # no JSONL persistence; per-step recording — and its
            # documented device-sync trade — applies either way)
            telemetry = Telemetry.memory("train")
        self.telemetry = telemetry
        live_plane = cfg.METRICS_PORT > 0 or cfg.ALERTS_MODE != "off"
        if (cfg.ASYNC_CHECKPOINT or cfg.TRACE
                or cfg.WATCHDOG_STALL_S > 0 or live_plane):
            # the checkpoint writer, the infeed producer (trace spans),
            # the watchdog/health monitors and the exposition handler
            # all record into / read this registry from other threads
            telemetry.make_threadsafe()
        # request-scoped tracing (--trace) + stall watchdog
        # (--watchdog_stall_s): per-step span trees linking the infeed
        # batch consumed and the async save triggered, and liveness
        # deadlines on the loop / infeed producer / checkpoint writer.
        # Off (the defaults), both are shared no-op singletons.
        tracer = Tracer.create(telemetry) if cfg.TRACE \
            else Tracer.disabled()
        self.tracer = tracer
        watchdog = Watchdog.create(
            telemetry, stall_s=cfg.WATCHDOG_STALL_S,
            mode=cfg.WATCHDOG_MODE, tracer=tracer, log=self.log)
        loop_hb = watchdog.register("train_loop")
        self._ckpt_heartbeat = watchdog.register("checkpoint_writer")
        # live metrics plane (ISSUE 7): health monitors + alert rules
        # swept on a cadence thread OFF the hot path, and the
        # /metrics //healthz //vars exposition server — one shared
        # wiring (obs/exposition.build_live_plane); no-op singletons
        # when the flags are off.
        from code2vec_tpu.obs.alerts import default_train_rules
        from code2vec_tpu.obs.health import default_train_monitors
        plane = build_live_plane(
            telemetry, metrics_port=cfg.METRICS_PORT,
            alerts_mode=cfg.ALERTS_MODE,
            alerts_rules=cfg.ALERTS_RULES,
            health_every_s=cfg.HEALTH_EVERY_S, watchdog=watchdog,
            monitors=default_train_monitors(),
            default_rules=default_train_rules,
            # identity block on /vars (ISSUE 17): the fleet collector
            # labels this member and keys its restart re-handshake on
            # run_id changes
            identity={"process_index": jax.process_index(),
                      "process_count": jax.process_count()},
            log=self.log)
        alerts = plane.alerts
        self.metrics_server = plane.metrics
        infeed_channel = SpanChannel() if tracer.enabled else None
        recorder = TrainStepRecorder(
            telemetry, gauge_every=cfg.NUM_BATCHES_TO_LOG_PROGRESS,
            tracer=tracer, infeed_channel=infeed_channel,
            heartbeat=loop_hb if watchdog.enabled else None,
            alerts=alerts if alerts.enabled else None)
        self._trace_recorder = recorder
        watchdog.start()
        plane.start()
        # tools/obs_top.py derives pc/s = examples-rate x this gauge
        # (static: a set-once config echo must not read as stale)
        telemetry.gauge("train/max_contexts", cfg.MAX_CONTEXTS,
                        emit=False, static=True)
        model_shards = 1 if self.mesh is None else \
            int(self.mesh.shape.get(MODEL_AXIS, 1))
        # shared analytic-model inputs (the floor gauges below AND the
        # phase comparator): derived once so the two planes cannot
        # disagree about the same quantity
        ns = cfg.NUM_SAMPLED_CLASSES if cfg.USE_SAMPLED_SOFTMAX else 0
        if self.mesh is None:
            data_shards = 1
        else:
            data_shards = max(1, int(
                self.mesh.shape.get(DCN_AXIS, 1)
                * self.mesh.shape.get(DATA_AXIS, 1)))
        procs = jax.process_count()
        if cfg.SPARSE_EMBEDDING_UPDATES and model_shards == 1:
            # live optimizer-efficiency plane (round 13): publish the
            # [U, E]-aware analytic step floor once; the health
            # engine's opt_efficiency monitor divides it by the
            # observed p50 step time every sweep, so a step-time
            # regression is visible on /metrics and tools/obs_top.py
            # mid-run, not just at bench time. (Static: analytic
            # facts, not heartbeats. Data-parallel meshes publish the
            # PER-DEVICE model — round 14: forward/backward
            # per-occurrence traffic covers the device's batch shard,
            # the apply phase covers the all-gathered GLOBAL list
            # mesh_sparse_apply replicates — which is the standing
            # assertion that no dense [V, E] carrier exists on the
            # data-parallel sparse path. Row-sharded tables
            # (model axis > 1) publish nothing: the window-masked
            # apply is not described by this model, and without the
            # gauge the monitor correctly stays 'unknown' instead of
            # reading false-good/bad.)
            from code2vec_tpu.training.sparse_update import (
                sparse_step_floor_bytes, sparse_update_phase_bytes)
            step_bytes = sparse_step_floor_bytes(
                self.params, cfg.TRAIN_BATCH_SIZE, cfg.MAX_CONTEXTS,
                num_sampled=ns, data_shards=data_shards,
                processes=procs)
            upd_bytes = sparse_update_phase_bytes(
                self.params, cfg.TRAIN_BATCH_SIZE, cfg.MAX_CONTEXTS,
                num_sampled=ns, processes=procs)
            ceiling = cfg.HBM_CEILING_GBPS * 1e9
            telemetry.gauge("train/step_floor_ms",
                            step_bytes / ceiling * 1e3, emit=False,
                            static=True)
            telemetry.gauge("train/sparse_update_bytes", upd_bytes,
                            emit=False, static=True)
            telemetry.gauge("train/sparse_update_floor_ms",
                            upd_bytes / ceiling * 1e3, emit=False,
                            static=True)
        # sampled phase attribution (--phase_profile, ISSUE 15): every
        # PHASE_SAMPLE_EVERY steps one step dispatches phase-split
        # (synced probe prefixes for attribution, the fused step for
        # the state update — trajectory bit-identical to unprofiled);
        # off, the loop pays one boolean check per step. Probes build
        # + warm lazily at the first sampled step.
        from code2vec_tpu.obs.phases import PhaseProfiler
        phase_kw = {}
        if cfg.PHASE_PROFILE == "on" and telemetry.enabled \
                and model_shards == 1:
            # the analytic per-phase comparator (model-sharded tables
            # are not described by it — same rule as the floor gauges
            # above: no gauge beats a false one)
            from code2vec_tpu.training.sparse_update import \
                phase_traffic_bytes
            phase_kw["phase_bytes"] = phase_traffic_bytes(
                self.params, cfg.TRAIN_BATCH_SIZE, cfg.MAX_CONTEXTS,
                num_sampled=ns, sparse=cfg.SPARSE_EMBEDDING_UPDATES,
                data_shards=data_shards, processes=procs)
            phase_kw["ceiling_gbps"] = cfg.HBM_CEILING_GBPS

        def _phase_probes():
            from code2vec_tpu.training.phase_probes import \
                make_code2vec_probes
            return make_code2vec_probes(
                self.dims, self.optimizer,
                use_sampled_softmax=cfg.USE_SAMPLED_SOFTMAX,
                num_sampled=cfg.NUM_SAMPLED_CLASSES,
                compute_dtype=self.compute_dtype,
                use_pallas=self.use_pallas, mesh=self.mesh,
                sparse_updates=cfg.SPARSE_EMBEDDING_UPDATES)

        phase_profiler = PhaseProfiler.create(
            telemetry, fused_step=self._train_step,
            probes_factory=_phase_probes,
            enabled=cfg.PHASE_PROFILE == "on",
            sample_every=cfg.PHASE_SAMPLE_EVERY, log=self.log,
            **phase_kw)
        loop_hb.busy()  # the first deadline covers step-0 compile too
        steps_into_training = 0
        # Double-buffered infeed (SURVEY.md §3.3): host parse +
        # host->device transfer of batch k+1 overlap step k on a daemon
        # thread; the loop below never blocks on the host between steps.
        # persistent_epochs keeps the SAME producer thread warm across
        # epoch boundaries (it parses/transfers epoch k+1 while the
        # boundary save + eval run) instead of cold-restarting it and
        # re-filling the double buffer each epoch.
        from code2vec_tpu.data.prefetch import persistent_epochs
        from code2vec_tpu.obs import infeed_produce_instrument
        infeed_hb = watchdog.register("infeed_producer")
        infeed = self._train_infeed(
            reader,
            instrument=infeed_produce_instrument(tracer, infeed_channel),
            heartbeat=infeed_hb if watchdog.enabled else None)
        # chaos failpoints (--faults, ISSUE 10): disarmed — the default
        # — each is one attribute read per step (the obs discipline)
        from code2vec_tpu.resilience import faults, retry
        if telemetry.enabled:
            retry.set_telemetry(telemetry)
        nan_fp, kill_fp = faults.train_step_points()
        try:
            for epoch, epoch_batches in persistent_epochs(
                    infeed, cfg.NUM_TRAIN_EPOCHS,
                    first_epoch=completed_epochs + 1):
                for dev_batch, batch in recorder.wrap(epoch_batches):
                    profiler.tick(steps_into_training, self.params)
                    # step rng keyed on the ABSOLUTE step (not a
                    # sequentially split stream): a run killed at step
                    # k and auto-resumed draws the same dropout /
                    # sampling keys the uninterrupted run would —
                    # recovery replays the trajectory bit-for-bit
                    step_rng = jax.random.fold_in(self.rng,
                                                  self.step_num)
                    if phase_profiler.enabled \
                            and phase_profiler.should_sample(
                                steps_into_training):
                        # sampled: probes first (measurement-only),
                        # then the fused dispatch for the real update
                        self.params, self.opt_state, loss = \
                            phase_profiler.run_split(
                                self.params, self.opt_state, dev_batch,
                                step_rng, step=self.step_num,
                                infeed_wait_ms=recorder.infeed_wait_ms
                                if recorder.enabled else None,
                                recorder=recorder
                                if recorder.enabled else None)
                    else:
                        self.params, self.opt_state, loss = \
                            self._train_step(self.params,
                                             self.opt_state, dev_batch,
                                             step_rng)
                    if nan_fp.armed and nan_fp.hit():
                        loss = loss * float("nan")  # poison the loss
                    if kill_fp.armed:
                        kill_fp.fire(step=self.step_num + 1)
                    self.step_num += 1
                    steps_into_training += 1
                    window_examples += batch.num_valid_examples
                    loss_f = (recorder.end_step(self.step_num, loss,
                                                batch.num_valid_examples,
                                                params=self.params)
                              if recorder.enabled else None)
                    if self.step_num % cfg.NUM_BATCHES_TO_LOG_PROGRESS == 0:
                        if loss_f is None:
                            # device sync only on log steps
                            loss_f = float(loss)
                        dt = time.time() - window_start
                        ex_s = window_examples / max(dt, 1e-9)
                        # path-contexts/sec = examples/sec * MAX_CONTEXTS —
                        # the BASELINE.json metric (SURVEY.md §4.2).
                        self.log(
                            f"epoch {epoch} step {self.step_num}: "
                            f"loss {loss_f:.4f}, {ex_s:.1f} ex/s, "
                            f"{ex_s * cfg.MAX_CONTEXTS:.0f} path-contexts/s")
                        scalars.write(self.step_num, {
                            "train/loss": loss_f,
                            "train/examples_per_sec": ex_s,
                            "train/path_contexts_per_sec":
                                ex_s * cfg.MAX_CONTEXTS})
                        window_examples, window_start = 0, time.time()
                epoch_end_work = False
                if cfg.is_saving and epoch % cfg.SAVE_EVERY_EPOCHS == 0:
                    # kick the save FIRST (async: returns after the
                    # snapshot) so eval below runs while the writer drains —
                    # boundary cost ~ max(eval, save tail), not save + eval
                    self._save_epoch = epoch  # -> step topology record
                    self.save(cfg.save_path, block=False)
                    epoch_end_work = True
                if cfg.is_testing and epoch % cfg.SAVE_EVERY_EPOCHS == 0:
                    eval_span = telemetry.span("train/eval_ms")
                    try:
                        results = self.evaluate()
                    except BaseException:
                        eval_span.cancel()  # dead eval: drop, don't leak
                        raise
                    eval_ms = eval_span.stop()
                    self.log(f"epoch {epoch} evaluation: {results}")
                    scalars.write(self.step_num, {
                        "eval/loss": results.loss,
                        "eval/top1": results.topk_acc[0],
                        "eval/subtoken_f1": results.subtoken_f1,
                        "eval/subtoken_precision": results.subtoken_precision,
                        "eval/subtoken_recall": results.subtoken_recall})
                    telemetry.event("eval", epoch=epoch, step=self.step_num,
                                    loss=results.loss,
                                    subtoken_f1=results.subtoken_f1,
                                    eval_ms=round(eval_ms, 3))
                    epoch_end_work = True
                if epoch_end_work:
                    # boundary work is progress: re-arm the loop's
                    # deadline so a long save/eval doesn't read as a
                    # stall (size --watchdog_stall_s above eval time)
                    loop_hb.beat()
                    # reset the throughput window: checkpoint + eval wall
                    # time must not be silently absorbed into the next
                    # epoch's first ex/s figure
                    window_examples, window_start = 0, time.time()
            if self._ckpt_writer is not None:
                # hard commit barrier: training is not done until the last
                # checkpoint's `state` rename committed (re-raises a
                # background write failure)
                self._ckpt_writer.wait()
            watchdog.poll()  # raise-mode: a stalled run dies loudly here
            alerts.poll()    # raise-mode: so does a firing alert
        finally:
            loop_hb.idle()
            watchdog.stop()  # no re-raise: must not mask loop errors
            plane.stop()
            if self._ckpt_writer is not None:
                # exception-path teardown: drain without
                # masking the in-flight error (a sticky
                # write failure still re-raises at the next
                # submit/wait/close)
                self._ckpt_writer.drain_quiet()
        profiler.finish(self.params)
        telemetry.close()
        scalars.close()
        self.log("training done")

    def _my_global_rows(self, local_batch_size: int) -> np.ndarray:
        """Positions of THIS host's rows inside the global batch built by
        shard_batch(process_local=True), discovered empirically (a tag
        array round-trip) rather than assumed from device order; cached —
        the layout is fixed for a given mesh and batch size."""
        key = (local_batch_size,)
        if getattr(self, "_row_map", None) is None:
            self._row_map = {}
        if key not in self._row_map:
            tags = np.full((local_batch_size,), jax.process_index(),
                           np.int32)
            gtags = fetch_global(shard_batch(
                self.mesh, (tags,), process_local=True)[0])
            pos = np.nonzero(gtags == jax.process_index())[0]
            assert len(pos) == local_batch_size
            self._row_map[key] = pos
        return self._row_map[key]

    # ---- evaluate (SURVEY.md §4.3) ----
    def evaluate(self) -> EvaluationResults:
        cfg = self.config
        assert cfg.test_data_path, "evaluate requires --test"
        multi = jax.process_count() > 1
        # Multi-host: each host parses and feeds a DISJOINT shard of the
        # eval file (global eval batch = H x TEST_BATCH_SIZE), decodes
        # only its own rows, and the metric partials are summed across
        # hosts at the end — no redundant parsing, eval scales with H.
        reader = open_reader(
            cfg.test_data_path, self.vocabs, cfg.MAX_CONTEXTS,
            cfg.TEST_BATCH_SIZE, shuffle=False, keep_strings=True,
            host_shard=jax.process_index() if multi else 0,
            num_host_shards=jax.process_count() if multi else 1)
        acc = MetricAccumulator(
            cfg.TOP_K_WORDS_CONSIDERED_DURING_PREDICTION)
        from code2vec_tpu.data.prefetch import prefetch_to_device
        infeed = prefetch_to_device(
            reader, lambda b: self._device_batch(b, process_local=multi),
            cfg.INFEED_PREFETCH)
        for dev_batch, batch in infeed:
            loss_sum, topk_ids, _ = self._eval_step(self.params, dev_batch)
            nv = batch.num_valid_examples
            names = (batch.target_strings[:nv] if batch.target_strings
                     else [self.vocabs.target_vocab.lookup_word(int(i))
                           for i in batch.target_index[:nv]])
            topk_global = fetch_global(topk_ids)
            if multi:
                mine = self._my_global_rows(batch.target_index.shape[0])
                topk_global = topk_global[mine]
            words = self._ids_to_words(topk_global[:nv])
            # loss_sum is computed over the GLOBAL batch (weights mask
            # padding), identical on every host — count it once.
            acc.update_batch(names, words,
                             float(loss_sum)
                             if (not multi or jax.process_index() == 0)
                             else 0.0)
        if multi:
            acc.merge_across_hosts()
        return acc.results()

    # ---- predict raw extractor lines (SURVEY.md §4.4) ----
    def prepare_predict_rows(self, predict_data_lines: Iterable[str]
                             ) -> PreparedRows:
        """Host half of `predict`: raw extractor lines -> un-padded
        per-method index rows. Pure host work — the serving layer runs
        this on client threads so the batcher thread only touches the
        device. Timed as `serve/parse_ms` (the pre-split `encode_ms`
        covered parse + pad; the phases now report separately)."""
        parse_span = self.telemetry.span("serve/parse_ms")
        try:
            lines = [ln for ln in predict_data_lines if ln.strip()]
            labels, src, pth, dst, mask, tstr, cstr = parse_c2v_rows(
                lines, self.vocabs, self.config.MAX_CONTEXTS,
                keep_strings=True)
        except BaseException:
            # a malformed row must not leak the span, and a dead parse
            # must not land in the parse_ms histogram
            parse_span.cancel()
            raise
        parse_span.stop()
        return PreparedRows(labels, src, pth, dst, mask, tstr, cstr)

    def predict_bucket_size(self, n: int) -> int:
        """Padded leading dim for an `n`-method predict batch: the next
        power of two (the jitted step compiles O(log n) variants instead
        of one per method count), rounded up to a multiple of the data
        axis when a mesh shards the batch."""
        padded_n = max(1, 1 << (n - 1).bit_length())
        if self.mesh is not None:
            # batch dim must divide the data axis to shard over the mesh
            # batch shards over ('dcn','data') jointly
            dax = self.mesh.shape[DATA_AXIS] * self.mesh.shape[DCN_AXIS]
            padded_n = -(-padded_n // dax) * dax
        return padded_n

    def warmup_predict(self, max_batch: int) -> List[int]:
        """Pre-compile the predict step's shape buckets up to (and
        including) `max_batch`'s bucket, so steady-state serving
        triggers zero new jit compilations. Returns the bucket sizes."""
        buckets = sorted({self.predict_bucket_size(n)
                          for n in [1 << i for i in range(
                              max(1, max_batch).bit_length())]
                          + [max(1, max_batch)]})
        # Commit the params to their current placement BEFORE the
        # warmup compiles: a hot weight swap restores COMMITTED arrays
        # (orbax restores to explicit shardings), and jit keys on
        # committedness — warming up against uncommitted init params
        # would make every post-swap batch a recompile.
        self.params = jax.tree_util.tree_map(
            lambda x: jax.device_put(x, x.sharding)
            if hasattr(x, "sharding") else x, self.params)
        for b in buckets:
            batch = (np.zeros((b,), np.int32),
                     np.zeros((b, self.dims.max_contexts), np.int32),
                     np.zeros((b, self.dims.max_contexts), np.int32),
                     np.zeros((b, self.dims.max_contexts), np.int32),
                     np.zeros((b, self.dims.max_contexts), np.float32),
                     np.zeros((b,), np.float32))
            if self.mesh is not None:
                batch = shard_batch(self.mesh, batch, process_local=False)
            out = self._predict_step(self.params, batch)
            jax.block_until_ready(out)
        return buckets

    def predict_compile_count(self) -> int:
        """Number of compiled predict-step variants (-1 when the
        backend's jit cache is not introspectable). Serving asserts this
        stays flat after `warmup_predict` — the zero-new-compilations
        acceptance check."""
        try:
            return int(self._predict_step._cache_size())
        except Exception:
            return -1

    def predict_device(self, prepared: PreparedRows):
        """Device phase of `predict`: pad the rows to their
        power-of-two bucket, run the jitted step once, fetch. Returns
        host arrays `(topk_ids, topk_probs, attention, code)` trimmed
        to `prepared.n` rows — decoding is a separate host phase
        (`decode_predictions`) so the serving batcher can fan it out to
        client threads instead of serializing it after every batch."""
        n = prepared.n
        # host phase: rows -> padded device batch (serve/encode_ms).
        # Trace spans (--trace) parent implicitly to the batcher's
        # serve/batch_flush span (thread-local current — this runs ON
        # the batcher thread when serving); off = one boolean check.
        tracing = self.tracer.enabled
        encode_span = self.telemetry.span("serve/encode_ms")
        t_encode = self.tracer.start_span("serve/encode", n=n) \
            if tracing else None
        try:
            padded_n = self.predict_bucket_size(n)
            weights = np.zeros((padded_n,), dtype=np.float32)
            weights[:n] = 1.0
            labels, src, pth, dst, mask = _pad_batch(
                (prepared.labels, prepared.src, prepared.pth,
                 prepared.dst, prepared.mask), padded_n)
            batch = (labels, src, pth, dst, mask, weights)
            if self.mesh is not None:
                batch = shard_batch(self.mesh, batch,
                                    process_local=False)
        except BaseException:
            # close on the error path too: an un-ended trace span sits
            # in the live-span table forever, and the batcher thread
            # serves many more requests after this one dies
            if t_encode is not None:
                t_encode.end()
            encode_span.cancel()
            raise
        if t_encode is not None:
            t_encode.end()
        encode_span.stop()
        # device phase: jitted step + host fetch (serve/predict_ms; the
        # fetch_global transfers are the device sync)
        predict_span = self.telemetry.span("serve/predict_ms")
        t_device = self.tracer.start_span("serve/device",
                                          padded_n=padded_n) \
            if tracing else None
        try:
            topk_ids, topk_probs, attn, code = self._predict_step(
                self.params, batch)
            topk_ids = fetch_global(topk_ids)[:n]
            topk_probs = fetch_global(topk_probs)[:n]
            attn = fetch_global(attn)[:n]
            code = fetch_global(code)[:n]
        except BaseException:
            if t_device is not None:
                t_device.end()
            predict_span.cancel()
            raise
        if t_device is not None:
            t_device.end()
        predict_span.stop()
        return topk_ids, topk_probs, attn, code

    def decode_predictions(self, prepared: PreparedRows, device_out
                           ) -> List[MethodPredictionResults]:
        """Host decode of `predict_device` output rows (row i of
        `device_out` is row i of `prepared`): vocab lookups + the
        attention-ranked path-contexts for interpretability."""
        cfg = self.config
        topk_ids, topk_probs, attn, code = device_out
        results = []
        for i, original in enumerate(prepared.target_strings):
            res = MethodPredictionResults(original_name=original)
            for j in range(topk_ids.shape[1]):
                word = self.vocabs.target_vocab.lookup_word(
                    int(topk_ids[i, j]))
                if word == SpecialVocabWords.PAD:
                    continue
                res.append_prediction(word, float(topk_probs[i, j]))
            # attention-ranked path-contexts for interpretability
            ctx_fields = prepared.context_strings[i]
            order = np.argsort(-attn[i])
            for j in order:
                if j >= len(ctx_fields) or prepared.mask[i, j] == 0:
                    continue
                parts = ctx_fields[j].split(",")
                if len(parts) != 3:
                    continue
                res.append_attention_path(float(attn[i, j]), parts[0],
                                          parts[1], parts[2])
            if cfg.export_code_vectors:
                res.code_vector = code[i]
            results.append(res)
        return results

    def predict_prepared(self, prepared: PreparedRows
                         ) -> List[MethodPredictionResults]:
        """Single-caller form: device phase + decode in one call.
        Accepts pre-parsed (possibly concatenated) rows."""
        if prepared.n == 0:
            return []
        return self.decode_predictions(prepared,
                                       self.predict_device(prepared))

    def predict(self, predict_data_lines: Iterable[str]
                ) -> List[MethodPredictionResults]:
        prepared = self.prepare_predict_rows(predict_data_lines)
        if prepared.n == 0:
            return []
        return self.predict_prepared(prepared)

    # ---- persistence ----
    def _checkpoint_writer(self) -> "ckpt.AsyncCheckpointWriter":
        if self._ckpt_writer is None:
            self._ckpt_writer = ckpt.AsyncCheckpointWriter(
                log=self.log,
                heartbeat=getattr(self, "_ckpt_heartbeat", None))
        return self._ckpt_writer

    def save(self, path: Optional[str] = None, block: bool = True) -> None:
        # NOTE: orbax save is a collective — every process must call it
        # (orbax coordinates a single logical writer internally); skipping
        # non-zero processes would deadlock cross-host saves. The async
        # writer preserves this: every process runs its own writer
        # thread with one-in-flight FIFO discipline, so the collective
        # sees the same per-process call order as the sync path.
        #
        # block=False (the train loop's epoch save) returns once the
        # snapshot is queued; callers that READ the checkpoint next
        # (tests, tools, end-of-training) keep the default barrier.
        path = path or self.config.save_path
        assert path
        state = {"params": self.params, "opt_state": self.opt_state,
                 "step": self.step_num}
        extra = {"use_sampled_softmax": self.config.USE_SAMPLED_SOFTMAX,
                 "num_sampled": self.config.NUM_SAMPLED_CLASSES,
                 "sparse_embedding_updates":
                     self.config.SPARSE_EMBEDDING_UPDATES,
                 "embedding_optimizer": self.config.EMBEDDING_OPTIMIZER,
                 "trust_ratio": self.config.TRUST_RATIO,
                 "trust_ratio_scope": self.config.TRUST_RATIO_SCOPE,
                 # always the EFFECTIVE schedule: for loaded models the
                 # manifest override already set cfg.LR_SCHEDULE to what
                 # the saved opt_state structure carries
                 "lr_schedule": self.config.LR_SCHEDULE,
                 "lr_warmup_steps": self.config.LR_WARMUP_STEPS,
                 # provenance only (no structural effect on restore)
                 "adv_rename_prob": self.config.ADV_RENAME_PROB,
                 "adv_rename_mode": self.config.ADV_RENAME_MODE}
        # per-step save-time topology (ISSUE 13): epoch set by the
        # train loop at boundary saves and CONSUMED here (reset to
        # None so a later manual save at a further-trained step can't
        # stamp a stale epoch that would make resume re-train it —
        # epoch-less records fall back to the save-topology
        # arithmetic, see models/setup.resume_epoch_offset)
        topology = {"epoch": getattr(self, "_save_epoch", None)}
        self._save_epoch = None
        # trace (--trace): the save's blocked window LINKS the step that
        # triggered it (the per-step trace the recorder keeps current),
        # and the writer thread parents its train/save_write span to
        # this context — the step -> save -> commit chain is one walk
        trace_span = None
        if self.tracer.enabled:
            rec = getattr(self, "_trace_recorder", None)
            last = rec.last_step_context if rec is not None else None
            trace_span = self.tracer.start_trace(
                "train/save_blocked", step=int(self.step_num),
                is_async=bool(self.config.ASYNC_CHECKPOINT))
            if last is not None:
                trace_span.links.append(last)
        blocked_span = self.telemetry.span("train/save_blocked_ms")
        try:
            if self.config.ASYNC_CHECKPOINT:
                writer = self._checkpoint_writer()
                writer.submit(path, state, self.step_num, self.vocabs,
                              self.dims, extra_manifest=extra,
                              max_to_keep=self.config.MAX_TO_KEEP,
                              topology=topology,
                              telemetry=self.telemetry,
                              tracer=self.tracer
                              if trace_span is not None else None,
                              trace_ctx=trace_span.context()
                              if trace_span is not None else None)
                if block:
                    writer.wait()
                blocked_ms = blocked_span.stop()
                self.log(f"queued checkpoint step {self.step_num} -> "
                         f"{path} (loop blocked {blocked_ms:.1f} ms)")
            else:
                ckpt.save_checkpoint(path, state, self.step_num,
                                     self.vocabs, self.dims,
                                     extra_manifest=extra,
                                     max_to_keep=self.config.MAX_TO_KEEP,
                                     topology=topology)
                blocked_ms = blocked_span.stop()
                # the sync save IS its own writer: total == blocked, and
                # the commit event keeps telemetry_report's boundary
                # table mode-agnostic
                self.telemetry.record_ms("train/save_total_ms",
                                         blocked_ms)
                self.telemetry.event("save_committed",
                                     step=self.step_num,
                                     total_ms=round(blocked_ms, 3))
                self.log(f"saved checkpoint step {self.step_num} -> "
                         f"{path}")
        except BaseException:
            # a failed submit/save (sticky writer error, dead disk)
            # must not leak the blocked span or leave the save trace
            # open in the live-span table
            blocked_span.cancel()
            if trace_span is not None:
                trace_span.end(outcome="error")
            raise
        if trace_span is not None:
            trace_span.end(blocked_ms=round(blocked_ms, 3))
        self.telemetry.event("save", step=self.step_num,
                             blocked_ms=round(blocked_ms, 3),
                             is_async=bool(self.config.ASYNC_CHECKPOINT))

    def release(self) -> None:
        cfg = self.config
        assert cfg.load_path
        if self._ckpt_writer is not None:
            # --load-style read of a dir this process may still be
            # writing: commit barrier first
            self._ckpt_writer.wait()
        dest = cfg.save_path or (cfg.load_path.rstrip("/") + ".release")
        ckpt.release_checkpoint(cfg.load_path, dest, self.params)
        self.log(f"released inference checkpoint -> {dest}")

    def close_session(self) -> None:
        # the reference's session-teardown hook doubles as the stop()
        # commit barrier: no checkpoint may be left half-written
        if self._ckpt_writer is not None:
            self._ckpt_writer.close()

    @staticmethod
    def _opt_param_view(params):
        """See ops/quant.opt_param_view (shared with bench.py so the
        opt_state structure can never drift between them)."""
        from code2vec_tpu.ops.quant import opt_param_view
        return opt_param_view(params)

    def get_embedding_table(self, vocab_type: VocabType) -> np.ndarray:
        key = {VocabType.Token: "token_emb", VocabType.Path: "path_emb",
               VocabType.Target: "target_emb"}[vocab_type]
        from code2vec_tpu.ops.quant import dequantize_table, is_quantized
        table = self.params[key]
        if is_quantized(table):
            table = dequantize_table(table)
        table = np.asarray(jax.device_get(table), dtype=np.float32)
        return table[:self.vocabs.get(vocab_type).size]

    def export_code_vectors_file(self, test_path: str,
                                 dest_path: str) -> None:
        """--export_code_vectors during --test: one code vector per test
        example, in input order (reference writes `<test>.vectors`)."""
        cfg = self.config
        reader = open_reader(test_path, self.vocabs, cfg.MAX_CONTEXTS,
                             cfg.TEST_BATCH_SIZE, shuffle=False,
                             keep_strings=True)
        encode_step = make_encode_step(self.dims,
                                       compute_dtype=self.compute_dtype,
                                       mesh=self.mesh)
        from code2vec_tpu.data.prefetch import prefetch_to_device
        infeed = prefetch_to_device(
            reader, lambda b: self._device_batch(b, process_local=False),
            cfg.INFEED_PREFETCH)
        with open(dest_path, "w", encoding="utf-8") as f:
            for dev_batch, batch in infeed:
                code = encode_step(self.params, dev_batch)
                code = fetch_global(code)[:batch.num_valid_examples]
                for row in code:
                    f.write(" ".join(f"{x:.6f}" for x in row) + "\n")
