"""Shared model-construction helpers for the two heads.

jax_model.Code2VecModel and vm_model.VarMisuseModel mirror each other's
lifecycle; the mesh construction and the LR-schedule/optimizer
resolution (manifest-aware, resume-horizon-extending) live here once so
the heads cannot drift.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax

from code2vec_tpu.config import Config
from code2vec_tpu.parallel.mesh import make_mesh


def infeed_split() -> "tuple[int, int]":
    """(host_shard, num_host_shards) for the train reader — re-derived
    from the LIVE process set at every (re)launch via
    `parallel/compat.cohort_world` (ISSUE 13). A cohort the supervisor
    re-formed at N−1 gets an N−1-way split with no resize-specific
    code in either model head; combined with the reader's GLOBAL
    per-epoch permutation, the re-formed cohort consumes the same
    global data stream a same-size uninterrupted run would."""
    from code2vec_tpu.parallel.compat import cohort_world
    return cohort_world()


def build_mesh(cfg: Config, *, with_context_axis: bool = True):
    """The model's mesh (or None for a plain single-device run): all
    axes from config, sized 1 when unused — `jax.devices()` is the
    live-cohort device set, so an elastically re-formed cohort's mesh
    rebuilds from the surviving processes (ISSUE 13)."""
    n_dev = len(jax.devices())
    model_axis = max(1, cfg.MESH_MODEL_AXIS)
    ctx_axis = max(1, cfg.MESH_CONTEXT_AXIS) if with_context_axis else 1
    dcn_axis = max(1, cfg.MESH_DCN_AXIS)
    if n_dev > 1 or model_axis > 1 or ctx_axis > 1 or dcn_axis > 1:
        return make_mesh(cfg.MESH_DATA_AXIS, model_axis, ctx_axis,
                         dcn=dcn_axis)
    return None


def build_optimizer(cfg: Config, count_examples_fn: Callable[[], int],
                    manifest: Optional[dict]):
    """The optimizer with the LR schedule resolved exactly as the
    checkpoint (if any) demands:

    - schedule comes from cfg (already manifest-overridden when
      loading — the opt_state structure is fixed at first training);
    - a non-constant schedule needs a decay horizon: this run's step
      count (from `count_examples_fn`, only called when training).
      A plain --load fine-tune extends the horizon past the restored
      step (it trains a FULL epoch budget more); an --auto_resume run
      does NOT — it resumes ITSELF (round 15: the restored step
      counts toward NUM_TRAIN_EPOCHS), so its horizon is the original
      run's epochs x steps-per-epoch and the resumed LR curve matches
      the uninterrupted run's at every absolute step (the chaos-parity
      contract, schedule-agnostic). Under an ELASTIC resume onto a
      different cohort size (ISSUE 13) the horizon re-derives at the
      NEW size (num_hosts = the live process count): the decayed
      curve then matches an uninterrupted run AT THE NEW SIZE resumed
      from the same step — the elastic parity bar — and deliberately
      NOT the old topology's curve, whose step count no longer maps
      to this run's steps (the chaos kill_resize acceptance pins
      constant LR, where the distinction vanishes);
    - eval/predict-only runs take no optimizer steps, so horizon 1
      yields the right opt_state STRUCTURE.
    """
    from code2vec_tpu.training.optimizers import (make_lr, make_optimizer,
                                                  schedule_total_steps,
                                                  warmup_length)
    schedule = cfg.LR_SCHEDULE
    total_steps = 0
    if schedule != "constant":
        if cfg.is_training:
            restored = (int(manifest.get("step", 0))
                        if cfg.is_loading and manifest else 0)
            total_steps = schedule_total_steps(
                count_examples_fn(), cfg.TRAIN_BATCH_SIZE,
                cfg.NUM_TRAIN_EPOCHS,
                num_hosts=jax.process_count(),
                restored_step=0 if cfg.AUTO_RESUME else restored)
            if schedule == "warmup_cosine":
                # resolve auto-warmup (0) to its effective length NOW so
                # the manifest records it and a resume follows the SAME
                # trajectory instead of re-deriving 5% of a new horizon
                cfg.LR_WARMUP_STEPS = warmup_length(total_steps,
                                                    cfg.LR_WARMUP_STEPS)
        else:
            total_steps = 1
    return make_optimizer(
        make_lr(cfg.LEARNING_RATE, schedule, total_steps,
                warmup_steps=cfg.LR_WARMUP_STEPS),
        cfg.EMBEDDING_OPTIMIZER, trust_ratio=cfg.TRUST_RATIO,
        trust_ratio_scope=cfg.TRUST_RATIO_SCOPE)


def resume_epoch_offset(cfg: Config, step_num: int,
                        count_examples_fn: Callable[[], int],
                        log: Callable[[str], None]) -> int:
    """Completed epochs to skip on --auto_resume (ISSUE 10; made
    topology-independent by ISSUE 13). A resumed run trains ONLY the
    remaining epochs, with the reader's shuffle stream advanced to
    match; together with the step-keyed rng in the train loops,
    recovery replays the uninterrupted trajectory exactly (the
    chaos-parity acceptance). Plain --load + --data keeps fine-tune
    semantics (a full NUM_TRAIN_EPOCHS more). ONE definition for both
    model heads: this arithmetic is the recovery contract, and
    hand-synced copies would drift.

    Resolution order:
    1. The restored step's save-time `topology.json` `epoch` field —
       saves happen at epoch boundaries, so the record IS the answer,
       exact across ANY resize history (a cohort re-formed at N−1 has
       a different steps-per-epoch than the one that counted the
       restored steps, and after several resizes the step count is a
       mixed-topology sum no single division can unwind).
    2. Its `num_processes` field: the restored step count over the
       SAVE-TIME per-host steps-per-epoch (the same ceil-div the
       reader's aligned batch count and the LR horizon use — exact
       because saves only happen at epoch boundaries) — covers
       same-run checkpoints written before the epoch field existed.
    3. Pre-elastic checkpoints (no record): the current topology's
       steps-per-epoch, the PR-10 behavior — exact whenever the
       topology never changed, which is the only history such a
       checkpoint can have."""
    if not (cfg.AUTO_RESUME and step_num > 0):
        return 0
    from code2vec_tpu.data.reader import steps_per_epoch
    topo = None
    if cfg.is_loading and cfg.load_path:
        from code2vec_tpu.training import checkpoint as ckpt_mod
        topo = ckpt_mod.load_step_topology(cfg.load_path, step_num)
    if topo is not None and topo.get("epoch") is not None:
        completed = min(cfg.NUM_TRAIN_EPOCHS, int(topo["epoch"]))
        if completed:
            log(f"auto-resume: restored step {step_num} = epoch "
                f"{completed} (save-time record, saved at "
                f"{topo.get('num_processes', '?')} process(es)); "
                f"training epochs "
                f"{completed + 1}..{cfg.NUM_TRAIN_EPOCHS}")
        return completed
    save_procs = (int(topo["num_processes"])
                  if topo is not None and topo.get("num_processes")
                  else jax.process_count())
    spe = steps_per_epoch(count_examples_fn(), cfg.TRAIN_BATCH_SIZE,
                          save_procs)
    completed = min(cfg.NUM_TRAIN_EPOCHS, step_num // spe)
    if completed:
        log(f"auto-resume: restored step {step_num} = {completed} "
            f"completed epoch(s) x {spe} steps (at {save_procs} "
            f"process(es)); training epochs "
            f"{completed + 1}..{cfg.NUM_TRAIN_EPOCHS}")
    return completed
