"""VarMisuse model orchestration (BASELINE.json configs[3]).

Mirrors models/jax_model.py's lifecycle (train / evaluate / save / load /
resume) for the pointer head in models/varmisuse.py, over `.vm.c2v`
datasets (data/varmisuse_gen.py format). Selected via `--head varmisuse`.
"""

from __future__ import annotations

import time
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from code2vec_tpu.config import Config
from code2vec_tpu.data.vm_reader import (VMTextReader, build_vm_vocabs)
from code2vec_tpu.models.encoder import ModelDims
from code2vec_tpu.models.varmisuse import init_vm_params
from code2vec_tpu.parallel.distributed import fetch_global
from code2vec_tpu.parallel.mesh import DATA_AXIS, DCN_AXIS
from code2vec_tpu.parallel.sharding import (shard_batch, shard_opt_state,
                                            shard_params)
from code2vec_tpu.training import checkpoint as ckpt
from code2vec_tpu.training.profiler import StepProfiler
from code2vec_tpu.training.vm_steps import (make_vm_eval_step,
                                            make_vm_train_step)


class VMEvalResults(NamedTuple):
    loss: float
    accuracy: float
    num_examples: int

    def __str__(self) -> str:
        return (f"vm loss: {self.loss:.5f}, pointer accuracy: "
                f"{self.accuracy:.5f} over {self.num_examples} examples")


class VarMisuseModel:
    def __init__(self, config: Config):
        cfg = self.config = config
        self.log = cfg.log
        from code2vec_tpu.obs import Telemetry, Tracer
        self.telemetry = Telemetry.disabled()  # train() swaps it in
        self.tracer = Tracer.disabled()        # ditto (--trace)
        self.compute_dtype = jnp.bfloat16 if cfg.USE_BF16 else jnp.float32
        # Pallas kernels are TPU-only; fall back to the XLA pool
        # elsewhere (tests run on the virtual CPU mesh).
        self.use_pallas = (cfg.USE_PALLAS
                           and jax.default_backend() == "tpu")

        from code2vec_tpu.models.setup import build_mesh, build_optimizer
        # no context axis: the vm head is bag-encoder-only (Config.verify)
        self.mesh = build_mesh(cfg, with_context_axis=False)
        model_axis = max(1, cfg.MESH_MODEL_AXIS)

        if cfg.is_loading:
            self.dims = ckpt.load_dims(cfg.load_path)
            cfg.MAX_CONTEXTS = self.dims.max_contexts
            manifest = ckpt.load_manifest(cfg.load_path)
            cfg.MAX_CANDIDATES = manifest.get("max_candidates",
                                              cfg.MAX_CANDIDATES)
            cfg.TABLES_DTYPE = self.dims.tables_dtype
            # fallback "adam" (the pre-manifest-key default), not the
            # current adafactor default — see jax_model.py
            cfg.EMBEDDING_OPTIMIZER = manifest.get(
                "embedding_optimizer", "adam")
            # opt_state structure follows this exactly like the
            # optimizer choice does (sparse dict vs optax chain)
            cfg.SPARSE_EMBEDDING_UPDATES = manifest.get(
                "sparse_embedding_updates", cfg.SPARSE_EMBEDDING_UPDATES)
            cfg.TRUST_RATIO = manifest.get("trust_ratio", False)
            from code2vec_tpu.training.optimizers import (
                resolve_checkpoint_schedule, resolve_checkpoint_warmup)
            cfg.LR_SCHEDULE = resolve_checkpoint_schedule(
                cfg.LR_SCHEDULE, manifest, cfg.log)
            cfg.LR_WARMUP_STEPS = resolve_checkpoint_warmup(
                cfg.LR_SCHEDULE, cfg.LR_WARMUP_STEPS, manifest, cfg.log)
            self.vocabs = ckpt.load_vocabs(cfg.load_path)
        else:
            assert cfg.train_data_path, "varmisuse needs --data or --load"
            self.vocabs = build_vm_vocabs(self._vm_path("train"),
                                          cfg.MAX_TOKEN_VOCAB_SIZE,
                                          cfg.MAX_PATH_VOCAB_SIZE)
            self.dims = ModelDims(
                token_vocab_size=self.vocabs.token_vocab.size,
                path_vocab_size=self.vocabs.path_vocab.size,
                target_vocab_size=self.vocabs.target_vocab.size,
                embeddings_size=cfg.DEFAULT_EMBEDDINGS_SIZE,
                max_contexts=cfg.MAX_CONTEXTS,
                dropout_keep_rate=cfg.DROPOUT_KEEP_RATE,
                vocab_pad_multiple=model_axis,
                tables_dtype=cfg.TABLES_DTYPE,
            )
        def n_train_examples() -> int:
            from code2vec_tpu.data.reader import count_examples
            return count_examples(self._vm_path("train"))

        self._n_train_examples = n_train_examples
        self.optimizer = build_optimizer(
            cfg, n_train_examples,
            manifest if cfg.is_loading else None)
        self.rng = jax.random.PRNGKey(cfg.SEED)
        self.rng, init_rng = jax.random.split(self.rng)
        params = init_vm_params(init_rng, self.dims)
        if cfg.SPARSE_EMBEDDING_UPDATES:
            # verify() enforces these for CLI runs; assert for
            # programmatic Config users (same contract as jax_model)
            assert cfg.EMBEDDING_OPTIMIZER == "adam", (
                "SPARSE_EMBEDDING_UPDATES requires "
                "EMBEDDING_OPTIMIZER='adam'")
            assert cfg.LR_SCHEDULE == "constant", (
                "SPARSE_EMBEDDING_UPDATES requires "
                "LR_SCHEDULE='constant'")
            from code2vec_tpu.training.vm_steps import \
                init_vm_sparse_opt_state
            opt_state = init_vm_sparse_opt_state(params, self.optimizer)
        else:
            opt_state = self.optimizer.init(params)
        self.step_num = 0
        if cfg.is_loading:
            full = ckpt.load_checkpoint(
                cfg.load_path,
                {"params": params, "opt_state": opt_state, "step": 0})
            params, opt_state = full["params"], full["opt_state"]
            self.step_num = int(full.get("step", 0))
        if self.mesh is not None:
            params = shard_params(self.mesh, params)
            opt_state = shard_opt_state(self.mesh, opt_state, params)
        self.params, self.opt_state = params, opt_state

        # background checkpoint writer (--async_checkpoint, default on);
        # lazy so load/eval-only instances never start the thread
        self._ckpt_writer = None
        from code2vec_tpu.training.sparse_update import \
            resolve_sparse_update_mode
        self._train_step = make_vm_train_step(
            self.dims, self.optimizer, compute_dtype=self.compute_dtype,
            use_pallas=self.use_pallas,
            sparse_updates=cfg.SPARSE_EMBEDDING_UPDATES,
            learning_rate=cfg.LEARNING_RATE,
            sparse_update_fused=resolve_sparse_update_mode(
                cfg.SPARSE_UPDATE_PALLAS),
            mesh=self.mesh)
        self._eval_step = make_vm_eval_step(
            self.dims, compute_dtype=self.compute_dtype,
            use_pallas=self.use_pallas)

    def _vm_path(self, split: str) -> str:
        p = self.config.train_data_path
        assert p
        return f"{p}.{split}.vm.c2v"

    def _host_batch_arrays(self, b):
        weights = np.zeros((b.label.shape[0],), np.float32)
        weights[:b.num_valid_examples] = 1.0
        weights *= b.row_valid   # drop rows whose label was truncated
        return (b.label, b.path_source_token_indices, b.path_indices,
                b.path_target_token_indices, b.context_valid_mask,
                b.cand_ids, b.cand_mask, weights)

    def _device_batch(self, b, process_local: bool = True):
        arrays = self._host_batch_arrays(b)
        if self.mesh is not None:
            return shard_batch(self.mesh, arrays,
                               process_local=process_local)
        # materialize on device HERE (async dispatch) so the prefetch
        # thread really transfers ahead — numpy passed into the jitted
        # step would transfer on the MAIN thread at call time
        return tuple(jnp.asarray(a) for a in arrays)

    def train(self) -> None:
        cfg = self.config
        # auto-resume epoch offset: the ONE shared arithmetic (see
        # models/setup.resume_epoch_offset — the recovery contract)
        from code2vec_tpu.models.setup import (infeed_split,
                                               resume_epoch_offset)
        completed_epochs = resume_epoch_offset(
            cfg, self.step_num, self._n_train_examples, self.log)
        # per-host infeed split from the LIVE process set (ISSUE 13)
        host_shard, num_host_shards = infeed_split()
        reader = VMTextReader(
            self._vm_path("train"), self.vocabs, cfg.MAX_CONTEXTS,
            cfg.MAX_CANDIDATES, cfg.TRAIN_BATCH_SIZE, shuffle=True,
            seed=cfg.SEED, host_shard=host_shard,
            num_host_shards=num_host_shards,
            epoch_offset=completed_epochs)
        self.log(f"varmisuse training: dims={self.dims}, "
                 f"max_candidates={cfg.MAX_CANDIDATES}")
        window, t0 = 0, time.time()
        profiler = StepProfiler(cfg.PROFILE_DIR, cfg.PROFILE_START_STEP,
                                cfg.PROFILE_STEPS, self.log)
        # Unified run telemetry (code2vec_tpu/obs/) — same per-step
        # step_ms/infeed_wait_ms/loss records as the code2vec head; the
        # shared recorder keeps the two loops' metrics comparable.
        from code2vec_tpu.obs import (SpanChannel, Telemetry, Tracer,
                                      TrainStepRecorder, Watchdog,
                                      build_live_plane)
        telemetry = Telemetry.create(
            cfg.TELEMETRY_DIR, config=cfg, mesh=self.mesh,
            component="train", log=self.log)
        if cfg.METRICS_PORT > 0 and not telemetry.enabled:
            # --metrics_port without --telemetry_dir: live exposition
            # over an in-memory registry (same as jax_model)
            telemetry = Telemetry.memory("train")
        self.telemetry = telemetry
        live_plane = cfg.METRICS_PORT > 0 or cfg.ALERTS_MODE != "off"
        if (cfg.ASYNC_CHECKPOINT or cfg.TRACE
                or cfg.WATCHDOG_STALL_S > 0 or live_plane):
            # the checkpoint writer, the infeed producer (trace spans),
            # the watchdog/health monitors and the exposition handler
            # all touch this registry cross-thread
            telemetry.make_threadsafe()
        # per-step tracing + stall watchdog — same wiring as jax_model
        # (shared recorder/obs layer keeps the two loops comparable)
        tracer = Tracer.create(telemetry) if cfg.TRACE \
            else Tracer.disabled()
        self.tracer = tracer
        watchdog = Watchdog.create(
            telemetry, stall_s=cfg.WATCHDOG_STALL_S,
            mode=cfg.WATCHDOG_MODE, tracer=tracer, log=self.log)
        loop_hb = watchdog.register("train_loop")
        self._ckpt_heartbeat = watchdog.register("checkpoint_writer")
        infeed_hb = watchdog.register("infeed_producer")
        # live metrics plane (ISSUE 7) — the ONE shared wiring
        # (obs/exposition.build_live_plane), same as jax_model
        from code2vec_tpu.obs.alerts import default_train_rules
        from code2vec_tpu.obs.health import default_train_monitors
        plane = build_live_plane(
            telemetry, metrics_port=cfg.METRICS_PORT,
            alerts_mode=cfg.ALERTS_MODE,
            alerts_rules=cfg.ALERTS_RULES,
            health_every_s=cfg.HEALTH_EVERY_S, watchdog=watchdog,
            monitors=default_train_monitors(),
            default_rules=default_train_rules,
            # identity block on /vars (ISSUE 17), same as jax_model
            identity={"process_index": jax.process_index(),
                      "process_count": jax.process_count()},
            log=self.log)
        alerts = plane.alerts
        self.metrics_server = plane.metrics
        infeed_channel = SpanChannel() if tracer.enabled else None
        recorder = TrainStepRecorder(
            telemetry, gauge_every=cfg.NUM_BATCHES_TO_LOG_PROGRESS,
            tracer=tracer, infeed_channel=infeed_channel,
            heartbeat=loop_hb if watchdog.enabled else None,
            alerts=alerts if alerts.enabled else None)
        self._trace_recorder = recorder
        watchdog.start()
        plane.start()
        telemetry.gauge("train/max_contexts", cfg.MAX_CONTEXTS,
                        emit=False, static=True)
        # sampled phase attribution (--phase_profile, ISSUE 15) — the
        # same profiler as jax_model over the vm head's probe kit (no
        # pre-attention seam: gather → forward → backward + the dense
        # apply probe; no analytic bytes — the vm id-count model is
        # not phase_traffic_bytes', so the roofline gauges stay absent
        # rather than wrong, the floor-gauge discipline)
        from code2vec_tpu.obs.phases import PhaseProfiler

        def _phase_probes():
            from code2vec_tpu.training.phase_probes import \
                make_vm_probes
            return make_vm_probes(self.dims,
                                  compute_dtype=self.compute_dtype,
                                  use_pallas=self.use_pallas)

        phase_profiler = PhaseProfiler.create(
            telemetry, fused_step=self._train_step,
            probes_factory=_phase_probes,
            enabled=cfg.PHASE_PROFILE == "on",
            sample_every=cfg.PHASE_SAMPLE_EVERY, log=self.log)
        loop_hb.busy()  # the first deadline covers step-0 compile too
        steps_into_training = 0
        from code2vec_tpu.data.prefetch import (build_train_infeed,
                                                persistent_epochs)
        from code2vec_tpu.obs import infeed_produce_instrument
        infeed = build_train_infeed(
            reader, chunk=cfg.INFEED_CHUNK, depth=cfg.INFEED_PREFETCH,
            mesh=self.mesh, host_arrays_fn=self._host_batch_arrays,
            device_batch_fn=self._device_batch, log=self.log,
            instrument=infeed_produce_instrument(tracer, infeed_channel),
            heartbeat=infeed_hb if watchdog.enabled else None)
        # chaos failpoints (--faults, ISSUE 10) — disarmed, each is one
        # attribute read per step (same wiring as jax_model)
        from code2vec_tpu.resilience import faults, retry
        if telemetry.enabled:
            retry.set_telemetry(telemetry)
        nan_fp, kill_fp = faults.train_step_points()
        # one warm producer thread across epoch boundaries (same as
        # jax_model): epoch k+1 parses/transfers during the boundary
        # save + eval instead of cold-restarting the double buffer
        try:
            for epoch, epoch_batches in persistent_epochs(
                    infeed, cfg.NUM_TRAIN_EPOCHS,
                    first_epoch=completed_epochs + 1):
                for dev_batch, batch in recorder.wrap(epoch_batches):
                    profiler.tick(steps_into_training, self.params)
                    # absolute-step-keyed rng: auto-resume replays the
                    # uninterrupted run's key stream (see jax_model)
                    k = jax.random.fold_in(self.rng, self.step_num)
                    if phase_profiler.enabled \
                            and phase_profiler.should_sample(
                                steps_into_training):
                        self.params, self.opt_state, loss = \
                            phase_profiler.run_split(
                                self.params, self.opt_state, dev_batch,
                                k, step=self.step_num,
                                infeed_wait_ms=recorder.infeed_wait_ms
                                if recorder.enabled else None,
                                recorder=recorder
                                if recorder.enabled else None)
                    else:
                        self.params, self.opt_state, loss = \
                            self._train_step(self.params,
                                             self.opt_state, dev_batch,
                                             k)
                    if nan_fp.armed and nan_fp.hit():
                        loss = loss * float("nan")  # poison the loss
                    if kill_fp.armed:
                        kill_fp.fire(step=self.step_num + 1)
                    self.step_num += 1
                    steps_into_training += 1
                    window += batch.num_valid_examples
                    loss_f = (recorder.end_step(self.step_num, loss,
                                                batch.num_valid_examples,
                                                params=self.params)
                              if recorder.enabled else None)
                    if self.step_num % cfg.NUM_BATCHES_TO_LOG_PROGRESS == 0:
                        if loss_f is None:
                            loss_f = float(loss)
                        dt = time.time() - t0
                        self.log(f"vm epoch {epoch} step {self.step_num}: "
                                 f"loss {loss_f:.4f}, "
                                 f"{window / max(dt, 1e-9):.1f} ex/s")
                        window, t0 = 0, time.time()
                epoch_end_work = False
                if cfg.is_saving and epoch % cfg.SAVE_EVERY_EPOCHS == 0:
                    # async: kick the save first so eval overlaps the
                    # writer tail (same boundary overlap as jax_model)
                    self._save_epoch = epoch  # -> step topology record
                    self.save(block=False)
                    epoch_end_work = True
                if cfg.is_testing and epoch % cfg.SAVE_EVERY_EPOCHS == 0:
                    eval_span = telemetry.span("train/eval_ms")
                    try:
                        results = self.evaluate()
                    except BaseException:
                        eval_span.cancel()  # dead eval: drop, don't leak
                        raise
                    eval_ms = eval_span.stop()
                    self.log(f"vm epoch {epoch}: {results}")
                    telemetry.event("eval", epoch=epoch, step=self.step_num,
                                    loss=results.loss,
                                    accuracy=results.accuracy,
                                    eval_ms=round(eval_ms, 3))
                    epoch_end_work = True
                if epoch_end_work:
                    # boundary work is progress for the loop's deadline
                    loop_hb.beat()
                    # checkpoint/eval wall time must not leak into the next
                    # window's first ex/s figure (same fix as jax_model)
                    window, t0 = 0, time.time()
            if self._ckpt_writer is not None:
                # hard commit barrier: end of training (re-raises a
                # background write failure)
                self._ckpt_writer.wait()
            watchdog.poll()  # raise-mode: a stalled run dies loudly here
            alerts.poll()    # raise-mode: so does a firing alert
        finally:
            loop_hb.idle()
            watchdog.stop()  # no re-raise: must not mask loop errors
            plane.stop()
            if self._ckpt_writer is not None:
                # exception-path teardown: drain without
                # masking the in-flight error (a sticky
                # write failure still re-raises at the next
                # submit/wait/close)
                self._ckpt_writer.drain_quiet()
        profiler.finish(self.params)
        telemetry.close()
        self.log("varmisuse training done")

    def evaluate(self, split_path: Optional[str] = None) -> VMEvalResults:
        cfg = self.config
        path = split_path or cfg.test_data_path
        assert path, "evaluate requires --test"
        multi = jax.process_count() > 1
        # Multi-host: each host parses a DISJOINT shard (global eval
        # batch = H x TEST_BATCH_SIZE). The eval step returns GLOBAL
        # weighted sums (identical on every host), so only the local
        # example count needs cross-host merging.
        reader = VMTextReader(path, self.vocabs, cfg.MAX_CONTEXTS,
                              cfg.MAX_CANDIDATES, cfg.TEST_BATCH_SIZE,
                              host_shard=jax.process_index() if multi
                              else 0,
                              num_host_shards=jax.process_count()
                              if multi else 1)
        loss_sum = correct = total = 0.0
        from code2vec_tpu.data.prefetch import prefetch_to_device
        infeed = prefetch_to_device(
            reader, lambda b: self._device_batch(b, process_local=multi),
            cfg.INFEED_PREFETCH)
        for dev_batch, batch in infeed:
            ls, cs, _pred = self._eval_step(self.params, dev_batch)
            loss_sum += float(ls)
            correct += float(cs)
            total += batch.num_valid_examples
        if multi:
            from code2vec_tpu.parallel.distributed import \
                allreduce_sum_hosts
            total = float(allreduce_sum_hosts([total])[0])
        total = max(total, 1.0)
        return VMEvalResults(loss_sum / total, correct / total,
                             int(total))

    def predict_batch(self, rows) -> np.ndarray:
        """Pointer predictions (candidate indices) for `.vm.c2v` rows."""
        from code2vec_tpu.data.vm_reader import parse_vm_rows

        cfg = self.config
        (labels, src, pth, dst, mask, cand, cand_mask, row_valid,
         _strings) = parse_vm_rows(list(rows), self.vocabs,
                                   cfg.MAX_CONTEXTS, cfg.MAX_CANDIDATES)
        n = labels.shape[0]
        weights = row_valid.copy()
        batch = [labels, src, pth, dst, mask, cand, cand_mask, weights]
        if self.mesh is not None:
            # pad the batch dim to divide the data axis
            dax = self.mesh.shape[DATA_AXIS] * self.mesh.shape[DCN_AXIS]
            padded = -(-n // dax) * dax
            if padded != n:
                for i, a in enumerate(batch):
                    pad = np.zeros((padded - n,) + a.shape[1:], a.dtype)
                    batch[i] = np.concatenate([a, pad], axis=0)
                batch[6][n:, 0] = 1.0  # keep softmax finite on pad rows
            batch = shard_batch(self.mesh, tuple(batch),
                                process_local=False)
        _ls, _cs, pred = self._eval_step(self.params, tuple(batch))
        return fetch_global(pred)[:n]

    def save(self, path: Optional[str] = None, block: bool = True) -> None:
        path = path or self.config.save_path
        assert path
        state = {"params": self.params, "opt_state": self.opt_state,
                 "step": self.step_num}
        extra = {"head": "varmisuse",
                 "max_candidates": self.config.MAX_CANDIDATES,
                 "embedding_optimizer": self.config.EMBEDDING_OPTIMIZER,
                 "sparse_embedding_updates":
                     self.config.SPARSE_EMBEDDING_UPDATES,
                 "trust_ratio": self.config.TRUST_RATIO,
                 "lr_schedule": self.config.LR_SCHEDULE,
                 "lr_warmup_steps": self.config.LR_WARMUP_STEPS}
        # per-step save-time topology (ISSUE 13): epoch consumed and
        # reset — see jax_model.save
        topology = {"epoch": getattr(self, "_save_epoch", None)}
        self._save_epoch = None
        trace_span = None
        if self.tracer.enabled:
            rec = getattr(self, "_trace_recorder", None)
            last = rec.last_step_context if rec is not None else None
            trace_span = self.tracer.start_trace(
                "train/save_blocked", step=int(self.step_num),
                is_async=bool(self.config.ASYNC_CHECKPOINT))
            if last is not None:
                trace_span.links.append(last)
        blocked_span = self.telemetry.span("train/save_blocked_ms")
        try:
            if self.config.ASYNC_CHECKPOINT:
                if self._ckpt_writer is None:
                    self._ckpt_writer = ckpt.AsyncCheckpointWriter(
                        log=self.log,
                        heartbeat=getattr(self, "_ckpt_heartbeat", None))
                self._ckpt_writer.submit(
                    path, state, self.step_num, self.vocabs, self.dims,
                    extra_manifest=extra,
                    max_to_keep=self.config.MAX_TO_KEEP,
                    topology=topology,
                    telemetry=self.telemetry,
                    tracer=self.tracer if trace_span is not None
                    else None,
                    trace_ctx=trace_span.context()
                    if trace_span is not None else None)
                if block:
                    self._ckpt_writer.wait()
                blocked_ms = blocked_span.stop()
                self.log(f"queued varmisuse checkpoint step "
                         f"{self.step_num} -> {path} "
                         f"(loop blocked {blocked_ms:.1f} ms)")
            else:
                ckpt.save_checkpoint(path, state, self.step_num,
                                     self.vocabs, self.dims,
                                     extra_manifest=extra,
                                     max_to_keep=self.config.MAX_TO_KEEP,
                                     topology=topology)
                blocked_ms = blocked_span.stop()
                self.telemetry.record_ms("train/save_total_ms",
                                         blocked_ms)
                self.telemetry.event("save_committed",
                                     step=self.step_num,
                                     total_ms=round(blocked_ms, 3))
                self.log(f"saved varmisuse checkpoint step "
                         f"{self.step_num} -> {path}")
        except BaseException:
            # a failed submit/save must not leak the blocked span or
            # leave the save trace open in the live-span table
            blocked_span.cancel()
            if trace_span is not None:
                trace_span.end(outcome="error")
            raise
        if trace_span is not None:
            trace_span.end(blocked_ms=round(blocked_ms, 3))
        self.telemetry.event("save", step=self.step_num,
                             blocked_ms=round(blocked_ms, 3),
                             is_async=bool(self.config.ASYNC_CHECKPOINT))

    def close_session(self) -> None:
        # stop() commit barrier: no checkpoint may be left half-written
        if self._ckpt_writer is not None:
            self._ckpt_writer.close()
