from code2vec_tpu.models.encoder import (  # noqa: F401
    ModelDims, init_params, encode, full_logits)
