"""Framework-agnostic model lifecycle + host-side metric computation.

Reference parity target: `model_base.py` (SURVEY.md §3 "Model base"):
`Code2VecModelBase` with `train()`, `evaluate()` returning
`EvaluationResults(topk_acc, subtoken_precision, subtoken_recall,
subtoken_f1, loss)`, `predict(lines)`, save/load orchestration,
`save_word2vec_format()`. Metric semantics (SURVEY.md §4.3): exact-match
top-k accuracy over legal predictions, and subtoken TP/FP/FN accumulated
from the first legal top-1 prediction vs. the true name.
"""

from __future__ import annotations

import abc
from typing import Iterable, List, Sequence

import numpy as np

from code2vec_tpu.common import (EvaluationResults, SubtokenStatistics,
                                 filter_impossible_names)
from code2vec_tpu.config import Config
from code2vec_tpu.vocab.vocabularies import Code2VecVocabs, Vocab, VocabType


class MetricAccumulator:
    """Accumulates top-k exact-match accuracy + subtoken stats over an
    evaluation run (host-side numpy/string code, as in the reference)."""

    def __init__(self, top_k: int):
        self.top_k = top_k
        self.num_examples = 0
        self.topk_correct = np.zeros((top_k,), dtype=np.int64)
        self.subtoken_stats = SubtokenStatistics()
        self.loss_sum = 0.0

    def update_batch(self, original_names: Sequence[str],
                     predicted_words: Sequence[Sequence[str]],
                     loss_sum: float = 0.0) -> None:
        self.loss_sum += float(loss_sum)
        for original, topk in zip(original_names, predicted_words):
            self.num_examples += 1
            legal = filter_impossible_names(list(topk))
            # top-k exact match: original found at rank r (in the legal
            # list) counts for every k > r.
            if original in legal:
                rank = legal.index(original)
                if rank < self.top_k:
                    self.topk_correct[rank:] += 1
            # subtoken stats vs. the best legal prediction
            top_prediction = legal[0] if legal else ""
            self.subtoken_stats.update(original, top_prediction)

    def merge_across_hosts(self) -> None:
        """Sum this accumulator's partials with every other process's
        (no-op single-process): the multi-host eval path shards the eval
        file per host, so each accumulator holds one host's examples."""
        from code2vec_tpu.parallel.distributed import allreduce_sum_hosts
        vec = np.concatenate([
            [self.num_examples, self.loss_sum,
             self.subtoken_stats.true_positive,
             self.subtoken_stats.false_positive,
             self.subtoken_stats.false_negative],
            self.topk_correct]).astype(np.float64)
        total = allreduce_sum_hosts(vec)
        self.num_examples = int(total[0])
        self.loss_sum = float(total[1])
        self.subtoken_stats.true_positive = int(total[2])
        self.subtoken_stats.false_positive = int(total[3])
        self.subtoken_stats.false_negative = int(total[4])
        self.topk_correct = total[5:].astype(np.int64)

    def results(self) -> EvaluationResults:
        n = max(self.num_examples, 1)
        return EvaluationResults(
            topk_acc=(self.topk_correct / n).tolist(),
            subtoken_precision=self.subtoken_stats.precision,
            subtoken_recall=self.subtoken_stats.recall,
            subtoken_f1=self.subtoken_stats.f1,
            loss=self.loss_sum / n,
        )


class Code2VecModelBase(abc.ABC):
    def __init__(self, config: Config):
        self.config = config
        # run telemetry (code2vec_tpu/obs/): train() replaces this with
        # a file-backed run when --telemetry_dir is set, and the serving
        # REPL injects its always-on latency registry; the disabled
        # singleton keeps predict()'s span calls branch-free. Same deal
        # for the request-scoped tracer (--trace): train() and the
        # PredictionServer install a recording one.
        from code2vec_tpu.obs import Telemetry, Tracer
        self.telemetry = Telemetry.disabled()
        self.tracer = Tracer.disabled()
        self.vocabs: Code2VecVocabs = self._load_or_create_vocabs()

    # ---- lifecycle ----
    @abc.abstractmethod
    def _load_or_create_vocabs(self) -> Code2VecVocabs: ...

    @abc.abstractmethod
    def train(self) -> None: ...

    @abc.abstractmethod
    def evaluate(self) -> EvaluationResults: ...

    @abc.abstractmethod
    def predict(self, predict_data_lines: Iterable[str]) -> List: ...

    @abc.abstractmethod
    def save(self, path: str) -> None: ...

    @abc.abstractmethod
    def release(self) -> None: ...

    @abc.abstractmethod
    def get_embedding_table(self, vocab_type: VocabType) -> np.ndarray: ...

    def close_session(self) -> None:
        """Reference API compatibility no-op (no TF session)."""

    # ---- word2vec export (SURVEY.md §4.5) ----
    def save_word2vec_format(self, dest_path: str,
                             vocab_type: VocabType) -> None:
        vocab: Vocab = self.vocabs.get(vocab_type)
        table = np.asarray(self.get_embedding_table(vocab_type))
        n, dim = vocab.size, table.shape[1]
        with open(dest_path, "w", encoding="utf-8") as f:
            f.write(f"{n} {dim}\n")
            for idx in range(n):
                word = vocab.lookup_word(idx)
                vec = " ".join(f"{x:.6f}" for x in table[idx])
                f.write(f"{word} {vec}\n")
