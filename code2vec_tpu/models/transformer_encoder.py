"""Transformer path-encoder (BASELINE.json configs[4]).

Replaces the reference's single-query attention pool with a set
transformer over the ≤MAX_CONTEXTS path-contexts. Design notes
(SURVEY.md §6 long-context row):

- Contexts are an UNORDERED bag, so there is no positional encoding —
  layers are permutation-equivariant (masked self-attention + MLP,
  pre-LN), and the code vector comes from a learned-query attention
  pool (PMA-style), which degenerates to exactly the reference's pool
  at zero layers.
- Everything is static-shape and jit-friendly; attention masks are
  additive log-masks. Heads/layers live in ModelDims so the jitted
  steps stay closed over static config.
- Activations keep the [B, C, D] layout with the context dim second, so
  a future context-parallel mesh axis shards `C` without a layout
  change (the axis is reserved in parallel/mesh.py; at size 1 today the
  sharding constraint is a no-op).
- Params sit under one "xf" subtree (replicated on the mesh — they are
  ~L*12*D^2 floats, tiny next to the vocab tables, which keep their
  row-sharded TP layout from parallel/sharding.py).
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from code2vec_tpu.models.encoder import ModelDims


def init_xf_params(rng: jax.Array, dims: ModelDims) -> Dict:
    """The "xf" subtree: input projection, L layers, pool query."""
    D = dims.context_vector_size
    H = dims.xf_heads
    assert D % H == 0, f"context_vector_size {D} % heads {H} != 0"
    mlp = dims.xf_mlp_ratio * D
    init = jax.nn.initializers.variance_scaling(1.0, "fan_avg", "uniform")
    keys = jax.random.split(rng, 2 + 4 * dims.xf_layers)
    layers = []
    for i in range(dims.xf_layers):
        k_qkv, k_o, k_up, k_down = keys[2 + 4 * i: 6 + 4 * i]
        layers.append({
            "ln1_scale": jnp.ones((D,), jnp.float32),
            "ln2_scale": jnp.ones((D,), jnp.float32),
            "qkv": init(k_qkv, (D, 3 * D), jnp.float32),
            "out": init(k_o, (D, D), jnp.float32),
            "mlp_up": init(k_up, (D, mlp), jnp.float32),
            "mlp_down": init(k_down, (mlp, D), jnp.float32),
        })
    return {
        "ln_f_scale": jnp.ones((D,), jnp.float32),
        "pool_query": init(keys[0], (D, 1), jnp.float32)[:, 0],
        "in_proj": init(keys[1], (D, D), jnp.float32),
        "layers": layers,
    }


def _rms_norm(x: jax.Array, scale: jax.Array) -> jax.Array:
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1,
                   keepdims=True)
    return (x.astype(jnp.float32) * jax.lax.rsqrt(var + 1e-6)
            ).astype(x.dtype) * scale.astype(x.dtype)


def _mha(x: jax.Array, qkv: jax.Array, out: jax.Array,
         log_mask: jax.Array, heads: int,
         ring_mesh=None, use_pallas: bool = False) -> jax.Array:
    B, C, D = x.shape
    hd = D // heads
    proj = x @ qkv.astype(x.dtype)                     # [B, C, 3D]
    q, k, v = jnp.split(proj, 3, axis=-1)

    def split_heads(t):
        return t.reshape(B, C, heads, hd).transpose(0, 2, 1, 3)

    q, k, v = split_heads(q), split_heads(k), split_heads(v)
    if ring_mesh is not None:
        from code2vec_tpu.ops.ring_attention import ring_attention
        ctx = ring_attention(q, k, v, log_mask, ring_mesh)
    elif use_pallas:
        # fused fwd+bwd kernels: no [B, H, C, C] tensor in HBM either
        # direction (ops/xf_attention.py)
        from code2vec_tpu.ops.xf_attention import fused_mha
        ctx = fused_mha(q, k, v, log_mask)
    else:
        logits = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32)
        # hd is the Python-int head dim: trace-time scale math, no
        # device sync here  # graftlint: disable=host-sync-in-hot-path
        logits = logits / jnp.sqrt(float(hd)) \
            + log_mask[:, None, None, :]
        attn = jax.nn.softmax(logits, axis=-1).astype(x.dtype)
        ctx = jnp.einsum("bhqk,bhkd->bhqd", attn, v)
    ctx = ctx.transpose(0, 2, 1, 3).reshape(B, C, D)
    return ctx @ out.astype(x.dtype)


def encode_transformer(params: Dict, source_ids: jax.Array,
                       path_ids: jax.Array, target_ids: jax.Array,
                       mask: jax.Array, *,
                       dims: ModelDims,
                       mesh=None,
                       dropout_rng: Optional[jax.Array] = None,
                       dropout_keep_rate: float = 1.0,
                       compute_dtype=jnp.float32,
                       use_pallas: bool = False
                       ) -> Tuple[jax.Array, jax.Array]:
    """Same contract as encoder.encode: returns (code [B, D] in compute
    dtype, pool attention [B, C] f32). With `use_pallas`, the
    self-attention runs as the fused Pallas kernel pair
    (ops/xf_attention.py — no [B, H, C, C] HBM materialization in
    either direction). With dims.ring_attention and a mesh whose 'ctx'
    axis is > 1, it runs as ring attention instead (K/V rotate via
    ppermute, O(C/s) per-device memory) — the ring path wins over the
    kernel because sharded-C blocks are small enough for XLA."""
    from code2vec_tpu.parallel.mesh import CONTEXT_AXIS
    ring_mesh = (mesh if (dims.ring_attention and mesh is not None
                          and dict(mesh.shape).get(CONTEXT_AXIS, 1) > 1)
                 else None)
    if ring_mesh is not None:
        use_pallas = False
    xf = params["xf"]
    emb = jnp.concatenate([
        jnp.take(params["token_emb"], source_ids, axis=0),
        jnp.take(params["path_emb"], path_ids, axis=0),
        jnp.take(params["token_emb"], target_ids, axis=0),
    ], axis=-1).astype(compute_dtype)                  # [B, C, D]

    if dropout_rng is not None and dropout_keep_rate < 1.0:
        keep = jax.random.bernoulli(dropout_rng, dropout_keep_rate,
                                    emb.shape)
        emb = jnp.where(keep, emb / dropout_keep_rate, 0.0)

    # all-pad rows: keep one live key so softmax stays finite
    safe_mask = jnp.where(jnp.sum(mask, axis=-1, keepdims=True) > 0,
                          mask, jnp.ones_like(mask))
    log_mask = jnp.log(jnp.maximum(safe_mask, 1e-30)).astype(jnp.float32)

    def layer_fn(x, layer):
        h = _rms_norm(x, layer["ln1_scale"])
        x = x + _mha(h, layer["qkv"], layer["out"], log_mask,
                     dims.xf_heads, ring_mesh=ring_mesh,
                     use_pallas=use_pallas)
        h = _rms_norm(x, layer["ln2_scale"])
        h = jax.nn.gelu(h @ layer["mlp_up"].astype(compute_dtype))
        return x + h @ layer["mlp_down"].astype(compute_dtype)

    if dims.xf_remat:
        # O(1)-in-depth activation memory for CodeBERT-scale encoders
        layer_fn = jax.checkpoint(layer_fn)

    x = emb @ xf["in_proj"].astype(compute_dtype)
    for layer in xf["layers"]:
        x = layer_fn(x, layer)

    x = _rms_norm(x, xf["ln_f_scale"])
    # learned-query pool (the reference's attention pool, over the
    # transformed representations)
    pool_logits = (x.astype(jnp.float32)
                   @ xf["pool_query"].astype(jnp.float32)) + log_mask
    attn = jax.nn.softmax(pool_logits, axis=-1)        # [B, C]
    code = jnp.einsum("bc,bcd->bd", attn.astype(compute_dtype), x)
    return code, attn
