"""VarMisuse head: pointer-style variable-misuse localization/repair.

BASELINE.json configs[3] ("variable-naming / VarMisuse head — reuse path
encoder, new target space"); SURVEY.md §8.3 step 8. The reference has no
such head — this is one of the driver-required stretch configs, built
the TPU-first way on top of the same encoder:

  - A method with one variable occurrence replaced by the special
    `slotvar` token is extracted to path-contexts as usual (the slot's
    contexts carry the syntactic environment of the hole).
  - The method's candidate variables (<= K, padded) are embedded with
    the SAME token table the encoder uses.
  - The code vector q = encode(contexts) queries a bilinear pointer:
        score_k = (q W) . tok_emb[cand_k]  + mask
    softmax over the K candidates, cross-entropy on the true variable.

Everything is static-shape ([B, K] candidates) and jit-compiled; the
head adds ONE [D, E] matrix, so DP/TP sharding rules are unchanged
(pointer matrix replicated like TRANSFORM).
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from code2vec_tpu.models.encoder import ModelDims, encode, init_params

SLOT_TOKEN = "slotvar"   # the hole marker; goes through normal
                         # token normalization (already lowercase)

Params = Dict[str, jax.Array]


def init_vm_params(rng: jax.Array, dims: ModelDims) -> Params:
    """Encoder params + the pointer matrix W [D, E]."""
    k_enc, k_ptr = jax.random.split(rng)
    params = init_params(k_enc, dims)
    init = jax.nn.initializers.variance_scaling(1.0, "fan_avg", "uniform")
    params["vm_pointer"] = init(
        k_ptr, (dims.context_vector_size, dims.embeddings_size),
        jnp.float32)
    return params


def vm_scores(params: Params, source_ids: jax.Array, path_ids: jax.Array,
              target_ids: jax.Array, mask: jax.Array,
              cand_ids: jax.Array, cand_mask: jax.Array, *,
              dropout_rng: Optional[jax.Array] = None,
              dropout_keep_rate: float = 1.0,
              compute_dtype=jnp.float32,
              use_pallas: bool = False) -> Tuple[jax.Array, jax.Array]:
    """Candidate scores.

    Args: the usual [B, C] context tensors + [B, K] candidate token ids
    and 0/1 candidate mask. Returns (scores [B, K] f32 with -inf on
    padded candidates, attention [B, C]).
    """
    code, attn = encode(params, source_ids, path_ids, target_ids, mask,
                        dropout_rng=dropout_rng,
                        dropout_keep_rate=dropout_keep_rate,
                        compute_dtype=compute_dtype,
                        use_pallas=use_pallas)
    cand = jnp.take(params["token_emb"], cand_ids, axis=0)  # [B, K, E]
    q = code.astype(jnp.float32) @ params["vm_pointer"]     # [B, E]
    scores = jnp.einsum("be,bke->bk", q,
                        cand.astype(jnp.float32))           # [B, K]
    scores = jnp.where(cand_mask > 0, scores, -1e9)
    return scores, attn


def vm_loss(params: Params, batch, *, dropout_rng=None,
            dropout_keep_rate: float = 1.0, compute_dtype=jnp.float32,
            use_pallas: bool = False) -> jax.Array:
    """Weighted-mean CE over candidates. batch = (labels [B],
    src, pth, dst, mask, cand_ids [B,K], cand_mask [B,K], weights [B])."""
    labels, src, pth, dst, mask, cand_ids, cand_mask, weights = batch
    scores, _ = vm_scores(params, src, pth, dst, mask, cand_ids,
                          cand_mask, dropout_rng=dropout_rng,
                          dropout_keep_rate=dropout_keep_rate,
                          compute_dtype=compute_dtype,
                          use_pallas=use_pallas)
    logp = jax.nn.log_softmax(scores, axis=-1)
    ce = -jnp.take_along_axis(logp, labels[:, None], axis=-1)[:, 0]
    denom = jnp.maximum(jnp.sum(weights), 1.0)
    return jnp.sum(ce * weights) / denom
