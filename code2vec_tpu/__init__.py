"""code2vec-tpu: a TPU-native framework for learning distributed
representations of code from AST path-contexts.

Re-implements the full capability surface of the reference
(noamyft/code2vec — see SURVEY.md; mount was empty so SURVEY.md is the
behavior contract, cited by section): path-context extraction (native C++
instead of the reference JavaExtractor JVM component), offline preprocessing
(`.c2v` / `.dict.c2v` interchange formats, SURVEY.md §3.2), a jit-compiled
JAX/XLA path-context encoder with masked attention pooling, full and sampled
softmax over the method-name vocabulary, data/model-parallel training over a
`jax.sharding.Mesh`, orbax checkpointing, subtoken-F1 evaluation, interactive
prediction, and word2vec-format embedding export.

The design is TPU-first, not a port: static shapes throughout, batched MXU
matmuls, XLA SPMD collectives over ICI for scaling (no NCCL analog), and
Pallas kernels for the fused attention-pool hot path.
"""

__version__ = "0.1.0"
