"""Hot weight reload from committed checkpoints (ISSUE 18 tentpole).

The Check-N-Run side of the serving plane: a watcher polls the
checkpoint dir for committed steps (the `step_<N>/state` rename is the
commit point — exactly what `training/checkpoint._step_dirs` counts),
verifies each candidate against its `checksums.json` sidecar, and rolls
verified weights across the `ReplicaPool` one replica at a time
(`pool.swap_params`, generation = step). The discipline is
commit-or-refuse:

  - sha256 mismatch / missing file / unreadable manifest -> the step is
    REFUSED: `serve/reload_refused` counter, a `reload_refused` event,
    and (when an alert engine is attached) an immediate sweep so the
    ticket-severity `reload_refused` rule fires. The step lands in a
    refused set so one corrupt write doesn't log-spam every poll; the
    pool keeps serving the weights it has.
  - checksums not written yet (the trainer dies — or is simply slow —
    in the rename->sidecar window) -> no verdict this sweep; the step
    is re-examined next poll instead of being served unverified.
  - IO errors while READING verified weights retry under the shared
    `RetryPolicy` shape (`reload-io`), with the `reload/read` failpoint
    inside the retried window so chaos runs exercise exactly the
    production path; exhausted retries refuse the step (reason "io")
    rather than crashing the serving plane.

Checksum verification is reimplemented here over the same manifest
format rather than imported: `training/checkpoint.py` imports jax at
module scope, and the serving control plane must import (and be guard-
tested) with jax blocked. Loading the weights themselves DOES need jax
— the default `load_fn` late-imports the checkpoint module only when a
verified step is actually swapped in; tests inject a stdlib `load_fn`.

`ReloadManager.create()` follows the disabled-singleton discipline:
poll_s <= 0 or no checkpoint dir returns a shared no-op.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import threading
import time
from typing import Callable, Optional

from code2vec_tpu.obs import Telemetry
from code2vec_tpu.resilience import faults
from code2vec_tpu.resilience.retry import RetryPolicy

__all__ = ["ReloadManager", "committed_steps", "verify_step_files",
           "CHECKSUMS_NAME"]

# the committed-checkpoint layout contract (training/checkpoint.py owns
# the write side; this module only ever reads)
_STEP_RE = re.compile(r"^step_(\d+)$")
CHECKSUMS_NAME = "checksums.json"


def committed_steps(ckpt_dir: str):
    """Sorted [(step, step_dir)] of COMMITTED steps only — a torn save
    (temp dir present, no renamed `state`) is invisible, the same rule
    `checkpoint._step_dirs` applies on the restore side."""
    out = []
    if os.path.isdir(ckpt_dir):
        for name in os.listdir(ckpt_dir):
            m = _STEP_RE.match(name)
            if m and os.path.exists(os.path.join(ckpt_dir, name,
                                                 "state")):
                out.append((int(m.group(1)),
                            os.path.join(ckpt_dir, name)))
    return sorted(out)


def _hash_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def verify_step_files(ckpt_dir: str, step: int) -> Optional[bool]:
    """`checkpoint.verify_step`'s tri-state, stdlib-only: True = every
    state file matches its recorded sha256 (and no file is missing or
    extra); False = corrupt; None = no checksums manifest yet."""
    step_dir = os.path.join(ckpt_dir, f"step_{step}")
    manifest_path = os.path.join(step_dir, CHECKSUMS_NAME)
    if not os.path.exists(manifest_path):
        return None
    try:
        with open(manifest_path, encoding="utf-8") as f:
            recorded = json.load(f)["files"]
    except (OSError, ValueError, KeyError):
        return False  # an unreadable integrity manifest IS corruption
    state_dir = os.path.join(step_dir, "state")
    actual = {}
    for base, _dirs, files in os.walk(state_dir):
        for name in files:
            p = os.path.join(base, name)
            rel = os.path.relpath(p, step_dir).replace(os.sep, "/")
            actual[rel] = _hash_file(p)
    if set(actual) != set(recorded):
        return False
    return all(actual[k] == v.get("sha256")
               for k, v in recorded.items())


class ReloadManager:
    """Watch a checkpoint dir, verify, swap. One instance per pool.

    `load_fn(step) -> params` is injectable; the default late-imports
    `training/checkpoint` and restores against the pool's live param
    template (verify=False there — THIS manager already verified, and
    a second full-tree hash per swap would double reload IO).
    """

    def __init__(self, ckpt_dir: str, pool, *,
                 load_fn: Optional[Callable[[int], object]] = None,
                 telemetry: Telemetry = None, alerts=None,
                 poll_s: float = 1.0,
                 clock: Callable[[], float] = time.monotonic,
                 retry: Optional[RetryPolicy] = None, log=None):
        self.enabled = True
        self.ckpt_dir = ckpt_dir
        self.pool = pool
        self._load_fn = load_fn
        tele = telemetry if telemetry is not None \
            else getattr(pool, "telemetry", None)
        self.telemetry = tele if tele is not None \
            else Telemetry.disabled()
        self.alerts = alerts
        self.poll_s = poll_s
        self._clock = clock
        self._log = log or (lambda *a, **k: None)
        self.retry = retry if retry is not None else RetryPolicy(
            "reload-io", max_attempts=3, base_delay_s=0.05,
            max_delay_s=1.0, retry_on=(OSError,),
            log=self._log)
        # start from the present: steps already on disk at construction
        # are the weights the pool booted from, not news
        steps = committed_steps(ckpt_dir)
        self.last_step = steps[-1][0] if steps else -1
        self.refused: set = set()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # ---- construction ----
    @classmethod
    def create(cls, ckpt_dir: Optional[str], pool, *,
               poll_s: float = 0.0, **kw) -> "ReloadManager":
        if not ckpt_dir or poll_s <= 0:
            return _NULL_RELOAD
        return cls(ckpt_dir, pool, poll_s=poll_s, **kw)

    @classmethod
    def disabled(cls) -> "ReloadManager":
        return _NULL_RELOAD

    # ---- the sweep ----
    def check_now(self) -> Optional[int]:
        """One watcher sweep. Returns the step swapped in, or None
        (nothing new / refused / verdict pending)."""
        steps = committed_steps(self.ckpt_dir)
        if not steps:
            return None
        step = steps[-1][0]
        if step <= self.last_step or step in self.refused:
            return None
        verdict = verify_step_files(self.ckpt_dir, step)
        if verdict is None:
            # committed state, no checksums yet: the trainer is inside
            # the rename->sidecar window (or died there). Wait — a
            # serving plane never swaps unverified weights.
            return None
        if verdict is False:
            self._refuse(step, reason="checksum_mismatch")
            return None
        try:
            params = self.retry.call(self._read_params, step)
        except OSError as e:
            self._refuse(step, reason="io", error=repr(e))
            return None
        self.pool.swap_params(params, generation=step)
        self.last_step = step
        self.telemetry.count("serve/reloads")
        self.telemetry.gauge("serve/reload_step", step, emit=False)
        self.telemetry.event("weights_reloaded", step=step)
        self._log(f"reload: step {step} verified and swapped in")
        return step

    def _read_params(self, step: int):
        # inside the retry window AND before any bytes move: chaos
        # `reload/read` io_error specs exercise the retry policy on
        # exactly the path production IO errors take
        faults.fire("reload/read", step=step, path=self.ckpt_dir)
        if self._load_fn is not None:
            return self._load_fn(step)
        import code2vec_tpu.training.checkpoint as ckpt
        template = self.pool.params_template()
        restored = ckpt.load_checkpoint(self.ckpt_dir,
                                        {"params": template},
                                        step=step, verify=False)
        return restored["params"]

    def _refuse(self, step: int, reason: str, **fields) -> None:
        self.refused.add(step)
        self.telemetry.count("serve/reload_refused")
        self.telemetry.event("reload_refused", step=step,
                             reason=reason, **fields)
        self._log(f"reload REFUSED step {step}: {reason}")
        if self.alerts is not None:
            # sweep immediately so the ticket-severity rule transitions
            # on the refusal, not up to a poll period later
            self.alerts.check_now()

    # ---- polling thread ----
    def start(self) -> "ReloadManager":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(target=self._loop,
                                            name="weight-reload",
                                            daemon=True)
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.poll_s):
            try:
                self.check_now()
            except Exception as e:
                # the watcher must outlive a bad sweep (transient FS
                # weirdness, a pool mid-close); refusals and retries
                # are handled above — this is the backstop
                self._log(f"reload sweep failed: {e!r}")
                self.telemetry.count("serve/reload_sweep_errors")

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=30.0)

    def status(self) -> dict:
        return {"last_step": self.last_step,
                "refused": sorted(self.refused),
                "poll_s": self.poll_s}


class _NullReloadManager(ReloadManager):
    """Reload off: the shared no-op singleton."""

    def __init__(self):
        self.enabled = False
        self.ckpt_dir = None
        self.pool = None
        self.telemetry = Telemetry.disabled()
        self.alerts = None
        self.poll_s = 0.0
        self.last_step = -1
        self.refused = set()
        self._thread = None

    def check_now(self):
        return None

    def start(self):
        return self

    def stop(self) -> None:
        pass

    def status(self) -> dict:
        return {"last_step": -1, "refused": [], "poll_s": 0.0}


_NULL_RELOAD = _NullReloadManager()
