"""Single-file extraction bridge for the predict REPL.

Reference parity target: `extractor.py` (SURVEY.md §2 L5, §3): subprocess
the extractor on one file, parse stdout into (method_name, context_lines),
raise on failure. The reference shells out to the JavaExtractor jar; we
shell out to the native C++ extractor (code2vec_tpu/extractor/, built by
build_extractor.sh) whose stdout format is identical (SURVEY.md §3.2).
"""

from __future__ import annotations

import os
import shutil
import subprocess
from typing import List, Tuple

from code2vec_tpu.config import Config

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_DEFAULT_BINARY = os.path.join(_REPO_ROOT, "code2vec_tpu", "extractor",
                               "build", "c2v_extract")


class ExtractorError(RuntimeError):
    pass


class Extractor:
    def __init__(self, config: Config, extractor_path: str = None,
                 max_path_length: int = 8, max_path_width: int = 2,
                 language: str = "java"):
        self.config = config
        self.max_path_length = max_path_length
        self.max_path_width = max_path_width
        self.language = language
        self.extractor_path = (extractor_path
                               or os.environ.get("C2V_EXTRACTOR")
                               or _DEFAULT_BINARY)

    def _binary(self) -> str:
        if os.path.exists(self.extractor_path):
            return self.extractor_path
        found = shutil.which("c2v_extract")
        if found:
            return found
        raise ExtractorError(
            f"native extractor not found at {self.extractor_path}; build "
            f"it with ./build_extractor.sh (see code2vec_tpu/extractor/)")

    def extract_paths(self, path: str) -> Tuple[List[str], List[str]]:
        """Returns (method_names, raw_context_lines) for one source file;
        line format: `name tok,pathHash,tok ...` (SURVEY.md §3.2)."""
        if self.language == "python":
            # Python parsing is native to the host (SURVEY.md §8.3 step 8)
            try:
                from code2vec_tpu.extractor.python_extractor import (
                    extract_file)
            except ImportError as e:
                raise ExtractorError(
                    f"python extractor unavailable: {e}") from e
            lines = extract_file(path, self.max_path_length,
                                 self.max_path_width)
        else:
            cmd = [self._binary(), "--file", path,
                   "--max_path_length", str(self.max_path_length),
                   "--max_path_width", str(self.max_path_width)]
            try:
                proc = subprocess.run(cmd, capture_output=True, text=True,
                                      timeout=120)
            except subprocess.TimeoutExpired as e:
                raise ExtractorError(
                    f"extractor timed out on {path}") from e
            if proc.returncode != 0:
                raise ExtractorError(
                    f"extractor failed ({proc.returncode}): {proc.stderr}")
            lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
        if not lines:
            raise ExtractorError(f"no methods extracted from {path}")
        names = [ln.split(" ", 1)[0] for ln in lines]
        return names, lines
