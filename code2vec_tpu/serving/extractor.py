"""Extraction bridge for the serving layer.

Reference parity target: `extractor.py` (SURVEY.md §2 L5, §3): run the
extractor on one file, parse stdout into (method_name, context_lines),
raise on failure. The reference shells out to the JavaExtractor jar; we
prefer the in-process ctypes bindings to the native C++ extractor
(extractor/native.py, libc2v.so — no subprocess spawn per request) and
fall back to the c2v_extract CLI, both built by build_extractor.sh with
identical output (SURVEY.md §3.2).

`ExtractorPool` is the serving server's persistent worker pool: N
threads sharing one `Extractor`, validated up front (`preflight()`), so
a missing or non-executable binary fails at server start with the
build_extractor.sh hint instead of as an opaque subprocess error on the
first request.

Crash recovery (ISSUE 10 satellite): a WORKER-LEVEL failure — an
exec-layer death (`ExtractorCrash`), or the `serve/extract` failpoint —
restarts the pool IN PLACE on a background thread (fresh `Extractor`,
fresh preflight, fresh executor) instead of poisoning every subsequent
request. While the restart is in flight, submissions shed with the
server's explicit `ServerOverloaded` (bounded failure, not a hang);
per-INPUT failures (bad source, no methods, timeout) stay plain
`ExtractorError` and never trigger a restart. Restart attempts ride
the shared `resilience/retry` policy; if they exhaust (the binary is
really gone), the pool goes dead and every submit re-raises the
preflight error with the build hint.
"""

from __future__ import annotations

import concurrent.futures
import os
import shutil
import subprocess
import threading
from typing import List, Optional, Tuple

from code2vec_tpu.config import Config
from code2vec_tpu.resilience import faults
from code2vec_tpu.resilience import retry as retry_mod

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_DEFAULT_BINARY = os.path.join(_REPO_ROOT, "code2vec_tpu", "extractor",
                               "build", "c2v_extract")
_BUILD_HINT = ("build it with ./build_extractor.sh "
               "(see code2vec_tpu/extractor/)")


class ExtractorError(RuntimeError):
    pass


class ExtractorCrash(ExtractorError):
    """A worker-level death (exec failure, injected crash) rather than
    a per-input failure: the pool restarts in place on seeing one.
    Subclasses ExtractorError so existing callers' contracts hold."""


class Extractor:
    def __init__(self, config: Config, extractor_path: str = None,
                 max_path_length: int = 8, max_path_width: int = 2,
                 language: str = "java", use_native: bool = True):
        self.config = config
        self.max_path_length = max_path_length
        self.max_path_width = max_path_width
        self.language = language
        # in-process libc2v (thread-safe: the C API is stateless) —
        # skips the per-request subprocess spawn when the lib is built
        self.use_native = use_native
        self.extractor_path = (extractor_path
                               or os.environ.get("C2V_EXTRACTOR")
                               or _DEFAULT_BINARY)

    def _native_lib(self):
        if not self.use_native or self.language != "java":
            return None
        from code2vec_tpu.extractor import native
        return native._load()

    def _binary(self) -> str:
        if os.path.exists(self.extractor_path):
            if not os.access(self.extractor_path, os.X_OK):
                raise ExtractorError(
                    f"native extractor at {self.extractor_path} is not "
                    f"executable (incomplete build?); re-{_BUILD_HINT}")
            return self.extractor_path
        found = shutil.which("c2v_extract")
        if found:
            return found
        raise ExtractorError(
            f"native extractor not found at {self.extractor_path}; "
            f"{_BUILD_HINT}")

    def preflight(self) -> None:
        """Validate the extraction backend up front (server start /
        pool construction) so misconfiguration raises `ExtractorError`
        with the build hint, not an opaque error mid-request."""
        if self.language == "python":
            try:
                import code2vec_tpu.extractor.python_extractor  # noqa: F401
            except ImportError as e:
                raise ExtractorError(
                    f"python extractor unavailable: {e}") from e
            return
        if self._native_lib() is not None:
            return
        self._binary()

    def extract_paths(self, path: str) -> Tuple[List[str], List[str]]:
        """Returns (method_names, raw_context_lines) for one source file;
        line format: `name tok,pathHash,tok ...` (SURVEY.md §3.2)."""
        # chaos failpoint (--faults): an injected worker death the pool
        # must survive by restarting in place; disarmed = one None check
        faults.fire("serve/extract", path=path)
        if self.language == "python":
            # Python parsing is native to the host (SURVEY.md §8.3 step 8)
            try:
                from code2vec_tpu.extractor.python_extractor import (
                    extract_file)
            except ImportError as e:
                raise ExtractorError(
                    f"python extractor unavailable: {e}") from e
            lines = extract_file(path, self.max_path_length,
                                 self.max_path_width)
        elif self._native_lib() is not None:
            # in-process extraction: no subprocess spawn per request
            from code2vec_tpu.extractor import native
            try:
                with open(path, "r", encoding="utf-8",
                          errors="replace") as f:
                    source = f.read()
            except OSError as e:
                raise ExtractorError(f"cannot read {path}: {e}") from e
            lines = native.extract_source(source, self.max_path_length,
                                          self.max_path_width)
        else:
            cmd = [self._binary(), "--file", path,
                   "--max_path_length", str(self.max_path_length),
                   "--max_path_width", str(self.max_path_width)]
            try:
                proc = subprocess.run(cmd, capture_output=True, text=True,
                                      timeout=120)
            except subprocess.TimeoutExpired as e:
                raise ExtractorError(
                    f"extractor timed out on {path}") from e
            except OSError as e:
                # exec failure (wrong arch, truncated binary, perms
                # dropped after the preflight) — a WORKER death, not a
                # per-input failure: the pool restarts on it
                raise ExtractorCrash(
                    f"cannot run extractor {cmd[0]}: {e}; "
                    f"re-{_BUILD_HINT}") from e
            if proc.returncode != 0:
                raise ExtractorError(
                    f"extractor failed ({proc.returncode}): {proc.stderr}")
            lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
        if not lines:
            raise ExtractorError(f"no methods extracted from {path}")
        names = [ln.split(" ", 1)[0] for ln in lines]
        return names, lines


class ExtractorPool:
    """Persistent extraction workers for the prediction server: N
    threads over ONE `Extractor` (stateless per call), preflighted at
    construction. Extraction requests stop paying a pool/interpreter
    spawn per request; with libc2v built they are fully in-process.

    A worker CRASH (`ExtractorCrash` / an injected `serve/extract`
    fault) restarts the pool in place: the crashing request re-raises,
    requests racing the restart shed with `ServerOverloaded`, and the
    next request after the rebuild succeeds — one bad exec never
    poisons the server's remaining lifetime."""

    def __init__(self, config: Config, workers: int = None,
                 telemetry=None, **extractor_kwargs):
        self._config = config
        self._extractor_kwargs = dict(extractor_kwargs)
        self._telemetry = telemetry
        self.extractor = Extractor(config, **extractor_kwargs)
        self.extractor.preflight()
        self._workers = workers if workers is not None \
            else max(1, config.SERVE_EXTRACT_WORKERS)
        self._lock = threading.Lock()
        self._pool = self._new_executor()
        self._generation = 0
        self._restarting = False
        self._closed = False
        self._dead: Optional[BaseException] = None

    def _new_executor(self) -> "concurrent.futures.ThreadPoolExecutor":
        return concurrent.futures.ThreadPoolExecutor(
            max_workers=self._workers, thread_name_prefix="extract")

    def _count(self, name: str) -> None:
        if self._telemetry is not None:
            self._telemetry.count(name)

    def submit(self, path: str) -> "concurrent.futures.Future":
        """Async extraction; the future resolves to
        (method_names, raw_context_lines) or raises `ExtractorError`.
        Sheds with `ServerOverloaded` while a crash restart is in
        flight; re-raises the terminal preflight error once restart
        attempts are exhausted."""
        from code2vec_tpu.serving.batcher import ServerOverloaded
        with self._lock:
            if self._dead is not None:
                raise self._dead
            if self._restarting:
                self._count("serve/shed")
                raise ServerOverloaded(
                    "extractor pool restarting after a worker crash")
            # submit UNDER the lock: _begin_restart flips _restarting
            # and shuts the old executor down under/after this same
            # lock, so a request that passed the check above must reach
            # the executor before the shutdown — submitting outside
            # would race it into RuntimeError('cannot schedule new
            # futures after shutdown') instead of the documented shed
            return self._pool.submit(self._run_extract,
                                     self._generation, path)

    def _run_extract(self, generation: int, path: str):
        try:
            return self.extractor.extract_paths(path)
        except (ExtractorCrash, faults.FaultInjected):
            self._begin_restart(generation)
            raise

    def _begin_restart(self, generation: int) -> None:
        with self._lock:
            if (self._closed or self._restarting
                    or self._generation != generation):
                return  # a newer pool already exists / is being built
            self._restarting = True
            old = self._pool
        self._count("serve/extractor_restart")
        old.shutdown(wait=False)
        threading.Thread(target=self._restart, daemon=True,
                         name="extract-restart").start()

    def _restart(self) -> None:
        """Background rebuild: fresh Extractor + preflight + executor,
        under the shared retry policy (a crash during a deploy's binary
        swap resolves itself; a permanently-gone binary exhausts the
        budget and the pool goes dead with the build hint attached)."""
        policy = retry_mod.RetryPolicy(
            "extractor-restart", max_attempts=3, base_delay_s=0.05,
            max_delay_s=1.0, retry_on=(ExtractorError, OSError))

        def build() -> Extractor:
            ex = Extractor(self._config, **self._extractor_kwargs)
            ex.preflight()
            return ex

        try:
            fresh = policy.call(build)
        except BaseException as e:
            with self._lock:
                self._dead = e
                self._restarting = False
            return
        with self._lock:
            if self._closed:
                return
            self.extractor = fresh
            self._pool = self._new_executor()
            self._generation += 1
            self._restarting = False

    @property
    def restarting(self) -> bool:
        with self._lock:
            return self._restarting

    def extract_paths(self, path: str) -> Tuple[List[str], List[str]]:
        """Synchronous extraction through the pool (keeps concurrent
        callers bounded by the worker count)."""
        return self.submit(path).result()

    def close(self) -> None:
        with self._lock:
            self._closed = True
            pool = self._pool
        pool.shutdown(wait=False)
