"""Extraction bridge for the serving layer.

Reference parity target: `extractor.py` (SURVEY.md §2 L5, §3): run the
extractor on one file, parse stdout into (method_name, context_lines),
raise on failure. The reference shells out to the JavaExtractor jar; we
prefer the in-process ctypes bindings to the native C++ extractor
(extractor/native.py, libc2v.so — no subprocess spawn per request) and
fall back to the c2v_extract CLI, both built by build_extractor.sh with
identical output (SURVEY.md §3.2).

`ExtractorPool` is the serving server's persistent worker pool: N
threads sharing one `Extractor`, validated up front (`preflight()`), so
a missing or non-executable binary fails at server start with the
build_extractor.sh hint instead of as an opaque subprocess error on the
first request.
"""

from __future__ import annotations

import concurrent.futures
import os
import shutil
import subprocess
from typing import List, Tuple

from code2vec_tpu.config import Config

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_DEFAULT_BINARY = os.path.join(_REPO_ROOT, "code2vec_tpu", "extractor",
                               "build", "c2v_extract")
_BUILD_HINT = ("build it with ./build_extractor.sh "
               "(see code2vec_tpu/extractor/)")


class ExtractorError(RuntimeError):
    pass


class Extractor:
    def __init__(self, config: Config, extractor_path: str = None,
                 max_path_length: int = 8, max_path_width: int = 2,
                 language: str = "java", use_native: bool = True):
        self.config = config
        self.max_path_length = max_path_length
        self.max_path_width = max_path_width
        self.language = language
        # in-process libc2v (thread-safe: the C API is stateless) —
        # skips the per-request subprocess spawn when the lib is built
        self.use_native = use_native
        self.extractor_path = (extractor_path
                               or os.environ.get("C2V_EXTRACTOR")
                               or _DEFAULT_BINARY)

    def _native_lib(self):
        if not self.use_native or self.language != "java":
            return None
        from code2vec_tpu.extractor import native
        return native._load()

    def _binary(self) -> str:
        if os.path.exists(self.extractor_path):
            if not os.access(self.extractor_path, os.X_OK):
                raise ExtractorError(
                    f"native extractor at {self.extractor_path} is not "
                    f"executable (incomplete build?); re-{_BUILD_HINT}")
            return self.extractor_path
        found = shutil.which("c2v_extract")
        if found:
            return found
        raise ExtractorError(
            f"native extractor not found at {self.extractor_path}; "
            f"{_BUILD_HINT}")

    def preflight(self) -> None:
        """Validate the extraction backend up front (server start /
        pool construction) so misconfiguration raises `ExtractorError`
        with the build hint, not an opaque error mid-request."""
        if self.language == "python":
            try:
                import code2vec_tpu.extractor.python_extractor  # noqa: F401
            except ImportError as e:
                raise ExtractorError(
                    f"python extractor unavailable: {e}") from e
            return
        if self._native_lib() is not None:
            return
        self._binary()

    def extract_paths(self, path: str) -> Tuple[List[str], List[str]]:
        """Returns (method_names, raw_context_lines) for one source file;
        line format: `name tok,pathHash,tok ...` (SURVEY.md §3.2)."""
        if self.language == "python":
            # Python parsing is native to the host (SURVEY.md §8.3 step 8)
            try:
                from code2vec_tpu.extractor.python_extractor import (
                    extract_file)
            except ImportError as e:
                raise ExtractorError(
                    f"python extractor unavailable: {e}") from e
            lines = extract_file(path, self.max_path_length,
                                 self.max_path_width)
        elif self._native_lib() is not None:
            # in-process extraction: no subprocess spawn per request
            from code2vec_tpu.extractor import native
            try:
                with open(path, "r", encoding="utf-8",
                          errors="replace") as f:
                    source = f.read()
            except OSError as e:
                raise ExtractorError(f"cannot read {path}: {e}") from e
            lines = native.extract_source(source, self.max_path_length,
                                          self.max_path_width)
        else:
            cmd = [self._binary(), "--file", path,
                   "--max_path_length", str(self.max_path_length),
                   "--max_path_width", str(self.max_path_width)]
            try:
                proc = subprocess.run(cmd, capture_output=True, text=True,
                                      timeout=120)
            except subprocess.TimeoutExpired as e:
                raise ExtractorError(
                    f"extractor timed out on {path}") from e
            except OSError as e:
                # exec failure (wrong arch, truncated binary, perms
                # dropped after the preflight) — keep the hint attached
                raise ExtractorError(
                    f"cannot run extractor {cmd[0]}: {e}; "
                    f"re-{_BUILD_HINT}") from e
            if proc.returncode != 0:
                raise ExtractorError(
                    f"extractor failed ({proc.returncode}): {proc.stderr}")
            lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
        if not lines:
            raise ExtractorError(f"no methods extracted from {path}")
        names = [ln.split(" ", 1)[0] for ln in lines]
        return names, lines


class ExtractorPool:
    """Persistent extraction workers for the prediction server: N
    threads over ONE `Extractor` (stateless per call), preflighted at
    construction. Extraction requests stop paying a pool/interpreter
    spawn per request; with libc2v built they are fully in-process."""

    def __init__(self, config: Config, workers: int = None,
                 **extractor_kwargs):
        self.extractor = Extractor(config, **extractor_kwargs)
        self.extractor.preflight()
        n = workers if workers is not None \
            else max(1, config.SERVE_EXTRACT_WORKERS)
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=n, thread_name_prefix="extract")

    def submit(self, path: str) -> "concurrent.futures.Future":
        """Async extraction; the future resolves to
        (method_names, raw_context_lines) or raises `ExtractorError`."""
        return self._pool.submit(self.extractor.extract_paths, path)

    def extract_paths(self, path: str) -> Tuple[List[str], List[str]]:
        """Synchronous extraction through the pool (keeps concurrent
        callers bounded by the worker count)."""
        return self.submit(path).result()

    def close(self) -> None:
        self._pool.shutdown(wait=False)
