"""code2vec_tpu.serving — the serving surface (ISSUE 3; external
plane ISSUE 18).

`PredictionServer` (server.py) is the batched entry point: request
queue -> dynamic micro-batcher (batcher.py) -> bucketed device batches,
with an LRU prediction cache, bounded-queue admission control, and a
persistent extractor worker pool (extractor.py). The interactive REPL
(interactive_predict.py) and the load generator (tools/loadgen.py) are
thin clients of it.

The external serving plane stacks on top: a `ReplicaPool`
(replicas.py) of N servers behind ONE generation-scoped cache with
least-outstanding dispatch and death/refill, a `ReloadManager`
(reload.py) hot-swapping verified committed checkpoints one replica at
a time, an `AutoScaler` (autoscale.py) sizing the pool off the SLO
alert rules, and a `ServingFrontend` (frontend.py) putting POST
/predict + /healthz + /metrics + /pool on a socket. All four are
stdlib-only at module scope (guard: tests/test_frontend.py).
"""

from code2vec_tpu.serving.autoscale import AutoScaler  # noqa: F401
from code2vec_tpu.serving.batcher import (MicroBatcher,  # noqa: F401
                                          PredictRequest,
                                          ServerOverloaded)
from code2vec_tpu.serving.extractor import (Extractor,  # noqa: F401
                                            ExtractorError,
                                            ExtractorPool)
from code2vec_tpu.serving.frontend import ServingFrontend  # noqa: F401
from code2vec_tpu.serving.reload import ReloadManager  # noqa: F401
from code2vec_tpu.serving.replicas import (Replica,  # noqa: F401
                                           ReplicaPool,
                                           SharedCacheView)
from code2vec_tpu.serving.server import (PredictionCache,  # noqa: F401
                                         PredictionServer, normalize_bag)
