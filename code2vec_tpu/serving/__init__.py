"""code2vec_tpu.serving — the serving surface (ISSUE 3).

`PredictionServer` (server.py) is the batched entry point: request
queue -> dynamic micro-batcher (batcher.py) -> bucketed device batches,
with an LRU prediction cache, bounded-queue admission control, and a
persistent extractor worker pool (extractor.py). The interactive REPL
(interactive_predict.py) and the load generator (tools/loadgen.py) are
thin clients of it.
"""

from code2vec_tpu.serving.batcher import (MicroBatcher,  # noqa: F401
                                          PredictRequest,
                                          ServerOverloaded)
from code2vec_tpu.serving.extractor import (Extractor,  # noqa: F401
                                            ExtractorError,
                                            ExtractorPool)
from code2vec_tpu.serving.server import (PredictionCache,  # noqa: F401
                                         PredictionServer, normalize_bag)
