"""Replica fleet behind the serving front-end (ISSUE 18 tentpole).

A `ReplicaPool` owns N `PredictionServer` instances (one model each, so
a wedged device call on one replica never blocks the others) behind ONE
shared `PredictionCache` and dispatches each request to the ready
replica with the fewest outstanding requests. The pool composes the
pieces the serving arc already shipped:

  - admission control stays per-replica (bounded queue, deadlines,
    `serve/shed`) — the pool never catches `ServerOverloaded`, shed is
    an explicit client-visible outcome, not a retry;
  - a replica that DIES mid-request (the `serve/kill` failpoint, or any
    non-input crash) is removed, the request retries on a surviving
    replica — zero requests lost — and a background refill grows the
    pool back toward target through the supervisor's replacement
    discipline (`replacement_fn` gate, one replica at a time);
  - hot weight swap (`swap_params`) invalidates the shared cache
    atomically, then drains-and-swaps ONE replica at a time, so the
    pool never drops below N-1 ready and post-swap predictions never
    mix old and new params (the cache generation refuses stale
    readers/writers);
  - zero new jit compilations under load: each replica warms its pow-2
    predict buckets at start, the pool records that compile count as
    the replica's baseline, and `compile_delta()` reports any compile
    the serving path triggered afterwards.

Telemetry rides the shared registry: `serve/pool_size` /
`serve/pool_ready` / `serve/pool_target` / `serve/pool_generation`
gauges, `serve/replica_dead` / `serve/replica_refill` counters, and a
`fleet`-style `pool_table()` for the front-end's `/pool` route.

Stdlib-only at module scope (the front-end guard test imports this with
jax blocked); the models behind the replicas are whatever the
`model_factory` builds.
"""

from __future__ import annotations

import copy
import threading
import time
from typing import Callable, List, Optional

from code2vec_tpu.obs import Telemetry
from code2vec_tpu.serving.batcher import ServerOverloaded
from code2vec_tpu.serving.server import PredictionCache, PredictionServer

__all__ = ["Replica", "ReplicaPool", "SharedCacheView"]

# client mistakes stay client errors: a malformed line must bounce off
# ONE replica as 400-class, not execute N times and drain the pool
_INPUT_ERRORS = (ValueError, KeyError, TypeError)

# replica lifecycle: starting -> ready -> (draining -> ready)* and
# terminally dead (crashed) or stopped (shrunk/closed)
_PICKABLE = "ready"


class SharedCacheView:
    """A replica's window onto the pool's shared cache: every get/put
    carries the OWNING replica's weight generation, so a mid-swap
    replica still serving old params can neither read entries computed
    under the new weights nor poison the cache with old-params results.
    Duck-types the `PredictionCache` surface `PredictionServer` uses
    (`capacity`, `get`, `put`, `__len__`)."""

    def __init__(self, cache: PredictionCache, replica: "Replica"):
        self._cache = cache
        self._replica = replica

    @property
    def capacity(self) -> int:
        return self._cache.capacity

    def get(self, key):
        return self._cache.get(key, generation=self._replica.generation)

    def put(self, key, value) -> None:
        self._cache.put(key, value, generation=self._replica.generation)

    def __len__(self) -> int:
        return len(self._cache)


class Replica:
    """One pool member: a `PredictionServer` plus the pool-side state
    the dispatcher and swapper need. Mutations happen under the pool
    lock; `server` itself is internally thread-safe."""

    def __init__(self, idx: int, generation: int):
        self.idx = idx
        self.generation = generation
        self.server: Optional[PredictionServer] = None
        self.state = "starting"
        self.outstanding = 0
        self.requests = 0
        self.failures = 0
        self.swaps = 0
        self.compile_baseline = 0
        self.born_s = time.monotonic()

    def row(self) -> dict:
        """One `pool_table()` row — the fleet-plane host-row shape."""
        c = (self.server.model.predict_compile_count()
             if self.server is not None else -1)
        return {"replica": self.idx, "state": self.state,
                "generation": self.generation,
                "outstanding": self.outstanding,
                "requests": self.requests, "failures": self.failures,
                "swaps": self.swaps,
                "compiles": c,
                "compile_delta": (max(0, c - self.compile_baseline)
                                  if c >= 0 else 0),
                "age_s": round(time.monotonic() - self.born_s, 3)}


class ReplicaPool:
    """N prediction replicas, one cache, least-outstanding dispatch.

    `model_factory()` builds one model per replica (called with the
    pool lock NOT held — factories may compile). The pool exposes the
    same `predict_lines` surface as a single `PredictionServer`, so
    `tools/loadgen.py` and the HTTP front-end drive either
    interchangeably.
    """

    def __init__(self, config, model_factory: Callable[[], object], *,
                 replicas: Optional[int] = None,
                 telemetry: Telemetry = None,
                 cache: Optional[PredictionCache] = None,
                 replacement_fn: Optional[Callable[[], bool]] = None,
                 log=None):
        self.config = config
        self._factory = model_factory
        tele = telemetry if telemetry is not None \
            else Telemetry.memory("serve")
        tele.make_threadsafe()
        self.telemetry = tele
        self.cache = cache if cache is not None \
            else PredictionCache(getattr(config, "SERVE_CACHE_SIZE", 0))
        self._replacement_fn = replacement_fn
        self._log = log if log is not None \
            else getattr(config, "log", None) or (lambda *a, **k: None)
        n = replicas if replicas is not None \
            else getattr(config, "SERVE_REPLICAS", 1)
        self.min_replicas = getattr(config, "SERVE_MIN_REPLICAS", 1)
        self.max_replicas = max(getattr(config, "SERVE_MAX_REPLICAS", n),
                                n)
        self._target = max(1, n)
        self._params = None           # set by the first swap_params
        self._params_gen: Optional[int] = None
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._replicas: List[Replica] = []
        self._next_idx = 0
        self._refill_threads: List[threading.Thread] = []
        self._closed = False

    # ---- lifecycle ----
    def start(self, warmup: bool = True) -> "ReplicaPool":
        for _ in range(self._target):
            self._add_replica(warmup=warmup)
        self._publish()
        return self

    def close(self) -> None:
        with self._lock:
            self._closed = True
            reps = list(self._replicas)
            refills = list(self._refill_threads)
            self._cv.notify_all()
        for t in refills:
            t.join(timeout=30.0)
        for rep in reps:
            self._stop_replica(rep, state="stopped")
        self._publish()

    def _replica_config(self):
        """Each replica gets a copy with the live-plane flags OFF: the
        pool/front-end owns the single exposition server and alert
        engine — N replicas must not race to bind N metrics ports."""
        cfg = copy.copy(self.config)
        cfg.METRICS_PORT = 0
        cfg.ALERTS_MODE = "off"
        return cfg

    def _add_replica(self, warmup: bool = True) -> Replica:
        """Build + start one replica and make it pickable. The model
        build and bucket warmup run OUTSIDE the pool lock (they may
        compile for seconds); the replica only becomes visible to the
        dispatcher once ready."""
        with self._lock:
            gen = self._params_gen if self._params_gen is not None else 0
            rep = Replica(self._next_idx, generation=gen)
            self._next_idx += 1
        model = self._factory()
        server = PredictionServer(
            self._replica_config(), model, telemetry=self.telemetry,
            cache=SharedCacheView(self.cache, rep))
        # a refill that joins after a swap must serve the CURRENT
        # weights, not the factory's initial ones
        params = self._params
        if params is not None:
            model.params = params
        server.start(warmup=warmup)
        rep.server = server
        c = model.predict_compile_count()
        rep.compile_baseline = c if c >= 0 else 0
        with self._lock:
            rep.state = "ready"
            self._replicas.append(rep)
            self._cv.notify_all()
        self._publish()
        return rep

    def _stop_replica(self, rep: Replica, state: str) -> None:
        with self._lock:
            rep.state = state
            if rep in self._replicas:
                self._replicas.remove(rep)
            self._cv.notify_all()
        if rep.server is not None:
            try:
                rep.server.close()
            except Exception as e:  # a dying replica must not take
                self._log(f"replica {rep.idx} close failed: {e!r}")
        self._publish()

    # ---- dispatch ----
    def _pick(self, exclude, wait_s: float = 5.0) -> Replica:
        """Least-outstanding ready replica (tie-break: lowest idx).
        Waits briefly when none is ready — the N=1 pool mid-swap has
        zero ready replicas for the drain window, and shedding there
        would turn every swap into downtime."""
        deadline = time.monotonic() + wait_s
        with self._lock:
            while True:
                if self._closed:
                    raise ServerOverloaded("replica pool closed")
                ready = [r for r in self._replicas
                         if r.state == _PICKABLE and r not in exclude]
                if ready:
                    rep = min(ready,
                              key=lambda r: (r.outstanding, r.idx))
                    rep.outstanding += 1
                    rep.requests += 1
                    return rep
                left = deadline - time.monotonic()
                if left <= 0:
                    raise ServerOverloaded("no ready replicas")
                self._cv.wait(timeout=left)

    def predict_lines(self, lines, deadline_ms: float = None):
        """Dispatch one request; on a replica DEATH (not overload, not
        a client input error) the request transparently retries on a
        surviving replica while a background refill replaces the dead
        one — the `serve_swap_kill` chaos leg's \"0 requests lost\"
        contract."""
        tried: List[Replica] = []
        # bound the retry walk: every attempt burns a distinct replica,
        # so max_replicas+1 attempts means the whole fleet died on us
        for _ in range(self.max_replicas + 1):
            rep = self._pick(exclude=tried)
            try:
                return rep.server.predict_lines(lines,
                                                deadline_ms=deadline_ms)
            except ServerOverloaded:
                raise
            except _INPUT_ERRORS:
                raise
            except Exception as e:
                tried.append(rep)
                self._on_replica_death(rep, e)
            finally:
                with self._lock:
                    rep.outstanding -= 1
                    self._cv.notify_all()
        raise ServerOverloaded(
            f"all {self.max_replicas + 1} dispatch attempts hit dead "
            f"replicas")

    def _on_replica_death(self, rep: Replica, exc: BaseException) -> None:
        with self._lock:
            if rep.state == "dead":      # concurrent requests on the
                return                   # same corpse report it once
            rep.state = "dead"
            rep.failures += 1
            self._cv.notify_all()
        self.telemetry.count("serve/replica_dead")
        self.telemetry.event("replica_dead", replica=rep.idx,
                             error=repr(exc))
        self._log(f"replica {rep.idx} died: {exc!r}")
        t = threading.Thread(target=self._reap_and_refill, args=(rep,),
                             name=f"replica-reap-{rep.idx}", daemon=True)
        t.start()
        with self._lock:
            self._refill_threads.append(t)

    def _reap_and_refill(self, rep: Replica) -> None:
        self._stop_replica(rep, state="dead")
        # grow back toward target one replica at a time, consulting the
        # same replacement gate the training supervisor uses — a budget
        # that says no leaves the pool smaller, not wedged
        while True:
            with self._lock:
                if self._closed or len(self._replicas) >= self._target:
                    return
            if self._replacement_fn is not None \
                    and not self._replacement_fn():
                self.telemetry.event("replica_refill_denied",
                                     replica=rep.idx)
                return
            self.telemetry.count("serve/replica_refill")
            self._add_replica(warmup=True)

    # ---- hot weight swap (reload.py drives this) ----
    def swap_params(self, params, generation: int) -> None:
        """Roll new weights across the fleet, one replica at a time.

        Commit point FIRST: the shared cache is atomically cleared and
        advanced to `generation`, so from that instant old-generation
        replicas are cache-isolated (no stale reads, no stale writes).
        Then each replica is drained (no new picks, in-flight requests
        finish), its params assigned (same shapes -> the warmed pow-2
        buckets stay compiled), its generation bumped, and it returns
        to ready before the next replica leaves — the pool never drops
        below N-1 ready."""
        with self._lock:
            self._params = params
            self._params_gen = generation
            reps = list(self._replicas)
        self.cache.invalidate(generation)
        for rep in reps:
            with self._lock:
                if rep.state != "ready":
                    continue
                rep.state = "draining"
                self._cv.notify_all()
            self._publish()
            self._drain(rep)
            rep.server.model.params = params
            with self._lock:
                rep.generation = generation
                rep.swaps += 1
                rep.state = "ready"
                self._cv.notify_all()
            self._publish()
        self.telemetry.event("weights_swapped", generation=generation,
                             replicas=len(reps))

    def _drain(self, rep: Replica, timeout_s: float = 60.0) -> None:
        deadline = time.monotonic() + timeout_s
        with self._lock:
            while rep.outstanding > 0:
                left = deadline - time.monotonic()
                if left <= 0:
                    self._log(f"replica {rep.idx} drain timed out with "
                              f"{rep.outstanding} outstanding")
                    return
                self._cv.wait(timeout=left)

    # ---- autoscaler surface ----
    def grow(self) -> bool:
        with self._lock:
            if self._closed or self._target >= self.max_replicas:
                return False
            self._target += 1
        self._add_replica(warmup=True)
        return True

    def shrink(self) -> bool:
        with self._lock:
            ready = [r for r in self._replicas if r.state == "ready"]
            if self._closed or self._target <= self.min_replicas \
                    or self._target <= 1 or len(ready) <= 1:
                return False
            self._target -= 1
            # youngest ready replica leaves: the long-lived ones carry
            # the warmest device state
            rep = max(ready, key=lambda r: r.idx)
            rep.state = "draining"
            self._cv.notify_all()
        self._publish()
        self._drain(rep)
        self._stop_replica(rep, state="stopped")
        return True

    # ---- introspection ----
    @property
    def target(self) -> int:
        with self._lock:
            return self._target

    def ready_count(self) -> int:
        with self._lock:
            return sum(1 for r in self._replicas if r.state == "ready")

    def size(self) -> int:
        with self._lock:
            return len(self._replicas)

    def generation(self) -> int:
        with self._lock:
            return self._params_gen if self._params_gen is not None else 0

    def params_template(self):
        """A live replica's current params — the restore template the
        reload manager hands to `load_checkpoint` (shapes/dtypes must
        match the checkpoint; any live replica's do)."""
        with self._lock:
            for rep in self._replicas:
                if rep.server is not None:
                    return rep.server.model.params
        raise RuntimeError("replica pool has no live replica to "
                           "template params from")

    def compile_delta(self) -> int:
        """Jit compilations the SERVING path triggered after warmup,
        summed over live replicas (models that cannot introspect report
        -1 and are skipped) — the chaos leg's zero-compile gate."""
        with self._lock:
            reps = list(self._replicas)
        total = 0
        for rep in reps:
            if rep.server is None:
                continue
            c = rep.server.model.predict_compile_count()
            if c >= 0:
                total += max(0, c - rep.compile_baseline)
        return total

    def wait_ready(self, n: int, timeout_s: float = 60.0) -> bool:
        deadline = time.monotonic() + timeout_s
        with self._lock:
            while sum(1 for r in self._replicas
                      if r.state == "ready") < n:
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._cv.wait(timeout=left)
            return True

    def pool_table(self) -> dict:
        """Fleet-plane-style snapshot for `/pool` and the chaos/bench
        reports: per-replica rows + pool aggregates."""
        with self._lock:
            rows = [r.row() for r in self._replicas]
            gen = self._params_gen if self._params_gen is not None else 0
            target = self._target
        ready = sum(1 for r in rows if r["state"] == "ready")
        return {"replicas": rows, "size": len(rows), "ready": ready,
                "target": target, "generation": gen,
                "cache_entries": len(self.cache),
                "cache_generation": self.cache.generation}

    def _publish(self) -> None:
        with self._lock:
            size = len(self._replicas)
            ready = sum(1 for r in self._replicas
                        if r.state == "ready")
            gen = self._params_gen if self._params_gen is not None else 0
            target = self._target
        self.telemetry.gauge("serve/pool_size", size, emit=False)
        self.telemetry.gauge("serve/pool_ready", ready, emit=False)
        self.telemetry.gauge("serve/pool_target", target, emit=False)
        self.telemetry.gauge("serve/pool_generation", gen, emit=False)
