"""Batched prediction server (ISSUE 3 tentpole).

Turns the per-request predict path (extract -> parse -> batch-1 device
call) into a throughput engine:

  - client threads call `predict_lines()` / `predict_file()`; parsing
    (`model.prepare_predict_rows`) runs on the CALLER's thread, so host
    work scales with clients while the device stays single-owner;
  - a `MicroBatcher` (serving/batcher.py) coalesces concurrent requests
    into one padded device batch at the power-of-two buckets the jitted
    predict step compiles — `start()` warms every bucket up to
    `--serve_batch_max`, so steady-state serving triggers ZERO new jit
    compilations;
  - an LRU prediction cache keyed by the normalized path-context bag:
    hits skip encode + device entirely (`serve/cache_hit` counter);
  - admission control: a bounded queue plus per-request deadline shed
    load with an explicit `ServerOverloaded` instead of unbounded
    latency growth (`serve/shed` counter);
  - extraction goes through a persistent `ExtractorPool` — no
    subprocess/pool spawn per request.

Telemetry (code2vec_tpu/obs): `serve/request_ms` / `serve/extract_ms`
histograms on the request path, `serve/parse_ms` / `serve/encode_ms` /
`serve/predict_ms` from the model, `serve/queue_depth` and
`serve/batch_occupancy` gauges, `serve/batch_methods` batch-size
histogram, and `serve/requests`, `serve/batches`, `serve/cache_hit`,
`serve/cache_miss`, `serve/shed` counters. The registry is made
thread-safe (`make_threadsafe`) because client threads, the extractor
pool, and the batcher all record into it.

Cache semantics: a method whose contexts exceed MAX_CONTEXTS is
downsampled at parse time by a draw seeded from the SAME normalized
bag the cache key uses (data/reader.parse_c2v_rows), so a cached
prediction equals what a fresh parse of that bag would produce —
regardless of where in a batch, or in what context order, the method
reappears.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from code2vec_tpu.common import MethodPredictionResults
from code2vec_tpu.config import Config
from code2vec_tpu.obs import (Telemetry, Tracer, Watchdog,
                              build_live_plane)
from code2vec_tpu.resilience import faults
from code2vec_tpu.serving.batcher import (MicroBatcher, PredictRequest,
                                          ServerOverloaded)
from code2vec_tpu.serving.extractor import ExtractorPool

__all__ = ["PredictionServer", "PredictionCache", "ServerOverloaded",
           "normalize_bag"]


def normalize_bag(line: str) -> Tuple[str, Tuple[str, ...]]:
    """Cache key for one extractor line: (method name, sorted bag of
    non-empty context fields). Context ORDER is irrelevant to the model
    (a bag-of-contexts / set encoder), so reordered extractions of the
    same method hit the same entry; padding fields ('' / ',,') are
    dropped the same way the parser drops them."""
    # rstrip exactly like parse_c2v_rows: a newline-terminated copy of
    # a line must hit the same cache entry as the bare one
    parts = line.rstrip("\n").split(" ")
    ctxs = sorted(p for p in parts[1:] if p and p != ",,")
    return parts[0], tuple(ctxs)


class PredictionCache:
    """Thread-safe LRU over normalized path-context bags. Values are the
    finished `MethodPredictionResults` — a hit skips parse, encode and
    the device round-trip entirely.

    Generations (ISSUE 18): when a ReplicaPool shares ONE cache across
    replicas, a hot weight swap must invalidate atomically — clear +
    bump happen under the same lock, and `get`/`put` carrying a stale
    `generation` are refused, so a mid-roll replica still running old
    params can neither read new-generation entries nor write old-params
    results back. Callers that never pass `generation` (the
    single-server path) are unaffected: None matches any generation."""

    def __init__(self, capacity: int):
        self.capacity = capacity
        self.generation = 0
        self._lock = threading.Lock()
        self._d: "collections.OrderedDict" = collections.OrderedDict()

    def get(self, key, generation: Optional[int] = None
            ) -> Optional[MethodPredictionResults]:
        if self.capacity <= 0:
            return None
        with self._lock:
            if generation is not None and generation != self.generation:
                return None
            val = self._d.get(key)
            if val is not None:
                self._d.move_to_end(key)
            return val

    def put(self, key, value: MethodPredictionResults,
            generation: Optional[int] = None) -> None:
        if self.capacity <= 0:
            return
        with self._lock:
            if generation is not None and generation != self.generation:
                return
            self._d[key] = value
            self._d.move_to_end(key)
            while len(self._d) > self.capacity:
                self._d.popitem(last=False)

    def invalidate(self, generation: int) -> None:
        """Drop every entry and advance to `generation` in one critical
        section — the atomic swap barrier. Concurrent readers see either
        (old entries, old generation) or (empty, new generation), never
        a mix."""
        with self._lock:
            self._d.clear()
            self.generation = generation

    def __len__(self) -> int:
        with self._lock:
            return len(self._d)


class PredictionServer:
    """The serving facade: request queue + micro-batcher + cache +
    extractor pool around one model. `InteractivePredictor` is a thin
    client of this; `tools/loadgen.py` drives it at target QPS."""

    def __init__(self, config: Config, model, telemetry: Telemetry = None,
                 tracer: Tracer = None, watchdog: Watchdog = None,
                 cache=None):
        self.config = config
        self.model = model
        tele = telemetry if telemetry is not None \
            else Telemetry.memory("serve")
        tele.make_threadsafe()
        self.telemetry = tele
        # the model's serve/encode_ms + serve/predict_ms spans land in
        # the same registry (as the REPL always arranged)
        model.telemetry = tele
        # request-scoped tracing (--trace, ISSUE 6): the client threads
        # open request/parse/decode spans, the batcher flush continues
        # them (serve/batch_flush + serve/encode + serve/device) via the
        # SpanContext riding each PredictRequest. Off = one boolean
        # check per request (the shared disabled tracer).
        if tracer is None:
            tracer = Tracer.create(tele) \
                if getattr(config, "TRACE", False) else Tracer.disabled()
        self.tracer = tracer
        model.tracer = tracer
        # stall watchdog (--watchdog_stall_s): the batcher consumer
        # heartbeats per flush — a hung device call or wedged flush
        # surfaces as a `stall` event + diagnostic dump
        if watchdog is None:
            watchdog = Watchdog.create(
                tele, stall_s=getattr(config, "WATCHDOG_STALL_S", 0.0),
                mode=getattr(config, "WATCHDOG_MODE", "warn"),
                tracer=tracer, log=getattr(config, "log", None))
        self.watchdog = watchdog
        self._batcher_hb = watchdog.register("batcher_consumer")
        # live metrics plane (ISSUE 7): /metrics //healthz //vars over
        # the serving registry (readiness gates on the batcher's
        # heartbeat), plus the serving health monitors (cache-hit
        # collapse, shed rate) and alert rules on a cadence thread —
        # the shared wiring; all no-op singletons with the flags off.
        from code2vec_tpu.obs.alerts import default_serving_rules
        from code2vec_tpu.obs.health import default_serving_monitors
        self._live_plane = build_live_plane(
            tele, metrics_port=getattr(config, "METRICS_PORT", 0),
            alerts_mode=getattr(config, "ALERTS_MODE", "off"),
            alerts_rules=getattr(config, "ALERTS_RULES", None),
            health_every_s=getattr(config, "HEALTH_EVERY_S", 1.0),
            watchdog=watchdog, monitors=default_serving_monitors(),
            default_rules=default_serving_rules,
            log=getattr(config, "log", None))
        self.health = self._live_plane.health
        self.alerts = self._live_plane.alerts
        self.metrics_server = self._live_plane.metrics
        # per-instance by default; a ReplicaPool injects a shared
        # generation-scoped view so N replicas hit ONE cache (ISSUE 18)
        self.cache = cache if cache is not None \
            else PredictionCache(config.SERVE_CACHE_SIZE)
        self.batcher = MicroBatcher(
            self._run_batch, max_batch=config.SERVE_BATCH_MAX,
            timeout_ms=config.SERVE_BATCH_TIMEOUT_MS,
            queue_depth=config.SERVE_QUEUE_DEPTH, telemetry=tele)
        self._extractors: Optional[ExtractorPool] = None
        self._extractor_kwargs: Optional[Dict] = None
        self._started = False
        self._lifecycle_lock = threading.Lock()

    # ---- lifecycle ----
    def start(self, warmup: bool = True) -> "PredictionServer":
        """Warm the predict-step shape buckets (compile once, serve
        forever) and start the batcher thread. Idempotent — safe under
        concurrent first requests (predict_lines auto-starts)."""
        with self._lifecycle_lock:
            if self._started:
                return self
            if warmup:
                t0 = time.perf_counter()
                buckets = self.model.warmup_predict(
                    self.config.SERVE_BATCH_MAX)
                self.telemetry.event(
                    "serve_warmup", buckets=buckets,
                    warmup_ms=round((time.perf_counter() - t0) * 1e3, 1),
                    compiled=self.model.predict_compile_count())
            self.batcher.start()
            self.watchdog.start()
            self._live_plane.start()
            self._started = True
        return self

    def close(self) -> None:
        with self._lifecycle_lock:
            self.batcher.stop()
            if self._extractors is not None:
                self._extractors.close()
                self._extractors = None
                self._extractor_kwargs = None
            self._started = False
        self.watchdog.stop()
        self._live_plane.stop()
        # after teardown so a raise-mode sticky stall/alert cannot
        # leak the batcher/extractor threads by raising mid-close
        self.watchdog.poll()
        self.alerts.poll()

    def extractor_pool(self, **extractor_kwargs) -> ExtractorPool:
        """The persistent extraction pool, built (and preflighted) once
        on first use so line-only serving never requires the binary.
        The first call fixes the extractor configuration — a later call
        with different kwargs is an error (swapping would close a pool
        other threads are extracting on)."""
        with self._lifecycle_lock:
            if self._extractors is None:
                self._extractors = ExtractorPool(self.config,
                                                 telemetry=self.telemetry,
                                                 **extractor_kwargs)
                self._extractor_kwargs = dict(extractor_kwargs)
            elif extractor_kwargs != self._extractor_kwargs:
                raise ValueError(
                    f"extractor pool already built with "
                    f"{self._extractor_kwargs}; restart the server to "
                    f"change extractor settings (got {extractor_kwargs})")
            return self._extractors

    # ---- request path (client threads) ----
    def predict_file(self, path: str, deadline_ms: float = None,
                     **extractor_kwargs) -> List[MethodPredictionResults]:
        """Extract one source file through the worker pool, then predict
        its methods through the batcher. `serve/request_ms` covers
        extract + predict end-to-end, exactly as the pre-server REPL
        recorded it."""
        request_span = self.telemetry.span("serve/request_ms")
        root = self.tracer.start_trace("serve/request", file=path) \
            if self.tracer.enabled else None
        span = self.telemetry.span("serve/extract_ms")
        ex_span = self.tracer.start_span("serve/extract", parent=root) \
            if root is not None else None
        try:
            _, lines = self.extractor_pool(**extractor_kwargs) \
                .extract_paths(path)
        except BaseException:
            # close the trace on the error path too — an un-ended root
            # would sit in the live-span table forever (and pollute
            # every watchdog stall dump with phantom requests); the
            # telemetry spans cancel (a dead extract's partial ms
            # would pollute the extract/request histograms), and
            # request_span must close HERE — its ownership only
            # transfers to predict_lines on the success path
            span.cancel()
            request_span.cancel()
            if root is not None:
                ex_span.end()
                root.end(outcome="error")
            raise
        if ex_span is not None:
            ex_span.end()
        extract_ms = span.stop()
        return self.predict_lines(lines, deadline_ms=deadline_ms,
                                  extract_ms=extract_ms,
                                  _request_span=request_span,
                                  _trace_root=root)

    def predict_lines(self, lines: Sequence[str],
                      deadline_ms: float = None,
                      extract_ms: float = None,
                      _request_span=None, _trace_root=None
                      ) -> List[MethodPredictionResults]:
        """Predict a bag of extractor lines (one result per non-empty
        line, input order). Raises `ServerOverloaded` when shed by
        admission control or past its deadline. `deadline_ms=0`
        explicitly disables the deadline (a single-user client waiting
        out a cold jit compile); None takes `--serve_deadline_ms`."""
        if not self._started:
            self.start()
        # chaos failpoint (--faults, ISSUE 13): a replica-process death
        # on the request path (action `kill` — the SIGKILL a replica
        # pool must absorb; ROADMAP item 1's serving-chaos hook).
        # Before any span opens so nothing leaks when it fires;
        # disarmed — the default — it is one None check.
        faults.fire("serve/kill")
        # host-only filter BEFORE the spans open: nothing here belongs
        # in request_ms, and the acquire-to-try window stays raise-free
        lines = [ln for ln in lines if ln.strip()]
        request_span = (_request_span if _request_span is not None
                        else self.telemetry.span("serve/request_ms"))
        # request-scoped trace root: ONE trace id follows this request
        # through the queue, the batcher flush, the device call and the
        # client-thread decode (--trace; off = one boolean check)
        root = _trace_root
        if root is None and self.tracer.enabled:
            root = self.tracer.start_trace("serve/request",
                                           n_methods=len(lines))
        if not lines:
            # all-blank input never reaches the queue and emits no
            # `request` event — cancel (not stop) so the request_ms
            # histogram, serve/requests counter, and report stay in
            # agreement about what counts as a request
            request_span.cancel()
            if root is not None:
                root.end(n_results=0)
            return []
        if deadline_ms is None:
            deadline_ms = self.config.SERVE_DEADLINE_MS
        deadline = (time.monotonic() + deadline_ms / 1e3
                    if deadline_ms and deadline_ms > 0 else None)
        try:
            # cache probe: hits never touch the queue (skipped entirely at
            # capacity 0 — no key sorts, no counters, on the load path)
            out: List[Optional[MethodPredictionResults]] = [None] * len(lines)
            use_cache = self.cache.capacity > 0
            keys: List = [None] * len(lines)
            miss_idx: List[int] = []
            if use_cache:
                for i, ln in enumerate(lines):
                    keys[i] = key = normalize_bag(ln)
                    hit = self.cache.get(key)
                    if hit is not None:
                        out[i] = hit
                        self.telemetry.count("serve/cache_hit")
                    else:
                        miss_idx.append(i)
                        self.telemetry.count("serve/cache_miss")
            else:
                miss_idx = list(range(len(lines)))

            if miss_idx:
                # host parse on the CALLER's thread — the batcher only sees
                # ready-to-pad rows; oversized requests chunk to max_batch
                # so every flush stays inside the warmed buckets
                parse_span = self.tracer.start_span(
                    "serve/parse", parent=root, n=len(miss_idx)) \
                    if root is not None else None
                try:
                    prepared = self.model.prepare_predict_rows(
                        [lines[i] for i in miss_idx])
                except BaseException:
                    # malformed input: close the trace instead of leaking
                    # root/parse into the live-span table on every bad
                    # request a long-running server sees
                    if root is not None:
                        parse_span.end()
                        root.end(outcome="error")
                    raise
                if parse_span is not None:
                    parse_span.end()
                root_ctx = root.context() if root is not None else None
                cap = self.batcher.max_batch
                chunks = [prepared.slice(at, min(at + cap, prepared.n))
                          for at in range(0, prepared.n, cap)]
                reqs = []
                for chunk in chunks:
                    req = PredictRequest(chunk, chunk.n, deadline=deadline,
                                         trace_ctx=root_ctx)
                    if not self.batcher.submit(req):
                        # shed the WHOLE request: resolve the sibling
                        # chunks already queued so the batcher skips them
                        # instead of computing results nobody will consume.
                        # serve/shed counts CHUNKS (queue units) on every
                        # shed path; loadgen's `shed` counts requests.
                        overload = ServerOverloaded(
                            "server shutting down"
                            if not self.batcher.running else
                            f"request queue full "
                            f"(depth {self.batcher.queue_depth})")
                        n_shed = 1  # the refused chunk
                        for prev in reqs:
                            if prev.fail(overload):
                                n_shed += 1
                        self.telemetry.count("serve/shed", n_shed)
                        if root is not None:
                            root.end(outcome="shed")
                        raise overload
                    reqs.append(req)
                miss_results: List[MethodPredictionResults] = []
                decode_span = None
                try:
                    for chunk, req in zip(chunks, reqs):
                        # wait past the deadline by one batch window so an
                        # in-flight batch containing this request can still
                        # land
                        wait_s = None
                        if deadline is not None:
                            wait_s = max(0.0, deadline - time.monotonic()) \
                                + self.batcher.timeout_s + 5.0
                        if not req.wait(wait_s):
                            if req.fail(ServerOverloaded(
                                    "request timed out")):
                                # our fail won (vs a late batch result)
                                self.telemetry.count("serve/shed")
                        if req.error is not None:
                            raise req.error
                        # decode on the CALLER's thread: the batcher's
                        # critical path stays device-only, decode
                        # parallelizes across clients
                        decode_span = self.tracer.start_span(
                            "serve/decode", parent=root, n=chunk.n) \
                            if root is not None else None
                        miss_results.extend(self.model.decode_predictions(
                            chunk, req.result))
                        if decode_span is not None:
                            decode_span.end()
                except BaseException:
                    # resolve any still-pending sibling chunks so the
                    # batcher skips them (no device work for a dead waiter)
                    dead = ServerOverloaded("sibling chunk failed")
                    for r in reqs:
                        r.fail(dead)
                    if root is not None:
                        if decode_span is not None:
                            decode_span.end()  # idempotent: safe if closed
                        root.end(outcome="error")
                    raise
                for i, res in zip(miss_idx, miss_results):
                    out[i] = res
                    if use_cache:
                        self.cache.put(keys[i], res)

            self.telemetry.count("serve/requests")
            request_ms = request_span.stop()
            if root is not None:
                root.end(n_results=len(lines),
                         n_cached=len(lines) - len(miss_idx))
            fields = {"request_ms": round(request_ms, 3),
                      "n_methods": len(lines),
                      "n_cached": len(lines) - len(miss_idx)}
            if extract_ms is not None:  # keep the PR-2 request-event shape
                fields["extract_ms"] = round(extract_ms, 3)
            self.telemetry.event("request", **fields)
            return out  # fully populated: every index was a hit or a miss
        except BaseException:
            # one outer fence for every error path (graftlint
            # resource-leak): a failed request must not leak its
            # telemetry span (cancel: a dead request's partial ms
            # would pollute serve/request_ms) or leave the trace
            # root in the live-span table; the specialized inner
            # handlers already ended their spans - end() is
            # idempotent, so this backstop double-closes safely
            request_span.cancel()
            if root is not None:
                root.end(outcome="error")
            raise

    # ---- batch execution (batcher thread) ----
    def _run_batch(self, requests: Sequence[PredictRequest]) -> List:
        """One coalesced device call; each request gets back the row
        slice of the device output matching its own rows (numpy views —
        no copy). Decode happens on the waiting client's thread.

        Tracing (--trace): the flush CONTINUES the first request's
        trace (parent = its root span context, so that request's
        queue -> batch -> device chain shares one trace id) and LINKS
        every other coalesced request — the many-to-one edges
        trace_report renders as Chrome flow events. Each request also
        gets a retroactive `serve/queue_wait` span built from its
        `enqueued_at` (same monotonic clock as the tracer). The span
        contexts were handed off BY the client threads; this thread
        only starts spans of its own, never ends theirs."""
        self._batcher_hb.busy()
        # duck-typed through the rows' own class (PreparedRows.concat
        # in production): the batch path must not import jax — the
        # serving plane is guard-tested with jax blocked on fake models
        prepared = type(requests[0].rows).concat(
            [r.rows for r in requests])
        flush_span = None
        if self.tracer.enabled:
            now = self.tracer.clock()
            ctxs = [r.trace_ctx for r in requests
                    if r.trace_ctx is not None]
            for r in requests:
                if r.trace_ctx is not None:
                    self.tracer.record_span(
                        "serve/queue_wait", r.enqueued_at, now,
                        parent=r.trace_ctx, track="serve-queue")
            flush_span = self.tracer.start_span(
                "serve/batch_flush",
                parent=ctxs[0] if ctxs else None,
                links=ctxs[1:], n_requests=len(requests),
                n_methods=prepared.n)
        try:
            if flush_span is not None:
                # context manager: serve/encode + serve/device inside
                # predict_device implicitly parent to the flush span
                with flush_span:
                    out = self.model.predict_device(prepared)
            else:
                out = self.model.predict_device(prepared)
        finally:
            self._batcher_hb.idle()
        split = []
        at = 0
        for r in requests:
            split.append(tuple(a[at:at + r.n] for a in out))
            at += r.n
        return split
