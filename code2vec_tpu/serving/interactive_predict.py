"""Interactive prediction REPL.

Reference parity target: `interactive_predict.py` (SURVEY.md §3, §4.4):
"Modify Input.java, press Enter" -> extract path-contexts -> predict
-> print top-k names with probabilities, attention-ranked path-contexts,
and optionally the code vector.

Since ISSUE 3 this is a thin client of `serving/server.py`: extraction
rides the persistent worker pool, prediction goes through the
micro-batcher (a single-user REPL flushes as a batch of one — output
identical to the direct path), and repeated extractions of an unchanged
file hit the LRU prediction cache.
"""

from __future__ import annotations

import os
import time

from code2vec_tpu.config import Config
from code2vec_tpu.obs import Telemetry, format_latency_line
from code2vec_tpu.serving.extractor import ExtractorError
from code2vec_tpu.serving.server import PredictionServer, ServerOverloaded

SHOW_TOP_CONTEXTS = 10
DEFAULT_INPUT_FILE = "Input.java"
EXIT_KEYWORDS = ("exit", "quit", "q")


class InteractivePredictor:
    def __init__(self, config: Config, model):
        self.config = config
        self.model = model
        # Serving latency histograms (code2vec_tpu/obs/): per-request
        # extract/encode/predict timers are ALWAYS live (per-request
        # cost is trivial; the p50/p95/p99 line is the product surface),
        # persisted as JSONL events only when --telemetry_dir is set.
        # Serving opens its OWN run: a train run in the same process
        # (code2vec.py --data ... --predict) closed its event log when
        # train() returned, so the serve phase gets a fresh run dir.
        tele = Telemetry.create(config.TELEMETRY_DIR, config=config,
                                mesh=getattr(model, "mesh", None),
                                component="serve")
        if not tele.enabled:
            tele = Telemetry.memory("serve")
        self.telemetry = tele
        # the server wires model.telemetry to the same registry and owns
        # the batcher/cache/extractor-pool lifecycle
        self.server = PredictionServer(config, model, telemetry=tele)

    def predict(self, input_file: str = DEFAULT_INPUT_FILE) -> None:
        print(f"Serving. Modify the file: \"{input_file}\", then press any "
              f"key when ready, or \"q\" / \"quit\" / \"exit\" to exit. "
              f"Type \"attack\" (or \"attack <targetName>\") to search "
              f"an adversarial rename for the current file.")
        # warmup=False: a single-user REPL compiles predict buckets as
        # it meets them (the pre-server behavior) instead of paying all
        # --serve_batch_max bucket compiles on the first keystroke;
        # warmed-bucket serving is the load path (tools/loadgen.py).
        self.server.start(warmup=False)
        # try/finally: Ctrl-C or piped-stdin EOF must still flush the
        # serve run's JSONL summary instead of crashing the REPL with an
        # uncaught EOFError and an unflushed event log.
        try:
            while True:
                try:
                    user_input = input()
                except (EOFError, KeyboardInterrupt):
                    # EOF (piped stdin exhausted) and Ctrl-C are exits,
                    # not errors
                    print("Exiting...")
                    return
                if user_input.strip().lower() in EXIT_KEYWORDS:
                    print("Exiting...")
                    return
                if not os.path.exists(input_file):
                    print(f"File not found: {input_file}")
                    continue
                words = user_input.strip().split()
                if words and words[0].lower() == "attack":
                    self._attack(input_file,
                                 words[1] if len(words) > 1 else None)
                    continue
                t0 = time.perf_counter()
                try:
                    # deadline_ms=0: a single user is never "overload" —
                    # the first request may sit out a cold jit compile
                    # (tens of seconds on TPU) and must still succeed
                    results = self.server.predict_file(input_file,
                                                       deadline_ms=0)
                except ExtractorError as e:
                    print(f"Extraction error: {e}")
                    continue
                except ServerOverloaded as e:
                    print(f"Server overloaded: {e}")
                    continue
                request_ms = (time.perf_counter() - t0) * 1e3
                for res in results:
                    print(f"Original name:\t{res.original_name}")
                    for pred in res.predictions:
                        print(f"\t({pred['probability']:.6f}) "
                              f"predicted: {pred['name']}")
                    print("Attention:")
                    for ap in res.attention_paths[:SHOW_TOP_CONTEXTS]:
                        print(f"{ap.attention_score:.6f}\tcontext: "
                              f"{ap.source_token},{ap.path},"
                              f"{ap.target_token}")
                    if res.code_vector is not None:
                        print("Code vector:")
                        print(" ".join(f"{x:.5f}"
                                       for x in res.code_vector))
                print(format_latency_line(
                    self.telemetry.timer("serve/request_ms"), request_ms))
        finally:
            self.server.close()
            self.telemetry.close()  # flush the serve run's summary

    def _attack(self, input_file: str, target: str) -> None:
        """REPL `attack [targetName]` command: run the gradient rename
        attack on the current file (attacks/source_attack.py) and print
        the verified outcome."""
        from code2vec_tpu.attacks.source_attack import (
            SourceAttack, normalize_target_name)
        if getattr(self, "_source_attack", None) is None:
            # one instance per session: the jitted attack steps compile
            # once; honors the same --attack_* knobs as the CLI driver
            self._source_attack = SourceAttack(
                self.config, self.model,
                top_k_candidates=self.config.ATTACK_TOPK,
                max_iters=self.config.ATTACK_ITERS)
        target = normalize_target_name(target)
        try:
            result = self._source_attack.attack_file(
                input_file, targeted=target is not None,
                target_name=target,
                max_renames=self.config.ATTACK_MAX_RENAMES)
        except (ExtractorError, ValueError) as e:
            print(f"Attack error: {e}")
            return
        print(str(result))
