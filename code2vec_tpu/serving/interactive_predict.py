"""Interactive prediction REPL.

Reference parity target: `interactive_predict.py` (SURVEY.md §3, §4.4):
"Modify Input.java, press Enter" -> extract path-contexts -> model.predict
-> print top-k names with probabilities, attention-ranked path-contexts,
and optionally the code vector.
"""

from __future__ import annotations

import os

from code2vec_tpu.config import Config
from code2vec_tpu.serving.extractor import Extractor, ExtractorError

SHOW_TOP_CONTEXTS = 10
DEFAULT_INPUT_FILE = "Input.java"
EXIT_KEYWORDS = ("exit", "quit", "q")


class InteractivePredictor:
    def __init__(self, config: Config, model):
        self.config = config
        self.model = model
        self.extractor = Extractor(config)

    def predict(self, input_file: str = DEFAULT_INPUT_FILE) -> None:
        print(f"Serving. Modify the file: \"{input_file}\", then press any "
              f"key when ready, or \"q\" / \"quit\" / \"exit\" to exit.")
        while True:
            user_input = input()
            if user_input.strip().lower() in EXIT_KEYWORDS:
                print("Exiting...")
                return
            if not os.path.exists(input_file):
                print(f"File not found: {input_file}")
                continue
            try:
                _, lines = self.extractor.extract_paths(input_file)
            except ExtractorError as e:
                print(f"Extraction error: {e}")
                continue
            results = self.model.predict(lines)
            for res in results:
                print(f"Original name:\t{res.original_name}")
                for pred in res.predictions:
                    print(f"\t({pred['probability']:.6f}) "
                          f"predicted: {pred['name']}")
                print("Attention:")
                for ap in res.attention_paths[:SHOW_TOP_CONTEXTS]:
                    print(f"{ap.attention_score:.6f}\tcontext: "
                          f"{ap.source_token},{ap.path},{ap.target_token}")
                if res.code_vector is not None:
                    print("Code vector:")
                    print(" ".join(f"{x:.5f}" for x in res.code_vector))
