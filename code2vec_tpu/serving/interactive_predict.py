"""Interactive prediction REPL.

Reference parity target: `interactive_predict.py` (SURVEY.md §3, §4.4):
"Modify Input.java, press Enter" -> extract path-contexts -> model.predict
-> print top-k names with probabilities, attention-ranked path-contexts,
and optionally the code vector.
"""

from __future__ import annotations

import os

from code2vec_tpu.config import Config
from code2vec_tpu.obs import Telemetry, format_latency_line
from code2vec_tpu.serving.extractor import Extractor, ExtractorError

SHOW_TOP_CONTEXTS = 10
DEFAULT_INPUT_FILE = "Input.java"
EXIT_KEYWORDS = ("exit", "quit", "q")


class InteractivePredictor:
    def __init__(self, config: Config, model):
        self.config = config
        self.model = model
        self.extractor = Extractor(config)
        # Serving latency histograms (code2vec_tpu/obs/): per-request
        # extract/encode/predict timers are ALWAYS live (per-request
        # cost is trivial; the p50/p95/p99 line is the product surface),
        # persisted as JSONL events only when --telemetry_dir is set.
        # Serving opens its OWN run: a train run in the same process
        # (code2vec.py --data ... --predict) closed its event log when
        # train() returned, so the serve phase gets a fresh run dir.
        tele = Telemetry.create(config.TELEMETRY_DIR, config=config,
                                mesh=getattr(model, "mesh", None),
                                component="serve")
        if not tele.enabled:
            tele = Telemetry.memory("serve")
        self.telemetry = tele
        # model.predict() records its serve/encode_ms and
        # serve/predict_ms spans into the same registry
        model.telemetry = tele

    def predict(self, input_file: str = DEFAULT_INPUT_FILE) -> None:
        print(f"Serving. Modify the file: \"{input_file}\", then press any "
              f"key when ready, or \"q\" / \"quit\" / \"exit\" to exit. "
              f"Type \"attack\" (or \"attack <targetName>\") to search "
              f"an adversarial rename for the current file.")
        while True:
            user_input = input()
            if user_input.strip().lower() in EXIT_KEYWORDS:
                print("Exiting...")
                self.telemetry.close()  # flush the serve run's summary
                return
            if not os.path.exists(input_file):
                print(f"File not found: {input_file}")
                continue
            words = user_input.strip().split()
            if words and words[0].lower() == "attack":
                self._attack(input_file,
                             words[1] if len(words) > 1 else None)
                continue
            request_span = self.telemetry.span("serve/request_ms")
            extract_span = self.telemetry.span("serve/extract_ms")
            try:
                _, lines = self.extractor.extract_paths(input_file)
            except ExtractorError as e:
                print(f"Extraction error: {e}")
                continue
            extract_ms = extract_span.stop()
            results = self.model.predict(lines)
            request_ms = request_span.stop()
            self.telemetry.count("serve/requests")
            self.telemetry.event(
                "request", request_ms=round(request_ms, 3),
                extract_ms=round(extract_ms, 3),
                n_methods=len(results))
            for res in results:
                print(f"Original name:\t{res.original_name}")
                for pred in res.predictions:
                    print(f"\t({pred['probability']:.6f}) "
                          f"predicted: {pred['name']}")
                print("Attention:")
                for ap in res.attention_paths[:SHOW_TOP_CONTEXTS]:
                    print(f"{ap.attention_score:.6f}\tcontext: "
                          f"{ap.source_token},{ap.path},{ap.target_token}")
                if res.code_vector is not None:
                    print("Code vector:")
                    print(" ".join(f"{x:.5f}" for x in res.code_vector))
            print(format_latency_line(
                self.telemetry.timer("serve/request_ms"), request_ms))

    def _attack(self, input_file: str, target: str) -> None:
        """REPL `attack [targetName]` command: run the gradient rename
        attack on the current file (attacks/source_attack.py) and print
        the verified outcome."""
        from code2vec_tpu.attacks.source_attack import (
            SourceAttack, normalize_target_name)
        if getattr(self, "_source_attack", None) is None:
            # one instance per session: the jitted attack steps compile
            # once; honors the same --attack_* knobs as the CLI driver
            self._source_attack = SourceAttack(
                self.config, self.model,
                top_k_candidates=self.config.ATTACK_TOPK,
                max_iters=self.config.ATTACK_ITERS)
        target = normalize_target_name(target)
        try:
            result = self._source_attack.attack_file(
                input_file, targeted=target is not None,
                target_name=target,
                max_renames=self.config.ATTACK_MAX_RENAMES)
        except (ExtractorError, ValueError) as e:
            print(f"Attack error: {e}")
            return
        print(str(result))
