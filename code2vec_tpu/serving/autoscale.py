"""SLO autoscaler for the replica pool (ISSUE 18 tentpole).

A policy loop, not a new signal plane: the scaler owns a private
`AlertEngine` over the SAME serving registry the pool records into,
evaluating the burn-rate/SLO rules (`obs/alerts.serving_slo_rules` —
p99 latency and shed burn-rate at page severity) and turning their
edge-triggered state into pool-size decisions:

  - any PAGE-severity rule firing  -> `pool.grow()` (one replica per
    tick — the supervisor's one-at-a-time grow-back discipline; the
    pool's `[min,max]` bounds and replacement gate still apply);
  - every rule ok for `hold_s`     -> `pool.shrink()` (one replica per
    quiet window, never below min, never below one ready replica).

The asymmetry is deliberate: scale up on the first confirmed burn,
scale down only after a sustained quiet period — a brief lull must not
shed the capacity the next burst needs. Ticket-severity rules
(`reload_refused`, `replica_dead`) inform but never scale: the pool
already self-heals those.

Everything is injectable (`clock`, `rules`, `every_s`) so the tier-1
tests drive up/down transitions on synthetic series with a fake clock
and zero sleeps. `create()` follows the disabled-singleton discipline.
Stdlib-only at module scope.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional, Sequence

from code2vec_tpu.obs import Telemetry
from code2vec_tpu.obs.alerts import (AlertEngine, AlertRule,
                                     serving_slo_rules)

__all__ = ["AutoScaler"]


class AutoScaler:
    """Grow/shrink a `ReplicaPool` off the serving SLO rules."""

    def __init__(self, pool, *, telemetry: Telemetry = None,
                 rules: Optional[Sequence[AlertRule]] = None,
                 slo_ms: float = 250.0, every_s: float = 5.0,
                 hold_s: float = 60.0,
                 clock: Callable[[], float] = time.monotonic,
                 log=None):
        self.enabled = True
        self.pool = pool
        tele = telemetry if telemetry is not None \
            else getattr(pool, "telemetry", None)
        self.telemetry = tele if tele is not None \
            else Telemetry.disabled()
        self.every_s = every_s
        self.hold_s = hold_s
        self._clock = clock
        self._log = log or (lambda *a, **k: None)
        self.engine = AlertEngine.create(
            self.telemetry, mode="warn",
            rules=list(rules) if rules is not None
            else serving_slo_rules(slo_ms),
            clock=clock)
        self._quiet_since: Optional[float] = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    @classmethod
    def create(cls, pool, *, enabled: bool = True,
               **kw) -> "AutoScaler":
        if not enabled or pool is None:
            return _NULL_AUTOSCALER
        return cls(pool, **kw)

    @classmethod
    def disabled(cls) -> "AutoScaler":
        return _NULL_AUTOSCALER

    # ---- one policy tick ----
    def tick(self, now: Optional[float] = None) -> Optional[str]:
        """Evaluate the rules and apply at most ONE size change.
        Returns "up" / "down" / None (what happened, for tests and the
        chaos report)."""
        t = self._clock() if now is None else now
        self.engine.evaluate(t)
        page_firing = [r.name for r in self.engine.rules
                       if r.state == "firing"
                       and r.severity == "page"]
        decision = None
        if page_firing:
            self._quiet_since = None
            if self.pool.grow():
                decision = "up"
                self.telemetry.count("serve/scale_up")
                self.telemetry.event("autoscale", direction="up",
                                     target=self.pool.target,
                                     firing=page_firing)
                self._log(f"autoscale UP -> {self.pool.target} "
                          f"(firing: {', '.join(page_firing)})")
        elif any(r.state == "pending" and r.severity == "page"
                 for r in self.engine.rules):
            # a page rule inside its for_s hold: not quiet, not burning
            # enough to grow yet — freeze the shrink timer
            self._quiet_since = None
        else:
            if self._quiet_since is None:
                self._quiet_since = t
            elif t - self._quiet_since >= self.hold_s:
                if self.pool.shrink():
                    decision = "down"
                    self.telemetry.count("serve/scale_down")
                    self.telemetry.event("autoscale",
                                         direction="down",
                                         target=self.pool.target)
                    self._log(f"autoscale DOWN -> {self.pool.target}")
                # one shrink per quiet window either way: re-arm
                self._quiet_since = t
        self.telemetry.gauge("serve/autoscale_target",
                             self.pool.target, emit=False)
        return decision

    # ---- cadence thread ----
    def start(self) -> "AutoScaler":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(target=self._loop,
                                            name="serve-autoscale",
                                            daemon=True)
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.every_s):
            try:
                self.tick()
            except Exception as e:
                # a failed tick (pool mid-close) must not kill the
                # policy loop for the rest of the process
                self._log(f"autoscale tick failed: {e!r}")
                self.telemetry.count("serve/autoscale_errors")

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=30.0)

    def status(self) -> dict:
        return {"target": self.pool.target if self.pool else 0,
                "hold_s": self.hold_s, "every_s": self.every_s,
                "rules": self.engine.status_table()}


class _NullAutoScaler(AutoScaler):
    """Autoscale off: the shared no-op singleton."""

    def __init__(self):
        self.enabled = False
        self.pool = None
        self.telemetry = Telemetry.disabled()
        self.engine = AlertEngine.disabled()
        self.every_s = 0.0
        self.hold_s = 0.0
        self._thread = None

    def tick(self, now=None):
        return None

    def start(self):
        return self

    def stop(self) -> None:
        pass

    def status(self) -> dict:
        return {"target": 0, "hold_s": 0.0, "every_s": 0.0,
                "rules": []}


_NULL_AUTOSCALER = _NullAutoScaler()
