"""Dynamic micro-batching for the prediction server (ISSUE 3).

Clipper-style adaptive batching (Crankshaw et al., NSDI 2017): concurrent
predict requests land in a bounded queue; a single batcher thread
coalesces them into one device batch, flushing on `--serve_batch_max`
total methods or a `--serve_batch_timeout_ms` deadline — whichever comes
first. The batch then pads to the power-of-two buckets the model's
jitted predict step already compiles, so steady-state serving triggers
zero new compilations (serving/server.py warms the buckets up front).

Admission control is explicit, not emergent: `submit()` on a full queue
returns False immediately (the caller sheds with `ServerOverloaded`),
and requests whose deadline expired while queued are shed at dequeue
time — bounded latency instead of unbounded queue growth.

This module is model-agnostic and stdlib-only: requests carry an opaque
`rows` payload plus its leading-dim size `n`; the server supplies
`batch_fn(requests) -> per-request results`. That keeps the
queue/deadline/flush logic unit-testable without jax.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Callable, List, Optional, Sequence

__all__ = ["ServerOverloaded", "PredictRequest", "MicroBatcher"]


class ServerOverloaded(RuntimeError):
    """Explicit load-shedding result: the request was refused (queue
    full) or dropped (deadline expired before it reached the device).
    Clients see this instead of unbounded latency growth."""


class PredictRequest:
    """One in-flight predict request: an opaque `rows` payload (the
    server passes pre-parsed `PreparedRows`), its leading-dim size `n`,
    and an absolute monotonic `deadline` (None = no deadline). The
    submitting thread blocks on `wait()`; the batcher thread resolves it
    via `finish()` / `fail()`.

    `trace_ctx` (ISSUE 6) is the request-scoped tracing handoff: an
    opaque `obs.trace.SpanContext` the CLIENT thread attaches and the
    batcher-thread flush reads to parent/link its spans — the batcher
    itself never starts or ends spans (it stays stdlib-only and
    trace-agnostic; `enqueued_at` doubles as the queue-wait span's
    start because both use `time.monotonic`, the tracer's clock)."""

    __slots__ = ("rows", "n", "deadline", "enqueued_at", "result",
                 "error", "trace_ctx", "_done", "_lock")

    def __init__(self, rows: Any, n: int,
                 deadline: Optional[float] = None,
                 trace_ctx: Any = None):
        assert n >= 1, "empty requests never reach the batcher"
        self.rows = rows
        self.n = n
        self.deadline = deadline
        self.enqueued_at = time.monotonic()
        self.result: Any = None
        self.error: Optional[BaseException] = None
        self.trace_ctx = trace_ctx
        self._done = threading.Event()
        self._lock = threading.Lock()

    def finish(self, result: Any) -> bool:
        # first resolution wins: a late batch result must not clobber a
        # timeout the waiter already acted on (and vice versa). Returns
        # whether THIS call resolved the request.
        with self._lock:
            if self._done.is_set():
                return False
            self.result = result
            self._done.set()
            return True

    def fail(self, error: BaseException) -> bool:
        with self._lock:
            if self._done.is_set():
                return False
            self.error = error
            self._done.set()
            return True

    @property
    def done(self) -> bool:
        return self._done.is_set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        """True when resolved; False on timeout (the batcher may still
        resolve it later — the caller decides whether to keep waiting)."""
        return self._done.wait(timeout)


class MicroBatcher:
    """Single consumer thread over a bounded request queue.

    Flush policy (`_collect`): block for the first request, open a
    `timeout_ms` coalescing window, and keep admitting queued requests
    until the batch holds `max_batch` methods or the window closes.
    `timeout_ms=0` degenerates to greedy drain-and-flush (lowest
    latency; batches still form naturally while the device is busy).
    A request whose methods would overflow `max_batch` stays queued for
    the next batch — request payloads are never split.
    """

    def __init__(self, batch_fn: Callable[[Sequence[PredictRequest]],
                                          Sequence[Any]],
                 *, max_batch: int = 64, timeout_ms: float = 2.0,
                 queue_depth: int = 128, telemetry=None):
        assert max_batch >= 1 and queue_depth >= 1 and timeout_ms >= 0
        self._batch_fn = batch_fn
        self.max_batch = max_batch
        self.timeout_s = timeout_ms / 1e3
        self.queue_depth = queue_depth
        from code2vec_tpu.obs import Telemetry
        self._tele = telemetry if telemetry is not None \
            else Telemetry.disabled()
        self._q: collections.deque = collections.deque()
        self._cond = threading.Condition()
        self._running = False
        self._thread: Optional[threading.Thread] = None

    # ---- lifecycle ----
    def start(self) -> None:
        with self._cond:  # atomic check-then-act: one consumer thread,
            if self._running:  # ever, under concurrent first requests
                return
            self._running = True
            self._thread = threading.Thread(
                target=self._run, name="micro-batcher", daemon=True)
            self._thread.start()

    def stop(self) -> None:
        """Stop the consumer; queued-but-unserved requests are failed
        with `ServerOverloaded` so no submitter blocks forever."""
        with self._cond:
            if not self._running:
                return
            self._running = False
            pending = list(self._q)
            self._q.clear()
            # detach the thread handle UNDER the lock (graftlint
            # lock-discipline: start() writes it locked, so stop()
            # clearing it bare raced a concurrent stop/start pair);
            # join AFTER release — joining under the lock would
            # deadlock against a consumer blocked in _cond.wait()
            thread, self._thread = self._thread, None
            self._cond.notify_all()
        for req in pending:
            req.fail(ServerOverloaded("server shutting down"))
        if thread is not None:
            thread.join(timeout=5)

    # ---- producer side ----
    def submit(self, req: PredictRequest) -> bool:
        """Enqueue; False when the bounded queue is full (admission
        control — the caller sheds with `ServerOverloaded`)."""
        if req.n > self.max_batch:
            # an oversized payload would flush as an unwarmed jit
            # bucket, breaking the zero-steady-state-compilation
            # invariant — callers chunk first (server.predict_lines)
            raise ValueError(
                f"request of {req.n} methods exceeds max_batch "
                f"{self.max_batch}; split it before submitting")
        with self._cond:
            if not self._running:
                return False
            if len(self._q) >= self.queue_depth:
                return False
            self._q.append(req)
            depth = len(self._q)
            self._cond.notify()
        self._tele.gauge("serve/queue_depth", depth, emit=False)
        return True

    @property
    def depth(self) -> int:
        with self._cond:
            return len(self._q)

    @property
    def running(self) -> bool:
        return self._running

    # ---- consumer side ----
    def _collect(self, me: threading.Thread) -> List[PredictRequest]:
        """One flush: first request (blocking) + coalescing window.

        `me` is the consumer's OWN thread object; `self._thread is me`
        is its generation token. A stop()/start() pair that completes
        while this consumer sleeps in wait() installs a NEW thread, and
        the `_running` flag is True again — so exit conditions check
        the token, not the flag, or the superseded consumer would keep
        draining alongside its replacement (two-consumer race)."""
        with self._cond:
            while self._thread is me and not self._q:
                self._cond.wait()
            if self._thread is not me:
                return []
            batch = [self._q.popleft()]
            n = batch[0].n
            flush_at = time.monotonic() + self.timeout_s
            while n < self.max_batch:
                if self._q:
                    if n + self._q[0].n > self.max_batch:
                        break  # would overflow: leave for the next batch
                    req = self._q.popleft()
                    batch.append(req)
                    n += req.n
                    continue
                remaining = flush_at - time.monotonic()
                if remaining <= 0:
                    break
                self._cond.wait(remaining)
                if self._thread is not me:
                    break
            # keep the gauge honest on the drain side too — submit-only
            # updates would freeze it at the last high-water mark
            depth = len(self._q)
        self._tele.gauge("serve/queue_depth", depth, emit=False)
        return batch

    def _shed_expired(self, batch: List[PredictRequest]
                      ) -> List[PredictRequest]:
        now = time.monotonic()
        live = []
        for req in batch:
            if req.done:
                # already resolved by its waiter (timeout, or a sibling
                # chunk's refusal) — don't spend device time on it
                continue
            if req.deadline is not None and now > req.deadline:
                if req.fail(ServerOverloaded(
                        f"deadline exceeded after "
                        f"{(now - req.enqueued_at) * 1e3:.0f} ms in "
                        f"queue")):
                    # count only when OUR fail resolved it — the
                    # waiter's timeout path counts its own shed
                    self._tele.count("serve/shed")
            else:
                live.append(req)
        return live

    def _run(self) -> None:
        me = threading.current_thread()
        while True:
            batch = self._collect(me)
            if not batch and self._thread is not me:
                # superseded (stop, or stop+start installed a fresh
                # consumer): any batch already dequeued above is still
                # OURS to finish — those requests left the queue and no
                # other consumer can see them
                return
            batch = self._shed_expired(batch)
            if not batch:
                continue
            n = sum(r.n for r in batch)
            self._tele.count("serve/batches")
            self._tele.record_ms("serve/batch_methods", n)
            self._tele.gauge("serve/batch_occupancy",
                             round(n / self.max_batch, 4), emit=False)
            try:
                results = self._batch_fn(batch)
            except BaseException as e:  # noqa: BLE001 — forwarded, not hidden
                for req in batch:
                    req.fail(e)
                continue
            assert len(results) == len(batch), (
                "batch_fn must return one result per request")
            for req, res in zip(batch, results):
                req.finish(res)
