"""HTTP front-end for the replica fleet (ISSUE 18 tentpole).

The socket ROADMAP item 1 names: a stdlib daemon-thread HTTP server
(the `obs/exposition.MetricsServer` pattern — inner handler class,
ThreadingHTTPServer, port 0 = ephemeral with `bound_port` telling the
truth) in front of a `ReplicaPool`:

  - `POST /predict` — JSON `{"lines": [...], "deadline_ms"?: N}` ->
    `{"predictions": [...], "n": K}`. Dispatch, batching, caching and
    admission control all live in the pool/replicas; this layer only
    translates HTTP <-> the in-process surface. `ServerOverloaded`
    maps to 429 (shed is an explicit outcome, not a 500), client input
    errors to 400, anything else to 500 — each with a JSON error body.
  - `GET /healthz` — readiness gates on the POOL: 503 until at least
    one replica is ready (and, when an alert engine is attached, while
    a page-severity rule is firing — the exposition `_healthz`
    discipline). Load balancers probe this during rolling swaps; the
    one-replica-at-a-time swap keeps it 200 throughout.
  - `GET /metrics` — the existing Prometheus exposition
    (`render_prometheus`) over the shared serving registry, so the
    `serve/*` counters, pool gauges and alert states ride the format
    every scraper already parses.
  - `GET /pool` — the fleet-style pool table (per-replica rows +
    aggregates) as JSON.

Stdlib-only at module scope (guard: tests/test_frontend.py imports and
round-trips this with jax blocked). `create()` follows the
disabled-singleton discipline: `--serve_port` 0/unset returns a shared
no-op, so call sites wire unconditionally; direct construction with
port=0 binds an ephemeral port (tests).
"""

from __future__ import annotations

import http.server
import json
import threading
from typing import Any, Callable, Dict, List, Optional

from code2vec_tpu.common import MethodPredictionResults
from code2vec_tpu.obs.exposition import render_prometheus
from code2vec_tpu.serving.batcher import ServerOverloaded

__all__ = ["ServingFrontend", "serialize_prediction"]

# client mistakes the pool re-raises untouched; the HTTP layer's 400
# class (mirrors replicas._INPUT_ERRORS — one bad request is the
# CLIENT's problem)
_CLIENT_ERRORS = (ValueError, KeyError, TypeError)

_MAX_BODY_BYTES = 16 << 20  # refuse absurd bodies before reading them


def serialize_prediction(res: MethodPredictionResults) -> Dict[str, Any]:
    """JSON shape for one method's predictions. `code_vector` stays
    out — it is a device-sized array nobody wants in a latency-bound
    response (a future `?vectors=1` can opt in)."""
    return {
        "original_name": res.original_name,
        "predictions": [{"name": p["name"],
                         "probability": float(p["probability"])}
                        for p in res.predictions],
        "attention_paths": [{"source_token": ap.source_token,
                             "path": ap.path,
                             "target_token": ap.target_token,
                             "attention_score":
                                 float(ap.attention_score)}
                            for ap in res.attention_paths],
    }


class ServingFrontend:
    """One HTTP server over one `ReplicaPool` (or anything exposing
    `predict_lines` / `ready_count` / `pool_table`)."""

    def __init__(self, pool, *, port: int, host: str = "",
                 telemetry=None, health=None, alerts=None,
                 reload_manager=None, autoscaler=None,
                 log: Optional[Callable[[str], None]] = None):
        self.enabled = True
        self.pool = pool
        tele = telemetry if telemetry is not None \
            else getattr(pool, "telemetry", None)
        self.telemetry = tele
        self.health = health
        self.alerts = alerts
        self.reload_manager = reload_manager
        self.autoscaler = autoscaler
        self.port = port
        self.host = host
        self.bound_port: Optional[int] = None
        self._log = log or (lambda _m: None)
        self._lock = threading.Lock()
        self._httpd: Optional[http.server.ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    # ---- construction ----
    @classmethod
    def create(cls, pool, *, port: int, **kw) -> "ServingFrontend":
        """Disabled singleton unless `--serve_port` is set (0 = off;
        tests that want an ephemeral port construct directly)."""
        if port <= 0 or pool is None:
            return _NULL_FRONTEND
        return cls(pool, port=port, **kw)

    @classmethod
    def disabled(cls) -> "ServingFrontend":
        return _NULL_FRONTEND

    # ---- request handling ----
    def _healthz(self) -> tuple:
        """Readiness = the pool can take a request RIGHT NOW: at least
        one ready replica, and no page-severity alert firing."""
        table = self.pool.pool_table()
        firing: List[str] = []
        if self.alerts is not None and self.alerts.enabled:
            firing = [r["rule"] for r in self.alerts.status_table()
                      if r["state"] == "firing"
                      and r.get("severity") == "page"]
        ok = table["ready"] > 0 and not firing
        body = {"status": "ok" if ok else "unhealthy",
                "ready": table["ready"], "size": table["size"],
                "target": table["target"],
                "generation": table["generation"],
                "alerts_firing": firing}
        return (200 if ok else 503), body

    def _predict(self, body: bytes) -> tuple:
        try:
            req = json.loads(body.decode("utf-8"))
        except (UnicodeDecodeError, ValueError):
            return 400, {"error": "body must be JSON"}
        if not isinstance(req, dict) \
                or not isinstance(req.get("lines"), list) \
                or not all(isinstance(x, str) for x in req["lines"]):
            return 400, {"error":
                         'expected {"lines": ["<extractor line>", ...]'
                         ', "deadline_ms"?: <number>}'}
        deadline_ms = req.get("deadline_ms")
        if deadline_ms is not None \
                and not isinstance(deadline_ms, (int, float)):
            return 400, {"error": "deadline_ms must be a number"}
        try:
            results = self.pool.predict_lines(req["lines"],
                                              deadline_ms=deadline_ms)
        except ServerOverloaded as e:
            return 429, {"error": str(e), "shed": True}
        except _CLIENT_ERRORS as e:
            return 400, {"error": str(e)}
        return 200, {"predictions": [serialize_prediction(r)
                                     for r in results],
                     "n": len(results)}

    def _respond_get(self, path: str) -> tuple:
        path = path.partition("?")[0]
        if path == "/metrics":
            text = render_prometheus(self.telemetry, None, self.health,
                                     self.alerts)
            return (200, "text/plain; version=0.0.4; charset=utf-8",
                    text.encode("utf-8"))
        if path == "/healthz":
            status, body = self._healthz()
            return (status, "application/json",
                    json.dumps(body, default=str).encode("utf-8"))
        if path == "/pool":
            table = self.pool.pool_table()
            if self.reload_manager is not None \
                    and self.reload_manager.enabled:
                table["reload"] = self.reload_manager.status()
            if self.autoscaler is not None \
                    and self.autoscaler.enabled:
                table["autoscale"] = self.autoscaler.status()
            return (200, "application/json",
                    json.dumps(table, default=str,
                               indent=1).encode("utf-8"))
        return (404, "text/plain",
                b"not found (try POST /predict, GET /healthz, "
                b"/metrics, /pool)\n")

    # ---- lifecycle ----
    def start(self) -> "ServingFrontend":
        with self._lock:
            if self._httpd is not None:
                return self
            front = self

            class _Handler(http.server.BaseHTTPRequestHandler):
                def _send(self, status: int, ctype: str,
                          payload: bytes) -> None:
                    self.send_response(status)
                    self.send_header("Content-Type", ctype)
                    self.send_header("Content-Length",
                                     str(len(payload)))
                    self.end_headers()
                    self.wfile.write(payload)

                def do_GET(self):  # noqa: N802 — http.server API
                    try:
                        status, ctype, payload = front._respond_get(
                            self.path)
                    except Exception as e:  # noqa: BLE001 — a probe
                        # must never take the serving plane down
                        status, ctype = 500, "text/plain"
                        payload = repr(e).encode("utf-8")
                    self._send(status, ctype, payload)

                def do_POST(self):  # noqa: N802 — http.server API
                    try:
                        if self.path.partition("?")[0] != "/predict":
                            self._send(404, "text/plain",
                                       b"POST /predict only\n")
                            return
                        try:
                            n = int(self.headers.get(
                                "Content-Length", "0"))
                        except ValueError:
                            n = -1
                        if n < 0 or n > _MAX_BODY_BYTES:
                            self._send(400, "application/json",
                                       b'{"error": "bad Content-'
                                       b'Length"}')
                            return
                        status, body = front._predict(self.rfile.read(n))
                        self._send(status, "application/json",
                                   json.dumps(body, default=str)
                                   .encode("utf-8"))
                    except Exception as e:  # noqa: BLE001 — one bad
                        # request thread must not kill the listener
                        self._send(500, "application/json",
                                   json.dumps({"error": repr(e)})
                                   .encode("utf-8"))

                def log_message(self, fmt, *args):
                    pass  # request chatter stays out of the serve log

            self._httpd = http.server.ThreadingHTTPServer(
                (self.host, self.port), _Handler)
            self._httpd.daemon_threads = True
            self.bound_port = self._httpd.server_address[1]
            self._thread = threading.Thread(
                target=self._httpd.serve_forever, daemon=True,
                name="serving-frontend")
            self._thread.start()
        self._log(f"serving: POST /predict, GET /healthz /metrics "
                  f"/pool on port {self.bound_port}")
        return self

    def stop(self) -> None:
        with self._lock:
            httpd, self._httpd = self._httpd, None
            thread, self._thread = self._thread, None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if thread is not None:
            thread.join(timeout=5)


class _NullServingFrontend(ServingFrontend):
    """The `--serve_port`-unset path: shared no-op singleton."""

    def __init__(self):
        self.enabled = False
        self.pool = None
        self.telemetry = None
        self.bound_port = None

    def start(self):
        return self

    def stop(self) -> None:
        pass


_NULL_FRONTEND = _NullServingFrontend()
