"""Vocabulary runtime.

Reference parity target: `vocabularies.py` (SURVEY.md §2 L2, §3):
`Code2VecVocabs`, `Vocab`, `VocabType.{Token,Target,Path}`, special words
PAD/OOV, word<->index lookup. Loads the pickled `.dict.c2v` histogram file
written by preprocessing (format: token-count dict, path-count dict,
target-count dict, num_training_examples — SURVEY.md §3.2), cuts each
histogram to its configured max size by descending frequency, and builds
index maps.

TPU-first note: there is no tf.lookup table here — lookup happens on the
host (numpy vectorized via python dict; hot path uses pre-binarized shards,
see data/binarize.py) and the device only ever sees fixed-shape int32
tensors.
"""

from __future__ import annotations

import enum
import pickle
from typing import Dict, Iterable, List, Optional, Tuple

from code2vec_tpu.common import SpecialVocabWords


class VocabType(enum.Enum):
    Token = 1
    Target = 2
    Path = 3


class Vocab:
    """A word<->index bijection with PAD=0 and OOV=1 reserved."""

    SPECIAL_WORDS: Tuple[str, ...] = (SpecialVocabWords.PAD,
                                      SpecialVocabWords.OOV)

    def __init__(self, vocab_type: VocabType, words: Iterable[str]):
        self.vocab_type = vocab_type
        self.word_to_index: Dict[str, int] = {}
        self.index_to_word: Dict[int, str] = {}
        for word in self.SPECIAL_WORDS:
            self._add(word)
        for word in words:
            if word not in self.word_to_index:
                self._add(word)

    def _add(self, word: str) -> None:
        idx = len(self.word_to_index)
        self.word_to_index[word] = idx
        self.index_to_word[idx] = word

    @property
    def size(self) -> int:
        return len(self.word_to_index)

    @property
    def pad_index(self) -> int:
        return self.word_to_index[SpecialVocabWords.PAD]

    @property
    def oov_index(self) -> int:
        return self.word_to_index[SpecialVocabWords.OOV]

    def lookup_index(self, word: str) -> int:
        return self.word_to_index.get(word, self.oov_index)

    def lookup_word(self, index: int) -> str:
        return self.index_to_word.get(index, SpecialVocabWords.OOV)

    @classmethod
    def create_from_freq_dict(cls, vocab_type: VocabType,
                              freq_dict: Dict[str, int],
                              max_size: int) -> "Vocab":
        """Keep the `max_size` most frequent words (ties broken by
        insertion order, matching Counter.most_common semantics)."""
        words = [w for w, _ in sorted(freq_dict.items(),
                                      key=lambda kv: (-kv[1],))][:max_size]
        return cls(vocab_type, words)

    # ---- (de)serialization: list of words in index order, specials first ----
    def to_word_list(self) -> List[str]:
        return [self.index_to_word[i] for i in range(self.size)]

    @classmethod
    def from_word_list(cls, vocab_type: VocabType,
                       words: List[str]) -> "Vocab":
        assert tuple(words[:len(cls.SPECIAL_WORDS)]) == cls.SPECIAL_WORDS, \
            "corrupt vocab: special words missing from head"
        return cls(vocab_type, words[len(cls.SPECIAL_WORDS):])


class Code2VecVocabs:
    """The three vocabularies (token / path / target) used by the model."""

    def __init__(self, token_vocab: Vocab, path_vocab: Vocab,
                 target_vocab: Vocab,
                 num_training_examples: Optional[int] = None):
        self.token_vocab = token_vocab
        self.path_vocab = path_vocab
        self.target_vocab = target_vocab
        self.num_training_examples = num_training_examples

    def get(self, vocab_type: VocabType) -> Vocab:
        return {VocabType.Token: self.token_vocab,
                VocabType.Path: self.path_vocab,
                VocabType.Target: self.target_vocab}[vocab_type]

    @classmethod
    def load_from_dict_file(cls, dict_path: str, max_token_vocab_size: int,
                            max_path_vocab_size: int,
                            max_target_vocab_size: int) -> "Code2VecVocabs":
        """Load the `.dict.c2v` pickle written by preprocess."""
        (token_counts, path_counts, target_counts,
         num_examples) = read_count_dicts(dict_path)
        return cls(
            Vocab.create_from_freq_dict(VocabType.Token, token_counts,
                                        max_token_vocab_size),
            Vocab.create_from_freq_dict(VocabType.Path, path_counts,
                                        max_path_vocab_size),
            Vocab.create_from_freq_dict(VocabType.Target, target_counts,
                                        max_target_vocab_size),
            num_training_examples=num_examples,
        )

    # ---- checkpoint sidecar (SURVEY.md §3.2 "Model checkpoint": vocab
    # saved next to the model so --load needs no dataset) ----
    def save(self, path: str) -> None:
        with open(path, "wb") as f:
            pickle.dump({
                "token": self.token_vocab.to_word_list(),
                "path": self.path_vocab.to_word_list(),
                "target": self.target_vocab.to_word_list(),
                "num_training_examples": self.num_training_examples,
            }, f)

    @classmethod
    def load(cls, path: str) -> "Code2VecVocabs":
        with open(path, "rb") as f:
            d = pickle.load(f)
        return cls(
            Vocab.from_word_list(VocabType.Token, d["token"]),
            Vocab.from_word_list(VocabType.Path, d["path"]),
            Vocab.from_word_list(VocabType.Target, d["target"]),
            num_training_examples=d.get("num_training_examples"),
        )


def read_count_dicts(dict_path: str):
    """The `.dict.c2v` sequential-pickle layout, owned HERE
    (SURVEY.md §3.2: token dict, path dict, target dict, num_examples,
    pickled in that order). Every consumer of the raw histograms
    (vocab construction, attacks/detect.py rarity tables) goes through
    this single reader."""
    with open(dict_path, "rb") as f:
        token_counts = pickle.load(f)
        path_counts = pickle.load(f)
        target_counts = pickle.load(f)
        try:
            num_examples = pickle.load(f)
        except EOFError:
            num_examples = None
    return token_counts, path_counts, target_counts, num_examples


def read_token_counts(dict_path: str) -> Dict[str, int]:
    """Just the token histogram (the FIRST pickled object — layout
    owned by read_count_dicts above): consumers that only need token
    frequencies (attacks/detect.py) skip deserializing the ~1M-entry
    path/target dicts."""
    with open(dict_path, "rb") as f:
        return pickle.load(f)
