from code2vec_tpu.vocab.vocabularies import (  # noqa: F401
    Vocab, VocabType, Code2VecVocabs)
