from code2vec_tpu.parallel.mesh import make_mesh  # noqa: F401
from code2vec_tpu.parallel.sharding import (  # noqa: F401
    param_pspecs, batch_pspec, shard_params, shard_batch)
