"""Version-portable wrappers for the JAX APIs the parallel layer leans
on — the seams where the installed JAX's surface has moved between the
versions this repo meets in the wild (the 0.4.x CPU wheels in CI
containers, the newer TPU builds on the driver).

Three seams, one module:

- `shard_map`: promoted from `jax.experimental.shard_map` (where the
  replication-check kwarg is `check_rep`) to top-level `jax.shard_map`
  (where it is `check_vma`). Every caller here wants the check OFF —
  the parallel bodies use collectives (`ppermute`, `all_gather`) whose
  replication typing the older checker rejects — so the wrapper owns
  the spelling.
- CPU device provisioning: `jax.config.update("jax_num_cpu_devices",
  n)` exists only on newer JAX; the env flag
  `XLA_FLAGS=--xla_force_host_platform_device_count=N`, read at
  backend init, is the one knob every supported version honors — so
  `cpu_worker_env` pins it (plus `JAX_PLATFORMS=cpu`) in the spawn
  environment BEFORE a worker's jax import, and every multi-process
  spawner (tests/mp_worker.py, tools/multichip_bench.py) provisions
  that way.
- CPU cross-process collectives: the 0.4.x CPU client refuses
  multi-process computations unless the Gloo collectives
  implementation is selected via `jax_cpu_collectives_implementation`
  BEFORE `jax.distributed.initialize`; newer JAX defaults to Gloo and
  drops the knob. `enable_cpu_collectives` sets it when present and is
  a no-op otherwise. parallel/distributed.maybe_initialize calls it,
  so every entry point (code2vec.py, tests/mp_worker.py,
  tools/multichip_bench.py) inherits the fix.
"""

from __future__ import annotations

import os
from typing import Any


def shard_map(f, *, mesh, in_specs, out_specs):
    """`jax.shard_map` / `jax.experimental.shard_map.shard_map` with the
    replication/varying-manual-axes check disabled under either
    spelling. The kwarg is probed, not version-guessed: the
    promote-to-top-level and the `check_rep`->`check_vma` rename were
    separate JAX releases, so a top-level `jax.shard_map` may still
    spell the kwarg `check_rep`."""
    import jax

    if hasattr(jax, "shard_map"):
        try:
            return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_vma=False)
        except TypeError:
            return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_rep=False)
    from jax.experimental.shard_map import shard_map as _shard_map
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=False)


def disable_cpu_async_dispatch() -> None:
    """Turn off the CPU client's async dispatch. With it on, two
    in-flight programs can interleave differently-sized collectives on
    the same Gloo TCP pair, which dies with
    `gloo::EnforceNotMet: op.preamble.length <= op.nbytes` —
    intermittently, under load (observed on the 2-process tier-1
    harness). Single-process training never calls this, so the
    steady-state CPU fast path keeps async dispatch; multi-process
    BENCHMARKS must apply this same knob to their single-process
    baseline leg so the timing comparison stays like-for-like
    (tools/multichip_bench.py does — via this standalone entry, since
    selecting Gloo itself without a distributed client would fail the
    backend build)."""
    import jax

    try:
        jax.config.update("jax_cpu_enable_async_dispatch", False)
    except (AttributeError, ValueError):
        pass  # newer JAX may drop/rename the knob; the race is 0.4.x-era


def enable_cpu_collectives() -> bool:
    """Select the Gloo CPU collectives implementation where the knob
    exists (it must be set before `jax.distributed.initialize`; without
    it the 0.4.x CPU client fails multi-process computations with
    "Multiprocess computations aren't implemented on the CPU backend").
    Returns True when the option was set (or JAX is new enough to
    default to Gloo). Also applies `disable_cpu_async_dispatch` (see
    there). Only call on a process that WILL join a distributed
    runtime: the Gloo client factory requires the distributed client,
    so a single-process backend build would fail with it selected."""
    import jax

    disable_cpu_async_dispatch()
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
        return True
    except (AttributeError, ValueError):
        # newer JAX: the option is gone because Gloo IS the default
        return not hasattr(jax.config, "jax_cpu_collectives_implementation")


def distributed_initialize(coordinator_address=None, num_processes=None,
                           process_id=None) -> None:
    """`jax.distributed.initialize`, with the coordination-service
    heartbeat tolerance widened on CPU backends. The public API drops
    the heartbeat knobs on 0.4.x, but the CPU Gloo harnesses this repo
    runs (2 OS processes x 4 virtual devices on a 2-core CI box) can
    starve a worker's heartbeat thread past the default 100 s tolerance
    during the first big XLA compile — the coordinator then EVICTS the
    healthy-but-descheduled worker and the peer dies mid-collective
    with `gloo ... Connection reset by peer` (observed on the multichip
    bench). TPU/GPU runs keep stock tolerances: there the default is
    the right failure detector, and eviction latency matters."""
    import jax

    relax = os.environ.get("JAX_PLATFORMS", "") == "cpu"
    kwargs = {}
    if coordinator_address is not None:
        kwargs = dict(coordinator_address=coordinator_address,
                      num_processes=num_processes,
                      process_id=process_id)
    if relax:
        try:
            from jax._src import xla_bridge
            from jax._src.distributed import global_state
            if xla_bridge.backends_are_initialized():
                raise RuntimeError(
                    "distributed_initialize must run before any JAX "
                    "computation (the public-API precondition)")
            global_state.initialize(
                service_heartbeat_interval_seconds=10,
                service_max_missing_heartbeats=60,
                client_heartbeat_interval_seconds=10,
                client_max_missing_heartbeats=60,
                **kwargs)
            return
        except (ImportError, TypeError):
            pass  # private surface moved: fall back to the public API
    jax.distributed.initialize(**kwargs)


# distinctive exit status for a wedged first collective (greppable in
# the spawner's captured worker output / returncode)
BARRIER_TIMEOUT_EXIT = 19


def first_collective_barrier(timeout_s: float = 90.0, *,
                             tag: str = "cohort-bringup",
                             setup_fn=None, barrier_fn=None,
                             on_timeout=None, log=None) -> None:
    """Bounded cohort bring-up (ISSUE 14 satellite — the PR 12
    postscript hang). On oversubscribed 1-core containers the
    loopback-Gloo rendezvous can wedge EVERY cohort member during
    bring-up — inside `jax.distributed.initialize` itself (it blocks
    until every peer connects) or at the FIRST collective right after
    it returns (the compat-docstring transport-race family). Each
    worker then blocks forever, the spawner burns its full
    communicate() wall, and one wedge eats a whole test module's
    budget.

    This arms a hard watchdog deadline over BOTH phases: `setup_fn`
    (the caller's distributed init, when provided) and a trivial
    `sync_global_devices` probe collective. If bring-up doesn't
    complete in `timeout_s`, the watchdog
    `os._exit(BARRIER_TIMEOUT_EXIT)`s THIS process — converting a
    silent module-eating hang into a fast, retryable worker death
    that the spawner's fresh-port retry
    (resilience/retry.transient_distributed) absorbs by re-forming
    the cohort. `os._exit`, not `sys.exit`: a wedged Gloo op holds
    locks no finally-block should touch, and SIGKILL-style death is
    exactly what the retry layer already classifies as a peer crash.

    Single-process runs skip the probe (nothing to rendezvous; the
    check runs AFTER setup_fn so it cannot touch the backend before
    init). `setup_fn` / `barrier_fn` / `on_timeout` are injectable so
    the deadline path is unit-testable without a wedgeable cohort
    (tests/test_parallel.py)."""
    import threading

    if on_timeout is None:
        def on_timeout():  # pragma: no cover - exercised via injection
            if log is not None:
                log(f"first-collective barrier '{tag}' timed out after "
                    f"{timeout_s}s — exiting for the spawner's "
                    "fresh-port retry")
            os._exit(BARRIER_TIMEOUT_EXIT)

    timer = threading.Timer(timeout_s, on_timeout)
    timer.daemon = True
    timer.start()
    try:
        if setup_fn is not None:
            setup_fn()
        if barrier_fn is not None:
            barrier_fn()
        else:
            import jax

            if jax.process_count() > 1:
                from jax.experimental import multihost_utils
                multihost_utils.sync_global_devices(tag)
    finally:
        timer.cancel()
        # reap the watchdog thread (cancel() alone leaves it parked
        # until the deadline); in the fired production path the
        # process is already gone via os._exit, so this never blocks
        timer.join()


class PhaseDeadline:
    """Re-armable per-phase deadline for spawned cohort workers — the
    companion of `first_collective_barrier` for everything AFTER
    bring-up. The loopback-Gloo race can wedge a later collective too
    (observed: a mid-workload hang burning the spawner's full 300 s
    communicate() wall); `beat(phase)` re-arms the deadline at each
    phase boundary, so any SINGLE phase wedging hard-exits the worker
    (default `os._exit(BARRIER_TIMEOUT_EXIT)`) within `timeout_s` of
    its last beat and the spawner's fresh-port retry re-forms the
    cohort. `close()` disarms and reaps the watchdog thread.

    This is a last-resort process killer for DISPOSABLE test/bench
    workers, not a replacement for obs.watchdog (which is in-process
    training observability with stack dumps); phases here are coarse
    (~seconds each idle), so the default 4x headroom absorbs a loaded
    box without false kills. `on_timeout` is injectable for unit
    tests (tests/test_parallel.py)."""

    def __init__(self, timeout_s: float = 120.0, *, on_timeout=None,
                 log=None):
        import threading

        self.timeout_s = timeout_s
        self._on_timeout = on_timeout
        self._log = log
        self._lock = threading.Lock()
        self._timer = None

    def _expire(self, phase: str) -> None:
        if self._on_timeout is not None:
            self._on_timeout(phase)
            return
        if self._log is not None:  # pragma: no cover - via injection
            self._log(f"phase deadline: {phase!r} wedged for "
                      f"{self.timeout_s}s — exiting for the spawner's "
                      "fresh-port retry")
        os._exit(BARRIER_TIMEOUT_EXIT)

    def beat(self, phase: str = "work",
             timeout_s: "float | None" = None) -> None:
        """Enter `phase`: the previous phase completed, re-arm.
        `timeout_s` overrides the default for THIS phase — the first
        compile-heavy phase needs more headroom (the compat
        distributed_initialize docstring: a first big XLA compile can
        starve a 1-core box past 100 s without being wedged)."""
        import threading

        new = threading.Timer(timeout_s or self.timeout_s,
                              self._expire, args=(phase,))
        new.daemon = True
        with self._lock:
            old, self._timer = self._timer, new
            new.start()
        if old is not None:
            old.cancel()
            old.join()

    def close(self) -> None:
        """Disarm and reap (the worker finished its workload)."""
        with self._lock:
            old, self._timer = self._timer, None
        if old is not None:
            old.cancel()
            old.join()


def cohort_world() -> "tuple[int, int]":
    """(process_index, process_count) of the LIVE cohort this process
    joined — the one seam topology-dependent host code re-derives the
    world from (ISSUE 13). After the supervisor re-forms a cohort at
    N−1, the relaunched children initialize the distributed runtime at
    the new size and everything built on this seam — the mesh
    (`models/setup.build_mesh` via `jax.devices()`) and the per-host
    infeed split (`models/setup.infeed_split`) — rebuilds itself from
    the surviving process set with no resize-specific code anywhere
    downstream. Single-process (a cohort re-formed at one survivor, or
    a plain run) reads (0, 1) without ever touching the distributed
    runtime."""
    import jax

    return int(jax.process_index()), int(jax.process_count())


def free_port() -> int:
    """An OS-assigned free TCP port for a coordinator about to bind —
    the one definition shared by every multi-process spawner (the
    tests/test_multihost.py fixture, tools/multichip_bench.py legs)."""
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def cpu_worker_env(n_devices: int, extra: dict[str, Any] | None = None
                   ) -> dict:
    """Environment for a spawned CPU worker process: CPU platform +
    n virtual devices pinned BEFORE its jax import (the portable way —
    no config API races). Used by the multi-process test/bench
    spawners."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={n_devices}")
    if extra:
        env.update({k: str(v) for k, v in extra.items()})
    return env
