"""Sharding rules: how the code2vec pytree and batches lay out on a mesh.

SURVEY.md §3.3 (TPU-native equivalents table):
- DP: batch dim sharded over 'data'; XLA inserts the gradient psum over
  ICI automatically during SPMD partitioning of the jitted step.
- TP (embedding sharding): the token table (~1.3M x 128) and target table
  (~261K x 384) shard their VOCAB dim over 'model' so dense embedding
  gradients scale (SURVEY.md §8.4 item 2). XLA turns `jnp.take` on a
  row-sharded table into a dynamic-slice + partial gather + psum, and the
  [B, D] @ [D, V] logits matmul into a reduce-scatter-friendly form.
- TRANSFORM / ATTENTION are tiny: replicated.

Vocab row counts must divide the model axis — ModelDims.vocab_pad_multiple
handles the padding at init time.
"""

from __future__ import annotations

from typing import Dict

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from code2vec_tpu.parallel.mesh import (CONTEXT_AXIS, DATA_AXIS, DCN_AXIS,
                                        MODEL_AXIS)


def param_pspecs() -> Dict[str, P]:
    return {
        "token_emb": P(MODEL_AXIS, None),
        "path_emb": P(MODEL_AXIS, None),
        "target_emb": P(MODEL_AXIS, None),
        "transform": P(None, None),
        "attention": P(None),
        "vm_pointer": P(None, None),   # VarMisuse head (tiny: replicated)
        # transformer encoder subtree ("xf"): one sharding for every leaf
        # (replicated — ~L*12*D^2 floats, tiny next to the vocab tables)
        "xf": P(),
    }


def batch_pspec() -> P:
    """Leading (batch) dim over ('dcn', 'data') jointly — within a
    slice the gradient reduction rides ICI, only the final cross-slice
    psum crosses DCN (a no-op composite at dcn=1); everything else
    replicated."""
    return P((DCN_AXIS, DATA_AXIS))


def context_batch_pspec() -> P:
    """[B, C] tensors with the context dim sharded over 'ctx' — the
    sequence/context-parallel layout for the transformer encoder."""
    return P((DCN_AXIS, DATA_AXIS), CONTEXT_AXIS)


def shard_params(mesh: Mesh, params) -> Dict[str, jax.Array]:
    specs = param_pspecs()

    def put(k, v):
        if isinstance(v, dict) and "q" in v:
            # int8 quantized table (ops/quant.py): rows shard like the
            # flat table would — q [V, E] and s [V, 1] both lead with
            # the vocab dim (data-parallel meshes replicate both)
            spec = specs[k]
            return {"q": jax.device_put(v["q"], NamedSharding(mesh, spec)),
                    "s": jax.device_put(v["s"], NamedSharding(mesh, spec))}
        return jax.device_put(v, NamedSharding(mesh, specs[k]))

    return {k: put(k, v) for k, v in params.items()}


def shard_opt_state(mesh: Mesh, opt_state, params):
    """Optimizer slots mirror their parameter's sharding; scalars/steps
    replicate."""
    specs = param_pspecs()
    # optax states are pytrees whose array leaves either match a param
    # shape (moments) or are scalars (counts). Map by shape. Subtree
    # params (e.g. "xf") contribute every leaf under their one spec.
    shapes_to_spec = {}
    for k, v in params.items():
        for leaf in jax.tree_util.tree_leaves(v):
            shapes_to_spec.setdefault(leaf.shape, specs[k])

    def put(leaf):
        if hasattr(leaf, "shape") and leaf.shape in shapes_to_spec:
            return jax.device_put(
                leaf, NamedSharding(mesh, shapes_to_spec[leaf.shape]))
        if hasattr(leaf, "shape"):
            return jax.device_put(leaf, NamedSharding(mesh, P()))
        return leaf

    return jax.tree_util.tree_map(put, opt_state)


def shard_batch(mesh: Mesh, arrays, *, process_local: bool = True,
                shard_contexts: bool = False):
    """Put a tuple of [B, ...] host arrays onto the mesh with the batch
    dim over 'data'. With shard_contexts=True, [B, C] arrays
    additionally shard their context dim over 'ctx' (context
    parallelism for the transformer encoder).

    Multi-process semantics depend on what the caller's B means:

    - process_local=True (training): every process passes its OWN disjoint
      local batch of size B; the global array has batch B * process_count.
      Built with `jax.make_array_from_process_local_data`, so no process
      needs the others' data — this is what makes the effective global
      batch actually scale with host count.
    - process_local=False (eval/predict): every process passes the SAME
      value; the global batch stays B, sliced across all devices. Built
      with `jax.make_array_from_callback`, which only reads the slices
      owned by this process's devices.
    """
    import numpy as np

    def sharding_for(a):
        if shard_contexts and getattr(a, "ndim", 1) == 2:
            return NamedSharding(mesh, context_batch_pspec())
        return NamedSharding(mesh, batch_pspec())

    if jax.process_count() == 1:
        return tuple(jax.device_put(a, sharding_for(a)) for a in arrays)
    # the np.asarray calls below normalize HOST batches before device
    # placement (the arrays are never on-device yet) — not the
    # device->host fetch graftlint's host-sync rule is hunting
    if process_local:
        return tuple(
            jax.make_array_from_process_local_data(
                sharding_for(a),
                np.asarray(a))  # graftlint: disable=host-sync-in-hot-path
            for a in arrays)
    return tuple(
        jax.make_array_from_callback(
            np.asarray(a).shape,  # graftlint: disable=host-sync-in-hot-path
            sharding_for(a),
            lambda idx, _a=np.asarray(a):  # graftlint: disable=host-sync-in-hot-path
            _a[idx])
        for a in arrays)
