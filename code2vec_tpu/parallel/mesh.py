"""Device-mesh construction.

SURVEY.md §3.3: the reference is single-device; the TPU framework scales by
SPMD over a `jax.sharding.Mesh` — the batch rides the 'data' axis
(gradient allreduce over ICI, replacing any NCCL analog) and the large
vocab tables shard over the 'model' axis. Axes are named, so a future
multi-slice ('dcn', 'data', 'model') mesh is a pure relabeling
(SURVEY.md §3.3 "keep mesh axes abstract").
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

DATA_AXIS = "data"
CONTEXT_AXIS = "ctx"
MODEL_AXIS = "model"


def make_mesh(data: int = 0, model: int = 1, context: int = 1,
              devices: Optional[Sequence] = None) -> Mesh:
    """Build a ('data', 'ctx', 'model') mesh.

    data=0 means "use all remaining devices on the data axis". For
    multi-host runs `jax.devices()` already spans hosts, so the same call
    produces a global mesh (jax.distributed.initialize is handled by the
    trainer entry point).

    The 'ctx' axis (default size 1, a no-op) is the context/sequence-
    parallel axis reserved for the transformer path-encoder
    (SURVEY.md §6 long-context row): sharding the MAX_CONTEXTS dim of
    [B, C, D] activations over it makes XLA insert the attention
    all-gathers over ICI — tested in tests/test_transformer.py.
    """
    devs = list(devices if devices is not None else jax.devices())
    n = len(devs)
    model = max(1, model)
    context = max(1, context)
    if data <= 0:
        if n % (model * context) != 0:
            raise ValueError(
                f"{n} devices not divisible by model*ctx="
                f"{model * context}")
        data = n // (model * context)
    need = data * model * context
    if need != n:
        # Allow a mesh over a subset only when explicitly requested.
        if need > n:
            raise ValueError(
                f"mesh {data}x{context}x{model} needs {need} devices, "
                f"have {n}")
        devs = devs[:need]
    arr = np.asarray(devs).reshape(data, context, model)
    return Mesh(arr, (DATA_AXIS, CONTEXT_AXIS, MODEL_AXIS))
