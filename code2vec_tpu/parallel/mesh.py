"""Device-mesh construction.

SURVEY.md §3.3: the reference is single-device; the TPU framework scales by
SPMD over a `jax.sharding.Mesh` — the batch rides the 'data' axis
(gradient allreduce over ICI, replacing any NCCL analog) and the large
vocab tables shard over the 'model' axis. Axes are named, so a future
multi-slice ('dcn', 'data', 'model') mesh is a pure relabeling
(SURVEY.md §3.3 "keep mesh axes abstract").
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

DATA_AXIS = "data"
MODEL_AXIS = "model"


def make_mesh(data: int = 0, model: int = 1,
              devices: Optional[Sequence] = None) -> Mesh:
    """Build a ('data', 'model') mesh.

    data=0 means "use all remaining devices on the data axis". For
    multi-host runs `jax.devices()` already spans hosts, so the same call
    produces a global mesh (jax.distributed.initialize is handled by the
    trainer entry point).
    """
    devs = list(devices if devices is not None else jax.devices())
    n = len(devs)
    if model <= 0:
        model = 1
    if data <= 0:
        if n % model != 0:
            raise ValueError(f"{n} devices not divisible by model={model}")
        data = n // model
    if data * model != n:
        # Allow a mesh over a subset only when explicitly requested.
        if data * model > n:
            raise ValueError(
                f"mesh {data}x{model} needs {data * model} devices, "
                f"have {n}")
        devs = devs[: data * model]
    arr = np.asarray(devs).reshape(data, model)
    return Mesh(arr, (DATA_AXIS, MODEL_AXIS))
