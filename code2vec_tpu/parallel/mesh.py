"""Device-mesh construction.

SURVEY.md §3.3: the reference is single-device; the TPU framework scales by
SPMD over a `jax.sharding.Mesh` — the batch rides the composite
('dcn', 'data') axes (within-slice gradient allreduce over ICI, final
cross-slice psum over DCN — replacing any NCCL analog), the large vocab
tables shard over 'model', and the transformer's context dim can shard
over 'ctx'. All four axes exist on every mesh; unused ones sit at size 1
as no-ops (SURVEY.md §3.3 "keep mesh axes abstract").
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

DCN_AXIS = "dcn"
DATA_AXIS = "data"
CONTEXT_AXIS = "ctx"
MODEL_AXIS = "model"


def make_mesh(data: int = 0, model: int = 1, context: int = 1,
              dcn: int = 1,
              devices: Optional[Sequence] = None) -> Mesh:
    """Build a ('dcn', 'data', 'ctx', 'model') mesh.

    data=0 means "use all remaining devices on the data axis". For
    multi-host runs `jax.devices()` already spans hosts, so the same call
    produces a global mesh (jax.distributed.initialize is handled by the
    trainer entry point).

    The 'ctx' axis (default size 1, a no-op) is the context/sequence-
    parallel axis reserved for the transformer path-encoder
    (SURVEY.md §6 long-context row): sharding the MAX_CONTEXTS dim of
    [B, C, D] activations over it makes XLA insert the attention
    all-gathers over ICI — tested in tests/test_transformer.py.

    The leading 'dcn' axis (default size 1, a no-op) is the multi-slice
    data axis (SURVEY.md §3.3: "DCN axis reserved for multi-slice"): the
    batch shards over ('dcn', 'data') jointly, so within a slice the
    gradient reduction rides ICI and only the final cross-slice psum
    crosses DCN. With dcn > 1 and no explicit `devices`, the device
    array is built with mesh_utils.create_hybrid_device_mesh so each
    slice's devices land contiguous on the 'dcn' axis (plain
    jax.devices() order doesn't guarantee slice-majority); environments
    without slice topology (the virtual-CPU tests) fall back to a plain
    reshape, which exercises the same axis layout and collectives.
    """
    devs = list(devices if devices is not None else jax.devices())
    n = len(devs)
    model = max(1, model)
    context = max(1, context)
    dcn = max(1, dcn)
    if data <= 0:
        if n % (dcn * model * context) != 0:
            raise ValueError(
                f"{n} devices not divisible by dcn*model*ctx="
                f"{dcn * model * context}")
        data = n // (dcn * model * context)
    need = dcn * data * model * context
    if need != n:
        # Allow a mesh over a subset only when explicitly requested.
        if need > n:
            raise ValueError(
                f"mesh {dcn}x{data}x{context}x{model} needs {need} "
                f"devices, have {n}")
        devs = devs[:need]
    axes = (DCN_AXIS, DATA_AXIS, CONTEXT_AXIS, MODEL_AXIS)
    if dcn > 1 and devices is None:
        try:
            from jax.experimental import mesh_utils
            hybrid = mesh_utils.create_hybrid_device_mesh(
                (data, context, model), (dcn, 1, 1), devices=devs)
            return Mesh(hybrid.reshape(dcn, data, context, model), axes)
        except Exception as e:
            # Expected only where devices carry no slice topology (the
            # virtual CPU mesh in tests). On real multi-slice hardware
            # the fallback reshape may interleave slices on the 'dcn'
            # axis and route per-step allreduces over DCN — loud
            # warning, not silence, so the throughput regression is
            # diagnosable.
            import logging
            logging.getLogger("code2vec-tpu").warning(
                "hybrid (slice-aware) mesh construction failed (%s); "
                "falling back to jax.devices() order — on real "
                "multi-slice hardware verify slice contiguity or pass "
                "an explicit device array", e)
    arr = np.asarray(devs).reshape(dcn, data, context, model)
    return Mesh(arr, axes)
