"""Multi-host initialization and cross-process data movement.

SURVEY.md §3.3 (comm-backend row): the reference is single-process; the
TPU framework scales to multi-host pod slices by running one JAX process
per host inside a single SPMD program — XLA collectives over ICI/DCN
replace the NCCL/MPI backend a GPU framework would carry. This module
owns the `jax.distributed.initialize` call (which must run before the
backend is first touched on every process) and the helpers that move
host data into / out of globally-sharded arrays.

Launch recipe (one command per host):

    python code2vec.py ... --dist_coordinator <host0>:<port> \
        --dist_num_processes <H> --dist_process_id <i>

or rely on auto-detection: on Cloud TPU pods / Slurm,
`jax.distributed.initialize()` discovers the topology itself, and this
module calls it whenever such an environment is detected.
"""

from __future__ import annotations

import os
from typing import Callable, Optional

# Environment markers that indicate "this process is one worker of a
# multi-host job". Explicit coordination uses JAX_COORDINATOR_ADDRESS;
# Slurm jobs expose SLURM_NTASKS; Cloud TPU pod slices expose a
# comma-separated TPU_WORKER_HOSTNAMES (single-host environments set it
# too, with one entry, so it only counts when it names several hosts).
_MULTIHOST_ENV_MARKERS = (
    "JAX_COORDINATOR_ADDRESS",
    "COORDINATOR_ADDRESS",
    "MEGASCALE_COORDINATOR_ADDRESS",
)


def _looks_multihost() -> bool:
    # CODE2VEC_DIST_DISABLE=1 is the escape hatch for processes launched
    # inside an allocation that *looks* multi-task but isn't one JAX job
    # (e.g. one task of a heterogeneous Slurm job): initialize() would
    # otherwise block forever waiting for peers that never connect.
    if os.environ.get("CODE2VEC_DIST_DISABLE", "").lower() in (
            "1", "true", "yes"):
        return False
    if any(os.environ.get(k) for k in _MULTIHOST_ENV_MARKERS):
        return True
    hostnames = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    if len([h for h in hostnames.split(",") if h.strip()]) > 1:
        return True
    # Slurm: SLURM_NTASKS>1 alone is too weak a signal (a single-task
    # step inside a multi-task allocation inherits it); require the
    # per-step variables JAX's Slurm cluster detection actually consumes
    # to be consistent too.
    ntasks = int(os.environ.get("SLURM_STEP_NUM_TASKS")
                 or os.environ.get("SLURM_NTASKS") or 1)
    return ntasks > 1 and "SLURM_PROCID" in os.environ \
        and "SLURM_STEP_NODELIST" in os.environ

_initialized = False


def maybe_initialize(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None,
                     log: Optional[Callable[[str], None]] = None) -> bool:
    """Call `jax.distributed.initialize` when this looks like (or is
    explicitly flagged as) one process of a multi-host job.

    Safe to call unconditionally: single-host runs detect nothing and
    return False without touching the backend. Returns True when the
    distributed runtime was initialized (or already was).
    """
    global _initialized
    if _initialized:
        return True

    flags = (coordinator_address, num_processes, process_id)
    if any(f is not None for f in flags) and any(f is None for f in flags):
        raise ValueError(
            "--dist_coordinator, --dist_num_processes and "
            "--dist_process_id must be given together (got "
            f"coordinator={coordinator_address!r}, "
            f"num_processes={num_processes!r}, process_id={process_id!r})")
    explicit = coordinator_address is not None
    if not (explicit or _looks_multihost()):
        return False

    import jax

    # CPU backends need the Gloo collectives implementation selected
    # BEFORE initialize() or multi-process computations fail outright;
    # harmless elsewhere (parallel/compat.py owns the version seam —
    # and its distributed_initialize widens the heartbeat tolerance on
    # oversubscribed CPU harnesses).
    from code2vec_tpu.parallel.compat import (distributed_initialize,
                                              enable_cpu_collectives)
    enable_cpu_collectives()

    kwargs = {}
    if explicit:
        kwargs = dict(coordinator_address=coordinator_address,
                      num_processes=num_processes,
                      process_id=process_id)
    if log is not None:
        # initialize() blocks until every peer connects — announce first
        # so a mis-detected topology is debuggable rather than a silent
        # hang (set CODE2VEC_DIST_DISABLE=1 to skip auto-detection).
        log(f"initializing jax.distributed (explicit={explicit}) — "
            "blocks until all peers connect")
    # Transient coordination-service/Gloo connect failures ride the
    # shared retry policy (ISSUE 10) instead of killing the worker on
    # the first hiccup. jax's State.initialize assigns the
    # global-state client BEFORE connect(), so a failed connect leaves
    # it set and a naive re-call raises "should only be called once"
    # forever, masking the real error — each failed attempt therefore
    # best-effort RESETS the distributed global state
    # (jax.distributed.shutdown clears client/service) so the retry
    # retries the connect, not the precondition. Genuine
    # non-transients give up immediately: the ordering precondition
    # ("must run before any JAX computation") and a reset that didn't
    # take ("should only be called once" — surfacing it beats burning
    # the budget on it). The `dist/init` failpoint exercises this.
    from code2vec_tpu.resilience import faults
    from code2vec_tpu.resilience import retry as retry_mod

    def _init() -> None:
        faults.fire("dist/init")
        try:
            distributed_initialize(**kwargs)
        except BaseException:
            import jax.distributed
            try:
                jax.distributed.shutdown()
            except Exception as reset_err:
                # keep the ORIGINAL connect error in flight; a failed
                # reset only means the next attempt gives up fast
                if log is not None:
                    log("distributed-state reset after failed init "
                        f"also failed: {reset_err}")
            raise

    retry_mod.transient_distributed(
        "distributed-init", log=log,
        giveup=lambda e: (
            "must run before any JAX computation" in str(e)
            or "should only be called once" in str(e))).call(_init)
    _initialized = True
    if log is not None:
        log(f"jax.distributed initialized: process "
            f"{jax.process_index()}/{jax.process_count()}, "
            f"{jax.local_device_count()} local / "
            f"{jax.device_count()} global devices")
    return True


def allreduce_sum_hosts(vec):
    """Sum a small host-side float64 vector across processes (identity
    for single-process runs). Used to merge per-host evaluation metric
    partials after a host-sharded eval pass.

    Exactness: process_allgather round-trips through a device array,
    which canonicalizes float64 -> float32 (x64 is off), so a value is
    only transmitted exactly below 2^24. Each per-host value is split
    into a 2^24 quotient and remainder before the gather and recombined
    in float64 after, keeping integer metric counts exact up to 2^48
    PER HOST (the cross-host summation itself happens host-side in
    float64)."""
    import numpy as np

    import jax

    vec = np.asarray(vec, np.float64)
    if jax.process_count() == 1:
        return vec
    from jax.experimental import multihost_utils
    SPLIT = float(1 << 24)
    hi = np.floor(vec / SPLIT)
    lo = vec - hi * SPLIT
    gathered = np.asarray(multihost_utils.process_allgather(
        np.stack([hi, lo]).astype(np.float32), tiled=False),
        np.float64)  # [H, 2, n]
    return (gathered[:, 0] * SPLIT + gathered[:, 1]).sum(axis=0)


def fetch_global(x):
    """Bring a (possibly non-fully-addressable) global array to the host
    as numpy, identical on every process.

    Single-process: plain np.asarray. Multi-process: allgather the
    process-local shards over the coordination backend so host-side code
    (metrics, prediction decoding) sees the full batch everywhere.

    This IS the deliberate device->host sync that ends the predict /
    eval hot paths — the results must reach the host to be decoded, and
    the predict path's `serve/predict_ms` telemetry span (jax_model.
    predict_device) budgets it explicitly. graftlint's host-sync rule
    SANCTIONS this function by name (round 14 — the parallel layer's
    counterpart of obs.device_sync: one named, greppable terminal-fetch
    seam instead of per-site suppressions; `code2vec_tpu/parallel/` is
    under NO_BASELINE_PREFIXES, so no grandfathering either). Policy:
    hot-path code that must bring a result to the host routes through
    fetch_global; an ad-hoc np.asarray/.item()/float() still gets
    flagged.
    """
    import jax
    import numpy as np

    if jax.process_count() == 1:
        return np.asarray(x)  # the deliberate result fetch (docstring)
    from jax.experimental import multihost_utils
    return np.asarray(multihost_utils.process_allgather(x, tiled=True))
