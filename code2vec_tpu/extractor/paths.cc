#include "paths.h"

#include <cctype>
#include <unordered_set>

namespace c2v {

int32_t JavaStringHash(const std::string& s) {
  int32_t h = 0;
  for (unsigned char c : s)
    h = static_cast<int32_t>(static_cast<uint32_t>(h) * 31u + c);
  return h;
}

namespace {

inline bool IsUpper(char c) { return c >= 'A' && c <= 'Z'; }
inline bool IsLower(char c) { return c >= 'a' && c <= 'z'; }
inline bool IsDigit(char c) { return c >= '0' && c <= '9'; }

// Mirror common.split_to_subtokens: split on _, digits, whitespace,
// lower->Upper boundaries and Upper-Upper-lower boundaries; each piece is
// normalized (strip non-letters; fallback lowercase original) and empty
// pieces dropped.
std::vector<std::string> SplitSubtokens(const std::string& word) {
  std::vector<std::string> pieces;
  std::string cur;
  auto flush = [&]() {
    if (!cur.empty()) {
      pieces.push_back(cur);
      cur.clear();
    }
  };
  size_t n = word.size();
  for (size_t i = 0; i < n; ++i) {
    char c = word[i];
    if (c == '_' || IsDigit(c) ||
        std::isspace(static_cast<unsigned char>(c))) {
      flush();
      continue;
    }
    if (i > 0) {
      char p = word[i - 1];
      if ((IsLower(p) && IsUpper(c)) ||
          (IsUpper(p) && IsUpper(c) && i + 1 < n && IsLower(word[i + 1]))) {
        flush();
      }
    }
    cur.push_back(c);
  }
  flush();
  // normalize each piece
  std::vector<std::string> out;
  for (auto& p : pieces) {
    std::string stripped;
    for (char c : p)
      if (std::isalpha(static_cast<unsigned char>(c)))
        stripped.push_back(
            static_cast<char>(std::tolower(static_cast<unsigned char>(c))));
    if (stripped.empty()) {
      for (char c : p)
        stripped.push_back(
            static_cast<char>(std::tolower(static_cast<unsigned char>(c))));
    }
    if (!stripped.empty()) out.push_back(stripped);
  }
  return out;
}

std::string JoinPipe(const std::vector<std::string>& parts) {
  std::string out;
  for (size_t i = 0; i < parts.size(); ++i) {
    if (i) out.push_back('|');
    out += parts[i];
  }
  return out;
}

// Leaf token text -> the normalized token emitted in contexts. Literals
// get value-preserving treatment: numbers stay numeric, strings are
// subtokenized content (or a placeholder when empty/non-alpha).
std::string LeafToken(const Node& node) {
  const std::string& t = node.type;
  const std::string& raw = node.leaf;
  if (t == "IntegerLiteralExpr" || t == "LongLiteralExpr" ||
      t == "DoubleLiteralExpr") {
    std::string digits;
    for (char c : raw)
      if (!std::isspace(static_cast<unsigned char>(c)) && c != '_' &&
          c != 'l' && c != 'L' && c != 'f' && c != 'F' && c != 'd' &&
          c != 'D')
        digits.push_back(
            static_cast<char>(std::tolower(static_cast<unsigned char>(c))));
    return digits.empty() ? "0" : digits;
  }
  if (t == "StringLiteralExpr") {
    if (raw.size() > 2) {
      std::string inner = raw.substr(1, raw.size() - 2);
      std::string norm = JoinPipe(SplitSubtokens(inner));
      if (!norm.empty()) return norm;
    }
    return "STR";
  }
  if (t == "CharLiteralExpr") {
    if (raw.size() > 2) {
      std::string inner = raw.substr(1, raw.size() - 2);
      std::string norm = JoinPipe(SplitSubtokens(inner));
      if (!norm.empty()) return norm;
    }
    return "CHR";
  }
  std::string norm = JoinPipe(SplitSubtokens(raw));
  return norm.empty() ? "TOKEN" : norm;
}

}  // namespace

std::string NormalizeToken(const std::string& raw) {
  std::string norm = JoinPipe(SplitSubtokens(raw));
  if (!norm.empty()) return norm;
  std::string lower;
  for (char c : raw)
    lower.push_back(
        static_cast<char>(std::tolower(static_cast<unsigned char>(c))));
  return lower;
}

namespace {

// Collect leaf node ids of a method subtree in DFS (source) order. The
// method's own SimpleName leaf (first SimpleName child of the method
// node) is replaced by the special METHOD_NAME token to prevent label
// leakage, matching the reference extractor.
void CollectLeaves(const Ast& ast, int node, int method_node,
                   std::vector<int>* leaves, std::vector<int>* depths,
                   int depth, int max_leaves) {
  if (static_cast<int>(leaves->size()) >= max_leaves) return;
  const Node& n = ast.at(node);
  if (n.children.empty() && !n.leaf.empty()) {
    leaves->push_back(node);
    depths->push_back(depth);
    return;
  }
  for (int c : n.children)
    CollectLeaves(ast, c, method_node, leaves, depths, depth + 1,
                  max_leaves);
}

}  // namespace

std::vector<MethodFeatures> ExtractFeatures(const Ast& ast,
                                            const std::vector<int>& methods,
                                            const ExtractOptions& opts) {
  std::vector<MethodFeatures> out;
  for (int m : methods) {
    const Node& mnode = ast.at(m);
    // the declaration's name leaf = first SimpleName child of the method
    int name_leaf = -1;
    for (int c : mnode.children) {
      if (ast.at(c).type == "SimpleName") { name_leaf = c; break; }
    }
    if (name_leaf < 0) continue;
    MethodFeatures mf;
    mf.name = NormalizeToken(ast.at(name_leaf).leaf);
    if (mf.name.empty()) continue;

    std::vector<int> leaves, depths;
    CollectLeaves(ast, m, m, &leaves, &depths, 0, opts.max_leaves);

    size_t L = leaves.size();
    // precompute ancestors-to-method for each leaf (paths are short; the
    // length filter prunes most pairs before LCA walk completes)
    for (size_t i = 0; i < L; ++i) {
      for (size_t j = i + 1; j < L; ++j) {
        int a = leaves[i], b = leaves[j];
        if (a == name_leaf && b == name_leaf) continue;
        // climb to equal depth, then together to the LCA
        int da = depths[i], db = depths[j];
        int ua = a, ub = b;
        int up_a = 0, up_b = 0;
        while (da > db) { ua = ast.at(ua).parent; --da; ++up_a; }
        while (db > da) { ub = ast.at(ub).parent; --db; ++up_b; }
        while (ua != ub && ua >= 0 && ub >= 0) {
          ua = ast.at(ua).parent;
          ub = ast.at(ub).parent;
          ++up_a;
          ++up_b;
        }
        if (ua < 0 || ua != ub) continue;
        int path_len = up_a + up_b;
        if (path_len > opts.max_path_length) continue;
        // width: child-index gap of the two arms at the LCA
        int ca = a, cb = b;
        for (int k = 0; k < up_a - 1; ++k) ca = ast.at(ca).parent;
        for (int k = 0; k < up_b - 1; ++k) cb = ast.at(cb).parent;
        int width = (up_a == 0) ? 0
                    : (up_b == 0) ? 0
                    : ast.at(cb).child_index - ast.at(ca).child_index;
        if (width < 0) width = -width;
        if (width > opts.max_path_width) continue;

        // render path: typeA ^ ... ^ LCA _ ... _ typeB
        std::string path;
        int cur = a;
        for (int k = 0; k < up_a; ++k) {
          path += ast.at(cur).type;
          path.push_back('^');
          cur = ast.at(cur).parent;
        }
        path += ast.at(cur).type;  // LCA
        // downward arm, collected bottom-up then appended in reverse
        std::vector<const std::string*> down;
        cur = b;
        for (int k = 0; k < up_b; ++k) {
          down.push_back(&ast.at(cur).type);
          cur = ast.at(cur).parent;
        }
        for (auto it = down.rbegin(); it != down.rend(); ++it) {
          path.push_back('_');
          path += **it;
        }

        std::string tok_a = (a == name_leaf) ? "METHOD_NAME"
                                             : LeafToken(ast.at(a));
        std::string tok_b = (b == name_leaf) ? "METHOD_NAME"
                                             : LeafToken(ast.at(b));
        std::string path_repr =
            opts.hash_paths ? std::to_string(JavaStringHash(path)) : path;
        mf.contexts.push_back(tok_a + "," + path_repr + "," + tok_b);
      }
    }
    if (!mf.contexts.empty()) out.push_back(std::move(mf));
  }
  return out;
}

std::string RenderLine(const MethodFeatures& mf) {
  std::string line = mf.name;
  for (const auto& c : mf.contexts) {
    line.push_back(' ');
    line += c;
  }
  return line;
}

}  // namespace c2v
