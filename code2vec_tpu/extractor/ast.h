// Arena AST for the native path-context extractor.
//
// Node type names follow JavaParser's class names (MethodDeclaration,
// BlockStmt, NameExpr, ...) so rendered paths look like the reference
// JavaExtractor's (SURVEY.md §3: path rendered as node-type sequence with
// direction markers). Binary/unary/assign nodes carry their operator in
// the type string (e.g. "BinaryExpr:plus") as JavaParser-based extractors
// do.
#pragma once

#include <string>
#include <vector>

namespace c2v {

struct Node {
  std::string type;    // JavaParser-style node type name
  std::string leaf;    // raw token text; non-empty iff this is a leaf
  int parent = -1;
  int child_index = 0;     // position among parent's children
  std::vector<int> children;
};

class Ast {
 public:
  int Add(std::string type, int parent, std::string leaf = "") {
    int id = static_cast<int>(nodes_.size());
    nodes_.push_back(Node{std::move(type), std::move(leaf), parent, 0, {}});
    if (parent >= 0) {
      nodes_[parent].children.push_back(id);
      nodes_[id].child_index =
          static_cast<int>(nodes_[parent].children.size()) - 1;
    }
    return id;
  }

  Node& at(int id) { return nodes_[id]; }
  const Node& at(int id) const { return nodes_[id]; }
  int size() const { return static_cast<int>(nodes_.size()); }

  // Re-parent `child` under `new_parent` (used when wrapping an already
  // parsed subtree, e.g. binary expressions built bottom-up).
  void Reparent(int child, int new_parent) {
    nodes_[child].parent = new_parent;
    nodes_[new_parent].children.push_back(child);
    nodes_[child].child_index =
        static_cast<int>(nodes_[new_parent].children.size()) - 1;
  }

 private:
  std::vector<Node> nodes_;
};

}  // namespace c2v
