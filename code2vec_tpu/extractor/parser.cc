#include "parser.h"

#include <functional>

namespace c2v {
namespace {

// JavaParser BinaryExpr.Operator names, keyed by operator spelling.
const char* BinOpName(const std::string& op) {
  if (op == "||") return "OR";
  if (op == "&&") return "AND";
  if (op == "|") return "BINARY_OR";
  if (op == "^") return "XOR";
  if (op == "&") return "BINARY_AND";
  if (op == "==") return "EQUALS";
  if (op == "!=") return "NOT_EQUALS";
  if (op == "<") return "LESS";
  if (op == ">") return "GREATER";
  if (op == "<=") return "LESS_EQUALS";
  if (op == ">=") return "GREATER_EQUALS";
  if (op == "<<") return "LEFT_SHIFT";
  if (op == ">>") return "SIGNED_RIGHT_SHIFT";
  if (op == ">>>") return "UNSIGNED_RIGHT_SHIFT";
  if (op == "+") return "PLUS";
  if (op == "-") return "MINUS";
  if (op == "*") return "MULTIPLY";
  if (op == "/") return "DIVIDE";
  if (op == "%") return "REMAINDER";
  return "UNKNOWN";
}

const char* AssignOpName(const std::string& op) {
  if (op == "=") return "ASSIGN";
  if (op == "+=") return "PLUS";
  if (op == "-=") return "MINUS";
  if (op == "*=") return "MULTIPLY";
  if (op == "/=") return "DIVIDE";
  if (op == "%=") return "REMAINDER";
  if (op == "&=") return "BINARY_AND";
  if (op == "|=") return "BINARY_OR";
  if (op == "^=") return "XOR";
  if (op == "<<=") return "LEFT_SHIFT";
  if (op == ">>=") return "SIGNED_RIGHT_SHIFT";
  if (op == ">>>=") return "UNSIGNED_RIGHT_SHIFT";
  return "ASSIGN";
}

class Parser {
 public:
  explicit Parser(std::vector<Token> toks) : toks_(std::move(toks)) {}

  ParseResult Run() {
    ParseCompilationUnit();
    result_.ast = std::move(ast_);
    return std::move(result_);
  }

 private:
  std::vector<Token> toks_;
  size_t pos_ = 0;
  Ast ast_;
  ParseResult result_;
  int depth_ = 0;

  struct DepthGuard {
    Parser* p;
    bool ok;
    explicit DepthGuard(Parser* p_) : p(p_), ok(++p_->depth_ < 220) {}
    ~DepthGuard() { --p->depth_; }
  };

  // ---- token helpers ----
  const Token& Cur() const { return toks_[pos_]; }
  const Token& Peek(size_t k = 1) const {
    size_t i = pos_ + k;
    return i < toks_.size() ? toks_[i] : toks_.back();
  }
  bool AtEnd() const { return Cur().kind == TokKind::End; }
  void Advance() { if (!AtEnd()) ++pos_; }
  bool Is(TokKind k, const char* text = nullptr) const {
    return Cur().kind == k && (!text || Cur().text == text);
  }
  bool IsOp(const char* text) const { return Is(TokKind::Operator, text); }
  bool IsKw(const char* text) const { return Is(TokKind::Keyword, text); }
  bool Eat(TokKind k, const char* text = nullptr) {
    if (Is(k, text)) { Advance(); return true; }
    return false;
  }
  bool EatOp(const char* text) { return Eat(TokKind::Operator, text); }
  bool EatKw(const char* text) { return Eat(TokKind::Keyword, text); }

  // Skip a balanced region starting at the current open token.
  void SkipBalanced(const char* open, const char* close) {
    int depth = 0;
    while (!AtEnd()) {
      if (IsOp(open)) ++depth;
      else if (IsOp(close)) {
        --depth;
        if (depth <= 0) { Advance(); return; }
      }
      Advance();
    }
  }

  void SkipToStatementSync() {
    int brace = 0;
    while (!AtEnd()) {
      if (IsOp(";") && brace == 0) { Advance(); return; }
      if (IsOp("{")) ++brace;
      if (IsOp("}")) {
        if (brace == 0) return;  // let the caller consume it
        --brace;
      }
      Advance();
    }
  }

  // ---- modifiers / annotations (dropped from the tree) ----
  void SkipModifiers() {
    static const char* kMods[] = {
        "public", "private", "protected", "static", "final", "abstract",
        "native", "synchronized", "transient", "volatile", "strictfp",
        "default", nullptr};
    for (;;) {
      bool any = false;
      for (const char** m = kMods; *m; ++m)
        if (IsKw(*m)) { Advance(); any = true; break; }
      if (!any) return;
    }
  }

  // ---- types ----
  bool LooksLikePrimitive() const {
    static const char* kPrims[] = {"int", "long", "short", "byte", "char",
                                   "boolean", "float", "double", nullptr};
    for (const char** p = kPrims; *p; ++p)
      if (IsKw(*p)) return true;
    return false;
  }

  // Try to skip a generic argument list `<...>` at the current position;
  // returns false (position restored) if it does not look like one.
  bool TrySkipTypeArgs() {
    if (!IsOp("<")) return false;
    size_t save = pos_;
    int depth = 0;
    int fuel = 400;
    while (!AtEnd() && fuel-- > 0) {
      if (IsOp("<")) ++depth;
      else if (IsOp(">")) { --depth; if (depth == 0) { Advance(); return true; } }
      else if (IsOp(">>")) { depth -= 2; if (depth <= 0) { Advance(); return true; } }
      else if (IsOp(">>>")) { depth -= 3; if (depth <= 0) { Advance(); return true; } }
      else if (Cur().kind != TokKind::Identifier && !IsOp(",") &&
               !IsOp("?") && !IsKw("extends") && !IsKw("super") &&
               !IsOp(".") && !IsOp("[") && !IsOp("]") &&
               !LooksLikePrimitive() && !IsOp("&")) {
        break;  // not a type-arg list (e.g. a comparison)
      }
      Advance();
    }
    pos_ = save;
    return false;
  }

  // Parse a type into the tree under `parent`. Returns node id or -1.
  int ParseType(int parent) {
    if (IsKw("void")) {
      int id = ast_.Add("VoidType", parent, Cur().text);
      Advance();
      return id;
    }
    if (LooksLikePrimitive()) {
      int id = ast_.Add("PrimitiveType", parent, Cur().text);
      Advance();
      while (IsOp("[") && Peek().text == "]") {
        Advance(); Advance();
        id = WrapArray(id, parent);
      }
      return id;
    }
    if (Cur().kind != TokKind::Identifier && !IsKw("var")) return -1;
    // qualified name a.b.C — leaf keeps the LAST segment (JavaParser's
    // ClassOrInterfaceType name)
    std::string last = Cur().text;
    Advance();
    TrySkipTypeArgs();
    while (IsOp(".") && Peek().kind == TokKind::Identifier) {
      Advance();
      last = Cur().text;
      Advance();
      TrySkipTypeArgs();
    }
    int id = ast_.Add("ClassOrInterfaceType", parent, last);
    while (IsOp("[") && Peek().text == "]") {
      Advance(); Advance();
      id = WrapArray(id, parent);
    }
    // varargs handled by caller
    return id;
  }

  int WrapArray(int component, int parent) {
    // Rebuild as ArrayType{component}; component was last child of parent.
    int arr = ast_.Add("ArrayType", parent);
    // move component under arr
    auto& pch = ast_.at(parent).children;
    for (size_t k = 0; k < pch.size(); ++k) {
      if (pch[k] == component) { pch.erase(pch.begin() + k); break; }
    }
    // fix child_index bookkeeping of remaining children
    for (size_t k = 0; k < pch.size(); ++k) ast_.at(pch[k]).child_index =
        static_cast<int>(k);
    ast_.at(arr).child_index = static_cast<int>(pch.size()) - 1;
    ast_.Reparent(component, arr);
    return arr;
  }

  // Heuristic: does a statement starting here look like a local variable
  // declaration?
  bool LooksLikeLocalVarDecl() {
    if (IsKw("final")) return true;
    if (IsKw("var") && Peek().kind == TokKind::Identifier) return true;
    if (LooksLikePrimitive()) return true;
    if (Cur().kind != TokKind::Identifier) return false;
    size_t save = pos_;
    bool result = false;
    Advance();
    // qualified segments
    while (IsOp(".") && Peek().kind == TokKind::Identifier) {
      Advance(); Advance();
    }
    TrySkipTypeArgs();
    while (IsOp("[") && Peek().text == "]") { Advance(); Advance(); }
    if (Cur().kind == TokKind::Identifier) {
      const Token& nxt = Peek();
      if (nxt.text == "=" || nxt.text == ";" || nxt.text == "," ||
          nxt.text == ")" || nxt.text == ":" || nxt.text == "[")
        result = true;
    }
    pos_ = save;
    return result;
  }

  // ---- compilation unit / declarations ----
  void ParseCompilationUnit() {
    int root = ast_.Add("CompilationUnit", -1);
    while (!AtEnd()) {
      if (EatKw("package") || EatKw("import")) {
        while (!AtEnd() && !EatOp(";")) Advance();
        continue;
      }
      SkipModifiers();
      if (IsKw("class") || IsKw("interface") || IsKw("enum") ||
          IsKw("record") || IsKw("@interface")) {
        ParseTypeDeclaration(root);
      } else if (IsOp(";")) {
        Advance();
      } else {
        Advance();  // stray token at top level
      }
    }
  }

  void ParseTypeDeclaration(int parent) {
    DepthGuard g(this);
    if (!g.ok) { SkipToStatementSync(); return; }
    std::string kw = Cur().text;
    Advance();
    const char* type = (kw == "enum") ? "EnumDeclaration"
                      : (kw == "record") ? "RecordDeclaration"
                      : "ClassOrInterfaceDeclaration";
    int id = ast_.Add(type, parent);
    if (Cur().kind == TokKind::Identifier) {
      ast_.Add("SimpleName", id, Cur().text);
      Advance();
    }
    TrySkipTypeArgs();  // type parameters
    // record header
    if (kw == "record" && IsOp("(")) {
      Advance();
      while (!AtEnd() && !IsOp(")")) {
        ParseParameter(id);
        if (!EatOp(",")) break;
      }
      EatOp(")");
    }
    while (EatKw("extends") || EatKw("implements")) {
      do {
        ParseType(id);
      } while (EatOp(","));
    }
    if (!EatOp("{")) { SkipToStatementSync(); return; }
    if (kw == "enum") ParseEnumConstants(id);
    while (!AtEnd() && !IsOp("}")) ParseMember(id);
    EatOp("}");
  }

  void ParseEnumConstants(int parent) {
    // constants: NAME(args)? {body}? , ... ;
    while (Cur().kind == TokKind::Identifier) {
      int c = ast_.Add("EnumConstantDeclaration", parent);
      ast_.Add("SimpleName", c, Cur().text);
      Advance();
      if (IsOp("(")) SkipBalanced("(", ")");
      if (IsOp("{")) {
        Advance();
        while (!AtEnd() && !IsOp("}")) ParseMember(c);
        EatOp("}");
      }
      if (!EatOp(",")) break;
    }
    EatOp(";");
  }

  void ParseMember(int parent) {
    DepthGuard g(this);
    if (!g.ok) { SkipToStatementSync(); EatOp("}"); return; }
    SkipModifiers();
    if (IsOp(";")) { Advance(); return; }
    if (IsKw("class") || IsKw("interface") || IsKw("enum") ||
        IsKw("record") || IsKw("@interface")) {
      ParseTypeDeclaration(parent);
      return;
    }
    if (IsOp("{")) {  // static/instance initializer block
      int init = ast_.Add("InitializerDeclaration", parent);
      ParseBlock(init);
      return;
    }
    TrySkipTypeArgs();  // method type parameters
    size_t save = pos_;
    // constructor: Identifier (
    if (Cur().kind == TokKind::Identifier && Peek().text == "(") {
      ParseCallableRest(parent, "ConstructorDeclaration", Cur().text,
                        /*has_return_type=*/false);
      return;
    }
    // method or field: Type Name ...
    int probe_parent = ast_.Add("__probe__", -1);
    int t = ParseType(probe_parent);
    if (t >= 0 && Cur().kind == TokKind::Identifier &&
        Peek().text == "(") {
      std::string name = Cur().text;
      int m = ast_.Add("MethodDeclaration", parent);
      AdoptProbe(probe_parent, m);
      ParseCallableRest(m, "", name, /*has_return_type=*/true);
      return;
    }
    if (t >= 0 && Cur().kind == TokKind::Identifier) {
      // field declaration(s)
      int f = ast_.Add("FieldDeclaration", parent);
      AdoptProbe(probe_parent, f);
      do {
        int vd = ast_.Add("VariableDeclarator", f);
        if (Cur().kind == TokKind::Identifier) {
          ast_.Add("SimpleName", vd, Cur().text);
          Advance();
        }
        while (IsOp("[") && Peek().text == "]") { Advance(); Advance(); }
        if (EatOp("=")) ParseVarInit(vd);
      } while (EatOp(","));
      if (!EatOp(";")) SkipToStatementSync();
      return;
    }
    // unrecognized member — resync
    pos_ = save;
    ++result_.dropped_methods;
    SkipMemberLike();
  }

  // Move the probe's children (parsed type nodes) under `new_parent`.
  void AdoptProbe(int probe, int new_parent) {
    auto children = ast_.at(probe).children;  // copy
    for (int c : children) ast_.Reparent(c, new_parent);
    ast_.at(probe).children.clear();
  }

  void SkipMemberLike() {
    // skip to `;` or a balanced `{...}`
    while (!AtEnd()) {
      if (IsOp(";")) { Advance(); return; }
      if (IsOp("{")) { SkipBalanced("{", "}"); return; }
      if (IsOp("}")) return;
      Advance();
    }
  }

  // Shared tail of methods/constructors: (params) throws? body
  // `callable_type` non-empty => create the node here (constructors);
  // empty => parent IS the already-created MethodDeclaration.
  void ParseCallableRest(int parent_or_self, const char* callable_type,
                         const std::string& name, bool has_return_type) {
    int m = parent_or_self;
    if (callable_type && *callable_type) {
      m = ast_.Add(callable_type, parent_or_self);
    }
    // The method's own name leaf: JavaExtractor replaces it with a
    // special token to prevent label leakage (the target IS the name).
    ast_.Add("SimpleName", m,
             has_return_type || std::string(callable_type) ==
                 "ConstructorDeclaration" ? name : name);
    Advance();  // name
    size_t guard = pos_;
    EatOp("(");
    while (!AtEnd() && !IsOp(")")) {
      ParseParameter(m);
      if (!EatOp(",")) break;
    }
    EatOp(")");
    while (IsOp("[") && Peek().text == "]") { Advance(); Advance(); }
    if (EatKw("throws")) {
      do {
        ParseType(m);
      } while (EatOp(","));
    }
    if (IsOp("{")) {
      size_t body_start = pos_;
      ParseBlock(m);
      (void)body_start;
      if (std::string(ast_.at(m).type) == "MethodDeclaration")
        result_.method_nodes.push_back(m);
      else if (ast_.at(m).type == "ConstructorDeclaration")
        result_.method_nodes.push_back(m);
    } else if (EatOp(";")) {
      // abstract/interface method: no body, still a method node but the
      // reference only emits methods with bodies — skip.
    } else if (EatOp("=")) {
      // annotation member default — skip to ;
      SkipToStatementSync();
    } else {
      if (pos_ == guard) Advance();
      ++result_.dropped_methods;
      SkipMemberLike();
    }
  }

  void ParseParameter(int parent) {
    SkipModifiers();
    int p = ast_.Add("Parameter", parent);
    ParseType(p);
    EatOp("...");  // varargs
    if (Cur().kind == TokKind::Identifier) {
      ast_.Add("SimpleName", p, Cur().text);
      Advance();
    }
    while (IsOp("[") && Peek().text == "]") { Advance(); Advance(); }
  }

  // ---- statements ----
  void ParseBlock(int parent) {
    DepthGuard g(this);
    int b = ast_.Add("BlockStmt", parent);
    if (!EatOp("{")) return;
    if (!g.ok) { SkipBalanced("{", "}"); return; }
    while (!AtEnd() && !IsOp("}")) {
      size_t before = pos_;
      ParseStatement(b);
      if (pos_ == before) Advance();  // always make progress
    }
    EatOp("}");
  }

  void ParseStatement(int parent) {
    DepthGuard g(this);
    if (!g.ok) { SkipToStatementSync(); return; }
    if (IsOp("{")) { ParseBlock(parent); return; }
    if (IsOp(";")) { ast_.Add("EmptyStmt", parent); Advance(); return; }
    if (IsKw("if")) { ParseIf(parent); return; }
    if (IsKw("while")) {
      int s = ast_.Add("WhileStmt", parent);
      Advance();
      ParseParenExpr(s);
      ParseStatement(s);
      return;
    }
    if (IsKw("do")) {
      int s = ast_.Add("DoStmt", parent);
      Advance();
      ParseStatement(s);
      if (EatKw("while")) ParseParenExpr(s);
      EatOp(";");
      return;
    }
    if (IsKw("for")) { ParseFor(parent); return; }
    if (IsKw("return")) {
      int s = ast_.Add("ReturnStmt", parent);
      Advance();
      if (!IsOp(";")) ParseExpression(s);
      if (!EatOp(";")) SkipToStatementSync();
      return;
    }
    if (IsKw("throw")) {
      int s = ast_.Add("ThrowStmt", parent);
      Advance();
      ParseExpression(s);
      if (!EatOp(";")) SkipToStatementSync();
      return;
    }
    if (IsKw("break")) {
      ast_.Add("BreakStmt", parent);
      Advance();
      if (Cur().kind == TokKind::Identifier) Advance();
      EatOp(";");
      return;
    }
    if (IsKw("continue")) {
      ast_.Add("ContinueStmt", parent);
      Advance();
      if (Cur().kind == TokKind::Identifier) Advance();
      EatOp(";");
      return;
    }
    if (IsKw("try")) { ParseTry(parent); return; }
    if (IsKw("switch")) { ParseSwitch(parent); return; }
    if (IsKw("synchronized")) {
      int s = ast_.Add("SynchronizedStmt", parent);
      Advance();
      if (IsOp("(")) ParseParenExpr(s);
      ParseStatement(s);
      return;
    }
    if (IsKw("assert")) {
      int s = ast_.Add("AssertStmt", parent);
      Advance();
      ParseExpression(s);
      if (EatOp(":")) ParseExpression(s);
      if (!EatOp(";")) SkipToStatementSync();
      return;
    }
    if (IsKw("yield")) {
      int s = ast_.Add("YieldStmt", parent);
      Advance();
      ParseExpression(s);
      if (!EatOp(";")) SkipToStatementSync();
      return;
    }
    if (IsKw("class") || IsKw("interface") || IsKw("enum")) {
      int s = ast_.Add("LocalClassDeclarationStmt", parent);
      ParseTypeDeclaration(s);
      return;
    }
    if (IsKw("this") && Peek().text == "(") {
      int s = ast_.Add("ExplicitConstructorInvocationStmt", parent);
      Advance();
      ParseArguments(s);
      EatOp(";");
      return;
    }
    if (IsKw("super") && Peek().text == "(") {
      int s = ast_.Add("ExplicitConstructorInvocationStmt", parent);
      Advance();
      ParseArguments(s);
      EatOp(";");
      return;
    }
    // labeled statement: Identifier ':' (but not switch-case / ternary)
    if (Cur().kind == TokKind::Identifier && Peek().text == ":") {
      int s = ast_.Add("LabeledStmt", parent);
      Advance(); Advance();
      ParseStatement(s);
      return;
    }
    if (LooksLikeLocalVarDecl()) {
      int s = ast_.Add("ExpressionStmt", parent);
      ParseVarDeclExpr(s);
      if (!EatOp(";")) SkipToStatementSync();
      return;
    }
    // expression statement
    int s = ast_.Add("ExpressionStmt", parent);
    ParseExpression(s);
    if (!EatOp(";")) SkipToStatementSync();
  }

  void ParseIf(int parent) {
    int s = ast_.Add("IfStmt", parent);
    Advance();
    ParseParenExpr(s);
    ParseStatement(s);
    if (EatKw("else")) ParseStatement(s);
  }

  void ParseParenExpr(int parent) {
    if (!EatOp("(")) { SkipToStatementSync(); return; }
    ParseExpression(parent);
    if (!EatOp(")")) {
      // resync to the matching paren
      int depth = 1;
      while (!AtEnd() && depth > 0) {
        if (IsOp("(")) ++depth;
        else if (IsOp(")")) --depth;
        Advance();
      }
    }
  }

  void ParseFor(int parent) {
    Advance();  // 'for'
    size_t save = pos_;
    // detect for-each: for ( Type name : expr )
    if (EatOp("(")) {
      size_t depth_save = pos_;
      (void)depth_save;
      bool foreach_detected = false;
      int scan_depth = 1;
      size_t scan = pos_;
      int fuel = 2000;
      while (scan < toks_.size() && scan_depth > 0 && fuel-- > 0) {
        const auto& t = toks_[scan];
        if (t.kind == TokKind::Operator) {
          if (t.text == "(") ++scan_depth;
          else if (t.text == ")") --scan_depth;
          else if (t.text == ";" && scan_depth == 1) break;
          else if (t.text == ":" && scan_depth == 1) {
            foreach_detected = true;
            break;
          } else if (t.text == "?" && scan_depth == 1) {
            break;  // ternary ':' would confuse the scan
          }
        }
        ++scan;
      }
      if (foreach_detected) {
        int s = ast_.Add("ForEachStmt", parent);
        int vd = ast_.Add("VariableDeclarationExpr", s);
        ParseType(vd);
        int var = ast_.Add("VariableDeclarator", vd);
        if (Cur().kind == TokKind::Identifier) {
          ast_.Add("SimpleName", var, Cur().text);
          Advance();
        }
        EatOp(":");
        ParseExpression(s);
        EatOp(")");
        ParseStatement(s);
        return;
      }
      int s = ast_.Add("ForStmt", parent);
      // init
      if (!IsOp(";")) {
        if (LooksLikeLocalVarDecl()) ParseVarDeclExpr(s);
        else {
          do { ParseExpression(s); } while (EatOp(","));
        }
      }
      EatOp(";");
      if (!IsOp(";")) ParseExpression(s);  // condition
      EatOp(";");
      if (!IsOp(")")) {
        do { ParseExpression(s); } while (EatOp(","));
      }
      EatOp(")");
      ParseStatement(s);
      return;
    }
    pos_ = save;
    SkipToStatementSync();
  }

  void ParseTry(int parent) {
    int s = ast_.Add("TryStmt", parent);
    Advance();
    if (IsOp("(")) {  // try-with-resources
      Advance();
      while (!AtEnd() && !IsOp(")")) {
        if (LooksLikeLocalVarDecl()) ParseVarDeclExpr(s);
        else ParseExpression(s);
        if (!EatOp(";")) break;
      }
      EatOp(")");
    }
    if (IsOp("{")) ParseBlock(s);
    while (IsKw("catch")) {
      int c = ast_.Add("CatchClause", s);
      Advance();
      if (EatOp("(")) {
        SkipModifiers();
        int p = ast_.Add("Parameter", c);
        ParseType(p);
        while (EatOp("|")) ParseType(p);  // union type
        if (Cur().kind == TokKind::Identifier) {
          ast_.Add("SimpleName", p, Cur().text);
          Advance();
        }
        EatOp(")");
      }
      if (IsOp("{")) ParseBlock(c);
    }
    if (EatKw("finally")) {
      if (IsOp("{")) ParseBlock(s);
    }
  }

  void ParseSwitch(int parent) {
    int s = ast_.Add("SwitchStmt", parent);
    Advance();
    ParseParenExpr(s);
    if (!EatOp("{")) { SkipToStatementSync(); return; }
    while (!AtEnd() && !IsOp("}")) {
      if (EatKw("case")) {
        int e = ast_.Add("SwitchEntry", s);
        do {
          ParseExpression(e);
        } while (EatOp(","));
        if (EatOp("->")) {
          ParseStatement(e);
          continue;
        }
        EatOp(":");
        while (!AtEnd() && !IsKw("case") && !IsKw("default") && !IsOp("}")) {
          size_t before = pos_;
          ParseStatement(e);
          if (pos_ == before) Advance();
        }
      } else if (EatKw("default")) {
        int e = ast_.Add("SwitchEntry", s);
        if (EatOp("->")) {
          ParseStatement(e);
          continue;
        }
        EatOp(":");
        while (!AtEnd() && !IsKw("case") && !IsKw("default") && !IsOp("}")) {
          size_t before = pos_;
          ParseStatement(e);
          if (pos_ == before) Advance();
        }
      } else {
        Advance();
      }
    }
    EatOp("}");
  }

  void ParseVarDeclExpr(int parent) {
    int d = ast_.Add("VariableDeclarationExpr", parent);
    SkipModifiers();
    ParseType(d);
    do {
      int vd = ast_.Add("VariableDeclarator", d);
      if (Cur().kind == TokKind::Identifier) {
        ast_.Add("SimpleName", vd, Cur().text);
        Advance();
      }
      while (IsOp("[") && Peek().text == "]") { Advance(); Advance(); }
      if (EatOp("=")) ParseVarInit(vd);
    } while (EatOp(","));
  }

  void ParseVarInit(int parent) {
    if (IsOp("{")) { ParseArrayInitializer(parent); return; }
    ParseExpression(parent);
  }

  void ParseArrayInitializer(int parent) {
    int a = ast_.Add("ArrayInitializerExpr", parent);
    EatOp("{");
    while (!AtEnd() && !IsOp("}")) {
      if (IsOp("{")) ParseArrayInitializer(a);
      else ParseExpression(a);
      if (!EatOp(",")) break;
    }
    EatOp("}");
  }

  void ParseArguments(int parent) {
    if (!EatOp("(")) return;
    while (!AtEnd() && !IsOp(")")) {
      ParseExpression(parent);
      if (!EatOp(",")) break;
    }
    EatOp(")");
  }

  // ---- expressions (precedence climbing; nodes built detached and
  // attached via Reparent so children keep source order) ----
  void ParseExpression(int parent) {
    int e = ParseAssignment();
    if (e >= 0) ast_.Reparent(e, parent);
  }

  int ParseAssignment() {
    DepthGuard g(this);
    if (!g.ok) { SkipToStatementSync(); return -1; }
    int lhs = ParseTernary();
    static const char* kAssign[] = {"=", "+=", "-=", "*=", "/=", "%=",
                                    "&=", "|=", "^=", "<<=", ">>=",
                                    ">>>=", nullptr};
    for (const char** a = kAssign; *a; ++a) {
      if (IsOp(*a)) {
        std::string op = Cur().text;
        Advance();
        int rhs = ParseAssignment();  // right-assoc
        int node = ast_.Add(std::string("AssignExpr:") + AssignOpName(op),
                            -1);
        if (lhs >= 0) ast_.Reparent(lhs, node);
        if (rhs >= 0) ast_.Reparent(rhs, node);
        return node;
      }
    }
    return lhs;
  }

  int ParseTernary() {
    int cond = ParseBinary(0);
    if (IsOp("?")) {
      Advance();
      int then_e = ParseAssignment();
      EatOp(":");
      int else_e = ParseAssignment();
      int node = ast_.Add("ConditionalExpr", -1);
      if (cond >= 0) ast_.Reparent(cond, node);
      if (then_e >= 0) ast_.Reparent(then_e, node);
      if (else_e >= 0) ast_.Reparent(else_e, node);
      return node;
    }
    return cond;
  }

  // precedence table for binary ops, lowest first
  int ParseBinary(int level) {
    static const std::vector<std::vector<std::string>> kLevels = {
        {"||"}, {"&&"}, {"|"}, {"^"}, {"&"},
        {"==", "!="},
        {"<", ">", "<=", ">=", "instanceof"},
        {"<<", ">>", ">>>"},
        {"+", "-"},
        {"*", "/", "%"},
    };
    if (level >= static_cast<int>(kLevels.size())) return ParseUnary();
    int lhs = ParseBinary(level + 1);
    for (;;) {
      bool matched = false;
      for (const auto& op : kLevels[level]) {
        if (op == "instanceof" ? IsKw("instanceof") : IsOp(op.c_str())) {
          // `<` here could open generics of a following decl — but in
          // expression position we treat it as less-than.
          if (op == "instanceof") {
            Advance();
            int node = ast_.Add("InstanceOfExpr", -1);
            if (lhs >= 0) ast_.Reparent(lhs, node);
            ParseType(node);
            // pattern variable (Java 16): instanceof Type name
            if (Cur().kind == TokKind::Identifier) {
              ast_.Add("SimpleName", node, Cur().text);
              Advance();
            }
            lhs = node;
          } else {
            Advance();
            int rhs = ParseBinary(level + 1);
            int node = ast_.Add(
                std::string("BinaryExpr:") + BinOpName(op), -1);
            if (lhs >= 0) ast_.Reparent(lhs, node);
            if (rhs >= 0) ast_.Reparent(rhs, node);
            lhs = node;
          }
          matched = true;
          break;
        }
      }
      if (!matched) return lhs;
    }
  }

  int ParseUnary() {
    DepthGuard g(this);
    if (!g.ok) { SkipToStatementSync(); return -1; }
    if (IsOp("!")) {
      Advance();
      int e = ParseUnary();
      int node = ast_.Add("UnaryExpr:LOGICAL_COMPLEMENT", -1);
      if (e >= 0) ast_.Reparent(e, node);
      return node;
    }
    if (IsOp("~")) {
      Advance();
      int e = ParseUnary();
      int node = ast_.Add("UnaryExpr:BITWISE_COMPLEMENT", -1);
      if (e >= 0) ast_.Reparent(e, node);
      return node;
    }
    if (IsOp("-")) {
      Advance();
      int e = ParseUnary();
      int node = ast_.Add("UnaryExpr:MINUS", -1);
      if (e >= 0) ast_.Reparent(e, node);
      return node;
    }
    if (IsOp("+")) {
      Advance();
      int e = ParseUnary();
      int node = ast_.Add("UnaryExpr:PLUS", -1);
      if (e >= 0) ast_.Reparent(e, node);
      return node;
    }
    if (IsOp("++") || IsOp("--")) {
      std::string op = Cur().text;
      Advance();
      int e = ParseUnary();
      int node = ast_.Add(std::string("UnaryExpr:") +
                          (op == "++" ? "PREFIX_INCREMENT"
                                      : "PREFIX_DECREMENT"), -1);
      if (e >= 0) ast_.Reparent(e, node);
      return node;
    }
    // cast: ( Type ) unary  — heuristic lookahead
    if (IsOp("(") && LooksLikeCast()) {
      Advance();
      int node = ast_.Add("CastExpr", -1);
      ParseType(node);
      EatOp(")");
      int e = ParseUnary();
      if (e >= 0) ast_.Reparent(e, node);
      return node;
    }
    return ParsePostfix();
  }

  bool LooksLikeCast() {
    // `( PrimitiveType )` always a cast; `( Name )` followed by an
    // identifier/literal/'(' and Name is a plausible type.
    size_t save = pos_;
    bool result = false;
    Advance();  // '('
    if (LooksLikePrimitive()) {
      size_t j = pos_;
      ++j;
      while (j < toks_.size() && toks_[j].text == "[" &&
             j + 1 < toks_.size() && toks_[j + 1].text == "]")
        j += 2;
      if (j < toks_.size() && toks_[j].text == ")") result = true;
    } else if (Cur().kind == TokKind::Identifier) {
      size_t j = pos_ + 1;
      int fuel = 100;
      while (j < toks_.size() && fuel-- > 0 &&
             (toks_[j].text == "." || toks_[j].text == "[" ||
              toks_[j].text == "]" ||
              toks_[j].kind == TokKind::Identifier))
        ++j;
      // allow one generic hop
      if (j < toks_.size() && toks_[j].text == "<") {
        int depth = 0;
        while (j < toks_.size() && fuel-- > 0) {
          if (toks_[j].text == "<") ++depth;
          else if (toks_[j].text == ">") { --depth; if (!depth) { ++j; break; } }
          else if (toks_[j].text == ">>") { depth -= 2; if (depth <= 0) { ++j; break; } }
          ++j;
        }
      }
      if (j < toks_.size() && toks_[j].text == ")" &&
          j + 1 < toks_.size()) {
        const Token& nxt = toks_[j + 1];
        if (nxt.kind == TokKind::Identifier ||
            nxt.kind == TokKind::IntLiteral ||
            nxt.kind == TokKind::FloatLiteral ||
            nxt.kind == TokKind::StringLiteral ||
            nxt.kind == TokKind::CharLiteral ||
            nxt.text == "(" || nxt.text == "new" || nxt.text == "this" ||
            nxt.text == "!" || nxt.text == "~")
          result = true;
      }
    }
    pos_ = save;
    return result;
  }

  int ParsePostfix() {
    int e = ParsePrimary();
    for (;;) {
      if (IsOp(".")) {
        // method call / field access / .class / .this / method ref
        Advance();
        TrySkipTypeArgs();  // explicit generic call foo.<T>bar()
        if (IsKw("class")) {
          Advance();
          int node = ast_.Add("ClassExpr", -1);
          if (e >= 0) ast_.Reparent(e, node);
          e = node;
          continue;
        }
        if (IsKw("this")) {
          Advance();
          int node = ast_.Add("ThisExpr", -1, "this");
          if (e >= 0) ast_.Reparent(e, node);
          e = node;
          continue;
        }
        if (IsKw("new")) {
          // qualified new — treat as ObjectCreationExpr with scope
          Advance();
          int node = ParseObjectCreation();
          if (e >= 0 && node >= 0) ast_.Reparent(e, node);
          e = node;
          continue;
        }
        if (Cur().kind == TokKind::Identifier) {
          std::string name = Cur().text;
          Advance();
          if (IsOp("(")) {
            int node = ast_.Add("MethodCallExpr", -1);
            if (e >= 0) ast_.Reparent(e, node);
            ast_.Add("SimpleName", node, name);
            ParseArguments(node);
            e = node;
          } else {
            int node = ast_.Add("FieldAccessExpr", -1);
            if (e >= 0) ast_.Reparent(e, node);
            ast_.Add("SimpleName", node, name);
            e = node;
          }
          continue;
        }
        continue;  // stray dot
      }
      if (IsOp("::")) {
        Advance();
        int node = ast_.Add("MethodReferenceExpr", -1);
        if (e >= 0) ast_.Reparent(e, node);
        if (Cur().kind == TokKind::Identifier || IsKw("new")) {
          ast_.Add("SimpleName", node, Cur().text);
          Advance();
        }
        e = node;
        continue;
      }
      if (IsOp("[")) {
        Advance();
        int node = ast_.Add("ArrayAccessExpr", -1);
        if (e >= 0) ast_.Reparent(e, node);
        if (!IsOp("]")) ParseExpression(node);
        EatOp("]");
        e = node;
        continue;
      }
      if (IsOp("++") || IsOp("--")) {
        std::string op = Cur().text;
        Advance();
        int node = ast_.Add(std::string("UnaryExpr:") +
                            (op == "++" ? "POSTFIX_INCREMENT"
                                        : "POSTFIX_DECREMENT"), -1);
        if (e >= 0) ast_.Reparent(e, node);
        e = node;
        continue;
      }
      return e;
    }
  }

  bool LooksLikeLambda() {
    // `ident ->` or `( params ) ->`
    if (Cur().kind == TokKind::Identifier && Peek().text == "->")
      return true;
    if (!IsOp("(")) return false;
    size_t j = pos_;
    int depth = 0;
    int fuel = 300;
    while (j < toks_.size() && fuel-- > 0) {
      if (toks_[j].text == "(") ++depth;
      else if (toks_[j].text == ")") {
        --depth;
        if (depth == 0)
          return j + 1 < toks_.size() && toks_[j + 1].text == "->";
      }
      ++j;
    }
    return false;
  }

  int ParseLambda() {
    int node = ast_.Add("LambdaExpr", -1);
    if (IsOp("(")) {
      Advance();
      while (!AtEnd() && !IsOp(")")) {
        SkipModifiers();
        int p = ast_.Add("Parameter", node);
        // typed or untyped param
        if (Cur().kind == TokKind::Identifier &&
            (Peek().text == "," || Peek().text == ")")) {
          ast_.Add("SimpleName", p, Cur().text);
          Advance();
        } else {
          ParseType(p);
          if (Cur().kind == TokKind::Identifier) {
            ast_.Add("SimpleName", p, Cur().text);
            Advance();
          }
        }
        if (!EatOp(",")) break;
      }
      EatOp(")");
    } else if (Cur().kind == TokKind::Identifier) {
      int p = ast_.Add("Parameter", node);
      ast_.Add("SimpleName", p, Cur().text);
      Advance();
    }
    EatOp("->");
    if (IsOp("{")) ParseBlock(node);
    else ParseExpression(node);
    return node;
  }

  int ParseObjectCreation() {
    // after 'new'
    int node = ast_.Add("ObjectCreationExpr", -1);
    int t = ParseType(node);
    if (IsOp("[") || (t >= 0 && ast_.at(t).type == "ArrayType")) {
      // array creation: new T[expr]... or new T[]{...}
      ast_.at(node).type = "ArrayCreationExpr";
      while (IsOp("[")) {
        Advance();
        if (!IsOp("]")) {
          int lvl = ast_.Add("ArrayCreationLevel", node);
          ParseExpression(lvl);
        }
        EatOp("]");
      }
      if (IsOp("{")) ParseArrayInitializer(node);
      return node;
    }
    if (IsOp("(")) ParseArguments(node);
    if (IsOp("{")) {
      // anonymous class body: members parsed so nested methods are
      // visited too (the reference's FunctionVisitor recurses into them)
      Advance();
      while (!AtEnd() && !IsOp("}")) ParseMember(node);
      EatOp("}");
    }
    return node;
  }

  int ParsePrimary() {
    DepthGuard g(this);
    if (!g.ok) { SkipToStatementSync(); return -1; }
    if (LooksLikeLambda()) return ParseLambda();
    const Token& t = Cur();
    switch (t.kind) {
      case TokKind::IntLiteral: {
        bool is_long = !t.text.empty() &&
                       (t.text.back() == 'l' || t.text.back() == 'L');
        int id = ast_.Add(is_long ? "LongLiteralExpr" : "IntegerLiteralExpr",
                          -1, t.text);
        Advance();
        return id;
      }
      case TokKind::FloatLiteral: {
        int id = ast_.Add("DoubleLiteralExpr", -1, t.text);
        Advance();
        return id;
      }
      case TokKind::CharLiteral: {
        int id = ast_.Add("CharLiteralExpr", -1, t.text);
        Advance();
        return id;
      }
      case TokKind::StringLiteral: {
        int id = ast_.Add("StringLiteralExpr", -1, t.text);
        Advance();
        return id;
      }
      default: break;
    }
    if (IsKw("true") || IsKw("false")) {
      int id = ast_.Add("BooleanLiteralExpr", -1, t.text);
      Advance();
      return id;
    }
    if (IsKw("null")) {
      int id = ast_.Add("NullLiteralExpr", -1, "null");
      Advance();
      return id;
    }
    if (IsKw("this")) {
      int id = ast_.Add("ThisExpr", -1, "this");
      Advance();
      return id;
    }
    if (IsKw("super")) {
      int id = ast_.Add("SuperExpr", -1, "super");
      Advance();
      return id;
    }
    if (IsKw("new")) {
      Advance();
      return ParseObjectCreation();
    }
    if (IsKw("switch")) {
      // switch expression (Java 14)
      int id = ast_.Add("SwitchExpr", -1);
      ParseSwitch(id);
      return id;
    }
    if (LooksLikePrimitive() || IsKw("void")) {
      // e.g. int.class, void.class
      int id = ast_.Add("PrimitiveType", -1, t.text);
      Advance();
      while (IsOp("[") && Peek().text == "]") { Advance(); Advance(); }
      return id;
    }
    if (IsOp("(")) {
      Advance();
      int node = ast_.Add("EnclosedExpr", -1);
      ParseExpression(node);
      EatOp(")");
      return node;
    }
    if (t.kind == TokKind::Identifier) {
      int id = ast_.Add("NameExpr", -1, t.text);
      Advance();
      if (IsOp("(")) {
        // unqualified call: wrap as MethodCallExpr with the name leaf
        int node = ast_.Add("MethodCallExpr", -1);
        ast_.at(id).type = "SimpleName";
        ast_.Reparent(id, node);
        ParseArguments(node);
        return node;
      }
      return id;
    }
    // unknown token in expression position
    Advance();
    return -1;
  }
};

}  // namespace

ParseResult ParseJava(const std::string& source) {
  Parser p(Lex(source));
  return p.Run();
}

}  // namespace c2v
