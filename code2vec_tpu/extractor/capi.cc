// C API for in-process extraction via ctypes (no subprocess overhead in
// the data pipeline). See code2vec_tpu/extractor/native.py.

#include <cstdlib>
#include <cstring>
#include <string>

#include "parser.h"
#include "paths.h"

extern "C" {

// Extract path-contexts from Java source text. Returns a malloc'd
// NUL-terminated buffer of newline-separated method lines (caller frees
// with c2v_free), or nullptr on failure.
char* c2v_extract_source(const char* source, int max_path_length,
                         int max_path_width, int max_leaves) {
  if (!source) return nullptr;
  c2v::ExtractOptions opts;
  opts.max_path_length = max_path_length;
  opts.max_path_width = max_path_width;
  if (max_leaves > 0) opts.max_leaves = max_leaves;
  c2v::ParseResult pr = c2v::ParseJava(source);
  auto features = c2v::ExtractFeatures(pr.ast, pr.method_nodes, opts);
  std::string out;
  for (const auto& mf : features) {
    out += c2v::RenderLine(mf);
    out.push_back('\n');
  }
  char* buf = static_cast<char*>(std::malloc(out.size() + 1));
  if (!buf) return nullptr;
  std::memcpy(buf, out.data(), out.size());
  buf[out.size()] = '\0';
  return buf;
}

void c2v_free(char* p) { std::free(p); }

// Java String.hashCode, exposed so Python-side tests can cross-check.
int c2v_java_string_hash(const char* s) {
  return c2v::JavaStringHash(s ? s : "");
}

}  // extern "C"
