// Recursive-descent parser for a practical Java subset -> JavaParser-like
// AST. Replaces the reference's JavaParser dependency (SURVEY.md §3.1:
// no JVM in this environment; §8.4 item 1: "a restricted Java grammar
// must still hit high method coverage"). Malformed constructs recover at
// brace/semicolon boundaries; methods that fail to parse are dropped and
// counted, never fatal.
#pragma once

#include <string>
#include <vector>

#include "ast.h"
#include "lexer.h"

namespace c2v {

struct ParseResult {
  Ast ast;
  std::vector<int> method_nodes;  // ids of MethodDeclaration nodes
  int dropped_methods = 0;
};

// Parse one compilation unit (never throws; best-effort recovery).
ParseResult ParseJava(const std::string& source);

}  // namespace c2v
