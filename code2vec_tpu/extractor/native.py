"""ctypes bindings for the native extractor (libc2v.so).

In-process extraction without subprocess overhead, for the data pipeline
and tests. Falls back to the c2v_extract CLI if the shared library is
missing. Build both with ./build_extractor.sh.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import List, Optional

_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_DIR, "build", "libc2v.so")
_BIN_PATH = os.path.join(_DIR, "build", "c2v_extract")

_lib = None


def _stale_warning() -> None:
    """Warn when a source file is newer than the built library, so a stale
    build can't silently serve old extraction behavior."""
    try:
        lib_mtime = os.path.getmtime(_LIB_PATH)
        for name in os.listdir(_DIR):
            if name.endswith((".cc", ".h")):
                if os.path.getmtime(os.path.join(_DIR, name)) > lib_mtime:
                    import warnings
                    warnings.warn(
                        f"native extractor source {name} is newer than "
                        f"{_LIB_PATH}; re-run ./build_extractor.sh",
                        RuntimeWarning, stacklevel=3)
                    return
    except OSError:
        pass


def _load() -> Optional[ctypes.CDLL]:
    global _lib
    if _lib is None and os.path.exists(_LIB_PATH):
        _stale_warning()
        lib = ctypes.CDLL(_LIB_PATH)
        lib.c2v_extract_source.restype = ctypes.c_void_p
        lib.c2v_extract_source.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                           ctypes.c_int, ctypes.c_int]
        lib.c2v_free.argtypes = [ctypes.c_void_p]
        lib.c2v_java_string_hash.restype = ctypes.c_int
        lib.c2v_java_string_hash.argtypes = [ctypes.c_char_p]
        _lib = lib
    return _lib


def available() -> bool:
    return os.path.exists(_LIB_PATH) or os.path.exists(_BIN_PATH)


def extract_source(source: str, max_path_length: int = 8,
                   max_path_width: int = 2,
                   max_leaves: int = 1000) -> List[str]:
    """Java source text -> extractor output lines (`name tok,hash,tok ...`)."""
    lib = _load()
    if lib is not None:
        ptr = lib.c2v_extract_source(source.encode("utf-8"),
                                     max_path_length, max_path_width,
                                     max_leaves)
        if not ptr:
            return []
        try:
            text = ctypes.string_at(ptr).decode("utf-8", errors="replace")
        finally:
            lib.c2v_free(ptr)
        return [ln for ln in text.splitlines() if ln.strip()]
    if os.path.exists(_BIN_PATH):
        import tempfile
        with tempfile.NamedTemporaryFile("w", suffix=".java",
                                         delete=False) as f:
            f.write(source)
            tmp = f.name
        try:
            proc = subprocess.run(
                [_BIN_PATH, "--file", tmp,
                 "--max_path_length", str(max_path_length),
                 "--max_path_width", str(max_path_width)],
                capture_output=True, text=True, timeout=120)
            return [ln for ln in proc.stdout.splitlines() if ln.strip()]
        finally:
            os.unlink(tmp)
    raise FileNotFoundError(
        "native extractor not built; run ./build_extractor.sh")


def java_string_hash(s: str) -> int:
    """Java String.hashCode (C implementation when built; the single
    pure-python implementation lives in python_extractor)."""
    lib = _load()
    if lib is not None:
        return lib.c2v_java_string_hash(s.encode("utf-8"))
    from code2vec_tpu.extractor.python_extractor import (
        java_string_hash as py_hash)
    return py_hash(s)
