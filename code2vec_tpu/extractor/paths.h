// Path-context enumeration over the AST, token normalization, and the
// output line format (SURVEY.md §3 "JavaExtractor (NATIVE)" + §3.2):
// per method, collect AST leaves, enumerate leaf pairs whose connecting
// path has length <= max_path_length and width <= max_path_width, render
// the path as a node-type sequence with direction markers, hash it with
// Java String.hashCode semantics, normalize leaf tokens (lowercase
// subtokens joined with '|'), and emit one line per method:
//   `name ctx1 ... ctxN`, ctx = `tok,pathHash,tok`.
#pragma once

#include <cstdint>
#include <string>
#include <vector>

#include "ast.h"

namespace c2v {

struct ExtractOptions {
  int max_path_length = 8;   // edges on the up+down path
  int max_path_width = 2;    // child-index gap at the pivot (LCA)
  int max_leaves = 1000;     // guard against O(L^2) blowup on huge methods
  bool hash_paths = true;    // false: emit the readable path string
};

// Java String.hashCode (32-bit wraparound) — the reference hashes path
// strings this way for compactness.
int32_t JavaStringHash(const std::string& s);

// common.py-compatible normalization: split camelCase/underscores/digits,
// strip non-letters (fallback: lowercased original), lowercase, join '|'.
std::string NormalizeToken(const std::string& raw);

// One extracted method: target name + context triples.
struct MethodFeatures {
  std::string name;                       // normalized target label
  std::vector<std::string> contexts;      // "tok,path,tok"
};

// Extract features for every method node in the AST.
std::vector<MethodFeatures> ExtractFeatures(const Ast& ast,
                                            const std::vector<int>& methods,
                                            const ExtractOptions& opts);

// Render a MethodFeatures as one output line.
std::string RenderLine(const MethodFeatures& mf);

}  // namespace c2v
