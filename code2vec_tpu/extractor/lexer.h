// Java lexer for the native path-context extractor.
//
// Replaces the reference's JVM JavaExtractor front half (SURVEY.md §3
// "JavaExtractor (NATIVE)": JavaParser-based lexing/parsing). No JVM
// exists in this environment, so tokenization is implemented from
// scratch: identifiers, keywords, int/float/char/string literals
// (including text blocks), operators, comments, annotations.
#pragma once

#include <cstdint>
#include <string>
#include <vector>

namespace c2v {

enum class TokKind : uint8_t {
  Identifier,
  Keyword,
  IntLiteral,
  FloatLiteral,
  CharLiteral,
  StringLiteral,
  Operator,   // punctuation + operators, spelled in `text`
  End,
};

struct Token {
  TokKind kind;
  std::string text;
  int line;
};

// Tokenize Java source. Comments and annotations-bodies are skipped;
// malformed input produces best-effort tokens (never throws).
std::vector<Token> Lex(const std::string& src);

bool IsJavaKeyword(const std::string& s);

}  // namespace c2v
