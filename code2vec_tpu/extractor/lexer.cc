#include "lexer.h"

#include <cctype>
#include <unordered_set>

namespace c2v {

bool IsJavaKeyword(const std::string& s) {
  static const std::unordered_set<std::string> kKeywords = {
      "abstract", "assert", "boolean", "break", "byte", "case", "catch",
      "char", "class", "const", "continue", "default", "do", "double",
      "else", "enum", "extends", "final", "finally", "float", "for",
      "goto", "if", "implements", "import", "instanceof", "int",
      "interface", "long", "native", "new", "package", "private",
      "protected", "public", "return", "short", "static", "strictfp",
      "super", "switch", "synchronized", "this", "throw", "throws",
      "transient", "try", "void", "volatile", "while", "record",
      "var", "true", "false", "null"};
  return kKeywords.count(s) > 0;
}

namespace {

inline bool IsIdentStart(char c) {
  return std::isalpha(static_cast<unsigned char>(c)) || c == '_' ||
         c == '$' || static_cast<unsigned char>(c) >= 0x80;
}
inline bool IsIdentPart(char c) {
  return IsIdentStart(c) || std::isdigit(static_cast<unsigned char>(c));
}

}  // namespace

std::vector<Token> Lex(const std::string& src) {
  std::vector<Token> out;
  size_t i = 0, n = src.size();
  int line = 1;
  auto push = [&](TokKind k, std::string text) {
    out.push_back(Token{k, std::move(text), line});
  };
  while (i < n) {
    char c = src[i];
    if (c == '\n') { ++line; ++i; continue; }
    if (std::isspace(static_cast<unsigned char>(c))) { ++i; continue; }
    // comments
    if (c == '/' && i + 1 < n && src[i + 1] == '/') {
      while (i < n && src[i] != '\n') ++i;
      continue;
    }
    if (c == '/' && i + 1 < n && src[i + 1] == '*') {
      i += 2;
      while (i + 1 < n && !(src[i] == '*' && src[i + 1] == '/')) {
        if (src[i] == '\n') ++line;
        ++i;
      }
      i = (i + 1 < n) ? i + 2 : n;
      continue;
    }
    // identifiers / keywords
    if (IsIdentStart(c)) {
      size_t j = i + 1;
      while (j < n && IsIdentPart(src[j])) ++j;
      std::string word = src.substr(i, j - i);
      // evaluate the kind BEFORE std::move empties `word` (argument
      // evaluation order is unspecified)
      TokKind kind = IsJavaKeyword(word) ? TokKind::Keyword
                                         : TokKind::Identifier;
      push(kind, std::move(word));
      i = j;
      continue;
    }
    // numeric literals
    if (std::isdigit(static_cast<unsigned char>(c)) ||
        (c == '.' && i + 1 < n &&
         std::isdigit(static_cast<unsigned char>(src[i + 1])))) {
      size_t j = i;
      bool is_float = false;
      if (src[j] == '0' && j + 1 < n &&
          (src[j + 1] == 'x' || src[j + 1] == 'X' || src[j + 1] == 'b' ||
           src[j + 1] == 'B')) {
        j += 2;
        while (j < n && (std::isalnum(static_cast<unsigned char>(src[j])) ||
                         src[j] == '_'))
          ++j;
      } else {
        while (j < n && (std::isdigit(static_cast<unsigned char>(src[j])) ||
                         src[j] == '_'))
          ++j;
        if (j < n && src[j] == '.') {
          is_float = true;
          ++j;
          while (j < n &&
                 (std::isdigit(static_cast<unsigned char>(src[j])) ||
                  src[j] == '_'))
            ++j;
        }
        if (j < n && (src[j] == 'e' || src[j] == 'E')) {
          is_float = true;
          ++j;
          if (j < n && (src[j] == '+' || src[j] == '-')) ++j;
          while (j < n && std::isdigit(static_cast<unsigned char>(src[j])))
            ++j;
        }
        if (j < n && (src[j] == 'f' || src[j] == 'F' || src[j] == 'd' ||
                      src[j] == 'D')) {
          is_float = true;
          ++j;
        } else if (j < n && (src[j] == 'l' || src[j] == 'L')) {
          ++j;
        }
      }
      push(is_float ? TokKind::FloatLiteral : TokKind::IntLiteral,
           src.substr(i, j - i));
      i = j;
      continue;
    }
    // char literal
    if (c == '\'') {
      size_t j = i + 1;
      while (j < n && src[j] != '\'') {
        if (src[j] == '\\') ++j;
        ++j;
      }
      j = (j < n) ? j + 1 : n;
      push(TokKind::CharLiteral, src.substr(i, j - i));
      i = j;
      continue;
    }
    // string literal (incl. """text blocks""")
    if (c == '"') {
      if (i + 2 < n && src[i + 1] == '"' && src[i + 2] == '"') {
        size_t j = i + 3;
        while (j + 2 < n &&
               !(src[j] == '"' && src[j + 1] == '"' && src[j + 2] == '"')) {
          if (src[j] == '\n') ++line;
          ++j;
        }
        j = (j + 2 < n) ? j + 3 : n;
        push(TokKind::StringLiteral, "\"<textblock>\"");
        i = j;
        continue;
      }
      size_t j = i + 1;
      while (j < n && src[j] != '"' && src[j] != '\n') {
        if (src[j] == '\\') ++j;
        ++j;
      }
      j = (j < n && src[j] == '"') ? j + 1 : j;
      push(TokKind::StringLiteral, src.substr(i, j - i));
      i = j;
      continue;
    }
    // annotations: skip `@Name` and a balanced `(...)` argument list.
    // (JavaParser models annotations as AST nodes; the reference's
    // extractor does not emit leaves from them, so dropping them at lex
    // time keeps the tree equivalent for path purposes.)
    if (c == '@') {
      size_t j = i + 1;
      if (j < n && IsIdentStart(src[j])) {
        while (j < n && (IsIdentPart(src[j]) || src[j] == '.')) ++j;
        // "@interface" is a declaration keyword, not an annotation use
        if (src.substr(i + 1, j - i - 1) == "interface") {
          push(TokKind::Keyword, "@interface");
          i = j;
          continue;
        }
        while (j < n && std::isspace(static_cast<unsigned char>(src[j])))
          ++j;
        if (j < n && src[j] == '(') {
          int depth = 0;
          do {
            if (src[j] == '(') ++depth;
            else if (src[j] == ')') --depth;
            else if (src[j] == '\n') ++line;
            ++j;
          } while (j < n && depth > 0);
        }
        i = j;
        continue;
      }
      ++i;
      continue;
    }
    // multi-char operators, longest-match
    static const char* kOps3[] = {">>>=", nullptr};
    static const char* kOps3b[] = {"<<=", ">>=", ">>>", "...", nullptr};
    static const char* kOps2[] = {"==", "!=", "<=", ">=", "&&", "||",
                                  "++", "--", "+=", "-=", "*=", "/=",
                                  "%=", "&=", "|=", "^=", "<<", ">>",
                                  "->", "::", nullptr};
    bool matched = false;
    for (const char** p = kOps3; *p && !matched; ++p)
      if (src.compare(i, 4, *p) == 0) {
        push(TokKind::Operator, *p); i += 4; matched = true;
      }
    for (const char** p = kOps3b; *p && !matched; ++p)
      if (src.compare(i, 3, *p) == 0) {
        push(TokKind::Operator, *p); i += 3; matched = true;
      }
    for (const char** p = kOps2; *p && !matched; ++p)
      if (src.compare(i, 2, *p) == 0) {
        push(TokKind::Operator, *p); i += 2; matched = true;
      }
    if (matched) continue;
    push(TokKind::Operator, std::string(1, c));
    ++i;
  }
  push(TokKind::End, "");
  return out;
}

}  // namespace c2v
