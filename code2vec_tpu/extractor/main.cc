// c2v_extract — native path-context extractor CLI.
//
// Drop-in for the reference's JVM invocation (SURVEY.md §2 L0):
//   java -jar JavaExtractor.jar --max_path_length 8 --max_path_width 2
//        --dir <d> --num_threads N   (or --file <f>)
// emits one line per method to stdout: `name tok,pathHash,tok ...`.

#include <atomic>
#include <cstring>
#include <filesystem>
#include <fstream>
#include <iostream>
#include <mutex>
#include <sstream>
#include <string>
#include <thread>
#include <vector>

#include "parser.h"
#include "paths.h"

namespace fs = std::filesystem;

namespace {

std::string ReadFile(const std::string& path) {
  std::ifstream f(path, std::ios::binary);
  std::ostringstream ss;
  ss << f.rdbuf();
  return ss.str();
}

std::string ProcessSource(const std::string& src,
                          const c2v::ExtractOptions& opts) {
  c2v::ParseResult pr = c2v::ParseJava(src);
  auto features = c2v::ExtractFeatures(pr.ast, pr.method_nodes, opts);
  std::string out;
  for (const auto& mf : features) {
    out += c2v::RenderLine(mf);
    out.push_back('\n');
  }
  return out;
}

}  // namespace

int main(int argc, char** argv) {
  c2v::ExtractOptions opts;
  std::string dir, file;
  int num_threads = static_cast<int>(std::thread::hardware_concurrency());
  bool no_hash = false;
  for (int i = 1; i < argc; ++i) {
    std::string a = argv[i];
    auto next = [&]() -> std::string {
      return (i + 1 < argc) ? argv[++i] : "";
    };
    auto next_int = [&](int* out) -> bool {
      std::string v = next();
      try {
        *out = std::stoi(v);
        return true;
      } catch (...) {
        std::cerr << "bad integer for " << a << ": '" << v << "'\n";
        return false;
      }
    };
    if (a == "--dir") dir = next();
    else if (a == "--file") file = next();
    else if (a == "--max_path_length") {
      if (!next_int(&opts.max_path_length)) return 2;
    } else if (a == "--max_path_width") {
      if (!next_int(&opts.max_path_width)) return 2;
    } else if (a == "--num_threads") {
      if (!next_int(&num_threads)) return 2;
    } else if (a == "--max_leaves") {
      if (!next_int(&opts.max_leaves)) return 2;
    }
    else if (a == "--no_hash") no_hash = true;
    else if (a == "--help" || a == "-h") {
      std::cout << "usage: c2v_extract (--dir D | --file F) "
                   "[--max_path_length 8] [--max_path_width 2] "
                   "[--num_threads N] [--max_leaves 1000] [--no_hash]\n";
      return 0;
    } else {
      std::cerr << "unknown flag: " << a << "\n";
      return 2;
    }
  }
  opts.hash_paths = !no_hash;

  if (!file.empty()) {
    std::error_code ec;
    if (!fs::is_regular_file(file, ec)) {
      std::cerr << "cannot read file: " << file << "\n";
      return 2;
    }
    std::cout << ProcessSource(ReadFile(file), opts);
    return 0;
  }
  if (dir.empty()) {
    std::cerr << "need --dir or --file\n";
    return 2;
  }

  std::vector<std::string> files;
  std::error_code ec;
  for (auto it = fs::recursive_directory_iterator(
           dir, fs::directory_options::skip_permission_denied, ec);
       it != fs::recursive_directory_iterator(); it.increment(ec)) {
    if (ec) break;
    if (it->is_regular_file(ec) && it->path().extension() == ".java")
      files.push_back(it->path().string());
  }

  // thread pool over files (reference: --num_threads 64 in preprocess.sh)
  std::atomic<size_t> next_idx{0};
  std::mutex out_mu;
  if (num_threads < 1) num_threads = 1;
  std::vector<std::thread> workers;
  for (int t = 0; t < num_threads; ++t) {
    workers.emplace_back([&]() {
      for (;;) {
        size_t i = next_idx.fetch_add(1);
        if (i >= files.size()) return;
        std::string out = ProcessSource(ReadFile(files[i]), opts);
        if (!out.empty()) {
          std::lock_guard<std::mutex> lock(out_mu);
          std::cout << out;
        }
      }
    });
  }
  for (auto& w : workers) w.join();
  return 0;
}
