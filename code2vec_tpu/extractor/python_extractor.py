"""Python AST path-context extractor — the python150k frontend
(SURVEY.md §8.3 step 8: "swap JavaExtractor -> Python AST extractor";
CPython `ast` in-process is acceptable since Python parsing is native to
the host — this asymmetry vs. the C++ Java extractor is deliberate).

Same output contract as the Java extractor (SURVEY.md §3.2): one line per
function, `name tok,pathHash,tok ...`, path hashed with Java
String.hashCode semantics so both frontends share preprocessing and
vocabulary code.
"""

from __future__ import annotations

import ast as pyast
from typing import List, Optional, Tuple

from code2vec_tpu.common import split_to_subtokens


def _normalize(name: str) -> str:
    return "|".join(split_to_subtokens(name)) or name.lower()


def java_string_hash(s: str) -> int:
    h = 0
    for b in s.encode("utf-8"):
        h = (h * 31 + b) & 0xFFFFFFFF
    return h - 0x100000000 if h >= 0x80000000 else h


class _Node:
    __slots__ = ("type", "leaf", "parent", "child_index", "children")

    def __init__(self, type_: str, parent: int, leaf: str = ""):
        self.type = type_
        self.leaf = leaf
        self.parent = parent
        self.child_index = 0
        self.children: List[int] = []


class _TreeBuilder:
    """Flatten a CPython ast into the same arena shape the C++ side uses.
    Leaves: identifiers (Name/arg/attr/keyword names), constants, and
    function names (replaced by METHOD_NAME inside their own subtree)."""

    def __init__(self) -> None:
        self.nodes: List[_Node] = []

    def add(self, type_: str, parent: int, leaf: str = "") -> int:
        nid = len(self.nodes)
        self.nodes.append(_Node(type_, parent, leaf))
        if parent >= 0:
            self.nodes[parent].children.append(nid)
            self.nodes[nid].child_index = \
                len(self.nodes[parent].children) - 1
        return nid

    def build(self, node: pyast.AST, parent: int) -> int:
        type_name = type(node).__name__
        # operator nodes fold into the parent type like the Java side's
        # BinaryExpr:PLUS
        if isinstance(node, pyast.BinOp):
            nid = self.add(f"BinOp:{type(node.op).__name__}", parent)
            self.build(node.left, nid)
            self.build(node.right, nid)
            return nid
        if isinstance(node, pyast.BoolOp):
            nid = self.add(f"BoolOp:{type(node.op).__name__}", parent)
            for v in node.values:
                self.build(v, nid)
            return nid
        if isinstance(node, pyast.UnaryOp):
            nid = self.add(f"UnaryOp:{type(node.op).__name__}", parent)
            self.build(node.operand, nid)
            return nid
        if isinstance(node, pyast.Compare):
            ops = "|".join(type(o).__name__ for o in node.ops)
            nid = self.add(f"Compare:{ops}", parent)
            self.build(node.left, nid)
            for c in node.comparators:
                self.build(c, nid)
            return nid
        if isinstance(node, pyast.Name):
            return self.add("Name", parent, node.id)
        if isinstance(node, pyast.arg):
            return self.add("arg", parent, node.arg)
        if isinstance(node, pyast.Constant):
            v = node.value
            if isinstance(v, str):
                leaf = v if v else "STR"
            elif v is None or isinstance(v, bool):
                leaf = str(v)
            else:
                leaf = str(v)
            return self.add(f"Constant:{type(v).__name__}", parent, leaf)
        if isinstance(node, pyast.Attribute):
            nid = self.add("Attribute", parent)
            self.build(node.value, nid)
            self.add("attr", nid, node.attr)
            return nid
        if isinstance(node, pyast.keyword):
            nid = self.add("keyword", parent)
            if node.arg:
                self.add("kwname", nid, node.arg)
            self.build(node.value, nid)
            return nid
        if isinstance(node, (pyast.FunctionDef, pyast.AsyncFunctionDef)):
            nid = self.add("FunctionDef", parent)
            self.add("name", nid, node.name)
            self.build(node.args, nid)
            for s in node.body:
                self.build(s, nid)
            # decorators/returns annotation excluded (label-adjacent noise)
            return nid
        # generic: recurse over child AST nodes in field order
        nid = self.add(type_name, parent)
        for _field, value in pyast.iter_fields(node):
            if isinstance(value, pyast.AST):
                self.build(value, nid)
            elif isinstance(value, list):
                for item in value:
                    if isinstance(item, pyast.AST):
                        self.build(item, nid)
        return nid


def _enumerate_paths(nodes: List[_Node], func_id: int, max_len: int,
                     max_width: int, max_leaves: int,
                     hash_paths: bool = True) -> Optional[Tuple[str, List[str]]]:
    func = nodes[func_id]
    name_leaf = next((c for c in func.children
                      if nodes[c].type == "name"), -1)
    if name_leaf < 0:
        return None
    target = _normalize(nodes[name_leaf].leaf)

    leaves: List[int] = []
    depths: List[int] = []

    def collect(nid: int, depth: int) -> None:
        if len(leaves) >= max_leaves:
            return
        n = nodes[nid]
        if not n.children and n.leaf:
            leaves.append(nid)
            depths.append(depth)
            return
        for c in n.children:
            collect(c, depth + 1)

    collect(func_id, 0)

    def token_of(nid: int) -> str:
        if nid == name_leaf:
            return "METHOD_NAME"
        n = nodes[nid]
        if n.type.startswith("Constant:"):
            kind = n.type.split(":", 1)[1]
            if kind in ("int", "float"):
                return n.leaf.lower()
            norm = _normalize(n.leaf)
            return norm or ("STR" if kind == "str" else "CONST")
        return _normalize(n.leaf) or "TOKEN"

    contexts: List[str] = []
    L = len(leaves)
    for i in range(L):
        for j in range(i + 1, L):
            a, b = leaves[i], leaves[j]
            da, db = depths[i], depths[j]
            ua, ub, up_a, up_b = a, b, 0, 0
            while da > db:
                ua = nodes[ua].parent
                da -= 1
                up_a += 1
            while db > da:
                ub = nodes[ub].parent
                db -= 1
                up_b += 1
            while ua != ub and ua >= 0 and ub >= 0:
                ua = nodes[ua].parent
                ub = nodes[ub].parent
                up_a += 1
                up_b += 1
            if ua < 0 or ua != ub:
                continue
            if up_a + up_b > max_len:
                continue
            ca, cb = a, b
            for _ in range(up_a - 1):
                ca = nodes[ca].parent
            for _ in range(up_b - 1):
                cb = nodes[cb].parent
            if up_a and up_b:
                width = abs(nodes[cb].child_index - nodes[ca].child_index)
                if width > max_width:
                    continue
            parts = []
            cur = a
            for _ in range(up_a):
                parts.append(nodes[cur].type)
                parts.append("^")
                cur = nodes[cur].parent
            parts.append(nodes[cur].type)
            down = []
            cur = b
            for _ in range(up_b):
                down.append(nodes[cur].type)
                cur = nodes[cur].parent
            for t in reversed(down):
                parts.append("_")
                parts.append(t)
            path = "".join(parts)
            pr = str(java_string_hash(path)) if hash_paths else path
            contexts.append(f"{token_of(a)},{pr},{token_of(b)}")
    if not contexts:
        return None
    return target, contexts


def extract_source(source: str, max_path_length: int = 8,
                   max_path_width: int = 2, max_leaves: int = 1000,
                   hash_paths: bool = True) -> List[str]:
    """Python source text -> extractor output lines."""
    try:
        tree = pyast.parse(source)
    except SyntaxError:
        return []
    tb = _TreeBuilder()
    tb.build(tree, -1)
    func_ids = [i for i, n in enumerate(tb.nodes)
                if n.type == "FunctionDef"]
    out = []
    for fid in func_ids:
        res = _enumerate_paths(tb.nodes, fid, max_path_length,
                               max_path_width, max_leaves, hash_paths)
        if res is not None:
            name, contexts = res
            out.append(name + " " + " ".join(contexts))
    return out


def extract_file(path: str, max_path_length: int = 8,
                 max_path_width: int = 2) -> List[str]:
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        return extract_source(f.read(), max_path_length, max_path_width)
