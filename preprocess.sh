#!/usr/bin/env bash
# Dataset build driver — reference-compatible (SURVEY.md §4.1):
# extractor over train/val/test dirs (shuf on train), then preprocessing,
# then binary shard int-ization for the TPU fast path.
set -euo pipefail

TRAIN_DIR=${TRAIN_DIR:-dataset/train}
VAL_DIR=${VAL_DIR:-dataset/val}
TEST_DIR=${TEST_DIR:-dataset/test}
DATASET_NAME=${DATASET_NAME:-java-small}
OUT_DIR=${OUT_DIR:-data/${DATASET_NAME}}
MAX_CONTEXTS=${MAX_CONTEXTS:-200}
WORD_VOCAB_SIZE=${WORD_VOCAB_SIZE:-1301136}
PATH_VOCAB_SIZE=${PATH_VOCAB_SIZE:-911417}
TARGET_VOCAB_SIZE=${TARGET_VOCAB_SIZE:-261245}
NUM_THREADS=${NUM_THREADS:-64}
MAX_PATH_LENGTH=${MAX_PATH_LENGTH:-8}
MAX_PATH_WIDTH=${MAX_PATH_WIDTH:-2}
EXTRACTOR=${EXTRACTOR:-code2vec_tpu/extractor/build/c2v_extract}

if [[ ! -x "${EXTRACTOR}" ]]; then
  echo "extractor not built; running ./build_extractor.sh" >&2
  ./build_extractor.sh
fi

mkdir -p "${OUT_DIR}"

extract() {
  "${EXTRACTOR}" --dir "$1" --max_path_length "${MAX_PATH_LENGTH}" \
    --max_path_width "${MAX_PATH_WIDTH}" --num_threads "${NUM_THREADS}"
}

echo "extracting ${TRAIN_DIR} ..." >&2
extract "${TRAIN_DIR}" | shuf > "${OUT_DIR}/${DATASET_NAME}.train.raw.txt"
echo "extracting ${VAL_DIR} ..." >&2
extract "${VAL_DIR}" > "${OUT_DIR}/${DATASET_NAME}.val.raw.txt"
echo "extracting ${TEST_DIR} ..." >&2
extract "${TEST_DIR}" > "${OUT_DIR}/${DATASET_NAME}.test.raw.txt"

python3 -m code2vec_tpu.data.preprocess \
  --train_data "${OUT_DIR}/${DATASET_NAME}.train.raw.txt" \
  --val_data "${OUT_DIR}/${DATASET_NAME}.val.raw.txt" \
  --test_data "${OUT_DIR}/${DATASET_NAME}.test.raw.txt" \
  --max_contexts "${MAX_CONTEXTS}" \
  --word_vocab_size "${WORD_VOCAB_SIZE}" \
  --path_vocab_size "${PATH_VOCAB_SIZE}" \
  --target_vocab_size "${TARGET_VOCAB_SIZE}" \
  --output_name "${OUT_DIR}/${DATASET_NAME}"

python3 -m code2vec_tpu.data.binarize --data "${OUT_DIR}/${DATASET_NAME}" \
  --max_contexts "${MAX_CONTEXTS}" \
  --word_vocab_size "${WORD_VOCAB_SIZE}" \
  --path_vocab_size "${PATH_VOCAB_SIZE}" \
  --target_vocab_size "${TARGET_VOCAB_SIZE}"

rm -f "${OUT_DIR}/${DATASET_NAME}".{train,val,test}.raw.txt
echo "dataset ready under ${OUT_DIR}/" >&2
