#!/usr/bin/env python3
"""Entry point — the reference CLI surface, unchanged (SURVEY.md §2 L6):

  python3 code2vec.py --data <prefix> --test <file> --save/--load <ckpt>
      [--predict] [--release] [--export_code_vectors]
      [--save_w2v <p>] [--save_t2v <p>] [--framework jax] [--backend tpu]

Dispatch order mirrors the reference `code2vec.py.__main__`: train if
--data, release if --release, w2v/t2v export if requested, predict REPL if
--predict, else evaluate if --test.
"""

import sys

from code2vec_tpu.config import Config
from code2vec_tpu.parallel.distributed import maybe_initialize
from code2vec_tpu.vocab.vocabularies import VocabType


def main() -> int:
    try:
        config = Config.load_from_args()
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    # Deterministic fault injection (ISSUE 10): arm the registry BEFORE
    # anything builds — sites fetch their handles at setup time, and
    # dist/init below is itself a site.
    if config.FAULTS:
        from code2vec_tpu.resilience import faults
        try:
            faults.install(config.FAULTS, log=config.log)
        except ValueError as e:
            print(f"error: --faults: {e}", file=sys.stderr)
            return 2
    # Multi-host jobs must initialize the distributed runtime before the
    # first backend touch; single-host runs detect nothing and continue.
    maybe_initialize(config.DIST_COORDINATOR, config.DIST_NUM_PROCESSES,
                     config.DIST_PROCESS_ID, log=config.log)
    # Preemption recovery: with --auto_resume, an existing checkpoint in
    # --save turns this run into a resume of itself — the SAME command
    # line continues after a pod restart instead of training from
    # scratch. This takes precedence over --load (a fine-tune's base
    # checkpoint): after a preemption the run's OWN progress in --save
    # is the thing to restore; --load applies only on the first run.
    if config.AUTO_RESUME and config.is_saving and config.is_training:
        from code2vec_tpu.training.checkpoint import latest_step
        step = latest_step(config.save_path)
        if step is not None:
            if config.is_loading and config.load_path != config.save_path:
                config.log(
                    f"--auto_resume: --save has checkpoint step {step}; "
                    f"resuming from it INSTEAD of --load "
                    f"{config.load_path}")
            else:
                config.log(f"--auto_resume: found checkpoint step "
                           f"{step} in {config.save_path}; resuming")
            config.load_path = config.save_path
    # A checkpoint knows which head trained it; adopt (or cross-check)
    # the manifest so `--load <vm_ckpt>` works without re-passing --head.
    if config.is_loading:
        import json
        import os
        mpath = os.path.join(config.load_path, "manifest.json")
        if os.path.exists(mpath):
            with open(mpath) as f:
                manifest = json.load(f)
            ckpt_head = manifest.get("head", "code2vec")
            if config.HEAD_EXPLICIT and ckpt_head != config.HEAD:
                print(f"error: checkpoint was trained with --head "
                      f"{ckpt_head}, but --head {config.HEAD} was given",
                      file=sys.stderr)
                return 2
            config.HEAD = ckpt_head
            # tables_dtype gates surfaces the same way head does
            # (--attack on an int8 checkpoint must fail the verify
            # below, not crash in the attack's table matvec)
            config.TABLES_DTYPE = manifest.get("tables_dtype",
                                               config.TABLES_DTYPE)
    # Config.verify() ran before the manifest could set HEAD or the
    # dims set TABLES_DTYPE; re-run it now that the effective values are
    # known — varmisuse checkpoints must reject the code2vec-only
    # surfaces (--predict/--release/--attack/--save_w2v/--save_t2v/
    # --export_code_vectors) and int8 checkpoints must reject --attack
    # with a clean error, not a downstream crash.
    try:
        config.verify()
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    from code2vec_tpu.serving.interactive_predict import InteractivePredictor
    if config.HEAD == "varmisuse":
        from code2vec_tpu.models.vm_model import VarMisuseModel
        model = VarMisuseModel(config)
    else:
        from code2vec_tpu.models.jax_model import Code2VecModel
        model = Code2VecModel(config)
    config.log(f"model loaded: framework=jax backend={config.BACKEND}")

    if config.release:
        model.release()
        return 0

    if config.ATTACK:
        # Adversarial attack on --attack_input's source (the noamyft
        # fork delta; attacks/source_attack.py). The printed outcome is
        # the model's prediction on the REWRITTEN source, re-extracted.
        from code2vec_tpu.attacks.source_attack import (
            SourceAttack, normalize_target_name)
        target = normalize_target_name(config.ATTACK_TARGET)
        attack = SourceAttack(config, model,
                              top_k_candidates=config.ATTACK_TOPK,
                              max_iters=config.ATTACK_ITERS)
        try:
            result = attack.attack_file(
                config.ATTACK_INPUT,
                method_index=config.ATTACK_METHOD_INDEX,
                targeted=config.ATTACK == "targeted",
                target_name=target,
                max_renames=config.ATTACK_MAX_RENAMES,
                deadcode=config.ATTACK_DEADCODE)
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        print(str(result))
        # only a VERIFIED success earns the .adversarial artifact —
        # scripts treat the file's existence as the success signal
        if result.adversarial_source is not None and \
                result.verified_success:
            dest = config.ATTACK_INPUT + ".adversarial"
            with open(dest, "w", encoding="utf-8") as f:
                f.write(result.adversarial_source)
            config.log(f"adversarial source -> {dest}")
        return 0

    if config.is_training:
        model.train()

    if config.save_w2v:
        model.save_word2vec_format(config.save_w2v, VocabType.Token)
        config.log(f"token embeddings (w2v format) -> {config.save_w2v}")
    if config.save_t2v:
        model.save_word2vec_format(config.save_t2v, VocabType.Target)
        config.log(f"target embeddings (w2v format) -> {config.save_t2v}")

    if config.is_predict:
        InteractivePredictor(config, model).predict()
    elif config.is_testing and not config.is_training:
        results = model.evaluate()
        print(str(results))
        if config.export_code_vectors:
            dest = config.test_data_path + ".vectors"
            model.export_code_vectors_file(config.test_data_path, dest)
            config.log(f"code vectors -> {dest}")

    model.close_session()
    return 0


if __name__ == "__main__":
    sys.exit(main())
