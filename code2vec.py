#!/usr/bin/env python3
"""Entry point — the reference CLI surface, unchanged (SURVEY.md §2 L6):

  python3 code2vec.py --data <prefix> --test <file> --save/--load <ckpt>
      [--predict] [--release] [--export_code_vectors]
      [--save_w2v <p>] [--save_t2v <p>] [--framework jax] [--backend tpu]

Dispatch order mirrors the reference `code2vec.py.__main__`: train if
--data, release if --release, w2v/t2v export if requested, predict REPL if
--predict, else evaluate if --test.
"""

import sys

from code2vec_tpu.config import Config
from code2vec_tpu.parallel.distributed import maybe_initialize
from code2vec_tpu.vocab.vocabularies import VocabType


def main() -> int:
    try:
        config = Config.load_from_args()
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    # Multi-host jobs must initialize the distributed runtime before the
    # first backend touch; single-host runs detect nothing and continue.
    maybe_initialize(config.DIST_COORDINATOR, config.DIST_NUM_PROCESSES,
                     config.DIST_PROCESS_ID, log=config.log)
    from code2vec_tpu.models.jax_model import Code2VecModel
    from code2vec_tpu.serving.interactive_predict import InteractivePredictor
    model = Code2VecModel(config)
    config.log(f"model loaded: framework=jax backend={config.BACKEND}")

    if config.release:
        model.release()
        return 0

    if config.is_training:
        model.train()

    if config.save_w2v:
        model.save_word2vec_format(config.save_w2v, VocabType.Token)
        config.log(f"token embeddings (w2v format) -> {config.save_w2v}")
    if config.save_t2v:
        model.save_word2vec_format(config.save_t2v, VocabType.Target)
        config.log(f"target embeddings (w2v format) -> {config.save_t2v}")

    if config.is_predict:
        InteractivePredictor(config, model).predict()
    elif config.is_testing and not config.is_training:
        results = model.evaluate()
        print(str(results))
        if config.export_code_vectors:
            dest = config.test_data_path + ".vectors"
            model.export_code_vectors_file(config.test_data_path, dest)
            config.log(f"code vectors -> {dest}")

    model.close_session()
    return 0


if __name__ == "__main__":
    sys.exit(main())
