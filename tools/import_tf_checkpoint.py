#!/usr/bin/env python3
"""Import a reference TensorFlow checkpoint into this framework.

The reference ships pretrained TF models — `tf.train.Saver` checkpoints
of exactly five trainable variables (SURVEY.md §3 `tensorflow_model.py`
row): WORDS_VOCAB [Vt,E], PATHS_VOCAB [Vp,E], TARGET_WORDS_VOCAB
[Vy,3E], TRANSFORM [3E,3E], ATTENTION [3E,1]. This tool maps them onto
this framework's param pytree and writes a loadable RELEASED checkpoint
(inference-ready, fresh optimizer state on resume) plus the vocab
sidecar, so a reference user's trained model transfers without
retraining:

  python tools/import_tf_checkpoint.py \
      --tf_checkpoint <ckpt_prefix_or_dir> --dict <data.dict.c2v> \
      --save <out_ckpt_dir> [--max_contexts 200] \
      [--word_vocab_size N] [--path_vocab_size N] [--target_vocab_size N]

Then: python code2vec.py --load <out_ckpt_dir> --predict   (etc.)

Caveats, stated rather than hidden (SURVEY.md §0: the reference mount
was empty, so exact variable scopes are [M] confidence): variables are
located by NAME SUBSTRING, tolerant of scope prefixes; every mapped
array is shape-checked against the vocab sizes derived from --dict, and
a mismatch is a loud error naming both shapes — run with the same vocab
size flags the model was trained with.

ROW-ORDER ASSUMPTION (shape checks cannot catch this): embedding row i
of each imported table is taken to mean the word that
`Vocab.create_from_freq_dict` assigns index i — special rows first
(PAD=0, OOV=1), then count-descending with stable ties, built from the
SAME --dict file the reference model was trained with. That matches the
reference's vocab construction as surveyed [M], but a reference fork
with a different special-row layout or tie order would import cleanly
with every row silently misaligned. That is why --verify_test exists:
pass any .c2v file with ground-truth labels drawn from the model's
training distribution and the importer re-predicts it with the imported
weights — a row misalignment collapses top-1 to ~0, so a sane score is
positive evidence the ordering assumption held.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# substring -> param key, in MOST-SPECIFIC-FIRST order: WORDS_VOCAB is
# a substring of TARGET_WORDS_VOCAB, so the target table must match
# before the token table is considered
_VAR_MAP = (
    ("TARGET_WORDS_VOCAB", "target_emb"),
    ("PATHS_VOCAB", "path_emb"),
    ("WORDS_VOCAB", "token_emb"),
    ("TRANSFORM", "transform"),
    ("ATTENTION", "attention"),
)


def locate_variables(reader) -> dict:
    """checkpoint variable name -> param key, by substring match."""
    names = list(reader.get_variable_to_shape_map())
    mapping = {}
    for sub, key in _VAR_MAP:
        hits = [n for n in names
                if sub in n and n not in mapping
                # Adam slot variables shadow the weights
                and not n.endswith(("/Adam", "/Adam_1"))]
        if not hits:
            raise SystemExit(
                f"error: no checkpoint variable matches '{sub}' "
                f"(have: {sorted(names)[:10]}...)")
        if len(hits) > 1:
            raise SystemExit(
                f"error: ambiguous match for '{sub}': {hits}")
        mapping[hits[0]] = key
    return mapping


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--tf_checkpoint", required=True,
                    help="TF checkpoint prefix (or its directory)")
    ap.add_argument("--dict", dest="dict_path", required=True,
                    help="the dataset's .dict.c2v (reference releases "
                         "ship it next to the model)")
    ap.add_argument("--save", required=True, help="output checkpoint dir")
    ap.add_argument("--max_contexts", type=int, default=200)
    ap.add_argument("--word_vocab_size", type=int, default=1_301_136)
    ap.add_argument("--path_vocab_size", type=int, default=911_417)
    ap.add_argument("--target_vocab_size", type=int, default=261_245)
    ap.add_argument("--verify_test", default=None,
                    help="a .c2v file with true labels; after import, "
                         "re-predict up to --verify_rows of it with the "
                         "imported weights and print top-k/F1 — the "
                         "semantic check for the row-order assumption "
                         "(see module docstring)")
    ap.add_argument("--verify_rows", type=int, default=256)
    a = ap.parse_args()

    import numpy as np
    import tensorflow as tf

    from code2vec_tpu.models.encoder import ModelDims
    from code2vec_tpu.training import checkpoint as ckpt
    from code2vec_tpu.vocab.vocabularies import Code2VecVocabs

    vocabs = Code2VecVocabs.load_from_dict_file(
        a.dict_path, a.word_vocab_size, a.path_vocab_size,
        a.target_vocab_size)

    path = a.tf_checkpoint
    if os.path.isdir(path):
        found = tf.train.latest_checkpoint(path)
        if found is None:
            raise SystemExit(f"error: no TF checkpoint under {path}")
        path = found
    reader = tf.train.load_checkpoint(path)
    mapping = locate_variables(reader)
    shapes = reader.get_variable_to_shape_map()

    # validate BEFORE materializing: a java-large checkpoint is >1.5 GB
    # and a vocab-size mismatch should fail in milliseconds, not after
    # reading every table
    by_key = {key: var for var, key in mapping.items()}
    E = shapes[by_key["token_emb"]][1]
    dims = ModelDims(
        token_vocab_size=vocabs.token_vocab.size,
        path_vocab_size=vocabs.path_vocab.size,
        target_vocab_size=vocabs.target_vocab.size,
        embeddings_size=E, max_contexts=a.max_contexts,
        tables_dtype="float32")  # imported weights stay exact
    expected = {
        "token_emb": [dims.token_vocab_size, E],
        "path_emb": [dims.path_vocab_size, E],
        "target_emb": [dims.target_vocab_size, 3 * E],
        "transform": [3 * E, 3 * E],
        # the reference stores ATTENTION as [3E, 1]; squeezed on load
        "attention": [3 * E, 1],
    }
    for key, shape in expected.items():
        got = list(shapes[by_key[key]])
        if got != shape and not (key == "attention"
                                 and got == shape[:1]):
            raise SystemExit(
                f"error: {by_key[key]} shape {got} does not match "
                f"{shape} derived from --dict and the vocab size "
                f"flags — re-run with the vocab sizes the reference "
                f"model was trained with (its training logs / "
                f"preprocess.sh record them)")

    params = {}
    for var_name, key in mapping.items():
        arr = np.asarray(reader.get_tensor(var_name), np.float32)
        if key == "attention" and arr.ndim == 2:
            arr = arr[:, 0]
        params[key] = arr
        print(f"  {var_name} {list(arr.shape)} -> {key}")

    os.makedirs(a.save, exist_ok=True)
    # a released checkpoint stores {"params"} ONLY (the loader restores
    # against that exact template and re-inits optimizer state) — match
    # release_checkpoint's structure, not the full training state
    state = {"params": params}
    ckpt.save_checkpoint(
        a.save, state, 0, vocabs, dims,
        extra_manifest={
            "released": True,
            "use_sampled_softmax": False,
            "sparse_embedding_updates": False,
            "embedding_optimizer": "adam",
            "lr_schedule": "constant",
            "imported_from": os.path.abspath(path),
        }, max_to_keep=1)
    print(f"imported TF checkpoint -> {a.save} (released; "
          f"`python code2vec.py --load {a.save} --predict` to serve)")

    if a.verify_test:
        import tempfile

        from code2vec_tpu.config import Config
        from code2vec_tpu.models.jax_model import Code2VecModel

        with open(a.verify_test, encoding="utf-8") as f:
            lines = [ln for _, ln in zip(range(a.verify_rows), f)
                     if ln.strip()]
        if not lines:
            raise SystemExit(
                f"error: --verify_test {a.verify_test} has no rows "
                "(the import above succeeded; re-run the check with a "
                "non-empty .c2v file)")
        with tempfile.NamedTemporaryFile(
                "w", suffix=".c2v", delete=False) as tmp:
            tmp.writelines(lines)
            sample = tmp.name
        try:
            cfg = Config(MAX_CONTEXTS=a.max_contexts,
                         TEST_BATCH_SIZE=min(256, len(lines)))
            cfg.load_path = a.save
            cfg.test_data_path = sample
            res = Code2VecModel(cfg).evaluate()
            print(f"verify_test ({len(lines)} rows): "
                  f"top1 {res.topk_acc[0]:.4f}, "
                  f"subtoken F1 {res.subtoken_f1:.4f}")
            if res.topk_acc[0] < 0.01:
                print("WARNING: top-1 is ~0 — the imported rows are "
                      "likely MISALIGNED with the vocab (wrong --dict, "
                      "wrong vocab-size flags, or a fork with a "
                      "different vocab ordering). Do not serve this "
                      "checkpoint.")
        finally:
            os.unlink(sample)
    return 0


if __name__ == "__main__":
    sys.exit(main())
