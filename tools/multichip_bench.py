#!/usr/bin/env python3
"""Multi-process scaling-efficiency bench: the MULTICHIP_r*.json
producer (ROADMAP item 2).

BENCH_r*.json answers "how fast is one chip"; this driver answers "what
fraction of that speed survives the REAL process boundary". It runs the
same synthetic train harness twice over the SAME global device count
and global batch:

  baseline  1 process  x (procs * devices_per_proc) local CPU devices
  multi     `--procs` OS processes x `--devices_per_proc` devices each,
            joined via `jax.distributed.initialize` with Gloo
            collectives (tests/mp_worker.py's harness shape) — the
            code path a v4-32 pod slice runs, minus the ICI.

`scaling_efficiency` = multi global pc/s / baseline global pc/s: with
equal chips and equal math, anything below 1.0 is pure
distribution cost (Gloo gradient allreduce, per-process infeed,
coordination). Both legs run with the CPU collective knobs applied
(`parallel/compat.enable_cpu_collectives` — async dispatch off), and
the multi leg's workers are CPU-pinned to disjoint equal core groups
(`taskset`) so each emulated host owns its cores the way a pod host
owns its chips — without pinning every worker's XLA threadpool claims
ALL cores and the ratio measures N× scheduler oversubscription, not
distribution cost. See `_core_groups` / the compat docstring.

Usage (repo root):

  python tools/multichip_bench.py                      # dense DP step
  python tools/multichip_bench.py --sparse             # sparse tables
  python tools/multichip_bench.py --telemetry_dir /tmp/tele
      # per-process run dirs + the `telemetry_report.py --merge` table

Kill-mid-run leg (ISSUE 13, on by default; `--no_kill_leg` skips it):
after the scaling pairs, the driver runs the elastic-recovery half of
`tools/chaos.py kill_resize` — a real 2-process training cohort under
the shrink-policy supervisor, one peer SIGKILLed mid-epoch, the cohort
re-formed at 1 process — and records the recovery cost into the round
file: `recovery_steps_lost` (kill step minus the committed step the
re-formed cohort resumed from) and `recovery_seconds` (kill to first
post-resize training step). `bench_regression --kind multichip` gates
both as lower-is-better.

Writes `MULTICHIP_r<next>.json` into `--out` (default: repo root; the
seed rounds r01-r05 are the driver's failed-dryrun records — their
shape carries no metrics and `tools/bench_regression.py --kind
multichip` skips them) and prints the result JSON to stdout, bench.py
style. `--no_write` suppresses the file for ad-hoc runs.

The worker half of this file re-executes itself with `--worker`; the
parent owns spawn, timeout and orphan cleanup (no worker survives a
failed run — the same discipline tests/conftest.py asserts for the
test suite's subprocesses).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Synthetic harness defaults: a large global batch so a step is
# compute-bound (the efficiency number should measure the distribution
# cost against real work, not the dispatch floor) and small vocab
# tables so the dense-grad allreduce doesn't swamp the 2-core CI
# container the harness was calibrated on. Measured there (round 14):
# the per-step multi-leg overhead is roughly CONSTANT in the batch but
# grows with max_contexts (0.737 at B=1536, 0.785 at B=4096, 0.874 at
# B=8192, all C=64; doubling C at B=4096 doubled the overhead) — so
# the calibrated shape is large-batch/modest-C, which is also the
# direction of the real java-large per-chip load. The config is
# recorded in every MULTICHIP_r*.json, so the regression gate always
# compares like-for-like rounds.
DEF_BATCH = 8192
DEF_CONTEXTS = 64
DEF_STEPS = 10
DEF_WARMUP = 2
DEF_TOKEN_VOCAB = 2048
DEF_PATH_VOCAB = 2048
DEF_TARGET_VOCAB = 2048
DEF_EMBED = 128
DEF_NUM_SAMPLED = 512


def _percentile(vals, p):
    """Linear-interpolated percentile (numpy 'linear' rule). The
    nearest-rank shortcut is WRONG for this driver's 2-element
    per-process p50 lists: int(round(0.5)) banker's-rounds to 0, so
    'p50' would always elect the FASTER worker and bias the gated
    scaling_efficiency headline optimistic."""
    s = sorted(vals)
    if not s:
        return float("nan")
    x = (p / 100.0) * (len(s) - 1)
    lo = int(x)
    hi = min(lo + 1, len(s) - 1)
    return s[lo] + (s[hi] - s[lo]) * (x - lo)


# ---------------------------------------------------------------- worker

def _worker(args) -> None:
    """One process of a leg. The parent exported JAX_PLATFORMS/XLA_FLAGS
    via compat.cpu_worker_env BEFORE this interpreter started, so the
    device count is pinned at backend build."""
    sys.path.insert(0, _REPO)

    from code2vec_tpu.parallel.compat import disable_cpu_async_dispatch
    from code2vec_tpu.parallel.distributed import maybe_initialize

    if args.num_procs > 1:
        # maybe_initialize applies the collective knobs itself
        maybe_initialize(
            coordinator_address=f"127.0.0.1:{args.port}",
            num_processes=args.num_procs, process_id=args.proc_id)
    else:
        # baseline leg: same timing knob (async dispatch off) without
        # the distributed runtime, so the legs differ ONLY in topology
        # (Gloo itself can't be selected without a distributed client)
        disable_cpu_async_dispatch()

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from code2vec_tpu.models.encoder import ModelDims, init_params
    from code2vec_tpu.parallel.mesh import make_mesh
    from code2vec_tpu.parallel.sharding import (shard_batch,
                                                shard_opt_state,
                                                shard_params)
    from code2vec_tpu.training.steps import make_train_step

    assert jax.process_count() == args.num_procs, (
        jax.process_count(), args.num_procs)

    dims = ModelDims(token_vocab_size=args.token_vocab,
                     path_vocab_size=args.path_vocab,
                     target_vocab_size=args.target_vocab,
                     embeddings_size=args.embed,
                     max_contexts=args.max_contexts,
                     dropout_keep_rate=1.0)
    mesh = make_mesh(0, 1)  # pure data parallelism over every device
    B_global = args.batch
    B_local = B_global // args.num_procs

    params = init_params(jax.random.PRNGKey(0), dims)
    optimizer = optax.adam(1e-3)
    if args.sparse:
        from code2vec_tpu.training.sparse_steps import \
            init_sparse_opt_state
        opt_state = init_sparse_opt_state(params, optimizer, True)
    else:
        opt_state = optimizer.init(params)
    params = shard_params(mesh, params)
    opt_state = shard_opt_state(mesh, opt_state, params)

    step = make_train_step(
        dims, optimizer, use_sampled_softmax=True,
        num_sampled=args.num_sampled, compute_dtype=jnp.float32,
        mesh=mesh if args.sparse else None,
        sparse_updates=args.sparse, learning_rate=1e-3)

    def local_batch(seed: int):
        """This process's slice of a deterministic GLOBAL batch — every
        leg sees identical global data regardless of process count."""
        r = np.random.default_rng(seed)
        C = dims.max_contexts
        lo, hi = args.proc_id * B_local, (args.proc_id + 1) * B_local
        labels = r.integers(0, dims.target_vocab_size, (B_global,),
                            dtype=np.int32)
        src = r.integers(0, dims.token_vocab_size, (B_global, C),
                         dtype=np.int32)
        pth = r.integers(0, dims.path_vocab_size, (B_global, C),
                         dtype=np.int32)
        dst = r.integers(0, dims.token_vocab_size, (B_global, C),
                         dtype=np.int32)
        mask = (r.random((B_global, C)) > 0.3).astype(np.float32)
        mask[:, 0] = 1.0
        weights = np.ones((B_global,), dtype=np.float32)
        return tuple(a[lo:hi] for a in
                     (labels, src, pth, dst, mask, weights))

    n_rot = 4  # rotate distinct batches so no cross-step result reuse
    batches = [shard_batch(mesh, local_batch(s), process_local=True)
               for s in range(n_rot)]
    assert batches[0][0].shape[0] == B_global

    telemetry = None
    if args.telemetry_dir:
        from code2vec_tpu.obs.telemetry import Telemetry

        class _Cfg:  # manifest snapshot: the fields the report reads
            MAX_CONTEXTS = args.max_contexts
            BATCH_SIZE = args.batch
            SPARSE_EMBEDDING_UPDATES = bool(args.sparse)

        telemetry = Telemetry.create(args.telemetry_dir, config=_Cfg(),
                                     mesh=mesh,
                                     component="multichip_bench")

    # keys pre-split outside the timed loop (bench.py discipline: a
    # split is its own dispatch)
    total = args.warmup + args.steps
    keys = list(jax.random.split(jax.random.PRNGKey(11), total))

    step_ms = []
    loss = None
    for i in range(total):
        t0 = time.perf_counter()
        params, opt_state, loss = step(params, opt_state,
                                       batches[i % n_rot], keys[i])
        lf = float(loss)  # per-step hard sync: honest walls, every leg
        dt_ms = (time.perf_counter() - t0) * 1e3
        if i >= args.warmup:
            step_ms.append(dt_ms)
            if telemetry is not None:
                telemetry.event("step", step=i - args.warmup,
                                step_ms=dt_ms, infeed_wait_ms=0.0,
                                examples=B_local, loss=lf)

    run_dir = getattr(telemetry, "run_dir", None)
    if telemetry is not None:
        telemetry.close()

    total_s = sum(step_ms) / 1e3
    local_pc_s = (B_local * dims.max_contexts * len(step_ms)) / total_s
    out = {
        "proc_id": args.proc_id,
        "num_procs": args.num_procs,
        "steps": len(step_ms),
        "ms_per_step_p50": _percentile(step_ms, 50),
        "ms_per_step_p95": _percentile(step_ms, 95),
        "local_pc_per_sec": local_pc_s,
        "final_loss": float(loss),
        "run_dir": run_dir,
    }
    with open(os.path.join(args.out_dir,
                           f"proc{args.proc_id}.json"), "w",
              encoding="utf-8") as f:
        json.dump(out, f)


# ---------------------------------------------------------------- parent

def _core_groups(num_procs: int) -> list:
    """Partition this box's cores into `num_procs` contiguous groups —
    one per worker, like a pod host owns its own chips. Without
    pinning, every worker's XLA threadpool sizes itself to ALL cores,
    so an N-process leg runs N× oversubscribed against the 1-process
    baseline. (On the 2-core CI box the pinned and unpinned ratios
    measure the same — the multi leg there is bound by loopback-TCP
    allreduce latency, not thread thrash — but on wider hosts the
    oversubscription term grows with the core count, so the harness
    always pins.) Returns [] when pinning can't be done fairly (fewer
    cores than workers, or no taskset)."""
    ncores = os.cpu_count() or 1
    if num_procs <= 1 or ncores < num_procs:
        return []
    import shutil
    if not shutil.which("taskset"):
        return []
    per = ncores // num_procs
    # leftover cores go unused on the multi leg: equal shares keep the
    # workers symmetric (a straggler drags every collective)
    return [list(range(i * per, (i + 1) * per))
            for i in range(num_procs)]


def _spawn_leg(num_procs: int, devices_per_proc: int, leg_dir: str,
               forward: list, telemetry_dir: str | None,
               timeout_s: float) -> dict:
    """Run one leg (1 or N processes), aggregate the per-process
    results. Kills every worker on any failure — no orphans."""
    sys.path.insert(0, _REPO)
    from code2vec_tpu.parallel.compat import cpu_worker_env, free_port

    os.makedirs(leg_dir, exist_ok=True)
    n_devices = num_procs * devices_per_proc if num_procs > 1 \
        else devices_per_proc
    port = free_port() if num_procs > 1 else 0
    env = cpu_worker_env(n_devices if num_procs == 1
                         else devices_per_proc)
    groups = _core_groups(num_procs)
    procs = []
    for pid in range(num_procs):
        pin = ["taskset", "-c",
               ",".join(str(c) for c in groups[pid])] if groups else []
        cmd = pin + [sys.executable, os.path.abspath(__file__),
                     "--worker",
                     "--proc_id", str(pid), "--num_procs",
                     str(num_procs),
                     "--port", str(port), "--out_dir", leg_dir] + forward
        if telemetry_dir:
            cmd += ["--telemetry_dir",
                    os.path.join(telemetry_dir, f"leg{num_procs}")]
        procs.append(subprocess.Popen(
            cmd, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True, cwd=_REPO))
    outs = []
    try:
        for p in procs:
            outs.append(p.communicate(timeout=timeout_s)[0])
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    for pid, (p, out) in enumerate(zip(procs, outs)):
        if p.returncode != 0:
            raise RuntimeError(
                f"worker {pid} of {num_procs}-process leg "
                f"failed (rc {p.returncode}):\n{out}")
    per_proc = []
    for pid in range(num_procs):
        with open(os.path.join(leg_dir, f"proc{pid}.json"),
                  encoding="utf-8") as f:
            per_proc.append(json.load(f))
    all_p50 = [r["ms_per_step_p50"] for r in per_proc]
    return {
        "n_processes": num_procs,
        "n_devices": n_devices,
        "pc_per_sec": sum(r["local_pc_per_sec"] for r in per_proc),
        "ms_per_step_p50": _percentile(all_p50, 50),
        "final_loss": per_proc[0]["final_loss"],
        "cpu_pinned": bool(groups),
        "per_process": per_proc,
    }


def next_round(out_dir: str) -> int:
    rounds = [0]
    for path in glob.glob(os.path.join(out_dir, "MULTICHIP_r*.json")):
        m = re.search(r"MULTICHIP_r(\d+)\.json$",
                      os.path.basename(path))
        if m:
            rounds.append(int(m.group(1)))
    return max(rounds) + 1


def build_result(base: dict, multi: dict, args_ns) -> dict:
    """The MULTICHIP result object. `scaling_efficiency` is the gated
    headline: multi-process throughput over the single-process
    same-chip-count baseline (equal chips, equal global batch — the
    ratio isolates pure distribution cost). It is computed from the
    MEDIAN step times: with equal global batch the throughput ratio is
    the inverse step-time ratio, and the median is robust to the
    transient multi-second Gloo hiccups the loopback TCP harness
    produces (the per-process p95 column keeps them visible;
    `scaling_efficiency_mean` is the mean-based ratio for
    comparison)."""
    eff = base["ms_per_step_p50"] / multi["ms_per_step_p50"] \
        if multi["ms_per_step_p50"] > 0 else float("nan")
    eff_mean = multi["pc_per_sec"] / base["pc_per_sec"] \
        if base["pc_per_sec"] > 0 else float("nan")
    # per-host step-time skew (ISSUE 17): worst member p50 over the
    # cohort median p50 — the offline twin of the fleet plane's live
    # `fleet/step_p50_skew`. 1.0 = perfectly even hosts; a straggler
    # inflates it and the lock-step all-reduce makes everyone pay, so
    # bench_regression gates it LOWER-is-better.
    member_p50 = [r["ms_per_step_p50"] for r in multi["per_process"]]
    med = _percentile(member_p50, 50)
    skew = max(member_p50) / med \
        if member_p50 and med > 0 else float("nan")
    return {
        "schema": "multichip",
        "sparse": bool(args_ns.sparse),
        "host_cores": os.cpu_count(),
        "cpu_pinned": bool(multi.get("cpu_pinned")),
        "n_processes": multi["n_processes"],
        "devices_per_process": args_ns.devices_per_proc,
        "n_devices": multi["n_devices"],
        "batch_global": args_ns.batch,
        "max_contexts": args_ns.max_contexts,
        "steps": args_ns.steps,
        "baseline_pc_per_sec": base["pc_per_sec"],
        "baseline_ms_per_step_p50": base["ms_per_step_p50"],
        "multi_pc_per_sec": multi["pc_per_sec"],
        "multi_ms_per_step_p50": multi["ms_per_step_p50"],
        "pc_per_sec_per_chip": multi["pc_per_sec"]
        / multi["n_devices"],
        "scaling_efficiency": eff,
        "scaling_efficiency_mean": eff_mean,
        "host_skew_ratio": skew,
        "loss_delta": abs(multi["final_loss"] - base["final_loss"]),
        "baseline": base,
        "multi": multi,
    }


def _add_harness_args(ap: argparse.ArgumentParser) -> None:
    ap.add_argument("--steps", type=int, default=DEF_STEPS)
    ap.add_argument("--warmup", type=int, default=DEF_WARMUP)
    ap.add_argument("--batch", type=int, default=DEF_BATCH)
    ap.add_argument("--max_contexts", type=int, default=DEF_CONTEXTS)
    ap.add_argument("--token_vocab", type=int, default=DEF_TOKEN_VOCAB)
    ap.add_argument("--path_vocab", type=int, default=DEF_PATH_VOCAB)
    ap.add_argument("--target_vocab", type=int,
                    default=DEF_TARGET_VOCAB)
    ap.add_argument("--embed", type=int, default=DEF_EMBED)
    ap.add_argument("--num_sampled", type=int, default=DEF_NUM_SAMPLED)
    ap.add_argument("--sparse", action="store_true",
                    help="sparse embedding updates (the round-14 mesh "
                         "path: dedup/segment-sum/live-row inside "
                         "shard_map — no dense [V, E] carrier)")
    ap.add_argument("--telemetry_dir", default=None)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="2-leg (1-process vs N-process Gloo) "
                    "scaling-efficiency bench; writes "
                    "MULTICHIP_r<next>.json")
    ap.add_argument("--worker", action="store_true",
                    help=argparse.SUPPRESS)
    ap.add_argument("--proc_id", type=int, default=0,
                    help=argparse.SUPPRESS)
    ap.add_argument("--num_procs", type=int, default=1,
                    help=argparse.SUPPRESS)
    ap.add_argument("--port", type=int, default=0,
                    help=argparse.SUPPRESS)
    ap.add_argument("--out_dir", default=None, help=argparse.SUPPRESS)
    ap.add_argument("--procs", type=int, default=2,
                    help="process count of the multi leg")
    ap.add_argument("--devices_per_proc", type=int, default=4)
    ap.add_argument("--out", default=_REPO,
                    help="where MULTICHIP_r<N>.json lands")
    ap.add_argument("--no_write", action="store_true",
                    help="print JSON only, write no round file")
    ap.add_argument("--timeout_s", type=float, default=900.0,
                    help="per-leg wall clock before workers are killed")
    ap.add_argument("--no_kill_leg", action="store_true",
                    help="skip the kill-mid-run recovery leg (the "
                         "elastic-resume cost measurement)")
    ap.add_argument("--reps", type=int, default=3,
                    help="baseline/multi leg pairs to run back-to-back;"
                         " the MEDIAN-ratio pair is reported (shared "
                         "boxes have minute-scale noise bursts — "
                         "adjacent pairing cancels them, the median "
                         "drops a burst that hits one pair)")
    _add_harness_args(ap)
    args = ap.parse_args(argv)

    if args.worker:
        _worker(args)
        return 0

    if args.batch % (args.procs * args.devices_per_proc):
        print(f"error: --batch {args.batch} must divide over "
              f"{args.procs} procs x {args.devices_per_proc} devices",
              file=sys.stderr)
        return 2

    forward = []
    for k in ("steps", "warmup", "batch", "max_contexts",
              "token_vocab", "path_vocab", "target_vocab", "embed",
              "num_sampled"):
        forward += [f"--{k}", str(getattr(args, k))]
    if args.sparse:
        forward.append("--sparse")

    # Gloo over loopback TCP intermittently dies mid-run with
    # `EnforceNotMet: op.preamble.length <= op.nbytes` (a transport
    # race the compat docstring documents; the crashed worker takes
    # its peer down with it). One rep's crash is transient infra, not
    # a measurement — retry the whole PAIR on a fresh port (each
    # attempt's _spawn_leg picks one) so the elected ratio never mixes
    # legs from different attempts. TimeoutExpired is the same failure
    # seen from the other side: the crashed worker's peer can sit
    # inside a collective until the (CPU-widened) heartbeat tolerance
    # expires, so the parent hits its communicate() wall first. The
    # retry itself is the shared resilience policy (ISSUE 10) — the
    # hand-rolled attempt loop this file used to carry is gone.
    sys.path.insert(0, _REPO)
    from code2vec_tpu.resilience import retry as retry_mod
    pair_retry = retry_mod.transient_distributed(
        "multichip-rep", base_delay_s=0.2,
        log=lambda m: print(m, file=sys.stderr))

    import tempfile
    pairs = []
    rep_retries = 0
    with tempfile.TemporaryDirectory(prefix="multichip_") as tmp:
        t0 = time.time()
        for rep in range(max(1, args.reps)):
            calls = {"n": 0}

            def run_pair():
                calls["n"] += 1
                tag = f"{rep}_{calls['n']}"
                base = _spawn_leg(
                    1, args.devices_per_proc * args.procs,
                    os.path.join(tmp, f"base{tag}"),
                    forward, args.telemetry_dir, args.timeout_s)
                multi = _spawn_leg(
                    args.procs, args.devices_per_proc,
                    os.path.join(tmp, f"multi{tag}"),
                    forward, args.telemetry_dir, args.timeout_s)
                return base, multi

            base, multi = pair_retry.call(run_pair)
            rep_retries += calls["n"] - 1
            pairs.append((base, multi))
            print(f"rep {rep}: base p50 "
                  f"{base['ms_per_step_p50']:.0f} ms, multi p50 "
                  f"{multi['ms_per_step_p50']:.0f} ms, ratio "
                  f"{base['ms_per_step_p50'] / multi['ms_per_step_p50']:.3f}",
                  file=sys.stderr)

        # kill-mid-run leg (ISSUE 13): the elastic-recovery cost of a
        # REAL training cohort losing a peer — reuses the run half of
        # tools/chaos.py kill_resize (shrink-policy supervisor, fault-
        # injected SIGKILL, re-form at N−1)
        kill_leg = None
        if not args.no_kill_leg:
            from tools import chaos as chaos_mod
            print("kill leg: 2-process cohort, SIGKILL one peer, "
                  "re-form at 1 ...", file=sys.stderr)
            kill_dir = os.path.join(tmp, "kill_leg")
            os.makedirs(kill_dir, exist_ok=True)
            kill_leg = chaos_mod.run_kill_resize(
                kill_dir, timeout_s=args.timeout_s)
            print(f"kill leg: resumed from step "
                  f"{kill_leg['resumed_from_step']}, steps lost "
                  f"{kill_leg['recovery_steps_lost']}, recovery "
                  f"{kill_leg['recovery_seconds']}s, resizes "
                  f"{kill_leg['resizes']}", file=sys.stderr)
        wall = time.time() - t0

    # elect the median-ratio pair: each pair's legs ran back-to-back,
    # so a slow-varying noise burst perturbs both legs of a pair and
    # cancels in its ratio; a burst spanning only one leg skews that
    # pair's ratio, and the median drops it
    ratios = [b["ms_per_step_p50"] / m["ms_per_step_p50"]
              for b, m in pairs]
    order = sorted(range(len(pairs)), key=lambda i: ratios[i])
    elected = order[(len(order) - 1) // 2]
    base, multi = pairs[elected]

    result = build_result(base, multi, args)
    result["bench_wall_s"] = wall
    result["rep_retries"] = rep_retries
    if kill_leg is not None:
        # the leg is a MEASUREMENT only when the injected kill really
        # fired after a committed checkpoint existed and the re-formed
        # cohort finished — a leg that lost every retry to the
        # loopback-Gloo startup race must not smuggle fabricated
        # numbers into the gated trajectory (they'd read as a phantom
        # regression now, then pad the MAD band against real ones)
        valid = bool(kill_leg["kill_fired"]
                     and kill_leg["supervisor_rc"] == 0
                     and kill_leg["resumed_from_step"] is not None)
        result["kill_leg"] = dict(
            {k: kill_leg[k] for k in
             ("kill_fired", "supervisor_rc", "restarts", "resizes",
              "full_relaunches", "cohort_size_final",
              "resumed_from_step", "kill_at_step")}, valid=valid)
        if valid:
            # gated headline metrics at top level (bench_regression
            # reads them flat, lower-is-better)
            result["recovery_steps_lost"] = \
                kill_leg["recovery_steps_lost"]
            result["recovery_seconds"] = kill_leg["recovery_seconds"]
        else:
            print("kill leg invalid after retries (transient infra); "
                  "recovery metrics NOT recorded this round",
                  file=sys.stderr)
    result["reps"] = [{"scaling_efficiency": r,
                       "baseline_ms_per_step_p50": b["ms_per_step_p50"],
                       "multi_ms_per_step_p50": m["ms_per_step_p50"],
                       "elected": i == elected}
                      for i, (r, (b, m)) in
                      enumerate(zip(ratios, pairs))]

    if args.telemetry_dir:
        # render the per-process runs as ONE logical multi-host run —
        # the telemetry_report --merge shape (obs_top renders the same
        # live via per-process --metrics_port scrapes)
        from tools.telemetry_report import render_merged
        run_dirs = [r["run_dir"] for r in multi["per_process"]
                    if r.get("run_dir")]
        if run_dirs:
            result["merged_report"] = render_merged(run_dirs)

    if not args.no_write:
        rnd = next_round(args.out)
        path = os.path.join(args.out, f"MULTICHIP_r{rnd:02d}.json")
        result["round"] = rnd
        with open(path, "w", encoding="utf-8") as f:
            json.dump(result, f, indent=1)
        print(f"wrote {path}", file=sys.stderr)

    print(json.dumps(result, indent=1))
    return 0


if __name__ == "__main__":
    sys.exit(main())
