#!/usr/bin/env python3
"""Summarize telemetry runs (code2vec_tpu/obs JSONL) into the
BASELINE.md table shape.

Usage:
  python tools/telemetry_report.py <telemetry_dir | run_dir> [run_dir...]

Given `--telemetry_dir`'s root (or one run directory), prints

  - one BASELINE.md-shaped headline table — a row per run with step
    events: config label, ms/step (p50), pc/s/chip (examples/sec x
    MAX_CONTEXTS over the instrumented wall: step + infeed wait),
    vs-V100 ratio (bench.py's denominator), infeed-wait p95, and the
    run_id as the Source column;
  - per-run detail tables: every timer histogram (count / mean /
    p50 / p95 / p99 / max), a phase-attribution table when the run
    sampled phases (--phase_profile: per-phase device ms joined with
    the analytic bytes gauges into GB/s and vs-ceiling utilization),
    serving request percentiles, final loss, gauges, an epoch-boundary
    table (save_blocked_ms / save_total_ms / eval_ms / save overlap
    ratio, from the save / save_committed / eval events), and any
    bench/profile events the run carried.

Pure stdlib + the repo's own modules; reads only the manifest + events
files, so it works on a laptop over a run dir scp'd from a pod.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

PCTS = (50, 95, 99)


def _v100_denominator() -> float:
    """bench.py's baseline denominator — imported lazily so this tool
    stays runnable on a machine without the repo's deps (bench pulls in
    numpy at module scope); the fallback is bench.py's pinned literal
    (BASELINE.md "Baseline denominator")."""
    try:
        from bench import V100_BASELINE_PATH_CONTEXTS_PER_SEC
        return V100_BASELINE_PATH_CONTEXTS_PER_SEC
    except Exception:
        return 1_940_000.0


def find_runs(path: str) -> List[str]:
    """`path` is one run dir (has manifest.json) or a telemetry root
    (run dirs one level down), newest first."""
    if os.path.exists(os.path.join(path, "manifest.json")):
        return [path]
    runs = [os.path.join(path, d)
            for d in sorted(os.listdir(path), reverse=True)
            if os.path.exists(os.path.join(path, d, "manifest.json"))]
    return runs


def load_run(run_dir: str):
    with open(os.path.join(run_dir, "manifest.json"),
              encoding="utf-8") as f:
        manifest = json.load(f)
    events: List[Dict[str, Any]] = []
    ev_path = os.path.join(run_dir, "events.jsonl")
    if os.path.exists(ev_path):
        with open(ev_path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if line:
                    events.append(json.loads(line))
    return manifest, events


def _pct(values: List[float], p: float) -> float:
    if not values:
        return float("nan")
    s = sorted(values)
    k = int(round(p / 100.0 * (len(s) - 1)))
    return s[max(0, min(len(s) - 1, k))]


def _config_label(manifest: Dict[str, Any]) -> str:
    cfg = manifest.get("config") or {}
    bits = [manifest.get("component", "run")]
    if cfg:
        bits.append(cfg.get("ENCODER_TYPE", "?"))
        bits.append(str(cfg.get("TABLES_DTYPE", "?")))
        bits.append(f"B={cfg.get('TRAIN_BATCH_SIZE', '?')}")
        bits.append(f"C={cfg.get('MAX_CONTEXTS', '?')}")
    mesh = manifest.get("mesh")
    if mesh:
        bits.append("mesh=" + "x".join(str(v) for v in mesh.values()))
    return " ".join(bits)


def summarize_steps(manifest: Dict[str, Any],
                    events: List[Dict[str, Any]]
                    ) -> Optional[Dict[str, Any]]:
    steps = [e for e in events if e.get("kind") == "step"]
    if not steps:
        return None
    step_ms = [float(e["step_ms"]) for e in steps if "step_ms" in e]
    wait_ms = [float(e.get("infeed_wait_ms", 0.0)) for e in steps]
    examples = sum(int(e.get("examples", 0)) for e in steps)
    total_s = (sum(step_ms) + sum(wait_ms)) / 1e3
    cfg = manifest.get("config") or {}
    max_contexts = int(cfg.get("MAX_CONTEXTS", 0) or 0)
    ex_s = examples / total_s if total_s > 0 else float("nan")
    pc_s = ex_s * max_contexts if max_contexts else float("nan")
    return {
        "n_steps": len(steps),
        "ms_per_step_p50": _pct(step_ms, 50),
        "step_ms": step_ms,
        "infeed_wait_ms": wait_ms,
        "examples": examples,
        "ex_per_sec": ex_s,
        "pc_per_sec": pc_s,
        "vs_v100": (pc_s / _v100_denominator()
                    if pc_s == pc_s else float("nan")),
        "final_loss": next((e.get("loss") for e in reversed(steps)
                            if "loss" in e), None),
    }


def _timer_rows(events: List[Dict[str, Any]]) -> Dict[str, Dict]:
    """Timer summaries: the close()-time `summary` event when present
    (it has every registry timer), else recomputed from raw events."""
    for e in reversed(events):
        if e.get("kind") == "summary" and e.get("timers"):
            return dict(e["timers"])
    # fallback: rebuild from per-event samples
    samples: Dict[str, List[float]] = {}
    for e in events:
        if e.get("kind") == "step":
            samples.setdefault("train/step_ms", []).append(
                float(e.get("step_ms", 0.0)))
            samples.setdefault("train/infeed_wait_ms", []).append(
                float(e.get("infeed_wait_ms", 0.0)))
        elif e.get("kind") == "request":
            samples.setdefault("serve/request_ms", []).append(
                float(e.get("request_ms", 0.0)))
        elif e.get("kind") == "profile" and "ms" in e:
            samples.setdefault(f"profile/{e.get('phase')}_ms",
                               []).append(float(e["ms"]))
    out = {}
    for name, vals in sorted(samples.items()):
        row = {"count": len(vals),
               "mean_ms": sum(vals) / len(vals),
               "max_ms": max(vals)}
        for p in PCTS:
            row[f"p{p}_ms"] = _pct(vals, p)
        out[name] = row
    return out


# canonical phase order: obs/phases.PHASE_ORDER plus the trailing
# fused_step timer (kept literal — this tool must stay runnable
# without the repo's deps; a test pins the copy equal)
_PHASE_ORDER = ("infeed_wait", "embed_gather", "concat_dense",
                "forward_pool", "backward", "table_apply",
                "backward_apply", "allreduce", "allreduce_exposed",
                "fused_step")


def phase_rows(events: List[Dict[str, Any]],
               gauges: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Per-phase attribution rows from the sampled `phase` events
    (--phase_profile, ISSUE 15): device-ms percentiles per phase,
    joined with the static analytic-bytes gauges into achieved GB/s
    and utilization vs the `train/phase_ceiling_gbps` ceiling — the
    BENCH phase table shape, rebuilt from a live run's telemetry."""
    samples: Dict[str, List[float]] = {}
    for e in events:
        if e.get("kind") != "phase":
            continue
        for k, v in e.items():
            if not k.endswith("_ms") or not isinstance(v, (int, float)):
                continue
            name = "fused_step" if k == "fused_ms" else k[:-3]
            if name in ("split_sum", "residual"):
                continue
            samples.setdefault(name, []).append(float(v))
    ceiling = gauges.get("train/phase_ceiling_gbps")
    ordered = [p for p in _PHASE_ORDER if p in samples]
    ordered += sorted(set(samples) - set(ordered))
    rows = []
    for name in ordered:
        vals = samples[name]
        p50 = _pct(vals, 50)
        row: Dict[str, Any] = {"phase": name, "n": len(vals),
                               "p50_ms": p50,
                               "p95_ms": _pct(vals, 95)}
        nb = gauges.get(f"train/phase_bytes/{name}")
        if isinstance(nb, (int, float)) and nb and p50 > 0:
            row["bytes"] = int(nb)
            row["gbps"] = nb / (p50 / 1e3) / 1e9
            if isinstance(ceiling, (int, float)) and ceiling:
                row["vs_ceiling"] = row["gbps"] / float(ceiling)
        rows.append(row)
    return rows


def boundary_rows(events: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Epoch-boundary rows from the checkpoint/eval events: one row per
    `save` event (kind="save": loop-side blocked_ms), joined with its
    `save_committed` (writer-side total_ms) by step and the epoch's
    `eval` event (eval_ms). `overlap` is the fraction of the save wall
    HIDDEN from the train loop: 1 - blocked/total (a synchronous save
    scores 0, a fully-backgrounded one approaches 1)."""
    commits: Dict[int, Dict[str, Any]] = {}
    for e in events:
        if e.get("kind") == "save_committed" and "step" in e:
            commits[int(e["step"])] = e
    evals: Dict[int, Dict[str, Any]] = {}
    for e in events:
        if e.get("kind") == "eval" and "step" in e:
            evals[int(e["step"])] = e
    rows = []
    for e in events:
        if e.get("kind") != "save" or "step" not in e:
            continue
        step = int(e["step"])
        blocked = float(e.get("blocked_ms", float("nan")))
        commit = commits.get(step)
        total = (float(commit["total_ms"])
                 if commit and "total_ms" in commit else float("nan"))
        ev = evals.get(step)
        eval_ms = (float(ev["eval_ms"])
                   if ev and "eval_ms" in ev else None)
        overlap = (1.0 - blocked / total
                   if total == total and total > 0 else float("nan"))
        rows.append({"step": step, "blocked_ms": blocked,
                     "total_ms": total, "eval_ms": eval_ms,
                     "overlap": overlap,
                     "is_async": bool(e.get("is_async", False))})
    return rows


def _fmt(v, nd: int = 2) -> str:
    if v is None:
        return "—"
    if isinstance(v, float):
        if v != v:  # nan
            return "—"
        return f"{v:,.{nd}f}"
    return str(v)


def render(run_dirs: List[str]) -> str:
    loaded = [(d, *load_run(d)) for d in run_dirs]
    lines: List[str] = []

    # ---- headline: the BASELINE.md shipped-table shape ----
    head = [(d, m, ev, summarize_steps(m, ev)) for d, m, ev in loaded]
    train_rows = [(d, m, ev, s) for d, m, ev, s in head if s]
    if train_rows:
        lines.append("| Config | ms/step | pc/s/chip | vs V100 (1.94M) "
                     "| infeed wait p95 (ms) | steps | Source |")
        lines.append("|---|---|---|---|---|---|---|")
        for _d, m, _ev, s in train_rows:
            lines.append(
                f"| {_config_label(m)} "
                f"| {_fmt(s['ms_per_step_p50'])} "
                f"| {_fmt(s['pc_per_sec'], 1)} "
                f"| {_fmt(s['vs_v100'], 3)} "
                f"| {_fmt(_pct(s['infeed_wait_ms'], 95))} "
                f"| {s['n_steps']} "
                f"| {m.get('run_id', '?')} |")
        lines.append("")

    # ---- per-run detail ----
    for _d, manifest, events, step_summary in head:
        rid = manifest.get("run_id", "?")
        dev = manifest.get("devices") or {}
        lines.append(f"## run {rid} ({manifest.get('component', '?')}, "
                     f"{dev.get('platform', '?')} x"
                     f"{dev.get('count', '?')}, "
                     f"process {manifest.get('process_index', 0)}"
                     f"/{manifest.get('process_count', 1)})")
        if step_summary:
            lines.append(f"- steps: {step_summary['n_steps']}, "
                         f"examples: {step_summary['examples']}, "
                         f"final loss: "
                         f"{_fmt(step_summary['final_loss'], 4)}, "
                         f"{_fmt(step_summary['ex_per_sec'], 1)} ex/s")
        timers = _timer_rows(events)
        if timers:
            lines.append("")
            lines.append("| Timer | count | mean ms | p50 | p95 | p99 "
                         "| max |")
            lines.append("|---|---|---|---|---|---|---|")
            for name, t in sorted(timers.items()):
                lines.append(
                    f"| {name} | {t.get('count', 0)} "
                    f"| {_fmt(t.get('mean_ms'))} "
                    f"| {_fmt(t.get('p50_ms'))} "
                    f"| {_fmt(t.get('p95_ms'))} "
                    f"| {_fmt(t.get('p99_ms'))} "
                    f"| {_fmt(t.get('max_ms'))} |")
        gauges = {}
        for e in events:
            if e.get("kind") == "gauge":
                gauges[e.get("name")] = e.get("value")
            elif e.get("kind") == "summary" and e.get("gauges"):
                gauges.update(e["gauges"])
        # ---- sampled phase attribution (--phase_profile, ISSUE 15) ----
        p_rows = phase_rows(events, gauges)
        if p_rows:
            lines.append("")
            lines.append("| Phase | samples | p50 ms | p95 ms | bytes "
                         "| GB/s | vs ceiling |")
            lines.append("|---|---|---|---|---|---|---|")
            for r in p_rows:
                lines.append(
                    f"| {r['phase']} | {r['n']} "
                    f"| {_fmt(r['p50_ms'], 3)} "
                    f"| {_fmt(r['p95_ms'], 3)} "
                    f"| {_fmt(r.get('bytes'), 0)} "
                    f"| {_fmt(r.get('gbps'), 1)} "
                    f"| {_fmt(r.get('vs_ceiling'), 3)} |")
        if gauges:
            lines.append("")
            lines.append("gauges: " + ", ".join(
                f"{k}={_fmt(v, 1)}" for k, v in sorted(gauges.items())))
        # ---- epoch boundaries: save blocked vs total, eval, overlap ----
        b_rows = boundary_rows(events)
        if b_rows:
            lines.append("")
            lines.append("| Epoch boundary (step) | mode "
                         "| save_blocked_ms | save_total_ms | eval_ms "
                         "| save overlap |")
            lines.append("|---|---|---|---|---|---|")
            for r in b_rows:
                lines.append(
                    f"| {r['step']} "
                    f"| {'async' if r['is_async'] else 'sync'} "
                    f"| {_fmt(r['blocked_ms'])} "
                    f"| {_fmt(r['total_ms'])} "
                    f"| {_fmt(r['eval_ms'])} "
                    f"| {_fmt(r['overlap'], 3)} |")
        # ---- alerts (obs/alerts.py): one row per edge-triggered
        # transition — the run's incident log in table form ----
        alert_events = [e for e in events if e.get("kind") == "alert"]
        if alert_events:
            t0 = manifest.get("created_unix")
            lines.append("")
            lines.append("| Alert | transition | rule kind | metric "
                         "| observed | threshold | severity | t+ s |")
            lines.append("|---|---|---|---|---|---|---|---|")
            for e in alert_events:
                offs = (_fmt(float(e["ts"]) - float(t0), 1)
                        if t0 is not None and "ts" in e else "—")
                lines.append(
                    f"| {e.get('rule', '?')} "
                    f"| {e.get('transition', '?')} "
                    f"| {e.get('rule_kind', '?')} "
                    f"| {e.get('metric', '?')} {e.get('op', '')} "
                    f"| {_fmt(e.get('value'), 4)} "
                    f"| {_fmt(e.get('threshold'), 4)} "
                    f"| {e.get('severity', '?')} | {offs} |")
        bench_events = [e for e in events if e.get("kind") == "bench"]
        for b in bench_events:
            lines.append("")
            lines.append(
                f"bench: {_fmt(b.get('value'), 1)} {b.get('metric')} "
                f"({_fmt(b.get('vs_baseline'), 3)}x V100, "
                f"{_fmt(b.get('ms_per_step'))} ms/step)")
        # ---- serving throughput (tools/loadgen.py runs): the
        # BASELINE.md serving row shape ----
        load_events = [e for e in events if e.get("kind") == "loadgen"]
        if load_events:
            lines.append("")
            lines.append("| Serving mode | conc | req | ok | shed "
                         "| req/s | p50 ms | p99 ms | new compiles |")
            lines.append("|---|---|---|---|---|---|---|---|---|")
            for e in load_events:
                lat = e.get("latency") or {}
                lines.append(
                    f"| {e.get('mode', '?')} "
                    f"| {e.get('concurrency', 1)} "
                    f"| {e.get('requests', 0)} | {e.get('ok', 0)} "
                    f"| {e.get('shed', 0)} "
                    f"| {_fmt(e.get('throughput_rps'))} "
                    f"| {_fmt(lat.get('p50_ms'))} "
                    f"| {_fmt(lat.get('p99_ms'))} "
                    f"| {_fmt(e.get('new_compilations_under_load'))} |")
        lines.append("")
    return "\n".join(lines).rstrip() + "\n"


def render_merged(run_dirs: List[str]) -> str:
    """`--merge`: treat the given run dirs as ONE logical multi-process
    run (one per host, the manifests carrying process_index /
    process_count — ROADMAP item 2's MULTICHIP reporting shape) and
    aggregate them into a single headline row: global throughput is the
    SUM of per-process pc/s (each host feeds its own shard), step
    latency percentiles pool every process's step samples, and the
    per-process rows below keep the skew visible (a straggler host
    shows up as a slow row, not a hidden average)."""
    loaded = [(d, *load_run(d)) for d in run_dirs]
    rows = []
    for d, m, ev in loaded:
        s = summarize_steps(m, ev)
        if s is None:
            print(f"warning: {d} has no step events; skipped from "
                  "merge", file=sys.stderr)
            continue
        rows.append((m, s))
    if not rows:
        return "(no runs with step events to merge)\n"
    counts = {m.get("process_count", 1) for m, _ in rows}
    lines: List[str] = []
    if len(counts) > 1 or len(rows) != max(counts):
        lines.append(f"warning: merging {len(rows)} run(s) whose "
                     f"manifests declare process_count {sorted(counts)}"
                     " — partial or mixed run set")
        lines.append("")
    rows.sort(key=lambda r: r[0].get("process_index", 0))
    all_step_ms = [ms for _, s in rows for ms in s["step_ms"]]
    all_wait_ms = [ms for _, s in rows for ms in s["infeed_wait_ms"]]
    total_pc = sum(s["pc_per_sec"] for _, s in rows
                   if s["pc_per_sec"] == s["pc_per_sec"])
    lines.append("| Config | procs | ms/step | pc/s (sum) "
                 "| vs V100 (1.94M) | infeed wait p95 (ms) | steps "
                 "| Source |")
    lines.append("|---|---|---|---|---|---|---|---|")
    m0 = rows[0][0]
    lines.append(
        f"| {_config_label(m0)} | {len(rows)} "
        f"| {_fmt(_pct(all_step_ms, 50))} "
        f"| {_fmt(total_pc, 1)} "
        f"| {_fmt(total_pc / _v100_denominator(), 3)} "
        f"| {_fmt(_pct(all_wait_ms, 95))} "
        f"| {max(s['n_steps'] for _, s in rows)} "
        f"| merged({len(rows)} runs) |")
    lines.append("")
    lines.append("| Process | steps | examples | ex/s | pc/s "
                 "| ms/step p50 | infeed p95 | run |")
    lines.append("|---|---|---|---|---|---|---|---|")
    for m, s in rows:
        lines.append(
            f"| {m.get('process_index', 0)}"
            f"/{m.get('process_count', 1)} "
            f"| {s['n_steps']} | {s['examples']} "
            f"| {_fmt(s['ex_per_sec'], 1)} "
            f"| {_fmt(s['pc_per_sec'], 1)} "
            f"| {_fmt(s['ms_per_step_p50'])} "
            f"| {_fmt(_pct(s['infeed_wait_ms'], 95))} "
            f"| {m.get('run_id', '?')} |")
    return "\n".join(lines) + "\n"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="summarize code2vec_tpu telemetry JSONL runs")
    ap.add_argument("paths", nargs="+",
                    help="telemetry root dir(s) or run dir(s)")
    ap.add_argument("--merge", action="store_true",
                    help="aggregate the given per-process run dirs "
                         "into ONE multi-host table (pc/s summed, "
                         "step percentiles pooled, per-process skew "
                         "rows below)")
    args = ap.parse_args(argv)
    run_dirs: List[str] = []
    for p in args.paths:
        found = find_runs(p)
        if not found:
            print(f"error: no telemetry runs under {p}",
                  file=sys.stderr)
            return 2
        run_dirs.extend(found)
    if args.merge:
        sys.stdout.write(render_merged(run_dirs))
        return 0
    sys.stdout.write(render(run_dirs))
    return 0


if __name__ == "__main__":
    sys.exit(main())
