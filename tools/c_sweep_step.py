#!/usr/bin/env python3
"""Padding-reduction step-time sweep: MAX_CONTEXTS in {200, 128, 100}.

VERDICT r4 item 2: the corpus context distribution is p50/p90 = 65/97
(BASELINE.md extractor coverage) yet every config runs C=200, so over
half the gather/scatter/attention work is padding. The quality half of
the argument is measured by tools/quality_study.py --max_contexts (the
reader's seeded over-cap sampling handles C < the binarized width);
this tool measures the device half: the shipped train step's time at
java-large capacities for each C, slope-timed exactly like bench.py
(same dims/optimizer/batch builders — imported from it).

Reporting note: examples/s is the number that converts to
time-to-quality (an example carries the same label at any C >= its
context count); path-contexts/s scales with C by definition and is
reported only for cross-checking against bench.

Usage: python tools/c_sweep_step.py [--contexts 200,128,100]
Prints one JSON line per C and a summary.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--contexts", default="200,128,100")
    ap.add_argument("--tables_dtype", default="bfloat16",
                    choices=["bfloat16", "int8"])
    ap.add_argument("--out", default=None)
    args = ap.parse_args()
    from tools._bench_common import load_bench_module
    bench = load_bench_module()

    rows = []
    for c in (int(s) for s in args.contexts.split(",")):
        pc, ms, _ = bench._measure_encoder(
            "bag", tables_dtype=args.tables_dtype, max_contexts=c)
        row = {
            "max_contexts": c,
            "tables_dtype": args.tables_dtype,
            "ms_per_step": round(ms, 2),
            "examples_per_sec": round(bench.BATCH / ms * 1e3, 1),
            "path_contexts_per_sec": round(pc, 1),
        }
        rows.append(row)
        print(json.dumps(row), flush=True)

    base = rows[0]
    for r in rows[1:]:
        r["examples_per_sec_vs_first"] = round(
            r["examples_per_sec"] / base["examples_per_sec"], 3)
    print(json.dumps({"summary": rows}), flush=True)
    if args.out:
        with open(args.out, "a") as f:
            for r in rows:
                f.write(json.dumps(r) + "\n")


if __name__ == "__main__":
    main()
