#!/usr/bin/env python3
"""Measure the REFERENCE's training-step math in TensorFlow 2.21.

BASELINE.md action item 2 / SURVEY.md §7: the ≥8x north star needs a
measured denominator, not a guess. The reference (per SURVEY.md §3,
`tensorflow_model.Code2VecModel._build_tf_training_graph`) trains, on one
GPU, fp32, full softmax:

  3 embedding gathers -> concat [B,C,384] -> dropout(keep .75)
  -> tanh(ctx @ TRANSFORM[384,384]) -> attention logits (@ ATTENTION[384,1])
  + log(mask) -> softmax over C -> weighted sum = code vector [B,384]
  -> logits = code @ TARGET_VOCAB^T [261245] -> sparse softmax CE -> Adam.

This script re-implements exactly that step as a tf.function and times it
on the host, alongside the host's practical GEMM peak, yielding the
step's achieved-efficiency fraction. tools/v100_roofline.py converts the
analytic step cost + standard GPU efficiency ranges into the documented
V100 denominator (BASELINE.md "Baseline denominator" section).

Usage: python tools/tf_baseline.py [--batch 256] [--steps 3] [--full]
  --full uses the java-large capacities (slow on small hosts); default
  uses reduced vocab capacities, which leaves the per-example FLOPs of
  the dominant terms unchanged except the target-vocab logits matmul,
  reported separately.
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np

# java-large capacities (SURVEY.md §3 config row)
TOKEN_VOCAB = 1_301_136
PATH_VOCAB = 911_417
TARGET_VOCAB = 261_245
EMB = 128
CTX = 200


def step_flops(batch: int, target_vocab: int) -> float:
    """Analytic fwd+bwd FLOPs of the reference step (matmul terms; the
    gathers/elementwise are bandwidth, not FLOPs)."""
    d = 3 * EMB
    transform = 2.0 * batch * CTX * d * d          # [B*C,384]@[384,384]
    attention = 2.0 * batch * CTX * d              # [B*C,384]@[384,1]
    logits = 2.0 * batch * d * target_vocab        # [B,384]@[384,V]
    fwd = transform + attention + logits
    return 3.0 * fwd  # bwd ~ 2x fwd for matmul chains


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--steps", type=int, default=3)
    ap.add_argument("--full", action="store_true",
                    help="full java-large vocab capacities")
    args = ap.parse_args()

    import tensorflow as tf

    tf.config.set_visible_devices([], "GPU")

    if args.full:
        vt, vp, vtar = TOKEN_VOCAB, PATH_VOCAB, TARGET_VOCAB
    else:
        # Reduced tables: embedding-gather traffic per example is
        # unchanged (gather cost ~ rows touched, not table size); only
        # the logits matmul shrinks, so we report it separately.
        vt, vp, vtar = 65_536, 65_536, 16_384

    rng = np.random.default_rng(0)
    init = tf.initializers.GlorotUniform(seed=0)
    words = tf.Variable(init((vt, EMB)), name="WORDS_VOCAB")
    paths = tf.Variable(init((vp, EMB)), name="PATHS_VOCAB")
    target = tf.Variable(init((vtar, 3 * EMB)), name="TARGET_WORDS_VOCAB")
    transform = tf.Variable(init((3 * EMB, 3 * EMB)), name="TRANSFORM")
    attention = tf.Variable(init((3 * EMB, 1)), name="ATTENTION")
    opt = tf.keras.optimizers.Adam(learning_rate=1e-3)
    variables = [words, paths, target, transform, attention]

    @tf.function(jit_compile=False)  # reference TF1 graph, no XLA
    def train_step(src, pth, dst, mask, labels):
        with tf.GradientTape() as tape:
            e = tf.concat([tf.nn.embedding_lookup(words, src),
                           tf.nn.embedding_lookup(paths, pth),
                           tf.nn.embedding_lookup(words, dst)], axis=-1)
            e = tf.nn.dropout(e, rate=0.25)
            flat = tf.reshape(e, [-1, 3 * EMB])
            ctx = tf.math.tanh(tf.matmul(flat, transform))
            attn_logits = tf.reshape(tf.matmul(ctx, attention),
                                     [-1, CTX]) + tf.math.log(mask)
            attn = tf.nn.softmax(attn_logits, axis=-1)
            code = tf.einsum("bc,bcd->bd", attn,
                             tf.reshape(ctx, [-1, CTX, 3 * EMB]))
            logits = tf.matmul(code, target, transpose_b=True)
            loss = tf.reduce_mean(
                tf.nn.sparse_softmax_cross_entropy_with_logits(
                    labels=labels, logits=logits))
        grads = tape.gradient(loss, variables)
        opt.apply_gradients(zip(grads, variables))
        return loss

    B = args.batch
    src = tf.constant(rng.integers(0, vt, (B, CTX)), tf.int32)
    pth = tf.constant(rng.integers(0, vp, (B, CTX)), tf.int32)
    dst = tf.constant(rng.integers(0, vt, (B, CTX)), tf.int32)
    mask = tf.constant(np.ones((B, CTX), np.float32))
    labels = tf.constant(rng.integers(0, vtar, (B,)), tf.int32)

    train_step(src, pth, dst, mask, labels)  # trace + warm
    t0 = time.perf_counter()
    for _ in range(args.steps):
        loss = train_step(src, pth, dst, mask, labels)
    _ = float(loss)
    dt = (time.perf_counter() - t0) / args.steps

    # Host practical GEMM peak (fp32), for the efficiency fraction.
    a = tf.constant(rng.normal(size=(4096, 4096)).astype(np.float32))
    b = tf.constant(rng.normal(size=(4096, 4096)).astype(np.float32))
    _ = tf.matmul(a, b)
    t0 = time.perf_counter()
    for _ in range(3):
        c = tf.matmul(a, b)
    _ = float(tf.reduce_sum(c))
    gemm_dt = (time.perf_counter() - t0) / 3
    gemm_flops = 2.0 * 4096**3 / gemm_dt

    flops = step_flops(B, vtar)
    achieved = flops / dt
    out = {
        "tf_version": __import__("tensorflow").__version__,
        "device": "host CPU",
        "batch": B,
        "vocab": {"token": vt, "path": vp, "target": vtar},
        "sec_per_step": round(dt, 4),
        "examples_per_sec": round(B / dt, 2),
        "path_contexts_per_sec": round(B * CTX / dt, 1),
        "analytic_matmul_flops_per_step": flops,
        "achieved_gflops": round(achieved / 1e9, 2),
        "host_gemm_peak_gflops": round(gemm_flops / 1e9, 2),
        "step_efficiency_vs_gemm_peak": round(achieved / gemm_flops, 3),
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
