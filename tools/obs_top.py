#!/usr/bin/env python3
"""obs_top: live multi-host terminal view over N `/metrics` endpoints
(ISSUE 7).

The pull-based counterpart of `tools/telemetry_report.py --merge`:
instead of aggregating per-process JSONL run dirs after the fact, poll
each host's `--metrics_port` exposition endpoint on an interval and
render ONE table — global throughput summed across hosts, per-host
rows keeping the skew visible (a straggler host is a slow row, not a
hidden average). MULTICHIP groundwork: a v4-32 pod run is 4 hosts ×
one endpoint each.

  python tools/obs_top.py host1:9100 host2:9100 [--interval 2]
  python tools/obs_top.py localhost:9100 --once   # one sample, no TUI

Rates (steps/s, examples/s, requests/s) are differenced between
consecutive polls of each endpoint's cumulative counters; a counter
that went BACKWARD means the process restarted (supervisor relaunch /
elastic resize zeroes its counters) — the row is annotated RESTARTED
and rates clamp to the new process's progress instead of rendering
negative steps/s. path-contexts/s = examples-rate × the
`train_max_contexts` gauge the train loop publishes. Health verdicts,
firing alerts, stalled components and stale gauges (age > --stale_s)
come straight off the same scrape; hosts running --phase_profile
additionally get a per-phase p50 column set (ISSUE 15). Pure stdlib
(urllib + the shared obs/promtext parser, itself re-only) — runs on a
laptop against a pod with nothing installed beyond this checkout.

`--fleet <url>` (ISSUE 17) switches the source: instead of scraping N
raw endpoints and differencing counters here, poll the supervisor-side
fleet collector's `/fleet` aggregate — per-host rows plus the cohort
signals only the collector can compute (straggler score with phase
attribution, loss/params divergence, measured clock offsets).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# ONE exposition parser + counter-reset discipline for every scrape
# consumer (ISSUE 17 hoist): obs_top grew the original; the shared
# module now owns it and the fleet collector imports the same one.
# Re-exported names keep the historical `from tools.obs_top import
# parse_prometheus` imports working.
from code2vec_tpu.obs.promtext import (CounterRates,  # noqa: E402
                                       labeled, parse_prometheus,
                                       scalar)

__all__ = ["EndpointState", "labeled", "main", "parse_prometheus",
           "render", "render_fleet", "render_phases", "scalar",
           "scrape"]

# canonical phase-column order: code2vec_tpu/obs/phases.py PHASE_ORDER
# plus the trailing fused_step timer (kept literal here so this tool
# stays runnable on a laptop with nothing installed; a test pins the
# copy equal to the canonical tuple); unknown phases append
# alphabetically
_PHASE_ORDER = ("infeed_wait", "embed_gather", "concat_dense",
                "forward_pool", "backward", "table_apply",
                "backward_apply", "allreduce", "allreduce_exposed",
                "fused_step")


def scrape(endpoint: str, timeout_s: float = 3.0) -> Dict:
    url = endpoint if "://" in endpoint else f"http://{endpoint}"
    with urllib.request.urlopen(f"{url.rstrip('/')}/metrics",
                                timeout=timeout_s) as resp:
        return parse_prometheus(resp.read().decode("utf-8"))


class EndpointState:
    """One endpoint's scrape history: the previous counter sample, so
    each poll yields rates."""

    def __init__(self, endpoint: str):
        self.endpoint = endpoint
        # the shared counter-reset discipline (obs/promtext): a counter
        # going BACKWARD annotates the row RESTARTED and rates clamp to
        # the new process's progress instead of negative steps/s
        self.rates = CounterRates()
        self.error: Optional[str] = None

    def poll(self, stale_s: float) -> Optional[Dict[str, Any]]:
        """Scrape once; returns a row dict (None until two samples
        exist for the rate fields — other fields fill in on the first
        poll)."""
        t = time.monotonic()
        try:
            metrics = scrape(self.endpoint)
            self.error = None
        except (urllib.error.URLError, OSError, ValueError) as e:
            self.error = str(getattr(e, "reason", e))
            return {"endpoint": self.endpoint, "error": self.error}
        rate = self.rates.advance(t, metrics)
        ex_rate = rate("train_examples")
        max_ctx = scalar(metrics, "train_max_contexts")
        stalled = [labels.get("component", "?")
                   for labels, v in metrics.get("component_stalled", ())
                   if v]
        firing = [labels.get("rule", "?")
                  for labels, v in metrics.get("alert_active", ())
                  if v]
        unhealthy = [labels.get("monitor", "?")
                     for labels, v in metrics.get("health_status", ())
                     if v]
        stale = [labels.get("gauge", "?")
                 for labels, v in metrics.get("gauge_age_seconds", ())
                 if v > stale_s]
        # sampled per-phase p50s (--phase_profile, ISSUE 15): one
        # column per train_phase_<name>_ms summary the host exports
        phases = {}
        for fam in metrics:
            if fam.startswith("train_phase_") and fam.endswith("_ms"):
                v = labeled(metrics, fam, quantile="0.5")
                if v is not None:
                    phases[fam[len("train_phase_"):-3]] = v
        return {
            "endpoint": self.endpoint,
            "steps": scalar(metrics, "train_steps"),
            "steps_s": rate("train_steps"),
            "ex_s": ex_rate,
            "pc_s": (ex_rate * max_ctx
                     if ex_rate is not None and max_ctx else None),
            "step_p50": labeled(metrics, "train_step_ms",
                                quantile="0.5"),
            # analytic-floor attainment (health/opt_efficiency: the
            # sparse path's static [U, E]-aware floor over observed
            # p50 step time) — an optimizer-efficiency regression is
            # a dropping number here, mid-run
            "opt_eff": scalar(metrics, "health_opt_efficiency"),
            "infeed_p95": labeled(metrics, "train_infeed_wait_ms",
                                  quantile="0.95"),
            "req_s": rate("serve_requests"),
            "queue_depth": scalar(metrics, "serve_queue_depth"),
            "loss": scalar(metrics, "train_loss"),
            "stalled": stalled,
            "alerts": firing,
            "unhealthy": unhealthy,
            "stale_gauges": stale,
            "restarted": self.rates.restarted,
            "phases": phases,
            "phase_coverage": scalar(metrics, "health_phase_coverage"),
        }


def _f(v, nd: int = 1) -> str:
    if v is None:
        return "—"
    if isinstance(v, float) and v != v:
        return "NaN"
    return f"{v:,.{nd}f}"


def render(rows: List[Dict[str, Any]]) -> str:
    """One frame: the summed headline + per-host skew rows (the
    telemetry_report --merge table shape, live)."""
    lines: List[str] = []
    ok_rows = [r for r in rows if "error" not in r]
    total_pc = sum(r["pc_s"] for r in ok_rows
                   if r.get("pc_s") is not None) or None
    total_req = sum(r["req_s"] for r in ok_rows
                    if r.get("req_s") is not None) or None
    n_bad = sum(bool(r.get("stalled") or r.get("alerts"))
                for r in ok_rows)
    lines.append(
        f"obs_top — {len(ok_rows)}/{len(rows)} hosts up | "
        f"pc/s (sum) {_f(total_pc)} | req/s (sum) {_f(total_req)} | "
        f"{n_bad} host(s) unhealthy | "
        f"{time.strftime('%H:%M:%S')}")
    lines.append(
        "| Host | steps | ex/s | pc/s | step p50 ms | opt eff "
        "| infeed p95 ms | req/s | q | loss | status |")
    lines.append("|---|---|---|---|---|---|---|---|---|---|---|")
    for r in rows:
        if "error" in r:
            lines.append(f"| {r['endpoint']} | DOWN: {r['error']} "
                         "| | | | | | | | | |")
            continue
        bits = []
        if r["stalled"]:
            bits.append("STALLED:" + ",".join(r["stalled"]))
        if r.get("restarted"):
            # counter reset this window (supervisor restart / elastic
            # resize): rates shown are the NEW process's, not deltas
            bits.append("RESTARTED")
        if r["alerts"]:
            bits.append("ALERT:" + ",".join(r["alerts"]))
        if r["unhealthy"]:
            bits.append("bad:" + ",".join(r["unhealthy"]))
        if r["stale_gauges"]:
            bits.append(f"{len(r['stale_gauges'])} stale gauge(s)")
        lines.append(
            f"| {r['endpoint']} | {_f(r['steps'], 0)} "
            f"| {_f(r['ex_s'])} | {_f(r['pc_s'])} "
            f"| {_f(r['step_p50'], 2)} | {_f(r.get('opt_eff'), 3)} "
            f"| {_f(r['infeed_p95'], 2)} "
            f"| {_f(r['req_s'])} | {_f(r['queue_depth'], 0)} "
            f"| {_f(r['loss'], 4)} "
            f"| {' '.join(bits) if bits else 'ok'} |")
    phase_lines = render_phases(rows)
    if phase_lines:
        lines.append("")
        lines.extend(phase_lines)
    return "\n".join(lines)


def render_phases(rows: List[Dict[str, Any]]) -> List[str]:
    """The per-phase column set (--phase_profile hosts): p50 device ms
    per sampled phase, one row per host, columns in canonical phase
    order — ROADMAP item 4's "where did the millisecond go" live.
    Empty when no host exports train_phase_* summaries."""
    with_phases = [r for r in rows if r.get("phases")]
    if not with_phases:
        return []
    seen = {p for r in with_phases for p in r["phases"]}
    cols = [p for p in _PHASE_ORDER if p in seen]
    cols += sorted(seen - set(cols))
    lines = ["| Host (phase p50 ms) | " + " | ".join(cols)
             + " | coverage |",
             "|---" * (len(cols) + 2) + "|"]
    for r in with_phases:
        vals = " | ".join(_f(r["phases"].get(c), 3) for c in cols)
        lines.append(f"| {r['endpoint']} | {vals} "
                     f"| {_f(r.get('phase_coverage'), 2)} |")
    return lines


def fetch_fleet(url: str, timeout_s: float = 3.0) -> Dict[str, Any]:
    """One `/fleet` aggregate off the supervisor-side collector."""
    base = url if "://" in url else f"http://{url}"
    base = base.rstrip("/")
    if not base.endswith("/fleet"):
        base += "/fleet"
    with urllib.request.urlopen(base, timeout=timeout_s) as resp:
        return json.loads(resp.read().decode("utf-8"))


def render_fleet(agg: Dict[str, Any]) -> str:
    """One frame off the fleet aggregate: cohort headline (summed
    throughput, straggler verdict with its attributed series,
    divergence), then per-host rows with measured clock offsets —
    the collector already did the differencing and the cross-host
    math, so this renders, it does not derive."""
    cohort = agg.get("cohort") or {}
    hosts = agg.get("hosts") or []
    lines: List[str] = []
    strag = cohort.get("straggler_score")
    strag_bit = "—"
    if strag is not None:
        strag_bit = f"{strag:.2f}x"
        if cohort.get("straggler_host"):
            strag_bit += (f" ({cohort['straggler_host']} via "
                          f"{cohort.get('straggler_series')})")
    div = "DIVERGED" if cohort.get("divergence") else "converged"
    lines.append(
        f"obs_top --fleet — {cohort.get('hosts_up', 0)}"
        f"/{cohort.get('hosts_total', 0)} hosts up | "
        f"pc/s (sum) {_f(cohort.get('pc_per_sec'))} | "
        f"straggler {strag_bit} | {div} | "
        f"clock spread {_f((cohort.get('clock_spread_s') or 0) * 1e3, 3)} ms | "
        f"{time.strftime('%H:%M:%S')}")
    lines.append("| Host | steps | ex/s | pc/s | step p50 ms "
                 "| infeed p50 ms | loss | straggler | clock off ms "
                 "| status |")
    lines.append("|---" * 10 + "|")
    for r in hosts:
        if not r.get("up"):
            lines.append(f"| {r['endpoint']} | DOWN: "
                         f"{r.get('error')} | | | | | | | | |")
            continue
        bits = []
        if r.get("restarted"):
            bits.append("RESTARTED")
        score = r.get("straggler_score")
        score_bit = "—"
        if score is not None:
            score_bit = f"{score:.2f}x {r.get('straggler_series')}"
        off = r.get("clock_offset_s")
        lines.append(
            f"| {r['endpoint']} | {_f(r.get('steps'), 0)} "
            f"| {_f(r.get('ex_s'))} | {_f(r.get('pc_s'))} "
            f"| {_f(r.get('step_p50'), 2)} "
            f"| {_f(r.get('infeed_p50'), 2)} "
            f"| {_f(r.get('loss'), 4)} | {score_bit} "
            f"| {_f(off * 1e3 if off is not None else None, 3)} "
            f"| {' '.join(bits) if bits else 'ok'} |")
    phase_lines = render_phases(hosts)
    if phase_lines:
        lines.append("")
        lines.extend(phase_lines)
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="live multi-host view over /metrics endpoints")
    ap.add_argument("endpoints", nargs="*",
                    help="host:port (or full URL) of each "
                         "--metrics_port exposition server")
    ap.add_argument("--fleet", default=None, metavar="URL",
                    help="poll the supervisor-side fleet collector's "
                         "/fleet aggregate instead of raw endpoints "
                         "(ISSUE 17)")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="poll interval in seconds")
    ap.add_argument("--once", action="store_true",
                    help="two quick polls (rates need a delta), one "
                         "printed frame, exit — the scripting mode")
    ap.add_argument("--count", type=int, default=0,
                    help="stop after N frames (0 = run until ^C)")
    ap.add_argument("--stale_s", type=float, default=60.0,
                    help="mark gauges older than this as stale")
    args = ap.parse_args(argv)
    if args.fleet is None and not args.endpoints:
        ap.error("give /metrics endpoints, or --fleet <url>")

    if args.fleet is not None:
        # aggregate mode: the collector differenced and derived; poll
        # and render its latest sweep (no warm-up frame needed)
        n = 0
        try:
            while True:
                try:
                    out = render_fleet(fetch_fleet(args.fleet))
                except (urllib.error.URLError, OSError,
                        ValueError) as e:
                    out = (f"obs_top --fleet — {args.fleet} DOWN: "
                           f"{getattr(e, 'reason', e)}")
                if not args.once and n:
                    sys.stdout.write("\x1b[2J\x1b[H")
                print(out)
                n += 1
                if args.once or (args.count and n >= args.count):
                    return 0
                time.sleep(max(args.interval, 0.05))
        except KeyboardInterrupt:
            return 0

    states = [EndpointState(e) for e in args.endpoints]

    def frame() -> List[Dict[str, Any]]:
        return [s.poll(args.stale_s) for s in states]

    if args.once:
        frame()  # prime the counter baselines
        time.sleep(max(args.interval, 0.05))
        print(render(frame()))
        return 0
    n = 0
    try:
        while True:
            rows = frame()
            if n:  # first frame has no rates yet; start painting at 2
                sys.stdout.write("\x1b[2J\x1b[H")  # clear + home
                print(render(rows))
            n += 1
            if args.count and n > args.count:
                return 0
            time.sleep(max(args.interval, 0.05))
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
