#!/usr/bin/env python3
"""Reproduce the v4-32 aggregate north-star accounting from a BENCH json.

BASELINE.md's aggregate claim ("16 x per-chip clears >=8x one V100 even
under a large-global-batch token penalty") is arithmetic over measured
quantities; this script recomputes it from any BENCH_r*.json (or
bench.py output) so the numbers in prose stay checkable.

Round 4 (VERDICT r3 item 5): the projection no longer implies DP
efficiency 1.0 — it carries an explicit per-step COLLECTIVE-TRAFFIC
model against published v4 ICI bandwidth for the two meshes the
framework actually ships (pure DP with replicated tables, and the
data x model mesh with row-sharded tables), and folds the RECOMMENDED
mesh's modeled efficiency into the aggregate as a DP-efficiency
factor (a deployment would pick the better mesh; the worse mesh's
efficiency is itemized as worst_case_efficiency so the pessimistic
bound stays visible). The formula terms (bytes per collective,
assumed bandwidths, per-step comm ms) are all in the output.

Usage: python tools/aggregate_projection.py BENCH_r03.json
       python bench.py | python tools/aggregate_projection.py -
"""

from __future__ import annotations

import json
import sys

V4_32_CHIPS = 16
NORTH_STAR_MULTIPLE = 8.0
# Token-budget penalties are MEASURED per mesh (BASELINE.md round-4
# large-batch study, warmup_cosine + sqrt-scaled LR recipe):
#   - data=4 x model=4 (recommended): global batch 4096 is
#     convergence-NEUTRAL at matched budget (F1 0.9305 vs control
#     0.9292) -> penalty 1.0.
#   - pure DP16: global batch 16384 needs 2x tokens to match
#     (0.8873 at 1x, 0.9270 at 2x vs control 0.9292) -> penalty 2.0.
TOKEN_PENALTY = {"data4xmodel4_rowsharded": 1.0,
                 "pure_dp16_replicated": 2.0}

# ---- model shapes (java-large; SURVEY.md §3 config row), padded the
# way models/encoder.ModelDims pads (vocab_pad_multiple here = the
# 'model' axis size when sharded, irrelevant at this granularity) ----
VT, VP, VY, E = 1_301_138, 911_419, 261_247, 128
D3 = 3 * E  # code-vector width = 384
CTX = 200
NUM_SAMPLED = 4096
GRAD_BYTES = 2  # bf16 tables -> bf16 grads (value_and_grad dtype rule)

# ---- published v4 interconnect assumptions (stated, not implied) ----
# TPU v4 (Jouppi et al., ISCA 2023): 3D-torus ICI, 6 links/chip,
# ~50 GB/s per direction per link. A ring allreduce over one mesh axis
# uses that axis's two links in both directions: effective per-chip
# ring bandwidth ~= 2 links x 50 GB/s = 100 GB/s. Single slice -> no
# DCN term (the 'dcn' mesh axis stays size 1 for v4-32).
ICI_RING_GBPS = 100.0

# ---- dense-compute share of the step (ADVICE r4 medium finding) ----
# parallel/sharding.py shards the BATCH over the data axis only, so on
# the data x model mesh the model-axis chips replicate the dense
# encoder/head compute on the same batch shard; only the TABLE-bound
# phases (gathers, backward scatter, optimizer streaming) divide by the
# model axis. The projection therefore models the mesh step as
#   t_mesh = dense_ms + (step_ms - dense_ms)/model_ax + comm_ms
# and the aggregate as data_ax * (b*CTX / t_mesh) — NOT chips * eff.
# dense_ms is analytic: the bag step's dense FLOPs (TRANSFORM fwd+bwd
# 3x 2*b*CTX*D3^2, attention pool, sampled head 3x 2*b*D3*(S+b)) at a
# deliberately LOW MXU efficiency (0.3 of the measured 151-181 TFLOP/s
# bf16 peak; the K=384 GEMMs run far below peak — tools/xf_profile.py
# measured 17-75% by shape). Low efficiency -> larger replicated share
# -> SMALLER claimed aggregate, so the conservative direction.
BF16_PEAK_TFLOPS = 151.0
DENSE_MXU_EFFICIENCY = 0.30


def _dense_ms(b: int) -> float:
    flops = (3 * 2 * b * CTX * D3 * D3          # TRANSFORM fwd+bwd
             + 3 * 2 * b * CTX * D3             # attention pool
             + 3 * 2 * b * D3 * (NUM_SAMPLED + b))  # sampled head
    return flops / (BF16_PEAK_TFLOPS * 1e12 * DENSE_MXU_EFFICIENCY) * 1e3


def _allreduce_ms(bytes_per_chip: float, axis: int) -> float:
    """Bidirectional-ring allreduce cost over one mesh axis:
    2*(N-1)/N * bytes / ring_bw (the standard ring formula)."""
    if axis <= 1:
        return 0.0
    return (2.0 * (axis - 1) / axis * bytes_per_chip
            / (ICI_RING_GBPS * 1e9) * 1e3)


def collective_model(per_chip_batch: int, step_ms: float) -> dict:
    """Per-step collective traffic for the java-large bag config on the
    two shipped v4-32 meshes, both itemized. `modeled_efficiency` (the
    factor main() folds into the aggregate) is the RECOMMENDED (better)
    mesh's; `worst_case_efficiency` keeps the other bound visible.

    Traffic inventory (matches parallel/sharding.py's placements):

    pure DP (data=16, model=1) — tables REPLICATED:
      every step allreduces the full dense table grads over the data
      axis: bf16 x (VT*E + VP*E + VY*3E) + small params. This is the
      expensive design the TP mesh exists to avoid.

    data=4 x model=4 — tables ROW-SHARDED over 'model':
      - table-shard grads allreduce over the DATA axis only:
        bytes / model_axis per chip.
      - forward gathers cross the 'model' axis: each data replica
        psums the gathered embedding activations [b, C, E] x 3 tables
        (src+dst from token, path) over the model axis; backward
        reverses it (reduce_scatter of activation grads) — same bytes.
      - sampled softmax: (S + b) target rows [*, 3E] gathered across
        'model' + the resulting logits psum — small, counted anyway.
      - small params (TRANSFORM 3Ex3E, ATTENTION 3E) allreduce over
        data axis — negligible but counted.
    """
    b = per_chip_batch
    table_grad_bytes = GRAD_BYTES * (VT * E + VP * E + VY * D3)
    small_bytes = 4 * (D3 * D3 + D3)  # f32 TRANSFORM/ATTENTION grads

    # ---- pure DP (data=16) ----
    dp_comm_ms = _allreduce_ms(table_grad_bytes + small_bytes,
                               V4_32_CHIPS)
    dp_eff = step_ms / (step_ms + dp_comm_ms)

    # ---- data=4 x model=4 ----
    data_ax, model_ax = 4, 4
    shard_grad_ms = _allreduce_ms(
        table_grad_bytes / model_ax + small_bytes, data_ax)
    # fwd psum + bwd reduce_scatter of gathered activations (bf16
    # compute dtype): 3 gathers of [b, CTX, E] each way
    act_bytes = 2 * (3 * b * CTX * E)
    gather_ms = 2 * _allreduce_ms(act_bytes, model_ax)
    # sampled head: (S+b) rows of [3E] each way + [b, S+b] logits psum
    head_bytes = 2 * ((NUM_SAMPLED + b) * D3 + b * (NUM_SAMPLED + b))
    head_ms = 2 * _allreduce_ms(head_bytes, model_ax)
    tp_comm_ms = shard_grad_ms + gather_ms + head_ms
    # the model-axis chips REPLICATE the dense compute on the shared
    # batch shard (shard_batch slices over 'data' only — ADVICE r4);
    # only the table-bound phases divide by model_ax
    dense_ms = _dense_ms(b)
    table_ms = max(step_ms - dense_ms, 0.0)
    tp_step_ms = dense_ms + table_ms / model_ax + tp_comm_ms
    tp_group_pc_per_sec = b * CTX / tp_step_ms * 1e3
    tp_aggregate = data_ax * tp_group_pc_per_sec

    return {
        "formula": "pure DP: agg = chips * per_chip * eff, eff = "
                   "step_ms/(step_ms + comm_ms). data x model: agg = "
                   "data_ax * b*CTX / t_mesh, t_mesh = dense_ms + "
                   "(step_ms - dense_ms)/model_ax + comm_ms (the "
                   "model-axis chips replicate the dense compute on "
                   "the shared batch shard — shard_batch shards over "
                   "'data' only). comm_ms = sum over collectives of "
                   "2*(N-1)/N * bytes / "
                   f"{ICI_RING_GBPS:.0f}GB/s ring ICI (v4: 6 links/"
                   "chip x ~50GB/s/dir, 2 per torus axis; Jouppi et "
                   "al. ISCA 2023). No compute/comm overlap assumed "
                   "(conservative: XLA does overlap grad allreduces "
                   "with remaining backward work).",
        "pure_dp16_replicated": {
            "allreduce_bytes_per_step": table_grad_bytes + small_bytes,
            "comm_ms": round(dp_comm_ms, 2),
            "dp_efficiency": round(dp_eff, 3),
        },
        "data4xmodel4_rowsharded": {
            "table_shard_grad_allreduce_bytes":
                int(table_grad_bytes / model_ax + small_bytes),
            "gather_activation_bytes_each_way": act_bytes,
            "sampled_head_bytes_each_way": head_bytes,
            "comm_ms": round(tp_comm_ms, 2),
            "replicated_dense_ms": round(dense_ms, 2),
            "sharded_table_ms": round(table_ms / model_ax, 2),
            "modeled_step_ms_per_group": round(tp_step_ms, 2),
            "aggregate_pc_per_sec": round(tp_aggregate, 1),
            "compute_replication_note":
                "the 4 model-axis chips run the dense encoder/head "
                "on the SAME 1024-example shard; aggregate counts "
                "each batch shard once (ADVICE r4 medium finding)",
        },
        "data_ax": data_ax,
        "tp_aggregate_pc_per_sec": round(tp_aggregate, 1),
        "dp_efficiency": round(dp_eff, 3),
    }


def main() -> None:
    src = sys.argv[1] if len(sys.argv) > 1 else "-"
    text = sys.stdin.read() if src == "-" else open(src).read()
    # accept bench.py's single line, a driver BENCH_r*.json wrapper
    # (bench line under "parsed"), or a log with the line at the end
    try:
        j = json.loads(text)
    except json.JSONDecodeError:
        j = json.loads(text.strip().splitlines()[-1])
    if "parsed" in j and isinstance(j["parsed"], dict):
        j = j["parsed"]

    per_chip = j["value"]
    # round-1 bench lines predate the denominator fields; fall back to
    # the documented 1.94M (BASELINE.md "Baseline denominator")
    denom = j.get("baseline_denominator", 1_940_000.0)
    band = j.get("baseline_band", (denom, denom))
    step_ms = j.get("ms_per_step", 1024 * CTX / per_chip * 1e3)
    comm = collective_model(per_chip_batch=1024, step_ms=step_ms)

    # pure DP16: every chip has its own 1024-example shard
    agg_dp = per_chip * V4_32_CHIPS * comm["dp_efficiency"]
    ttq_dp = agg_dp / denom / TOKEN_PENALTY["pure_dp16_replicated"]
    # data=4 x model=4: 4 batch shards, each run by a 4-chip model
    # group (dense compute replicated inside the group — the aggregate
    # counts each shard ONCE; ADVICE r4 medium finding)
    agg_tp = comm["tp_aggregate_pc_per_sec"]
    ttq_tp = agg_tp / denom / TOKEN_PENALTY["data4xmodel4_rowsharded"]

    mesh = ("data4xmodel4_rowsharded" if ttq_tp >= ttq_dp
            else "pure_dp16_replicated")
    agg, ttq = (agg_tp, ttq_tp) if ttq_tp >= ttq_dp else (agg_dp, ttq_dp)
    out = {
        "per_chip_pc_per_sec": per_chip,
        "per_chip_vs_v100": round(per_chip / denom, 2),
        "collective_model": comm,
        "recommended_mesh": mesh,
        "v4_32_aggregate_pc_per_sec": round(agg, 1),
        "v4_32_modeled_vs_v100": round(agg / denom, 1),
        "v4_32_modeled_vs_v100_band": [round(agg / band[1], 1),
                                       round(agg / band[0], 1)],
        "token_budget_penalty": TOKEN_PENALTY[mesh],
        "token_penalty_basis": "measured (BASELINE.md round-4 "
                               "large-batch study): global B=4096 "
                               "neutral at 1x budget; B=16384 matches "
                               "at 2x",
        "v4_32_time_to_quality_vs_v100": round(ttq, 1),
        "v4_32_time_to_quality_by_mesh": {
            "pure_dp16_replicated": round(ttq_dp, 1),
            "data4xmodel4_rowsharded": round(ttq_tp, 1)},
        "north_star_multiple": NORTH_STAR_MULTIPLE,
        "north_star_met": bool(min(ttq_dp, ttq_tp)
                               >= NORTH_STAR_MULTIPLE),
        "assumes": "collective model + dense-compute replication model "
                   "above (dryrun-validated shardings; real multi-chip "
                   "not measurable here); token penalties are measured "
                   "per mesh, not assumed",
    }
    print(json.dumps(out, indent=1))


if __name__ == "__main__":
    main()
