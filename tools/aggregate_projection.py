#!/usr/bin/env python3
"""Reproduce the v4-32 aggregate north-star accounting from a BENCH json.

BASELINE.md's aggregate claim ("16 x per-chip clears >=8x one V100 even
under a large-global-batch token penalty") is arithmetic over measured
quantities; this script recomputes it from any BENCH_r*.json (or
bench.py output) so the numbers in prose stay checkable.

Usage: python tools/aggregate_projection.py BENCH_r03.json
       python bench.py | python tools/aggregate_projection.py -
"""

from __future__ import annotations

import json
import sys

V4_32_CHIPS = 16
NORTH_STAR_MULTIPLE = 8.0
# Large global batches are NOT convergence-neutral at matched token
# budget (BASELINE.md large-batch study): budget extra tokens for the
# 16-way-DP global batch. 2x is conservative — the measured worst gap
# was 1.7 F1 at 8x batch growth with a tuned LR.
TOKEN_BUDGET_PENALTY = 2.0


def main() -> None:
    src = sys.argv[1] if len(sys.argv) > 1 else "-"
    text = sys.stdin.read() if src == "-" else open(src).read()
    # accept bench.py's single line, a driver BENCH_r*.json wrapper
    # (bench line under "parsed"), or a log with the line at the end
    try:
        j = json.loads(text)
    except json.JSONDecodeError:
        j = json.loads(text.strip().splitlines()[-1])
    if "parsed" in j and isinstance(j["parsed"], dict):
        j = j["parsed"]

    per_chip = j["value"]
    # round-1 bench lines predate the denominator fields; fall back to
    # the documented 1.94M (BASELINE.md "Baseline denominator")
    denom = j.get("baseline_denominator", 1_940_000.0)
    band = j.get("baseline_band", (denom, denom))
    agg = per_chip * V4_32_CHIPS
    out = {
        "per_chip_pc_per_sec": per_chip,
        "per_chip_vs_v100": round(per_chip / denom, 2),
        "v4_32_aggregate_pc_per_sec": agg,
        "v4_32_raw_vs_v100": round(agg / denom, 1),
        "v4_32_raw_vs_v100_band": [round(agg / band[1], 1),
                                   round(agg / band[0], 1)],
        "token_budget_penalty": TOKEN_BUDGET_PENALTY,
        "v4_32_time_to_quality_vs_v100": round(
            agg / denom / TOKEN_BUDGET_PENALTY, 1),
        "north_star_multiple": NORTH_STAR_MULTIPLE,
        "north_star_met": bool(agg / denom / TOKEN_BUDGET_PENALTY
                               >= NORTH_STAR_MULTIPLE),
        "assumes": "linear DP scaling over ICI (dryrun-validated mesh; "
                   "not measurable on one chip) and the conservative "
                   "token penalty above for the 16x global batch",
    }
    print(json.dumps(out, indent=1))


if __name__ == "__main__":
    main()
