#!/usr/bin/env python3
"""Phase-level profile of the java-large training step on the local chip.

Times, SLOPE-TIMED (two chained-run lengths, differenced — the tunneled
axon platform adds ~2 ms per dispatched call plus ~100 ms fixed sync
cost, which single-chain timing cannot separate; see BASELINE.md round-3
methodology note), each of:

  - HBM streaming bandwidth (fold-resistant in-jit copy loop) — ceiling
  - forward only (encode + sampled softmax loss)
  - forward + backward (grads materialized)
  - full step (fwd + bwd + optimizer), adam and adafactor

Usage: python tools/profile_step.py [--batch 1024] [--steps 20]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

TOKEN_VOCAB = 1_301_136
PATH_VOCAB = 911_417
TARGET_VOCAB = 261_245
CTX = 200
NUM_SAMPLED = 4096


def timeit(fn, sync, steps, warmup=3):
    """Slope timing: run chains of `steps` and `3*steps` calls and
    difference them, cancelling both the fixed sync overhead and (to
    first order) nothing else — per-call dispatch cost is part of the
    steady-state step cost and is retained deliberately (a real train
    loop pays it too)."""
    def chain(n):
        out = None
        t0 = time.perf_counter()
        for _ in range(n):
            out = fn()
        sync(out)
        return time.perf_counter() - t0

    chain(warmup)
    t1 = chain(steps)
    t2 = chain(3 * steps)
    return (t2 - t1) / (2 * steps)


def main(argv=None) -> None:
    # argv=None (programmatic callers) means "no flags", NOT sys.argv —
    # the CLI entry below passes sys.argv[1:] explicitly (same contract
    # as bench.main, so a test calling main() never eats pytest's argv)
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=1024)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--telemetry_dir", default=None,
                    help="also emit each phase measurement as telemetry "
                         "events (code2vec_tpu/obs) so ad-hoc profiling "
                         "and BENCH rounds share one JSONL format")
    args = ap.parse_args(argv if argv is not None else [])
    B = args.batch

    from code2vec_tpu.obs import Telemetry
    tele = Telemetry.create(args.telemetry_dir, component="profile")

    def emit(phase: str, ms: float, **extra) -> None:
        tele.record_ms(f"profile/{phase}_ms", ms)
        tele.event("profile", phase=phase, ms=round(ms, 3),
                   batch=B, **extra)

    import jax
    import jax.numpy as jnp

    from code2vec_tpu.models.encoder import ModelDims, encode, init_params
    from code2vec_tpu.ops.sampled_softmax import sampled_softmax_loss
    from code2vec_tpu.training.steps import make_train_step

    # bf16 tables — the SHIPPED config (round-4 reconcile fix: this
    # tool previously defaulted to f32 tables while BASELINE.md labeled
    # its floors "bf16 tables"; f32 measures ~5 ms/step slower)
    dims = ModelDims(token_vocab_size=TOKEN_VOCAB,
                     path_vocab_size=PATH_VOCAB,
                     target_vocab_size=TARGET_VOCAB,
                     embeddings_size=128, max_contexts=CTX,
                     tables_dtype="bfloat16")
    params = init_params(jax.random.PRNGKey(0), dims)

    r = np.random.default_rng(0)
    labels = jnp.asarray(r.integers(0, TARGET_VOCAB, (B,), dtype=np.int32))
    src = jnp.asarray(r.integers(0, TOKEN_VOCAB, (B, CTX), dtype=np.int32))
    pth = jnp.asarray(r.integers(0, PATH_VOCAB, (B, CTX), dtype=np.int32))
    dst = jnp.asarray(r.integers(0, TOKEN_VOCAB, (B, CTX), dtype=np.int32))
    mask = jnp.ones((B, CTX), jnp.float32)
    weights = jnp.ones((B,), jnp.float32)
    batch = (labels, src, pth, dst, mask, weights)
    rng = jax.random.PRNGKey(1)

    # ---- HBM streaming ceiling (shared helper, ops/membench.py) ----
    from code2vec_tpu.ops.membench import measure_hbm_ceiling

    bw = measure_hbm_ceiling()
    print(f"HBM streaming (1 GiB copy): {bw/1e9:.0f} GB/s effective")
    tele.gauge("profile/hbm_ceiling_gbps", round(bw / 1e9, 1),
               emit=False)
    tele.event("profile", phase="hbm_ceiling", gbps=round(bw / 1e9, 1))

    # ---- forward only ----
    def loss_fn(params, rng):
        code, _ = encode(params, src, pth, dst, mask,
                         compute_dtype=jnp.bfloat16)
        loss, _ = sampled_softmax_loss(
            params["target_emb"], code, labels, rng, NUM_SAMPLED,
            example_weights=weights, vocab_size=TARGET_VOCAB)
        return loss

    fwd = jax.jit(loss_fn)
    dt = timeit(lambda: fwd(params, rng), lambda o: float(o), args.steps)
    print(f"forward only:        {dt*1e3:6.2f} ms")
    emit("forward", dt * 1e3)

    # ---- forward + backward ----
    grad_fn = jax.jit(jax.value_and_grad(loss_fn))
    dt = timeit(lambda: grad_fn(params, rng), lambda o: float(o[0]),
                args.steps)
    print(f"forward + backward:  {dt*1e3:6.2f} ms")
    emit("forward_backward", dt * 1e3)

    # ---- full step, dense Adam ----
    def run_full(label, step, opt_state0):
        p = jax.tree_util.tree_map(jnp.copy, params)
        s = opt_state0
        k = jax.random.PRNGKey(2)
        nonlocal_state = {"p": p, "s": s, "k": k}

        def one():
            st = nonlocal_state
            st["k"], sub = jax.random.split(st["k"])
            st["p"], st["s"], loss = step(st["p"], st["s"], batch, sub)
            return loss

        dt = timeit(one, lambda o: float(o), args.steps)
        pc = B * CTX / dt
        print(f"{label}: {dt*1e3:6.2f} ms -> {pc/1e6:.2f}M pc/s")
        emit(label.replace(" ", "_").replace("(", "").replace(")", ""),
             dt * 1e3, pc_per_sec=round(pc, 1))
        return dt

    from code2vec_tpu.training.optimizers import make_optimizer

    for oname in ("adam", "adafactor"):
        opt = make_optimizer(1e-3, oname)
        step = make_train_step(dims, opt, use_sampled_softmax=True,
                               num_sampled=NUM_SAMPLED,
                               compute_dtype=jnp.bfloat16,
                               use_pallas=jax.default_backend() == "tpu")
        run_full(f"full step ({oname})", step, opt.init(params))

    tele.close()


if __name__ == "__main__":
    main(sys.argv[1:])
