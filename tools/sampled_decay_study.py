#!/usr/bin/env python3
"""Root-cause instrumentation for the sampled-softmax f32 top1 decay.

Round-2 quality study (BASELINE.md) found sampled+f32 tables plateau
~2.6 F1 points below full softmax on the 50K-name corpus, with top1
DECAYING late in training, while bf16 tables "evidently damp" the
instability. This tool trains the sampled config and captures, every
`--probe_epochs` epochs:

  - val top1 split by target-frequency decile (head = most frequent);
  - mean L2 norm of target-embedding rows per decile;
  - mean Adam second-moment (nu) per decile for the target table;
  - mean bias-corrected update magnitude per decile (the quantity that
    bf16 storage would round away once it drops below ~1/256 of the
    row's scale — the hypothesized damping mechanism).

Mechanism hypotheses it separates:
  H1 head-negative pressure: the log-uniform sampler draws head classes
     as negatives almost every step, so between their (rarer) positive
     occurrences their logits are pushed down; late in training the
     positive/negative pressure balance tips and head top1 decays.
     Signature: head-decile top1 falls while tail deciles hold; head row
     norms keep moving late in training.
  H2 effective-LR spike: Adam nu for converged head rows decays, so the
     per-row effective LR rises late and the rows oscillate. Signature:
     nu(head) falling while update magnitude holds or grows.
  H3 bf16 damping: with bf16 tables the late tiny updates round to zero
     (|update| < row_scale/256), freezing converged rows — stability by
     quantization. Signature: f32 update magnitudes late in training
     sitting below the bf16 rounding threshold for head rows.

Usage (after the corpus build in BASELINE.md "Quality study"):
  python tools/sampled_decay_study.py --data /tmp/qs/ds/qs \
      --epochs 12 --tables_dtype float32 [--lr 1e-3] [--out out.jsonl]
Run once with float32 and once with bfloat16; diff the trajectories.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def target_freq_deciles(vocabs, train_prefix: str, n_deciles: int = 10):
    """Decile boundaries over target ids ranked by training frequency.
    Vocab ids are already frequency-ordered (Vocab.create_from_freq_dict
    sorts by count), so deciles are contiguous id ranges past the
    specials."""
    V = vocabs.target_vocab.size
    first_real = 2  # PAD, OOV
    ids = np.arange(first_real, V)
    return np.array_split(ids, n_deciles)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--data", required=True)
    ap.add_argument("--epochs", type=int, default=12)
    ap.add_argument("--probe_epochs", type=int, default=2)
    ap.add_argument("--batch", type=int, default=1024)
    ap.add_argument("--num_sampled", type=int, default=4096)
    ap.add_argument("--tables_dtype", default="float32",
                    choices=["float32", "bfloat16"])
    ap.add_argument("--lr", type=float, default=1e-3)
    ap.add_argument("--seed", type=int, default=239)
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    import jax.numpy as jnp

    from code2vec_tpu.config import Config
    from code2vec_tpu.models.jax_model import Code2VecModel

    cfg = Config(
        MAX_CONTEXTS=200, MAX_TOKEN_VOCAB_SIZE=150_000,
        MAX_PATH_VOCAB_SIZE=150_000, MAX_TARGET_VOCAB_SIZE=60_000,
        TRAIN_BATCH_SIZE=args.batch, TEST_BATCH_SIZE=args.batch,
        NUM_TRAIN_EPOCHS=args.probe_epochs, SAVE_EVERY_EPOCHS=1000,
        NUM_BATCHES_TO_LOG_PROGRESS=100000, LEARNING_RATE=args.lr,
        SEED=args.seed, USE_SAMPLED_SOFTMAX=True,
        NUM_SAMPLED_CLASSES=args.num_sampled,
        TABLES_DTYPE=args.tables_dtype,
        # the probes read Adam's mu/nu chain state — pin adam explicitly
        # (the shipped default is adafactor, whose state is factored)
        EMBEDDING_OPTIMIZER="adam",
    )
    cfg.train_data_path = args.data
    cfg.test_data_path = args.data + ".val.c2v"
    model = Code2VecModel(cfg)
    deciles = target_freq_deciles(model.vocabs, args.data)

    def probe(epoch_end: int) -> dict:
        # --- per-decile top1 over the val set ---
        from code2vec_tpu.data.reader import open_reader
        reader = open_reader(cfg.test_data_path, model.vocabs,
                             cfg.MAX_CONTEXTS, cfg.TEST_BATCH_SIZE,
                             shuffle=False)
        correct = np.zeros(len(deciles))
        count = np.zeros(len(deciles))
        dec_of = np.zeros(model.vocabs.target_vocab.size, np.int32) - 1
        for d, ids in enumerate(deciles):
            dec_of[ids] = d
        for batch in reader:
            dev = model._device_batch(batch, process_local=False)
            _, topk_ids, _ = model._eval_step(model.params, dev)
            nv = batch.num_valid_examples
            top1 = np.asarray(topk_ids)[:nv, 0]
            true = batch.target_index[:nv]
            for t, p in zip(true, top1):
                d = dec_of[t]
                if d >= 0:
                    count[d] += 1
                    correct[d] += float(t == p)
        top1_by_decile = (correct / np.maximum(count, 1)).round(4)

        # --- table / optimizer-state statistics per decile ---
        emb = np.asarray(model.params["target_emb"], np.float32)
        row_norm = np.linalg.norm(emb, axis=1)
        # Adam state: chain(scale_by_adam_f32_moments, scale) -> [0].nu
        nu = model.opt_state[0].nu["target_emb"]
        nu_row = np.asarray(jnp.mean(nu, axis=1), np.float32)
        mu = model.opt_state[0].mu["target_emb"]
        count_t = int(model.opt_state[0].count)
        bc1 = 1.0 - 0.9 ** max(count_t, 1)
        bc2 = 1.0 - 0.999 ** max(count_t, 1)
        upd = np.asarray(jnp.mean(jnp.abs(
            (mu / bc1) / (jnp.sqrt(nu / bc2) + 1e-8)), axis=1), np.float32)
        out = {"epoch": epoch_end, "tables_dtype": args.tables_dtype,
               "lr": args.lr,
               "top1_by_decile": top1_by_decile.tolist(),
               "row_norm_by_decile":
                   [round(float(row_norm[ids].mean()), 4)
                    for ids in deciles],
               "nu_by_decile":
                   [float(nu_row[ids].mean()) for ids in deciles],
               "lr_x_update_by_decile":
                   [float(args.lr * upd[ids].mean()) for ids in deciles],
               # bf16 rounding threshold for a row of this scale:
               # updates below norm/sqrt(D)/256 round to nothing
               "bf16_round_threshold_by_decile":
                   [round(float(row_norm[ids].mean())
                          / np.sqrt(emb.shape[1]) / 256, 8)
                    for ids in deciles]}
        print(json.dumps(out), flush=True)
        if args.out:
            with open(args.out, "a") as f:
                f.write(json.dumps(out) + "\n")
        return out

    done = 0
    while done < args.epochs:
        t0 = time.time()
        model.train()  # runs cfg.NUM_TRAIN_EPOCHS (= probe_epochs)
        done += cfg.NUM_TRAIN_EPOCHS
        print(f"epochs {done}/{args.epochs} "
              f"({time.time() - t0:.0f}s)", file=sys.stderr)
        probe(done)


if __name__ == "__main__":
    main()
