#!/usr/bin/env python3
"""Phase attribution for the int8-tables step (BASELINE.md round 5).

The full int8 step measured slower than bf16 (43.3 ms with threefry
dither, 38.5 ms with the fused hash dither, vs 30.7 bf16) — this tool
splits the regression by phase so the doc can say WHERE the bytes
saving loses to added work. Slope-timed exactly like bench.py, at
java-large capacities, for each tables_dtype:

  - fwd+bwd only (value_and_grad of the shared train loss): isolates
    the gather/dequant + scatter side;
  - optimizer.update + apply only (precomputed grads): isolates the
    adafactor chain + (for int8) the requantize pass;
  - full step (reference point = bench.py's number).

Usage: python tools/int8_profile.py [--out f]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _build_grad_fn(loss_fn, params0, quantized):
    """Jitted fwd+bwd for one tables_dtype — a factory so the jit is
    evaluated ONCE per dtype, outside the measurement loops (the
    graftlint retrace-hazard fix: the old inline construction rebuilt a
    fresh callable with an empty compile cache inside `main`'s dtype
    loop)."""
    import jax
    import jax.numpy as jnp

    from code2vec_tpu.ops.quant import is_quantized

    if not quantized:
        return jax.jit(jax.value_and_grad(loss_fn))
    qkeys = sorted(k for k in params0 if is_quantized(params0[k]))

    @jax.jit
    def grad_fn(params, batch, rng):
        def lf(carriers, params):
            virt = dict(params)
            for k, c in carriers.items():
                virt[k] = dict(params[k], g=c)
            return loss_fn(virt, batch, rng)
        carriers = {k: jnp.zeros(params[k]["q"].shape,
                                 jnp.bfloat16) for k in qkeys}
        return jax.value_and_grad(
            lf, argnums=(0, 1), allow_int=True)(carriers, params)

    return grad_fn


def _build_apply_step(optimizer, flat_grads):
    """Jitted optimizer.update + apply on precomputed grads (same
    factory-per-dtype reasoning as `_build_grad_fn`)."""
    import functools

    import jax
    import jax.numpy as jnp
    import optax

    from code2vec_tpu.ops.quant import is_quantized, requantize

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def apply_step(params, opt_state, rng):
        qkeys_l = sorted(k for k in params if is_quantized(params[k]))
        rng, *qrngs = jax.random.split(rng, 1 + len(qkeys_l))
        flat_params = {k: (jnp.zeros(params[k]["q"].shape,
                                     jnp.bfloat16)
                           if is_quantized(params[k]) else params[k])
                       for k in params}
        updates, opt_state = optimizer.update(flat_grads, opt_state,
                                              flat_params)
        new_params = {}
        for k, qrng in zip(qkeys_l, qrngs):
            new_params[k] = requantize(params[k], updates[k], qrng)
        for k in params:
            if k not in new_params:
                new_params[k] = optax.apply_updates(params[k],
                                                    updates[k])
        return new_params, opt_state, rng

    return apply_step


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None)
    ap.add_argument("--dtypes", default="bfloat16,int8")
    args = ap.parse_args()
    from tools._bench_common import load_bench_module
    bench = load_bench_module()

    import jax
    import jax.numpy as jnp

    from code2vec_tpu.models.encoder import init_params
    from code2vec_tpu.ops.quant import is_quantized, opt_param_view
    from code2vec_tpu.training.optimizers import make_optimizer
    from code2vec_tpu.training.steps import make_train_loss_fn

    rows = []
    for tdtype in args.dtypes.split(","):
        dims = bench._java_large_dims("bag", tables_dtype=tdtype)
        params0 = init_params(jax.random.PRNGKey(0), dims)
        optimizer = make_optimizer(1e-3)
        batches = bench._device_batches()
        loss_fn = make_train_loss_fn(
            dims, use_sampled_softmax=True, num_sampled=bench.NUM_SAMPLED,
            compute_dtype=jnp.bfloat16,
            use_pallas=jax.default_backend() == "tpu")
        quantized = tdtype == "int8"

        # ---- fwd+bwd ----
        grad_fn = _build_grad_fn(loss_fn, params0, quantized)

        def chain_fb(n, rng, _params=params0, _grad_fn=grad_fn):
            rng, sub = jax.random.split(rng)
            keys = list(jax.random.split(sub, max(n, 1)))
            t0 = time.perf_counter()
            for i in range(n):
                out = _grad_fn(_params, batches[i % len(batches)],
                               keys[i])
            # hard sync via host transfer of the scalar loss
            # (block_until_ready can return early on this platform)
            float(out[0])
            return time.perf_counter() - t0, rng

        fb_ms = bench._slope_time(chain_fb, jax.random.PRNGKey(3)) * 1e3

        # ---- optimizer.update + apply on precomputed grads ----
        view = opt_param_view(params0)
        opt_state0 = optimizer.init(view)
        flat_grads = {k: (jnp.full(view[k].shape, 1e-3, jnp.bfloat16)
                          if is_quantized(params0[k])
                          else jnp.full(params0[k].shape, 1e-3,
                                        params0[k].dtype))
                      for k in params0}
        apply_step = _build_apply_step(optimizer, flat_grads)

        def chain_opt(n, state, apply_step=apply_step):
            params, opt_state, rng = state
            t0 = time.perf_counter()
            for _ in range(n):
                params, opt_state, rng = apply_step(params, opt_state,
                                                    rng)
            float(jax.tree_util.tree_leaves(params)[0].ravel()[0])
            return time.perf_counter() - t0, (params, opt_state, rng)

        # apply_step donates its params/opt_state, so hand it real
        # copies: params0 is reused by the full-step measurement below
        params_copy = jax.tree_util.tree_map(jnp.copy, params0)
        opt_ms = bench._slope_time(
            chain_opt, (params_copy, opt_state0,
                        jax.random.PRNGKey(5))) * 1e3

        # ---- full step (bench's own measurement path) ----
        full_pc, full_ms, _ = bench._measure_encoder(
            "bag", tables_dtype=tdtype)

        row = {"tables_dtype": tdtype,
               "fwd_bwd_ms": round(fb_ms, 2),
               "optimizer_apply_ms": round(opt_ms, 2),
               "full_step_ms": round(full_ms, 2),
               "full_pc_per_sec": round(full_pc, 1)}
        rows.append(row)
        print(json.dumps(row), flush=True)

    if args.out:
        with open(args.out, "a") as f:
            for r in rows:
                f.write(json.dumps(r) + "\n")


if __name__ == "__main__":
    main()
