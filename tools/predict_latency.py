#!/usr/bin/env python3
"""Prediction latency at java-large capacities (SURVEY.md §7 row).

The reference claims "milliseconds per example" serving latency (code2vec
paper; BASELINE.md row, confidence Low). This measures this framework's
equivalents on the real chip:

  - device_predict_ms: the jitted predict step (encode -> full [1, Vy]
    logits -> top-k) at batch 1, java-large dims, slope-timed (the
    tunneled platform adds ~100 ms fixed sync + ~2 ms/dispatch that a
    production host does not pay; the slope cancels it).
  - device_predict_call_ms: the same step timed as one naive dispatch+
    sync round trip — what THIS dev VM actually observes per call
    through the tunnel (upper bound; not a property of the chip).
  - extract_ms: the native C++ extractor CLI on Input.java (subprocess
    wall time, includes process startup — the REPL pays exactly this).
  - tensorize_ms: host-side c2v row -> padded int32 tensors.
  - repl_end_to_end_ms: extract + tensorize + one naive predict call.

Params are random at java-large shapes (latency is shape-, not
value-dependent). Usage: python tools/predict_latency.py [--out f]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EXTRACTOR = os.path.join(REPO, "code2vec_tpu/extractor/build/c2v_extract")


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None)
    ap.add_argument("--steps", type=int, default=50)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np

    from code2vec_tpu.models.encoder import init_params
    from code2vec_tpu.training.steps import make_predict_step

    sys.path.insert(0, REPO)
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(REPO, "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)

    dims = bench._java_large_dims("bag")
    params = init_params(jax.random.PRNGKey(0), dims)
    step = make_predict_step(dims, compute_dtype=jnp.bfloat16,
                             use_pallas=jax.default_backend() == "tpu")
    r = np.random.default_rng(0)
    batch = (jnp.zeros((1,), jnp.int32),
             jnp.asarray(r.integers(0, dims.token_vocab_size, (1, 200)),
                         jnp.int32),
             jnp.asarray(r.integers(0, dims.path_vocab_size, (1, 200)),
                         jnp.int32),
             jnp.asarray(r.integers(0, dims.token_vocab_size, (1, 200)),
                         jnp.int32),
             jnp.ones((1, 200), jnp.float32),
             jnp.ones((1,), jnp.float32))

    def run_n(n):
        t0 = time.perf_counter()
        for _ in range(n):
            ids, probs, _attn, _code = step(params, batch)
        float(probs[0, 0])  # hard sync (host transfer)
        return time.perf_counter() - t0

    run_n(3)  # warm the compile cache
    # slope: cancels the tunnel's fixed sync + per-dispatch overhead
    t_a, t_b = run_n(10), run_n(10 + args.steps)
    device_ms = (t_b - t_a) / args.steps * 1e3
    # naive single-call latency (what this tunneled VM observes)
    calls = [run_n(1) for _ in range(5)]
    call_ms = sorted(calls)[len(calls) // 2] * 1e3

    # ---- extractor + tensorize (host side) ----
    extract_ms = tensorize_ms = None
    sample = os.path.join(REPO, "Input.java")
    if os.path.exists(EXTRACTOR) and os.path.exists(sample):
        ts = []
        for _ in range(5):
            t0 = time.perf_counter()
            out = subprocess.run([EXTRACTOR, "--file", sample],
                                 capture_output=True, text=True,
                                 check=True).stdout
            ts.append(time.perf_counter() - t0)
        extract_ms = sorted(ts)[2] * 1e3
        line = out.strip().splitlines()[0]
        from code2vec_tpu.data.reader import parse_c2v_rows
        from code2vec_tpu.vocab.vocabularies import Code2VecVocabs
        del Code2VecVocabs  # tensorize timing uses a synthetic vocab:

        # real vocab lookup is a dict probe per token — emulate with the
        # tiny test vocab would understate hashing cost, so time the
        # split/pad path on the raw line against a stub that maps every
        # token to a fixed id (the dict probe itself is O(100ns)/token)
        class _Stub:
            pad_index = 0
            oov_index = 1

            def lookup_index(self, w):
                return 2

        stub = type("V", (), {})()
        stub.token_vocab = _Stub()
        stub.path_vocab = _Stub()
        stub.target_vocab = _Stub()
        t0 = time.perf_counter()
        for _ in range(20):
            parse_c2v_rows([line], stub, dims.max_contexts)
        tensorize_ms = (time.perf_counter() - t0) / 20 * 1e3

    row = {
        "metric": "prediction_latency_java_large",
        "device_predict_ms_batch1": round(device_ms, 3),
        "device_predict_call_ms_tunneled": round(call_ms, 1),
        "extract_ms_subprocess": (round(extract_ms, 1)
                                  if extract_ms else None),
        "tensorize_ms": (round(tensorize_ms, 2)
                         if tensorize_ms else None),
        "repl_end_to_end_ms_tunneled": (
            round(call_ms + extract_ms + tensorize_ms, 1)
            if extract_ms else None),
        "backend": jax.default_backend(),
        "note": "device_predict_ms is the chip latency (slope-timed; "
                "production-host number); *_tunneled rows include this "
                "dev VM's ~100 ms tunnel round trip and subprocess "
                "startup, an environment artifact",
    }
    print(json.dumps(row))
    if args.out:
        with open(args.out, "a") as f:
            f.write(json.dumps(row) + "\n")


if __name__ == "__main__":
    main()
