"""graftlint: repo-aware static analysis for the jax_graft codebase.

ISSUE 4 tentpole: the bug classes pytest cannot see — host syncs that
only cost performance, jit retrace storms that only fire under load,
data races that only fire under concurrency, and flag/doc drift that
only bites users — are exactly the classes prior rounds kept re-fixing
by hand (ADVICE r5 #1-#3, the batcher lock race, the bag-order
downsample bug). code2vec itself is static analysis over ASTs; this
package walks OUR ASTs to keep those classes fixed.

Contract: stdlib-only (`ast` + `tokenize`, never `import jax` /
`import tensorflow` / any scanned module), so the suite runs in tier-1
on any platform in well under the 30 s budget. `tests/test_graftlint.py`
proves the no-JAX/no-TF property with the blocked-module subprocess
pattern from tests/test_obs_guard.py.

ISSUE 14 tentpole: summary-based interprocedural analysis — a first
pass computes per-function summaries (collective effects,
nondeterminism draws/returns, per-host identity returns, escaping /
donated params; tools/graftlint/dataflow.py `compute_summaries`), a
worklist fixpoint widens them over the shared heuristic call graph
(core.Scan), and the rules see one call hop deeper: `spmd-divergence`
(collectives under process-divergent control — the distributed-
deadlock class) and `nondeterminism` (wall clock / global rng /
fs-or-set iteration order / id()-hash() flowing into the
resume-parity surface).

Usage:
    python -m tools.graftlint [--format json|sarif] [--rules r1,r2] [paths]
Suppression:
    # graftlint: disable=<rule>[,<rule>...]       (this line / next line)
    # graftlint: disable-file=<rule>[,<rule>...]  (whole file)
Baseline:
    graftlint_baseline.json at the repo root grandfathers pre-existing
    findings (line-number-insensitive match); `--write-baseline`
    regenerates it, review the diff before committing.
"""

from tools.graftlint.core import (DEFAULT_PATHS, Finding, FileContext,
                                  REPO_ROOT, Rule, all_rules, get_rule,
                                  run_lint)

__all__ = ["DEFAULT_PATHS", "Finding", "FileContext", "REPO_ROOT",
           "Rule", "all_rules", "get_rule", "run_lint"]
