"""graftlint CLI.

    python -m tools.graftlint                       # default scan set
    python -m tools.graftlint --format json serving
    python -m tools.graftlint --rules lock-discipline,config-drift
    python -m tools.graftlint --changed             # pre-commit fast path
    python -m tools.graftlint --write-baseline      # regenerate + review

Exit status: 0 = no non-baselined findings, 1 = findings, 2 = usage.
Stale baseline entries (fixed findings whose entry lingers) are
reported but do not fail the run — `--write-baseline` drops them.

`--changed` lints only the .py files `git diff --name-only <base>`
(plus untracked files) reports under the default scan set — the
pre-commit gate stops paying the full-repo scan on every commit; the
full scan still runs as tier-1 (tests/test_graftlint.py), so repo-wide
rules (call-graph reachability, config drift) lose nothing.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
from typing import List

from tools.graftlint import baseline as baseline_mod
from tools.graftlint.core import (DEFAULT_PATHS, EXCLUDE_DIRS,
                                  REPO_ROOT, all_rules, iter_py_files,
                                  run_lint)


def changed_py_files(root: str, base: str = "HEAD") -> List[str]:
    """Repo-root-relative .py paths changed vs `base` (worktree diff +
    untracked), restricted to the default scan set and graftlint's
    exclude rules. Deleted files are dropped (nothing to parse)."""
    def git(*args: str) -> List[str]:
        # quotepath=off: git would otherwise octal-escape-and-quote
        # non-ASCII paths, which then fail the isfile check and skip
        # the file from the gate silently
        out = subprocess.run(["git", "-c", "core.quotepath=off",
                              *args], cwd=root,
                             capture_output=True, text=True, check=True)
        return [ln.strip() for ln in out.stdout.splitlines()
                if ln.strip()]

    names = set(git("diff", "--name-only", base))
    names.update(git("ls-files", "--others", "--exclude-standard"))
    kept = []
    for rel in sorted(names):
        parts = rel.split("/")
        if not rel.endswith(".py") or parts[0] not in DEFAULT_PATHS:
            continue
        if any(p in EXCLUDE_DIRS for p in parts):
            continue  # fixtures plant deliberate true positives
        if os.path.isfile(os.path.join(root, rel)):
            kept.append(rel)
    return kept


def main(argv: List[str] = None) -> int:
    rules = all_rules()
    p = argparse.ArgumentParser(
        prog="python -m tools.graftlint",
        description="repo-aware static analysis (see README.md "
                    "'Static analysis')")
    p.add_argument("paths", nargs="*", default=list(DEFAULT_PATHS),
                   help=f"files/dirs to scan (default: "
                        f"{' '.join(DEFAULT_PATHS)})")
    p.add_argument("--format", choices=["text", "json"], default="text")
    p.add_argument("--rules", default=None,
                   help="comma-separated subset of: "
                        + ", ".join(sorted(rules)))
    p.add_argument("--baseline", default=baseline_mod.DEFAULT_BASELINE,
                   help="baseline file (default: repo-root "
                        "graftlint_baseline.json)")
    p.add_argument("--no-baseline", action="store_true",
                   help="report every finding, grandfathered or not")
    p.add_argument("--changed", action="store_true",
                   help="lint only files changed vs --base (git diff "
                        "+ untracked) — the fast pre-commit path")
    p.add_argument("--base", default="HEAD",
                   help="base ref for --changed (default: HEAD)")
    p.add_argument("--write-baseline", action="store_true",
                   help="rewrite the baseline from this run's findings "
                        "(refuses serving/ and obs/ entries) and exit 0")
    p.add_argument("--root", default=REPO_ROOT, help=argparse.SUPPRESS)
    args = p.parse_args(argv)

    selected = None
    if args.rules:
        selected = [r.strip() for r in args.rules.split(",") if r.strip()]
        unknown = [r for r in selected if r not in rules]
        if unknown:
            print(f"unknown rule(s): {', '.join(unknown)} "
                  f"(have: {', '.join(sorted(rules))})", file=sys.stderr)
            return 2

    if args.changed:
        if args.paths != list(DEFAULT_PATHS):
            print("--changed computes its own file list; drop the "
                  "path arguments", file=sys.stderr)
            return 2
        if args.write_baseline:
            print("--write-baseline needs the full scan (a changed-"
                  "only baseline would drop every other entry)",
                  file=sys.stderr)
            return 2
        try:
            args.paths = changed_py_files(args.root, args.base)
        except (OSError, subprocess.CalledProcessError) as e:
            print(f"--changed: git failed: {e}", file=sys.stderr)
            return 2
        if not args.paths:
            if args.format == "json":
                print(json.dumps({"findings": [], "grandfathered": 0,
                                  "stale_baseline": []}, indent=2))
            else:
                print(f"graftlint: no changed .py files vs {args.base}"
                      " — 0 findings")
            return 0

    try:
        findings = run_lint(args.paths, root=args.root, rules=selected)
    except FileNotFoundError as e:
        print(e, file=sys.stderr)
        return 2

    if args.write_baseline:
        if selected is not None or sorted(args.paths) != sorted(
                DEFAULT_PATHS):
            # a partial scan would overwrite the baseline with its
            # subset, silently deleting every entry outside the scope
            print("--write-baseline requires a full default scan "
                  "(no --rules, no path arguments) — a partial scan "
                  "would drop out-of-scope baseline entries",
                  file=sys.stderr)
            return 2
        refused = baseline_mod.write(findings, args.baseline)
        print(f"baseline: wrote {len(findings) - len(refused)} "
              f"entr{'y' if len(findings) - len(refused) == 1 else 'ies'}"
              f" -> {args.baseline}")
        for f in refused:
            print(f"REFUSED (fix, don't baseline): {f.render()}")
        return 1 if refused else 0

    entries = [] if args.no_baseline else baseline_mod.load(args.baseline)
    # On a SCOPED scan (path or rule subset), out-of-scope baseline
    # entries are simply not re-checked — comparing them against
    # partial findings would misreport every one as stale ("fixed").
    # In scope = the entry's rule ran AND its path was scanned (or the
    # run actually produced findings for that path — repo-wide rules
    # emit root-file findings like README.md regardless of the path
    # args). The full default scan skips the filter so staleness
    # reporting stays complete where the baseline is actually written.
    full_scope = selected is None and sorted(args.paths) == sorted(
        DEFAULT_PATHS)
    if entries and not full_scope:
        scanned = {os.path.relpath(p, args.root).replace(os.sep, "/")
                   for p in iter_py_files(args.paths, args.root)}
        produced = {f.path for f in findings}
        rules_run = set(selected) if selected else set(rules)
        entries = [e for e in entries
                   if e.get("rule") in rules_run
                   and (e.get("path") in scanned
                        or e.get("path") in produced)]
    new, old, stale = baseline_mod.split(findings, entries)

    if args.format == "json":
        print(json.dumps({
            "findings": [f.to_json() for f in new],
            "grandfathered": len(old),
            "stale_baseline": stale,
        }, indent=2))
    else:
        for f in new:
            print(f.render())
        if stale:
            print(f"note: {len(stale)} stale baseline entr"
                  f"{'y' if len(stale) == 1 else 'ies'} (fixed "
                  "findings — regenerate with --write-baseline):")
            for e in stale:
                print(f"  {e.get('path')}: {e.get('rule')}: "
                      f"{e.get('message')}")
        print(f"graftlint: {len(new)} finding"
              f"{'' if len(new) == 1 else 's'}"
              f" ({len(old)} grandfathered, "
              f"{len(findings)} total, "
              f"rules: {len(selected or rules)})")
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
