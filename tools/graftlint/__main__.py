"""graftlint CLI.

    python -m tools.graftlint                       # default scan set
    python -m tools.graftlint --format json serving
    python -m tools.graftlint --rules lock-discipline,config-drift
    python -m tools.graftlint --changed             # pre-commit fast path
    python -m tools.graftlint --write-baseline      # regenerate + review

Exit status: 0 = no non-baselined findings, 1 = findings, 2 = usage.
Stale baseline entries (fixed findings whose entry lingers) are
reported but do not fail the run — `--write-baseline` drops them.

`--changed` lints only the .py files `git diff --name-only <base>`
(plus untracked files) reports under the default scan set — the
pre-commit gate stops paying the full-repo scan on every commit; the
full scan still runs as tier-1 (tests/test_graftlint.py), so repo-wide
rules (call-graph reachability, config drift) lose nothing. Since the
summary layer (ISSUE 14) made a callee's BODY able to change a
caller's findings (a function growing a collective effect indicts
every divergent call site one hop up) — and a changed CALL SITE can
only be judged with its callee's summary in the scan set — `--changed`
is summary-aware: it re-lints the changed files PLUS the files holding
their direct callers and callees (one cheap parse of the scan set
finds them; no rules run on anything else).

`--format sarif` emits SARIF 2.1.0 for CI annotation / editor ingest;
the `json` and `text` contracts are unchanged.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
from typing import Dict, List

from tools.graftlint import baseline as baseline_mod
from tools.graftlint.core import (DEFAULT_PATHS, EXCLUDE_DIRS,
                                  FileContext, REPO_ROOT, Rule, Scan,
                                  all_rules, iter_py_files, run_lint)


def changed_py_files(root: str, base: str = "HEAD") -> List[str]:
    """Repo-root-relative .py paths changed vs `base` (worktree diff +
    untracked), restricted to the default scan set and graftlint's
    exclude rules. Deleted files are dropped (nothing to parse)."""
    def git(*args: str) -> List[str]:
        # quotepath=off: git would otherwise octal-escape-and-quote
        # non-ASCII paths, which then fail the isfile check and skip
        # the file from the gate silently
        out = subprocess.run(["git", "-c", "core.quotepath=off",
                              *args], cwd=root,
                             capture_output=True, text=True, check=True)
        return [ln.strip() for ln in out.stdout.splitlines()
                if ln.strip()]

    names = set(git("diff", "--name-only", base))
    names.update(git("ls-files", "--others", "--exclude-standard"))
    kept = []
    for rel in sorted(names):
        parts = rel.split("/")
        if not rel.endswith(".py") or parts[0] not in DEFAULT_PATHS:
            continue
        if any(p in EXCLUDE_DIRS for p in parts):
            continue  # fixtures plant deliberate true positives
        if os.path.isfile(os.path.join(root, rel)):
            kept.append(rel)
    return kept


def _parse_default_set(root: str) -> Scan:
    """One rule-free parse of the default scan set (missing dirs are
    skipped — hermetic test repos carry only `tools/`)."""
    present = [d for d in DEFAULT_PATHS
               if os.path.isdir(os.path.join(root, d))]
    ctxs = []
    for path in iter_py_files(present, root):
        try:
            ctxs.append(FileContext(path, root))
        except SyntaxError:
            continue  # the lint run itself reports parse errors
    return Scan(ctxs, root)


def _wants_scan(rules, selected) -> bool:
    """True when any selected rule overrides `check_scan` — only those
    can see across call boundaries, so only they need the subset-scan
    soundness machinery."""
    return any(type(rules[r]).check_scan is not Rule.check_scan
               for r in (selected or list(rules)))


def _full_set_ambiguous(scan: Scan) -> frozenset:
    """Function names the FULL scan set defines more than once. A
    subset scan must refuse to uniqueness-resolve these — with the
    other definition's file outside the subset the name LOOKS unique
    and would resolve to the wrong def, producing phantom findings
    tier-1 never emits (core.CallGraph docstring)."""
    return frozenset(name for name, hits in scan.graph.by_name.items()
                     if len(hits) > 1)


def summary_scope(root: str, changed_rel: List[str]
                  ) -> "tuple[List[str], frozenset]":
    """The context a `--changed` subset scan needs to agree with the
    full scan: (extra_files, ambiguous_names).

    `extra_files` is the TRANSITIVE closure of caller files above the
    diff (a changed body's new effect propagates up arbitrarily many
    summary hops — A→B→C with C growing a collective indicts a
    divergent call in A) plus the transitive callee files below the
    diff and below every pulled-in caller (a call site can only be
    judged with its callee's full summary CHAIN in the scan set).
    Leaf-ish diffs stay cheap; a hub-file diff honestly approaches the
    full scan, which is the soundness floor. `ambiguous_names` is the
    subset-resolution fence (`_full_set_ambiguous`)."""
    scan = _parse_default_set(root)
    changed = set(changed_rel)
    fwd: dict = {}
    rev: dict = {}
    for fn in scan.functions:
        for callee in scan.graph.callees(fn):
            if callee.ctx.rel != fn.ctx.rel:
                fwd.setdefault(fn.ctx.rel, set()).add(callee.ctx.rel)
                rev.setdefault(callee.ctx.rel, set()).add(fn.ctx.rel)
    out: set = set()
    frontier = set(changed)
    while frontier:  # transitive callers
        frontier = {caller for f in frontier
                    for caller in rev.get(f, ())
                    if caller not in out and caller not in changed}
        out |= frontier
    seen = set(changed) | out
    frontier = set(seen)
    while frontier:  # transitive callees (of the diff AND its callers)
        frontier = {callee for f in frontier
                    for callee in fwd.get(f, ())
                    if callee not in seen}
        seen |= frontier
        out |= frontier
    return sorted(out - changed), _full_set_ambiguous(scan)


def to_sarif(new, rules: Dict[str, object], grandfathered: int,
             stale: List[dict]) -> dict:
    """Minimal SARIF 2.1.0: one run, the registered rules as the tool
    driver's rule table, one result per NEW finding (grandfathered /
    stale counts ride in run properties — SARIF consumers only need
    the actionable set)."""
    return {
        "$schema": "https://json.schemastore.org/sarif-2.1.0.json",
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "graftlint",
                "informationUri":
                    "https://example.invalid/graftlint#static-analysis",
                "rules": [{"id": name,
                           "shortDescription": {"text": rule.description}}
                          for name, rule in sorted(rules.items())],
            }},
            "results": [{
                "ruleId": f.rule,
                "level": "warning",
                "message": {"text": f.message + (
                    f" ({f.detail})" if f.detail else "")},
                "locations": [{"physicalLocation": {
                    "artifactLocation": {"uri": f.path},
                    "region": {"startLine": max(1, f.line)},
                }}],
            } for f in new],
            "properties": {"grandfathered": grandfathered,
                           "stale_baseline": stale},
        }],
    }


def main(argv: List[str] = None) -> int:
    rules = all_rules()
    p = argparse.ArgumentParser(
        prog="python -m tools.graftlint",
        description="repo-aware static analysis (see README.md "
                    "'Static analysis')")
    p.add_argument("paths", nargs="*", default=list(DEFAULT_PATHS),
                   help=f"files/dirs to scan (default: "
                        f"{' '.join(DEFAULT_PATHS)})")
    p.add_argument("--format", choices=["text", "json", "sarif"],
                   default="text")
    p.add_argument("--rules", default=None,
                   help="comma-separated subset of: "
                        + ", ".join(sorted(rules)))
    p.add_argument("--baseline", default=baseline_mod.DEFAULT_BASELINE,
                   help="baseline file (default: repo-root "
                        "graftlint_baseline.json)")
    p.add_argument("--no-baseline", action="store_true",
                   help="report every finding, grandfathered or not")
    p.add_argument("--changed", action="store_true",
                   help="lint only files changed vs --base (git diff "
                        "+ untracked) — the fast pre-commit path")
    p.add_argument("--base", default="HEAD",
                   help="base ref for --changed (default: HEAD)")
    p.add_argument("--write-baseline", action="store_true",
                   help="rewrite the baseline from this run's findings "
                        "(refuses serving/ and obs/ entries) and exit 0")
    p.add_argument("--root", default=REPO_ROOT, help=argparse.SUPPRESS)
    args = p.parse_args(argv)

    selected = None
    if args.rules:
        selected = [r.strip() for r in args.rules.split(",") if r.strip()]
        unknown = [r for r in selected if r not in rules]
        if unknown:
            print(f"unknown rule(s): {', '.join(unknown)} "
                  f"(have: {', '.join(sorted(rules))})", file=sys.stderr)
            return 2

    ambiguous: frozenset = frozenset()
    if args.changed:
        if args.paths != list(DEFAULT_PATHS):
            print("--changed computes its own file list; drop the "
                  "path arguments", file=sys.stderr)
            return 2
        if args.write_baseline:
            print("--write-baseline needs the full scan (a changed-"
                  "only baseline would drop every other entry)",
                  file=sys.stderr)
            return 2
        try:
            args.paths = changed_py_files(args.root, args.base)
        except (OSError, subprocess.CalledProcessError) as e:
            print(f"--changed: git failed: {e}", file=sys.stderr)
            return 2
        if not args.paths:
            if args.format == "json":
                print(json.dumps({"findings": [], "grandfathered": 0,
                                  "stale_baseline": []}, indent=2))
            elif args.format == "sarif":
                print(json.dumps(to_sarif([], rules, 0, []), indent=2))
            else:
                print(f"graftlint: no changed .py files vs {args.base}"
                      " — 0 findings")
            return 0
        # summary-aware gate (module docstring): a changed body can
        # change findings any number of summary hops up, and a changed
        # call site needs its callee summary chain present — re-lint
        # the transitive caller/callee files too, refusing subset-only
        # uniqueness resolution. Skipped entirely when no selected
        # rule consults the scan (a per-file-rules-only run can't see
        # across call boundaries, so the expansion would only slow the
        # fast path).
        if _wants_scan(rules, selected):
            extra, ambiguous = summary_scope(args.root, args.paths)
            if extra and args.format == "text":
                print(f"graftlint: --changed re-linting {len(extra)} "
                      f"caller/callee file(s) too "
                      "(summary-aware gate)")
            args.paths = args.paths + extra
    elif sorted(args.paths) != sorted(DEFAULT_PATHS) \
            and _wants_scan(rules, selected):
        # a path-scoped scan is a subset scan too: without the fence
        # it could uniqueness-resolve a name the full scan set defines
        # twice (the other file being outside the given paths) and
        # emit phantom findings tier-1 never shows
        ambiguous = _full_set_ambiguous(_parse_default_set(args.root))

    try:
        findings = run_lint(args.paths, root=args.root, rules=selected,
                            ambiguous_names=ambiguous)
    except FileNotFoundError as e:
        print(e, file=sys.stderr)
        return 2

    if args.write_baseline:
        if selected is not None or sorted(args.paths) != sorted(
                DEFAULT_PATHS):
            # a partial scan would overwrite the baseline with its
            # subset, silently deleting every entry outside the scope
            print("--write-baseline requires a full default scan "
                  "(no --rules, no path arguments) — a partial scan "
                  "would drop out-of-scope baseline entries",
                  file=sys.stderr)
            return 2
        refused = baseline_mod.write(findings, args.baseline)
        print(f"baseline: wrote {len(findings) - len(refused)} "
              f"entr{'y' if len(findings) - len(refused) == 1 else 'ies'}"
              f" -> {args.baseline}")
        for f in refused:
            print(f"REFUSED (fix, don't baseline): {f.render()}")
        return 1 if refused else 0

    entries = [] if args.no_baseline else baseline_mod.load(args.baseline)
    # On a SCOPED scan (path or rule subset), out-of-scope baseline
    # entries are simply not re-checked — comparing them against
    # partial findings would misreport every one as stale ("fixed").
    # In scope = the entry's rule ran AND its path was scanned (or the
    # run actually produced findings for that path — repo-wide rules
    # emit root-file findings like README.md regardless of the path
    # args). The full default scan skips the filter so staleness
    # reporting stays complete where the baseline is actually written.
    full_scope = selected is None and sorted(args.paths) == sorted(
        DEFAULT_PATHS)
    if entries and not full_scope:
        scanned = {os.path.relpath(p, args.root).replace(os.sep, "/")
                   for p in iter_py_files(args.paths, args.root)}
        produced = {f.path for f in findings}
        rules_run = set(selected) if selected else set(rules)
        entries = [e for e in entries
                   if e.get("rule") in rules_run
                   and (e.get("path") in scanned
                        or e.get("path") in produced)]
    new, old, stale = baseline_mod.split(findings, entries)

    if args.format == "json":
        print(json.dumps({
            "findings": [f.to_json() for f in new],
            "grandfathered": len(old),
            "stale_baseline": stale,
        }, indent=2))
    elif args.format == "sarif":
        print(json.dumps(to_sarif(new, rules, len(old), stale),
                         indent=2))
    else:
        for f in new:
            print(f.render())
        if stale:
            print(f"note: {len(stale)} stale baseline entr"
                  f"{'y' if len(stale) == 1 else 'ies'} (fixed "
                  "findings — regenerate with --write-baseline):")
            for e in stale:
                print(f"  {e.get('path')}: {e.get('rule')}: "
                      f"{e.get('message')}")
        print(f"graftlint: {len(new)} finding"
              f"{'' if len(new) == 1 else 's'}"
              f" ({len(old)} grandfathered, "
              f"{len(findings)} total, "
              f"rules: {len(selected or rules)})")
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
