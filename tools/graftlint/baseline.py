"""Baseline (grandfathered findings) handling.

The checked-in `graftlint_baseline.json` lists findings that predate a
rule (or are deliberate and too structural for an inline suppression —
e.g. the predict path's result fetch). Matching is line-number-FREE
(rule + path + symbol + message), so editing an unrelated part of a
file neither resurrects nor silently grows the grandfathered set.

Workflow:
  - new finding -> fix it, suppress it inline (with a reason), or — for
    pre-existing debt only — add it with `--write-baseline` and review
    the diff;
  - fixed finding -> its entry goes STALE; the CLI reports stale
    entries so the baseline only ever shrinks (`--write-baseline`
    drops them).

Policy (ISSUE 4, extended by ISSUE 5): the baseline must stay EMPTY
for `code2vec_tpu/serving/`, `code2vec_tpu/obs/` and
`code2vec_tpu/training/` — the threaded serving layer, the telemetry
registry and the training subsystem (which now hosts the async
checkpoint writer thread) are exactly where these hazard classes are
bugs, not debt. tests/test_graftlint.py enforces that.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Sequence, Tuple

from tools.graftlint.core import Finding, REPO_ROOT

DEFAULT_BASELINE = os.path.join(REPO_ROOT, "graftlint_baseline.json")

# baselining is forbidden under these trees (ISSUE 4 acceptance;
# training/ added with the async checkpoint writer — ISSUE 5; ops/
# with the fused sparse-update kernel — ISSUE 8; parallel/ with the
# multi-host burndown — ISSUE 9: the distribution layer ships
# lint-clean, fetch_global is a sanctioned seam not a suppression;
# resilience/ with the fault/retry layer — ISSUE 10: the subsystem
# whose whole job is not losing errors may never grandfather one)
NO_BASELINE_PREFIXES = ("code2vec_tpu/serving/", "code2vec_tpu/obs/",
                        "code2vec_tpu/training/", "code2vec_tpu/ops/",
                        "code2vec_tpu/parallel/",
                        "code2vec_tpu/resilience/")


def _entry(f: Finding) -> Dict[str, str]:
    return {"rule": f.rule, "path": f.path, "symbol": f.symbol,
            "message": f.message}


def _entry_key(e: Dict[str, str]) -> Tuple[str, str, str, str]:
    return (e.get("rule", ""), e.get("path", ""), e.get("symbol", ""),
            e.get("message", ""))


def load(path: str = DEFAULT_BASELINE) -> List[Dict[str, str]]:
    if not os.path.exists(path):
        return []
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    return list(data.get("findings", []))


def split(findings: Sequence[Finding], entries: Sequence[Dict[str, str]]
          ) -> Tuple[List[Finding], List[Finding], List[Dict[str, str]]]:
    """-> (new, grandfathered, stale_entries). Duplicate-aware: N
    identical findings need N baseline entries (a second instance of a
    grandfathered hazard is NEW)."""
    budget: Dict[tuple, int] = {}
    for e in entries:
        budget[_entry_key(e)] = budget.get(_entry_key(e), 0) + 1
    new: List[Finding] = []
    old: List[Finding] = []
    for f in findings:
        k = f.key()
        if budget.get(k, 0) > 0:
            budget[k] -= 1
            old.append(f)
        else:
            new.append(f)
    stale = []
    for e in entries:
        k = _entry_key(e)
        if budget.get(k, 0) > 0:
            budget[k] -= 1
            stale.append(e)
    return new, old, stale


def write(findings: Sequence[Finding],
          path: str = DEFAULT_BASELINE) -> List[Finding]:
    """Write the baseline from a finding list, REFUSING entries under
    the no-baseline trees (those must be fixed or inline-suppressed).
    Returns the refused findings."""
    refused = [f for f in findings
               if f.path.startswith(NO_BASELINE_PREFIXES)]
    kept = [f for f in findings
            if not f.path.startswith(NO_BASELINE_PREFIXES)]
    data = {
        "_comment": (
            "graftlint grandfathered findings. Matched by "
            "rule+path+symbol+message (line-insensitive). Fix entries "
            "and regenerate with --write-baseline; never baseline "
            f"findings under {', '.join(NO_BASELINE_PREFIXES)} "
            "(tests/test_graftlint.py enforces this)."),
        "findings": [_entry(f) for f in kept],
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=2, sort_keys=False)
        f.write("\n")
    return refused
