"""swallowed-error: a broad except that does NOTHING erases the only
evidence a failure happened.

The resilience layer (ISSUE 10) makes errors load-bearing: retries
classify them, the supervisor restarts on them, sticky errors surface
them at barriers. A `except Exception: pass` (or bare `except:` /
`except BaseException:` with an empty body, or except-and-`continue`)
deletes that signal — the run limps on and the postmortem finds
nothing. This rule flags exactly the DO-NOTHING shape:

  - the handler catches broadly: bare `except:`, `Exception`,
    `BaseException` (directly or inside a tuple);
  - AND its body consists solely of `pass` / `continue` / a bare
    constant expression (docstring, `...`) — no raise, no logging, no
    fallback assignment, no error stash.

Anything that DOES something with the error is out of scope by
construction: `self._error = e` (the sticky-error stash), `return
fallback`, a log call, a re-raise — none of those bodies are
do-nothing. Narrow excepts (`except queue.Full: continue`) are fine:
naming the exception IS the documentation.

Sanctioned teardown paths: handlers inside functions named like
teardown (`close`, `stop`, `shutdown`, `teardown`, `__exit__`,
`__del__`, `drain*`/`_drain*`, `cleanup`/`_cleanup`) or anywhere under
a `finally:` block — best-effort cleanup legitimately swallows, and the
original error (if any) is already in flight there.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from tools.graftlint.core import (FileContext, Finding, Rule,
                                  dotted_name, register)

RULE = "swallowed-error"

_BROAD = frozenset({"Exception", "BaseException"})

_TEARDOWN_NAMES = frozenset({"close", "stop", "shutdown", "teardown",
                             "__exit__", "__del__", "cleanup",
                             "_cleanup"})


def _is_broad(exc_type) -> bool:
    """Does this handler's type catch Exception or wider?"""
    if exc_type is None:
        return True  # bare except:
    if isinstance(exc_type, ast.Tuple):
        return any(_is_broad(e) for e in exc_type.elts)
    name = dotted_name(exc_type)
    return name in _BROAD or name in {f"builtins.{b}" for b in _BROAD}


def _is_do_nothing(body) -> bool:
    """True when the handler body neither acts on nor records the
    error: only pass/continue/bare-constant statements."""
    for stmt in body:
        if isinstance(stmt, (ast.Pass, ast.Continue)):
            continue
        if isinstance(stmt, ast.Expr) \
                and isinstance(stmt.value, ast.Constant):
            continue  # docstring / `...`
        return False
    return True


def _teardown_func(name: str) -> bool:
    return (name in _TEARDOWN_NAMES or name.startswith("drain")
            or name.startswith("_drain"))


class _Walker:
    """Tree walk tracking the enclosing function name and whether the
    node executes inside a `finally:` block (both sanction a swallow)."""

    def __init__(self, ctx: FileContext):
        self.ctx = ctx
        self.findings: List[Finding] = []

    def visit(self, node: ast.AST, func: str = "<module>",
              in_finally: bool = False) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # a def INSIDE a finally block is a fresh scope — its body
            # runs whenever it is called, not as teardown
            func, in_finally = node.name, False
        elif isinstance(node, ast.Try):
            for stmt in node.body + node.orelse:
                self.visit(stmt, func, in_finally)
            for handler in node.handlers:
                self._check_handler(handler, func, in_finally)
                for stmt in handler.body:
                    self.visit(stmt, func, in_finally)
            for stmt in node.finalbody:
                self.visit(stmt, func, True)
            return
        for child in ast.iter_child_nodes(node):
            self.visit(child, func, in_finally)

    def _check_handler(self, handler: ast.ExceptHandler, func: str,
                       in_finally: bool) -> None:
        if not _is_broad(handler.type):
            return
        if not _is_do_nothing(handler.body):
            return
        if in_finally or _teardown_func(func):
            return
        what = "bare except:" if handler.type is None else \
            f"except {_render(handler.type)}:"
        self.findings.append(Finding(
            rule=RULE, path=self.ctx.rel, line=handler.lineno,
            symbol=func,
            message=(f"{what} swallows the error with no log, "
                     "re-raise, or fallback — the failure signal the "
                     "resilience layer routes on is erased; log it, "
                     "narrow the except, stash it, or move the "
                     "swallow into a sanctioned teardown path")))


def _render(exc_type) -> str:
    if isinstance(exc_type, ast.Tuple):
        return "(" + ", ".join(_render(e) for e in exc_type.elts) + ")"
    return dotted_name(exc_type) or "<?>"


@register
class SwallowedErrorRule(Rule):
    name = RULE
    description = ("broad `except Exception/BaseException/bare` whose "
                   "body only passes/continues — the error is erased "
                   "without log, re-raise, or fallback (teardown "
                   "paths and finally blocks sanctioned)")

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        w = _Walker(ctx)
        w.visit(ctx.tree)
        return w.findings
