"""lock-discipline: attributes mutated both inside and outside a class's
lock.

A lightweight static race detector for the threaded layers (the serving
queue/batcher, the thread-safe telemetry registry): once a class owns a
lock, EVERY mutation of a given attribute should agree about holding
it. An attribute written under `with self._lock` in one method and
bare in another is exactly the race pytest only catches once in a
thousand runs (the PR-3 batcher lifecycle race was this shape).

Scope: any class that assigns a `threading.Lock/RLock/Condition/
Semaphore` (or calls `make_threadsafe`-style installers — detected as a
lock-ish-named self attribute) anywhere in its body. Classes with no
lock are skipped entirely — a single-threaded dataclass mutating its
own fields is not a finding (obs.TimerStat is the canonical
false-positive: ITS thread safety is the OWNING registry's lock).

A mutation is: assignment / augmented assignment to `self.x` or
`self.x[...]`, or a mutator-method call (`append`, `popleft`,
`clear`, ...) on `self.x`. "Inside the lock" means lexically within a
`with` whose context manager is a lock-ish-named self attribute
(`self._lock`, `self._cond`, `self._lifecycle_lock`) or call
(`self._guard()`). Exemptions: `__init__`-family methods (construction
is single-threaded by convention), the lock attributes themselves.

Reads are deliberately out of scope: lock-free reads of
monotonic/atomic flags are an idiom this codebase uses on purpose
(`MicroBatcher.running`); racy READ bugs need dynamic tools.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Set, Tuple

from tools.graftlint.core import (FileContext, Finding, Rule, call_name,
                                  is_self_attr, register)

RULE = "lock-discipline"

_LOCK_CTORS = frozenset({"Lock", "RLock", "Condition", "Semaphore",
                         "BoundedSemaphore"})
# anchored to name SEGMENTS: `_lifecycle_lock`, `_cond`, `_guard` are
# locks; `_retry_seconds` ('cond') and `_assembled` ('sem') are not
_LOCKISH_RE = re.compile(
    r"(^|_)(lock|cond|mutex|guard|sem|semaphore)s?(_|$)", re.IGNORECASE)
_INIT_METHODS = frozenset({"__init__", "__post_init__", "__new__",
                           "__init_subclass__"})
_MUTATORS = frozenset({
    "append", "appendleft", "extend", "extendleft", "insert", "pop",
    "popleft", "popitem", "remove", "clear", "add", "discard",
    "update", "setdefault", "move_to_end", "sort", "reverse", "put",
    "put_nowait",
})


def _is_lock_ctor(node: ast.AST) -> bool:
    return isinstance(node, ast.Call) and call_name(node) in _LOCK_CTORS


def _lockish_with_item(item: ast.withitem) -> bool:
    """`with self._lock:` / `with self._cond:` / `with self._guard():`"""
    expr = item.context_expr
    if isinstance(expr, ast.Call):
        expr = expr.func
    attr = is_self_attr(expr)
    return attr is not None and bool(_LOCKISH_RE.search(attr))


def _mutated_attrs(node: ast.AST) -> List[Tuple[str, int]]:
    """Every (attr, line) this statement mutates on self — a
    tuple-unpack (`a, self.x = ..., ...`) can mutate several."""
    targets: List[ast.AST] = []
    if isinstance(node, ast.Assign):
        if _is_lock_ctor(node.value):
            # `self.x = threading.Lock()` installs the lock, it does
            # not race on it (pass 1 collects it as a lock attr)
            return []
        targets = list(node.targets)
    elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
        if isinstance(node, ast.AnnAssign) and node.value is None:
            return []
        targets = [node.target]
    elif isinstance(node, ast.Call) and isinstance(
            node.func, ast.Attribute) and node.func.attr in _MUTATORS:
        attr = is_self_attr(node.func.value)
        return [(attr, node.lineno)] if attr is not None else []
    # flatten tuple/list unpacking targets
    out: List[Tuple[str, int]] = []
    while targets:
        tgt = targets.pop()
        if isinstance(tgt, (ast.Tuple, ast.List)):
            targets.extend(tgt.elts)
            continue
        if isinstance(tgt, ast.Starred):
            targets.append(tgt.value)
            continue
        while isinstance(tgt, ast.Subscript):  # self.x[k] = v mutates x
            tgt = tgt.value
        attr = is_self_attr(tgt)
        if attr is not None:
            out.append((attr, node.lineno))
    return out


class _ClassScan(ast.NodeVisitor):
    """Collect per-attribute (locked_lines, unlocked_lines) over every
    method of one class."""

    def __init__(self):
        self.lock_attrs: Set[str] = set()
        self.locked: Dict[str, List[int]] = {}
        self.unlocked: Dict[str, List[int]] = {}
        self.sites: Dict[str, List[str]] = {}
        self._with_depth = 0
        self._method = ""

    def scan_method(self, node: ast.FunctionDef) -> None:
        self._method = node.name
        self._with_depth = 0
        for child in node.body:
            self.visit(child)

    def visit_With(self, node):
        locked = any(_lockish_with_item(i) for i in node.items)
        if locked:
            self._with_depth += 1
        self.generic_visit(node)
        if locked:
            self._with_depth -= 1

    visit_AsyncWith = visit_With

    def visit_FunctionDef(self, node):
        # a nested def's body runs at CALL time under whoever calls it;
        # don't attribute the enclosing method's lock context to it
        depth, self._with_depth = self._with_depth, 0
        for child in node.body:
            self.visit(child)
        self._with_depth = depth

    visit_AsyncFunctionDef = visit_FunctionDef

    def _record(self, node: ast.AST) -> None:
        for attr, line in _mutated_attrs(node):
            if _LOCKISH_RE.search(attr):
                self.lock_attrs.add(attr)
                continue
            bucket = self.locked if self._with_depth > 0 \
                else self.unlocked
            bucket.setdefault(attr, []).append(line)
            self.sites.setdefault(attr, []).append(
                f"{self._method}:{line}"
                f"{' (locked)' if self._with_depth > 0 else ''}")

    def visit_Assign(self, node):
        if _is_lock_ctor(node.value):
            for tgt in node.targets:
                attr = is_self_attr(tgt)
                if attr is not None:
                    self.lock_attrs.add(attr)
        self._record(node)
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        self._record(node)
        self.generic_visit(node)

    def visit_AnnAssign(self, node):
        if node.value is not None and _is_lock_ctor(node.value):
            attr = is_self_attr(node.target)
            if attr is not None:
                self.lock_attrs.add(attr)
        self._record(node)
        self.generic_visit(node)

    def visit_Call(self, node):
        self._record(node)
        self.generic_visit(node)


@register
class LockDisciplineRule(Rule):
    name = RULE
    description = ("in lock-owning classes, attributes mutated both "
                   "inside and outside `with self._lock` blocks — the "
                   "static shape of a data race")

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            methods = [m for m in node.body
                       if isinstance(m, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))]
            scan = _ClassScan()
            # pass 1: lock declarations anywhere in the class —
            # `self._lock = threading.Lock()` in any method (__init__
            # included) OR the class-attribute idiom
            # (`_lock = threading.Lock()` in the class body, still
            # taken as `with self._lock:`)
            for item in node.body:
                val = getattr(item, "value", None)
                if isinstance(item, (ast.Assign, ast.AnnAssign)) and \
                        val is not None and _is_lock_ctor(val):
                    tgts = item.targets if isinstance(item, ast.Assign) \
                        else [item.target]
                    scan.lock_attrs.update(
                        t.id for t in tgts if isinstance(t, ast.Name))
            for m in methods:
                for n in ast.walk(m):
                    val = getattr(n, "value", None)
                    if isinstance(n, (ast.Assign, ast.AnnAssign)) and \
                            val is not None and _is_lock_ctor(val):
                        tgts = n.targets if isinstance(n, ast.Assign) \
                            else [n.target]
                        for tgt in tgts:
                            attr = is_self_attr(tgt)
                            if attr is not None:
                                scan.lock_attrs.add(attr)
            # pass 2: mutation sites — construction methods exempt
            # (single-threaded by convention; racing on a half-built
            # object is a different bug class)
            for m in methods:
                if m.name not in _INIT_METHODS:
                    scan.scan_method(m)
            if not scan.lock_attrs:
                continue
            for attr in sorted(set(scan.locked) & set(scan.unlocked)):
                sites = ", ".join(scan.sites.get(attr, []))
                line = scan.unlocked[attr][0]
                findings.append(Finding(
                    rule=RULE, path=ctx.rel, line=line,
                    symbol=f"{node.name}.{attr}",
                    message=(f"self.{attr} is mutated both under "
                             f"{'/'.join(sorted(scan.lock_attrs))} and "
                             f"without it ({sites}) — take the lock at "
                             "every mutation site or document the "
                             "attribute as single-threaded")))
        return findings
