"""config-drift: the Config dataclass, its argparse overlay, and the
README flag docs must agree.

The flag surface is the product (reference-parity CLI, SURVEY.md §2
L6), and it drifts in four distinct ways, each of which has bitten a
round or would have:

  - dead flag: `add_argument` whose dest `load_from_args` never reads —
    the flag parses and silently does nothing;
  - phantom dest: `ns.X` read in `load_from_args` with no matching
    `add_argument` — AttributeError the first time that path runs;
  - unknown attr: `verify()` / any method touching `self.UPPERCASE`
    that is not a dataclass field — a typo'd guard that guards nothing;
  - doc drift: an argparse flag README never mentions, or a flag
    documented in README's knobs section that argparse no longer
    accepts.

Plus the completeness invariant: every UPPERCASE Config field is
either assigned from `ns.*` in `load_from_args` (CLI-reachable) or
listed in `CONFIG_CONSTANTS` (config.py's explicit no-CLI register) —
adding a new attr forces a conscious choice between a flag and a
documented constant.

README matching: a flag counts as documented if it appears ANYWHERE in
README.md (word-boundary match). The reverse direction (stale docs)
only polices fenced code blocks of sections whose heading mentions
"flags"/"knobs" — prose and tool-CLI examples (`--requests`, `--n`)
are other programs' surfaces.

All parsing is AST/text — config.py is never imported.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Iterable, List, Optional, Sequence, Set, Tuple

from tools.graftlint.core import FileContext, Finding, Rule, register

RULE = "config-drift"

_FLAG_RE = re.compile(r"(?<![\w-])--([A-Za-z][\w-]*)")
_HEADING_RE = re.compile(r"^#{2,3}\s")
_FLAG_SECTION_RE = re.compile(r"^#{2,3}\s.*\b(flags|knobs)\b",
                              re.IGNORECASE)


def _const_str_set(node: ast.AST) -> Optional[Set[str]]:
    """Literal str elements of a set/tuple/list/frozenset(...) node."""
    if isinstance(node, ast.Call) and getattr(
            node.func, "id", "") == "frozenset" and node.args:
        node = node.args[0]
    if isinstance(node, (ast.Set, ast.Tuple, ast.List)):
        out = set()
        for e in node.elts:
            if not (isinstance(e, ast.Constant)
                    and isinstance(e.value, str)):
                return None
            out.add(e.value)
        return out
    return None


class ConfigModel:
    """Everything config-drift needs, lifted from config.py's AST."""

    def __init__(self, tree: ast.Module):
        self.fields: Set[str] = set()          # UPPERCASE dataclass attrs
        self.constants: Set[str] = set()       # CONFIG_CONSTANTS entries
        self.flags: List[Tuple[str, int]] = []  # (--flag, line)
        self.dests: List[Tuple[str, int]] = []  # (dest, line)
        self.ns_reads: Set[str] = set()        # ns.X in load_from_args
        self.cfg_writes: Set[str] = set()      # cfg.X in load_from_args
        self.self_refs: List[Tuple[str, int]] = []  # self.UPPER anywhere
        self._walk(tree)

    def _walk(self, tree: ast.Module) -> None:
        for node in tree.body:
            if isinstance(node, ast.Assign) and any(
                    getattr(t, "id", "") == "CONFIG_CONSTANTS"
                    for t in node.targets):
                self.constants = _const_str_set(node.value) or set()
            if isinstance(node, ast.ClassDef) and node.name == "Config":
                self._walk_config(node)

    def _walk_config(self, cls: ast.ClassDef) -> None:
        for item in cls.body:
            if isinstance(item, ast.AnnAssign) and isinstance(
                    item.target, ast.Name):
                name = item.target.id
                if name.isupper():
                    self.fields.add(name)
            elif isinstance(item, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                if item.name == "arguments_parser":
                    self._walk_parser(item)
                elif item.name == "load_from_args":
                    self._walk_loader(item)
                else:
                    self._walk_method(item)

    def _walk_parser(self, fn: ast.FunctionDef) -> None:
        for node in ast.walk(fn):
            if not (isinstance(node, ast.Call) and getattr(
                    node.func, "attr", "") == "add_argument"):
                continue
            long_flag = None
            for a in node.args:
                if isinstance(a, ast.Constant) and isinstance(
                        a.value, str) and a.value.startswith("--"):
                    long_flag = a.value
            if long_flag is None:
                continue  # short-only options have no doc contract
            self.flags.append((long_flag, node.lineno))
            dest = None
            for kw in node.keywords:
                if kw.arg == "dest" and isinstance(
                        kw.value, ast.Constant):
                    dest = kw.value.value
            if dest is None:
                dest = long_flag.lstrip("-").replace("-", "_")
            self.dests.append((dest, node.lineno))

    def _walk_loader(self, fn: ast.FunctionDef) -> None:
        for node in ast.walk(fn):
            if isinstance(node, ast.Attribute) and isinstance(
                    node.value, ast.Name):
                if node.value.id == "ns":
                    self.ns_reads.add(node.attr)
                elif node.value.id == "cfg" and isinstance(
                        node.ctx, ast.Store):
                    self.cfg_writes.add(node.attr)

    def _walk_method(self, fn: ast.FunctionDef) -> None:
        for node in ast.walk(fn):
            if isinstance(node, ast.Attribute) and isinstance(
                    node.value, ast.Name) and node.value.id == "self" \
                    and node.attr.isupper():
                self.self_refs.append((node.attr, node.lineno))


def _readme_flags(readme_text: str) -> Tuple[Set[str], Set[str]]:
    """-> (flags mentioned anywhere, flags inside flag-section fences)."""
    anywhere = {f"--{m}" for m in _FLAG_RE.findall(readme_text)}
    fenced: Set[str] = set()
    in_section = in_fence = False
    for line in readme_text.splitlines():
        if _HEADING_RE.match(line):
            in_section = bool(_FLAG_SECTION_RE.match(line))
            in_fence = False
            continue
        if line.startswith("```"):
            in_fence = not in_fence
            continue
        if in_section and in_fence:
            fenced.update(f"--{m}" for m in _FLAG_RE.findall(line))
    return anywhere, fenced


def check_config_drift(config_path: str, readme_path: str,
                       rel_config: str = "code2vec_tpu/config.py",
                       rel_readme: str = "README.md"
                       ) -> List[Finding]:
    """The whole rule as a path-in/findings-out function so fixture
    tests can aim it at a miniature config/README pair."""
    with open(config_path, "r", encoding="utf-8") as f:
        model = ConfigModel(ast.parse(f.read()))
    readme_text = ""
    if os.path.exists(readme_path):
        with open(readme_path, "r", encoding="utf-8") as f:
            readme_text = f.read()
    documented, fenced = _readme_flags(readme_text)
    findings: List[Finding] = []

    def add(line: int, symbol: str, message: str,
            path: str = rel_config) -> None:
        findings.append(Finding(rule=RULE, path=path, line=line,
                                symbol=symbol, message=message))

    for dest, line in model.dests:
        if dest not in model.ns_reads:
            add(line, f"--{dest}",
                f"dead flag: dest '{dest}' is never read in "
                "load_from_args — the flag parses and silently does "
                "nothing")
    dest_names = {d for d, _ in model.dests}
    for read in sorted(model.ns_reads - dest_names):
        add(0, f"ns.{read}",
            f"phantom dest: load_from_args reads ns.{read} but no "
            "add_argument declares it — AttributeError when parsing")
    for attr, line in model.self_refs:
        if attr not in model.fields:
            add(line, f"self.{attr}",
                f"unknown attr: self.{attr} is not a Config dataclass "
                "field (typo'd verify rule guards nothing)")
    for flag, line in model.flags:
        if flag not in documented:
            add(line, flag,
                f"undocumented flag: {flag} is not mentioned anywhere "
                f"in {rel_readme}")
    known_flags = {f for f, _ in model.flags}
    for flag in sorted(fenced - known_flags):
        add(0, flag,
            f"stale doc: {flag} appears in {rel_readme}'s flag docs "
            "but argparse does not accept it", path=rel_readme)
    for field in sorted(model.fields - model.cfg_writes
                        - model.constants):
        add(0, field,
            f"unwired attr: Config.{field} has no CLI path "
            "(load_from_args never assigns it) and is not listed in "
            "CONFIG_CONSTANTS — add a flag or register the constant")
    for name in sorted(model.constants & model.cfg_writes):
        add(0, name,
            f"Config.{name} is listed in CONFIG_CONSTANTS but IS "
            "assigned in load_from_args — drop it from the constants "
            "register")
    for name in sorted(model.constants - model.fields):
        add(0, name,
            f"CONFIG_CONSTANTS names '{name}' which is not a Config "
            "dataclass field")
    return findings


@register
class ConfigDriftRule(Rule):
    name = RULE
    description = ("Config fields <-> argparse flags <-> README docs "
                   "consistency (dead flags, phantom dests, typo'd "
                   "verify attrs, un-/stale-documented flags, unwired "
                   "fields)")

    def check_repo(self, ctxs: Sequence[FileContext],
                   root: str) -> Iterable[Finding]:
        config_path = os.path.join(root, "code2vec_tpu", "config.py")
        if not os.path.exists(config_path):
            return ()
        return check_config_drift(
            config_path, os.path.join(root, "README.md"))
