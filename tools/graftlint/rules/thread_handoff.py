"""thread-handoff: an object mutated after being handed to another
thread.

The PR-4 MicroBatcher stop/start race was this shape: a request object
was enqueued for the batcher thread, then the submitting thread kept
mutating it — two owners, no lock, and pytest catches it one run in a
thousand. lock-discipline fences `self.*` attributes; this rule
generalizes the discipline to FLOWED values using the dataflow core's
escape lattice (tools/graftlint/dataflow.py): a local name ESCAPES
when it is

  - passed to `Thread(target=..., args=(...))` (the new thread closes
    over it),
  - `q.put(...)` / `q.put_nowait(...)` (the consumer dequeues it),
  - `executor.submit(f, x)` (the worker receives it),
  - `channel.send(...)` (the SpanChannel-style side channel), or
  - stored to `self.<attr>` in a LOCK-OWNING class (another thread can
    reach it through the shared object);

and a later mutation of the escaped name by the origin thread —
attribute/subscript store, augmented assignment, or a mutator-method
call (`append`, `update`, `clear`, ...) — OUTSIDE a `with
self._lock:`-style block is the static shape of the race. Rebinding
the name kills the escape (building a fresh item per loop iteration is
the idiom, not a bug); mutating BEFORE the handoff is fine (that is
the fix this rule suggests).

Sub-check ("never raise from the monitor thread", the watchdog/monitor
discipline — ARCHITECTURE.md): a locally-defined function handed to
`Thread(target=...)` where either the thread's `name=` or the
function's own name marks it as a monitor/watchdog loop must not
contain a bare `raise` outside any try/except — an exception kills the
monitor silently and the run loses its liveness detection exactly when
it hangs. Record the failure (telemetry event, sticky error) instead.

Under-reach: only plain local names are tracked (`self` handed as a
bound-method target is the class's own lock-discipline problem, not a
flowed value); unresolvable mutations drop the fact.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Set

from tools.graftlint import dataflow as df
from tools.graftlint.core import (FileContext, Finding, Rule, call_name,
                                  is_self_attr, register)
from tools.graftlint.rules.lock_discipline import (_INIT_METHODS,
                                                   _MUTATORS,
                                                   _is_lock_ctor,
                                                   _lockish_with_item)

RULE = "thread-handoff"

_QUEUE_METHODS = frozenset({"put", "put_nowait"})
_SUBMIT_METHODS = frozenset({"submit"})
_CHANNEL_METHODS = frozenset({"send"})
_MONITORISH = ("monitor", "watchdog", "watcher")


def _lock_owning_classes(tree: ast.AST) -> Set[str]:
    """Class names that install a threading lock anywhere in their
    body (the lock-discipline scope rule: no lock, no cross-thread
    mutation contract to enforce)."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        for n in ast.walk(node):
            val = getattr(n, "value", None)
            if isinstance(n, (ast.Assign, ast.AnnAssign)) \
                    and val is not None and _is_lock_ctor(val):
                out.add(node.name)
                break
    return out


def _is_thread_ctor(call: ast.Call) -> bool:
    return call_name(call) in ("Thread", "Timer")


def _thread_name_kwarg(call: ast.Call) -> str:
    for kw in call.keywords:
        if kw.arg == "name" and isinstance(kw.value, ast.Constant) \
                and isinstance(kw.value.value, str):
            return kw.value.value
    return ""


def _unguarded_raises(fn: ast.AST) -> List[ast.Raise]:
    """`raise` statements in `fn` not lexically inside a try that has
    except handlers (those may be deliberate signal-and-catch)."""
    out: List[ast.Raise] = []

    def walk(node: ast.AST, guarded: bool) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef, ast.Lambda)):
                continue
            if isinstance(child, ast.Raise) and not guarded:
                out.append(child)
            if isinstance(child, ast.Try) and child.handlers:
                for stmt in child.body:
                    walk(stmt, True)
                for h in child.handlers:
                    walk(h, guarded)
                for stmt in child.orelse + child.finalbody:
                    walk(stmt, guarded)
                continue
            walk(child, guarded)

    walk(fn, False)
    return out


# state fact per local name: ("escaped", how, line)


class _Flow(df.FlowVisitor):
    def __init__(self, ctx: FileContext, fn: ast.AST, cls: str,
                 lock_classes: Set[str], findings: List[Finding]):
        self.ctx = ctx
        self.fn = fn
        self.cls = cls
        self.owns_lock = cls in lock_classes
        self.findings = findings
        self.qualname = f"{cls}.{fn.name}" if cls else fn.name
        self.lock_depth = 0
        self.local_defs: Dict[str, ast.AST] = {}
        self.flagged: Set[str] = set()
        self.monitor_flagged: Set[str] = set()

    def join_states(self, a, b):
        out = dict(b)
        out.update(a)  # escaped-on-either-path stays escaped
        return out

    # --- escapes ---

    def _escape(self, name: str, how: str, line: int, state) -> None:
        if "." in name or name == "self":
            return  # flowed VALUES only; self.* is lock-discipline's job
        state.setdefault(name, ("escaped", how, line))

    def _check_monitor_target(self, call: ast.Call) -> None:
        target = None
        for kw in call.keywords:
            if kw.arg == "target":
                target = df.dotted(kw.value)
        if not target or target not in self.local_defs:
            return
        tname = _thread_name_kwarg(call).lower()
        monitorish = any(m in tname for m in _MONITORISH) \
            or any(m in target.lower() for m in _MONITORISH)
        if not monitorish or target in self.monitor_flagged:
            return
        for r in _unguarded_raises(self.local_defs[target]):
            self.monitor_flagged.add(target)
            self.findings.append(Finding(
                rule=RULE, path=self.ctx.rel, line=r.lineno,
                symbol=self.qualname,
                detail=f"thread created at line {call.lineno}",
                message=(f"`{target}` runs on a monitor/watchdog "
                         "thread and raises — an unhandled exception "
                         "kills the monitor silently, losing liveness "
                         "detection exactly when the run hangs; "
                         "record the failure (telemetry event, sticky "
                         "error surfaced at the next beat/poll) "
                         "instead of raising")))
            break

    def _process_calls(self, node: ast.AST, state) -> None:
        for call in (n for n in ast.walk(node)
                     if isinstance(n, ast.Call)):
            if _is_thread_ctor(call):
                self._check_monitor_target(call)
                for _kw, d, anode in df.arg_names(call):
                    self._escape(d, "Thread(...)", anode.lineno, state)
                # args=(x, y) / kwargs={...}: the tuple is a literal,
                # the names INSIDE it are what escape
                for kw in call.keywords:
                    if isinstance(kw.value, (ast.Tuple, ast.List,
                                             ast.Dict)):
                        for d, rnode in df.reads(kw.value):
                            self._escape(d.split(".", 1)[0],
                                         "Thread(...)", rnode.lineno,
                                         state)
                continue
            if isinstance(call.func, ast.Attribute):
                m = call.func.attr
                how = None
                if m in _QUEUE_METHODS:
                    how = f".{m}(...)"
                elif m in _SUBMIT_METHODS:
                    how = ".submit(...)"
                elif m in _CHANNEL_METHODS:
                    how = ".send(...)"
                if how is not None:
                    for _kw, d, anode in df.arg_names(call):
                        self._escape(d, how, anode.lineno, state)

    # --- mutations ---

    def _flag_mutation(self, name: str, line: int, state) -> None:
        fact = state.get(name)
        if fact is None or fact[0] != "escaped" \
                or self.lock_depth > 0:
            return
        # one finding per (name, escape site): the loop fixpoint pass
        # re-executes bodies, and a rebind+re-escape at the SAME site
        # must not double-report
        if (name, fact[2]) in self.flagged:
            return
        self.flagged.add((name, fact[2]))
        self.findings.append(Finding(
            rule=RULE, path=self.ctx.rel, line=line,
            symbol=self.qualname,
            detail=f"escaped via {fact[1]} at line {fact[2]}",
            message=(f"`{name}` is mutated after being handed to "
                     f"another thread via {fact[1]} — the receiving "
                     "thread may already own it; mutate before the "
                     "handoff, hand off a copy, or take the class "
                     "lock at both sites")))

    def _process_mutations(self, stmt: ast.AST, state) -> None:
        targets: List[ast.AST] = []
        if isinstance(stmt, ast.Assign):
            targets = stmt.targets
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            targets = [stmt.target]
        for t in targets:
            for base in df.mutated_bases(t):
                if "." not in base:
                    self._flag_mutation(base, stmt.lineno, state)
            # `lst += [...]` on a bare name: for mutable values this is
            # an in-place extend of an object the consumer may already
            # own — the PR-4 race shape (for immutables it is a rebind,
            # and the kill below ends tracking either way)
            if isinstance(stmt, ast.AugAssign) \
                    and isinstance(t, ast.Name):
                self._flag_mutation(t.id, stmt.lineno, state)
        for call in (n for n in ast.walk(stmt)
                     if isinstance(n, ast.Call)):
            if isinstance(call.func, ast.Attribute) \
                    and call.func.attr in _MUTATORS:
                base = df.dotted(call.func.value)
                if base and "." not in base:
                    self._flag_mutation(base, call.lineno, state)

    # --- engine hooks ---

    def on_with(self, stmt, state):
        locked = any(_lockish_with_item(i) for i in stmt.items)
        if locked:
            self.lock_depth += 1
        return locked

    def after_with(self, token, state):
        if token:
            self.lock_depth -= 1

    def on_expr(self, expr, state):
        self._process_calls(expr, state)

    def on_stmt(self, stmt, state):
        self._process_mutations(stmt, state)
        self._process_calls(stmt, state)
        if isinstance(stmt, ast.Assign):
            # attribute-store on a lock-owning class: the value is now
            # reachable by every thread that can see `self`
            # (construction methods exempt — single-threaded by the
            # lock-discipline convention, nobody else sees self yet)
            for t in stmt.targets:
                if self.owns_lock and is_self_attr(t) is not None \
                        and self.fn.name not in _INIT_METHODS:
                    d = df.dotted(stmt.value)
                    if d:
                        self._escape(d, f"self.{is_self_attr(t)} = ...",
                                     stmt.lineno, state)
            for t in stmt.targets:
                for name in df.bound_names(t):
                    if "." not in name:
                        state.pop(name, None)  # rebind kills the escape
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            # after an AugAssign the name may be rebound (immutables) —
            # one report max, then tracking ends
            for name in df.bound_names(stmt.target):
                if "." not in name:
                    state.pop(name, None)

    def on_nested_def(self, node, state):
        self.local_defs[node.name] = node


@register
class ThreadHandoffRule(Rule):
    name = RULE
    description = ("a value mutated after escaping to another thread "
                   "(Thread/queue.put/executor.submit/channel.send/"
                   "shared-attr store) without the class lock; plus "
                   "the never-raise-from-monitor-thread discipline on "
                   "escaped callables")

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        lock_classes = _lock_owning_classes(ctx.tree)
        for fn, cls in df.iter_functions(ctx.tree):
            df.run_flow(fn, _Flow(ctx, fn, cls, lock_classes, findings))
        return findings
