"""spmd-divergence: a collective effect reachable on only SOME
processes of the cohort.

The stack's hardest-won invariant (PR 5's one-in-flight async writer,
PR 9's Gloo cohorts, PR 12's elastic re-form) is that every process
executes the same collective/checkpoint-submit sequence in the same
order — one process skipping (or repeating) a collective deadlocks the
rest inside the rendezvous, a failure mode only the slow multiprocess
chaos tests could see until now. This rule catches the static shape of
that bug: a collective-effect call (dataflow.collective_effect_label —
lax collectives, shard_map regions, jax.distributed init, orbax
checkpoint save/restore, the async writer's submit/wait, or ANY call
whose summary inherits one of those) sitting under PROCESS-DIVERGENT
control:

  - a branch whose test reads per-host identity — `process_index()`,
    `host_id()`, `local_devices()`/`local_device_count()`,
    `getpid()`/`gethostname()`, a name assigned from one of those, or
    a call to a function whose SUMMARY says it returns a per-host
    value (`faults._process_index`, `compat.cohort_world` — the
    interprocedural hop);
  - the remainder of a block after a process-divergent early exit
    (`if process_index(): return` poisons everything below);
  - an `except` handler body — only the processes that raised take it,
    which is exactly the distributed-deadlock retry class (one process
    re-issuing a collective alone);
  - a loop whose trip condition / iterable is per-host.

Branches on `process_count()` / `device_count()` are NOT divergent —
those are cohort-uniform — and neither is per-host data flowing into
tensors (that is the multihost tagging mechanism, jax_model's
`_my_global_rows`).

Sanctioned seams (the audited exceptions, by (qualname, path suffix)):
`distributed_initialize` / `maybe_initialize._init`'s retry — the ONE
place a failed collective is deliberately re-issued, because a failed
INIT left no cohort to desynchronize from (each attempt resets the
distributed state first; the module docstring owns the policy) — and
the process-0 sidecar writers `write_step_checksums` /
`write_step_topology` plus their caller seam in `save_checkpoint`:
pure file IO that runs AFTER the commit rename, so by the time process
0 diverges to write `checksums.json`/`topology.json` every process has
already completed the same collective save (ARCHITECTURE.md
"Summaries: one hop deeper, still never import" has the full
argument). Sanctioned bodies are skipped; CALLS to sanctioned
functions still flag when they sit under divergent control elsewhere —
the audit covers their bodies, not their callers.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Tuple

from tools.graftlint import dataflow as df
from tools.graftlint.core import (Finding, FnInfo, Rule, Scan, register)

RULE = "spmd-divergence"

# (qualname, path-suffix) of the audited seams (module docstring)
_SANCTIONED = frozenset({
    ("distributed_initialize", "parallel/compat.py"),
    ("maybe_initialize", "parallel/distributed.py"),
    ("_init", "parallel/distributed.py"),
    ("save_checkpoint", "training/checkpoint.py"),
    ("write_step_checksums", "training/checkpoint.py"),
    ("write_step_topology", "training/checkpoint.py"),
})


def _is_sanctioned(fn: FnInfo) -> bool:
    return any(fn.qualname == q and fn.ctx.rel.endswith(suffix)
               for q, suffix in _SANCTIONED)


# exception types that depend only on the CODE, not the environment:
# every process of a homogeneous cohort (same interpreter, same wheel)
# raises them identically, so a handler catching ONLY these is
# cohort-uniform — the compat version probes (`except TypeError:`
# around the shard_map kwarg rename) are the canonical shape. IO /
# runtime errors stay divergent: only the host whose disk hiccuped
# takes that handler.
_UNIFORM_EXCEPTIONS = frozenset({
    "TypeError", "AttributeError", "ImportError", "ModuleNotFoundError",
    "NameError", "NotImplementedError", "SyntaxError"})


def _uniform_handler(handler: ast.ExceptHandler) -> bool:
    """True when the handler catches ONLY code-uniform exception
    types (see _UNIFORM_EXCEPTIONS)."""
    t = handler.type
    if t is None:
        return False  # bare except: catches env-dependent errors too
    types = t.elts if isinstance(t, ast.Tuple) else [t]
    names = []
    for e in types:
        if isinstance(e, ast.Name):
            names.append(e.id)
        elif isinstance(e, ast.Attribute):
            names.append(e.attr)
        else:
            return False
    return bool(names) and all(n in _UNIFORM_EXCEPTIONS for n in names)


def _walk_pruned(node: ast.AST):
    """Walk an expression/statement tree WITHOUT entering nested
    function/class/lambda bodies: those run in their own frame at CALL
    time — merely DEFINING a lambda holding a collective under a
    divergent branch executes nothing (review round: `fn = lambda v:
    psum(v, ...)` under a rank branch must not flag; calling it does,
    wherever that happens)."""
    stack = [node]
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef, ast.Lambda)):
            continue
        yield n
        stack.extend(ast.iter_child_nodes(n))


def _terminates(body: List[ast.stmt]) -> bool:
    """The block always leaves the enclosing block (direct last-
    statement check — under-reach on nested shapes)."""
    return bool(body) and isinstance(
        body[-1], (ast.Return, ast.Raise, ast.Continue, ast.Break))


class _FnScan:
    """One function's divergence walk: tracks per-host-tainted names in
    program order, carries the active divergence reason down into
    branch arms / handler bodies, flags collective effects inside
    divergent regions."""

    def __init__(self, fn: FnInfo, scan: Scan, findings: List[Finding]):
        self.fn = fn
        self.scan = scan
        self.findings = findings
        self.ckptrs = df.checkpointer_names(fn.node)
        self.flagged = set()  # (line, label) — no duplicate reports

    # --- per-host taint + divergence tests ---

    def _call_reason(self, call: ast.Call) -> Optional[str]:
        src = df._direct_source(call)
        if src is not None and src[0] == "process-identity":
            return f"`{src[1]}`"
        target = self.scan.graph.resolve_call(self.fn, call)
        if target is not None and not _is_sanctioned(target):
            summ = self.scan.summaries.get(target.key)
            if summ is not None and summ.returns_process_identity:
                return (f"`{target.qualname}()` (returns a per-host "
                        "value)")
        return None

    def _expr_reason(self, expr: Optional[ast.AST],
                     state: Dict[str, str]) -> Optional[str]:
        """Why evaluating `expr` can differ across processes, or None.
        Calls are OPAQUE taint barriers: `open_reader(host_shard=
        process_index())` yields a reader whose batch count is aligned
        across hosts by an audited contract — the analysis cannot
        prove divergence through a call result, so it drops the fact
        (the under-reach policy). A call counts only when IT returns
        per-host identity (directly or per its summary)."""
        if expr is None:
            return None
        if isinstance(expr, ast.Call):
            return self._call_reason(expr)
        if isinstance(expr, (ast.Name, ast.Attribute)) \
                and isinstance(getattr(expr, "ctx", None), ast.Load):
            d = df.dotted(expr)
            for name, why in state.items():
                if d and (df.is_name_or_prefix(d, name)
                          or df.is_name_or_prefix(name, d)):
                    return why
            return None
        if isinstance(expr, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            return None
        for child in ast.iter_child_nodes(expr):
            reason = self._expr_reason(child, state)
            if reason is not None:
                return reason
        return None

    def _update_taint(self, stmt: ast.AST, state: Dict[str, str]) -> None:
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            value = getattr(stmt, "value", None)
            targets = stmt.targets if isinstance(stmt, ast.Assign) \
                else [stmt.target]
            reason = self._expr_reason(value, state) if value is not None \
                else None
            for t in targets:
                for d in df.bound_names(t):
                    if reason is not None:
                        state[d] = reason
                    elif not isinstance(stmt, ast.AugAssign):
                        state.pop(d, None)  # reassignment kills

    # --- collective-effect detection + reporting ---

    def _effect_label(self, call: ast.Call) -> Optional[Tuple[str, str]]:
        """(label, via-qualname) when the call performs/inherits a
        collective effect."""
        label = df.collective_effect_label(call, self.ckptrs)
        if label is not None:
            return (label, "")
        target = self.scan.graph.resolve_call(self.fn, call)
        if target is None or _is_sanctioned(target):
            return None
        summ = self.scan.summaries.get(target.key)
        if summ is not None and summ.collective:
            label = next(iter(sorted(summ.collective)))
            return (label, target.qualname)
        return None

    def _flag(self, node: ast.AST, reason: str) -> None:
        for n in _walk_pruned(node):
            if not isinstance(n, ast.Call):
                continue
            hit = self._effect_label(n)
            if hit is None:
                continue
            label, via = hit
            if (n.lineno, label) in self.flagged:
                continue
            self.flagged.add((n.lineno, label))
            detail = f"divergent control: {reason}"
            if via:
                detail += f"; effect inherited via {via}"
            self.findings.append(Finding(
                rule=RULE, path=self.fn.ctx.rel, line=n.lineno,
                symbol=self.fn.qualname, detail=detail,
                message=(f"{label} executes under process-divergent "
                         f"control ({reason}) — every process must "
                         "run the same collective sequence or the "
                         "cohort deadlocks; hoist it out of the "
                         "divergent region, or make this an audited "
                         "seam (rules/spmd_divergence.py docstring)")))

    def _flag_ifexp_arms(self, stmt: ast.AST,
                         state: Dict[str, str]) -> None:
        """`x = psum(...) if process_index() == 0 else y` — divergence
        expressed as a ternary inside an otherwise-uniform statement."""
        for n in _walk_pruned(stmt):
            if isinstance(n, ast.IfExp):
                reason = self._expr_reason(n.test, state)
                if reason is not None:
                    self._flag(n.body, f"branch on {reason}")
                    self._flag(n.orelse, f"branch on {reason}")

    # --- the walk ---

    def walk(self, body: List[ast.stmt], state: Dict[str, str],
             divergent: Optional[str]) -> None:
        for stmt in body:
            if isinstance(stmt, ast.If):
                reason = self._expr_reason(stmt.test, state)
                arm_div = divergent or (
                    f"branch on {reason}" if reason else None)
                if divergent:
                    self._flag(stmt.test, divergent)
                self.walk(stmt.body, dict(state), arm_div)
                self.walk(stmt.orelse, dict(state), arm_div)
                if reason and not divergent:
                    # a divergent early exit poisons the remainder
                    if _terminates(stmt.body) and not stmt.orelse:
                        divergent = (f"code after a process-divergent "
                                     f"early exit (branch on {reason})")
                    elif stmt.orelse and _terminates(stmt.orelse) \
                            and not _terminates(stmt.body):
                        divergent = (f"code after a process-divergent "
                                     f"early exit (branch on {reason})")
                continue
            if isinstance(stmt, (ast.While,)):
                reason = self._expr_reason(stmt.test, state)
                body_div = divergent or (
                    f"loop bounded by {reason}" if reason else None)
                if divergent:
                    self._flag(stmt.test, divergent)
                self.walk(stmt.body, dict(state), body_div)
                self.walk(stmt.orelse, dict(state), body_div)
                continue
            if isinstance(stmt, (ast.For, ast.AsyncFor)):
                reason = self._expr_reason(stmt.iter, state)
                body_div = divergent or (
                    f"loop over {reason}" if reason else None)
                if divergent:
                    self._flag(stmt.iter, divergent)
                inner = dict(state)
                if reason:
                    for d in df.bound_names(stmt.target):
                        inner[d] = reason
                self.walk(stmt.body, inner, body_div)
                self.walk(stmt.orelse, dict(state), body_div)
                continue
            if isinstance(stmt, ast.Try):
                self.walk(stmt.body, dict(state), divergent)
                for h in stmt.handlers:
                    h_div = divergent
                    if h_div is None and not _uniform_handler(h):
                        h_div = ("an exception handler only the "
                                 "process(es) that raised can take")
                    self.walk(h.body, dict(state), h_div)
                self.walk(stmt.orelse, dict(state), divergent)
                self.walk(stmt.finalbody, dict(state), divergent)
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                if divergent:
                    for item in stmt.items:
                        self._flag(item.context_expr, divergent)
                self.walk(stmt.body, state, divergent)
                continue
            if hasattr(ast, "Match") and isinstance(stmt, ast.Match):
                reason = self._expr_reason(stmt.subject, state)
                case_div = divergent or (
                    f"match on {reason}" if reason else None)
                for case in stmt.cases:
                    self.walk(case.body, dict(state), case_div)
                continue
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue  # a nested frame is scanned as its own fn
            # leaf statement
            if divergent:
                self._flag(stmt, divergent)
            else:
                self._flag_ifexp_arms(stmt, state)
            self._update_taint(stmt, state)


@register
class SpmdDivergenceRule(Rule):
    name = RULE
    description = ("a collective effect (lax collective / shard_map / "
                   "jax.distributed init / orbax checkpoint IO / async "
                   "writer submit-wait, directly or via a callee's "
                   "summary) under process-divergent control — "
                   "process_index()-style branches, divergent early "
                   "exits, exception handlers")

    def check_scan(self, scan: Scan) -> Iterable[Finding]:
        findings: List[Finding] = []
        for fn in scan.functions:
            if _is_sanctioned(fn):
                continue
            _FnScan(fn, scan, findings).walk(
                list(fn.node.body), {}, None)
        return findings
