"""donation-safety: a read of a buffer after it was donated to a step.

The train steps donate their params/opt_state (`jax.jit(...,
donate_argnums=(0, 1))`): XLA reuses the input buffers for the
outputs, so after the call the ORIGINAL arrays are deleted — a later
read returns garbage on TPU and silently works on CPU, which is
exactly why `snapshot_state` exists (PR 5: the async checkpoint
writer reading donated params) and why pytest never sees this class.

The normal idiom is clean BY CONSTRUCTION — the same statement that
donates rebinds the name, which kills the taint:

    params, opt_state, loss = step(params, opt_state, batch, rng)  # ok

The bug shapes this rule catches (dataflow over tools/graftlint/
dataflow.py, per-function):

    new_p, new_o, loss = step(params, opt_state, batch, rng)
    save(params)                        # read of a donated buffer

    state = {"params": params}          # state aliases params' buffers
    params, opt, loss = step(params, opt, batch, rng)
    writer.submit(state)                # aliased read of donated buffers

Donating callables are recognized from:
  - a name bound (function/module scope) to `jit`/`pjit` with a
    literal `donate_argnums=`/`donate_argnames=` (incl. through
    `functools.partial(jax.jit, ...)`), or to one of the repo's step
    factories (`make_train_step` & friends — the ONE step-construction
    seam, training/steps.py);
  - a def decorated with jit-with-donate, called by name in its file;
  - `self.X = make_train_step(...)`-style class attributes, called as
    `self.X(...)` in any method of that class (models/jax_model.py's
    `self._train_step`);
  - an immediately-invoked `jax.jit(f, donate_argnums=...)(...)`.

Sanction: a name assigned from `snapshot_state(...)` (or an explicit
copy: `jnp.copy`, `copy.deepcopy`, `.copy()`, `jax.device_get`) holds
fresh buffers — it never inherits taint through the alias edge, which
is precisely what makes the snapshot-then-step checkpoint idiom clean.

Interprocedural hop (ISSUE 14): a call to a function whose SUMMARY
says its body donates a param (`Summary.donated_params` — a wrapper
like `def run_step(params, opt, b, r): return step(params, opt, b,
r)`) taints the caller's argument exactly like a direct donating call
would: the wrapper's callee deleted the buffers either way. The donor
vocabulary (jit_donate_spec, FileDonors, the factory table) moved to
tools/graftlint/dataflow.py so the summary pass shares one
definition of "donating callable" with this rule.

Under-reach (dataflow.py has the policy): donation only taints plain
dotted-name arguments; unresolvable callees donate nothing; one
finding per donated name per function (the first read).
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Tuple

from tools.graftlint import dataflow as df
from tools.graftlint.core import (FileContext, Finding, FnInfo, Rule,
                                  Scan, call_name, register)
from tools.graftlint.dataflow import (FileDonors as _FileDonors,
                                      Spec, donating_value_spec
                                      as _donating_value_spec,
                                      jit_donate_spec)

RULE = "donation-safety"

_SNAPSHOT_CALLS = df.SNAPSHOT_CALLS


# state facts (per dotted name):
#   ("donor", spec)          name is a donating callable
#   ("donated", callee, ln)  name's buffers were donated at line ln
#   ("snap",)                fresh buffers (snapshot/copy result)
#   ("alias", names)         may refer to the same object as `names`


class _Flow(df.FlowVisitor):
    def __init__(self, ctx: FileContext, fn: ast.AST, cls: str,
                 donors: _FileDonors, findings: List[Finding],
                 fn_info: Optional[FnInfo] = None,
                 scan: Optional[Scan] = None):
        self.ctx = ctx
        self.fn = fn
        self.cls = cls
        self.donors = donors
        self.findings = findings
        self.fn_info = fn_info
        self.scan = scan
        self.qualname = f"{cls}.{fn.name}" if cls else fn.name
        # one finding per (name, donation site) — the loop fixpoint
        # pass must not double-report
        self.flagged = set()

    def join_states(self, a, b):
        out = dict(b)
        for name, fact in a.items():
            other = out.get(name)
            if other is None or other == fact:
                out[name] = fact
            elif fact[0] == "donated":
                out[name] = fact  # donated-on-either-path stays donated
            elif other[0] == "donated":
                pass
            else:
                out[name] = fact
        return out

    # --- donation machinery ---

    def _callee_spec(self, func: ast.AST, state) -> Optional[Spec]:
        d = df.dotted(func)
        if d:
            fact = state.get(d)
            if fact is not None and fact[0] == "donor":
                return fact[1]
            if d in self.donors.defs or d in self.donors.module_names:
                return self.donors.defs.get(d) \
                    or self.donors.module_names.get(d)
            if self.cls and (self.cls, d) in self.donors.class_attrs:
                return self.donors.class_attrs[(self.cls, d)]
        if isinstance(func, ast.Call):
            return jit_donate_spec(func)
        return None

    def _taint(self, name: str, callee: str, line: int, state,
               via_alias: bool = False) -> None:
        fact = state.get(name)
        state[name] = ("donated", callee, line, via_alias)
        # alias closure (one level, both directions): `b = a` then
        # donate(a) poisons b; donate(b) poisons a
        for other, ofact in list(state.items()):
            if other == name or ofact is None:
                continue
            if ofact[0] == "alias" and any(
                    df.is_name_or_prefix(name, m) or m == name
                    for m in ofact[1]):
                state[other] = ("donated", callee, line, True)
        if fact is not None and fact[0] == "alias":
            for m in fact[1]:
                mfact = state.get(m)
                if mfact is None or mfact[0] not in ("snap", "donated"):
                    state[m] = ("donated", callee, line, True)

    def _summary_spec(self, node: ast.Call) -> Optional[Spec]:
        """The ISSUE 14 hop: the callee's SUMMARY says its body donates
        some of its params (a wrapper around a donating step) — the
        caller's buffers are gone just the same."""
        if self.scan is None or self.fn_info is None:
            return None
        target = self.scan.graph.resolve_call(self.fn_info, node)
        if target is None or target.cls:
            return None  # method position shifts: under-reach
        summ = self.scan.summaries.get(target.key)
        if summ is None or not summ.donated_params:
            return None
        return (tuple(sorted(summ.donated_params)), ())

    def _apply_calls(self, stmt: ast.AST, state) -> None:
        for node in ast.walk(stmt):
            if not isinstance(node, ast.Call):
                continue
            spec = self._callee_spec(node.func, state)
            if spec is None:
                spec = self._summary_spec(node)
            if spec is None:
                continue
            callee = df.dotted(node.func) or call_name(node) or "jit"
            argnums, argnames = spec
            for i, a in enumerate(node.args):
                if i in argnums:
                    d = df.dotted(a)
                    if d:
                        self._taint(d, callee, node.lineno, state)
            for kw in node.keywords:
                if kw.arg in argnames:
                    d = df.dotted(kw.value)
                    if d:
                        self._taint(d, callee, kw.value.lineno, state)

    def _flag_reads(self, node: ast.AST, state) -> None:
        for read, rnode in df.reads(node):
            for name, fact in list(state.items()):
                if fact[0] != "donated":
                    continue
                if df.is_name_or_prefix(read, name):
                    state.pop(name, None)  # one finding per donation
                    if (name, fact[2]) in self.flagged:
                        continue
                    self.flagged.add((name, fact[2]))
                    via = " through an alias" if fact[3] else ""
                    self.findings.append(Finding(
                        rule=RULE, path=self.ctx.rel,
                        line=getattr(rnode, "lineno",
                                     getattr(node, "lineno", 0)),
                        symbol=self.qualname,
                        detail=f"donated at line {fact[2]}",
                        message=(
                            f"`{name}` is read after being donated"
                            f"{via} to `{fact[1]}(...)` — donated "
                            "buffers are deleted by the callee; rebind "
                            "the name from the step's result, or "
                            "snapshot (snapshot_state / jnp.copy) "
                            "BEFORE the donating call")))

    # --- engine hooks ---

    def on_expr(self, expr, state):
        self._flag_reads(expr, state)
        self._apply_calls(expr, state)

    def on_stmt(self, stmt, state):
        if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
            value = stmt.value
            targets = stmt.targets if isinstance(stmt, ast.Assign) \
                else ([stmt.target] if stmt.value is not None else [])
            if value is not None:
                self._flag_reads(value, state)
                self._apply_calls(value, state)
            for t in targets:
                # a subscript/attribute STORE does not read the base's
                # buffers; only flag reads inside its index expression
                for sub in ast.walk(t):
                    if isinstance(sub, ast.Subscript):
                        self._flag_reads(sub.slice, state)
            names = [d for t in targets for d in df.bound_names(t)]
            for d in names:
                state.pop(d, None)
            if value is None or not names:
                return
            fact = self._value_fact(value, state)
            if fact is not None:
                for d in names:
                    state[d] = fact
            return
        if isinstance(stmt, ast.AugAssign):
            self._flag_reads(stmt.target, state)
            self._flag_reads(stmt.value, state)
            self._apply_calls(stmt.value, state)
            for d in df.bound_names(stmt.target):
                state.pop(d, None)
            return
        if isinstance(stmt, ast.Delete):
            for t in stmt.targets:
                d = df.dotted(t)
                if d:
                    state.pop(d, None)
            return
        # Expr / Return / Raise / Assert / anything else: reads + calls
        self._flag_reads(stmt, state)
        self._apply_calls(stmt, state)

    def _value_fact(self, value: ast.AST, state) -> Optional[tuple]:
        """The fact the assigned name(s) should carry for this RHS."""
        if isinstance(value, ast.Call):
            spec = _donating_value_spec(value)
            if spec is not None:
                return ("donor", spec)
            if call_name(value) in _SNAPSHOT_CALLS:
                return ("snap",)
            return None
        d = df.dotted(value)
        if d:
            # donor aliasing: `step = self._train_step` keeps the spec
            fact = state.get(d)
            if fact is not None and fact[0] == "donor":
                return fact
            if d in self.donors.defs:
                return ("donor", self.donors.defs[d])
            if self.cls and (self.cls, d) in self.donors.class_attrs:
                return ("donor", self.donors.class_attrs[(self.cls, d)])
            return ("alias", (d,))
        if isinstance(value, (ast.Dict, ast.List, ast.Tuple, ast.Set)):
            names = tuple(sorted({r for r, _n in df.reads(value)}))
            if names:
                return ("alias", names)
        if isinstance(value, ast.IfExp):
            a = self._value_fact(value.body, state)
            b = self._value_fact(value.orelse, state)
            return a or b
        return None

    def on_nested_def(self, node, state):
        # closure capture: a nested def/lambda reading a donated name
        # will observe deleted buffers whenever it eventually runs
        bound = {a.arg for a in getattr(node.args, "args", ())} \
            if hasattr(node, "args") else set()
        for read, rnode in df.reads(node):
            root = read.split(".", 1)[0]
            if root in bound:
                continue
            for name, fact in list(state.items()):
                if fact[0] == "donated" \
                        and df.is_name_or_prefix(read, name):
                    state.pop(name, None)
                    if (name, fact[2]) in self.flagged:
                        continue
                    self.flagged.add((name, fact[2]))
                    self.findings.append(Finding(
                        rule=RULE, path=self.ctx.rel,
                        line=getattr(rnode, "lineno", node.lineno),
                        symbol=self.qualname,
                        detail=f"donated at line {fact[2]}",
                        message=(
                            f"`{name}` is captured by a nested "
                            f"function after being donated to "
                            f"`{fact[1]}(...)` — the closure will read "
                            "deleted buffers; snapshot before the "
                            "donating call")))


@register
class DonationSafetyRule(Rule):
    name = RULE
    description = ("a name read/returned/captured after being passed "
                   "to a donating call (jit donate_argnums, the "
                   "make_train_step seams, or a callee whose summary "
                   "donates its params) — reassignment kills the "
                   "taint, snapshot_state results are sanctioned")

    def check_scan(self, scan: Scan) -> Iterable[Finding]:
        findings: List[Finding] = []
        for fn_info in scan.functions:
            ctx = fn_info.ctx
            # the summary pass caches FileDonors on the context —
            # reuse it instead of paying the donor pre-pass twice
            donors = df._file_donors(ctx)
            df.run_flow(fn_info.node,
                        _Flow(ctx, fn_info.node, fn_info.cls, donors,
                              findings, fn_info=fn_info, scan=scan))
        return findings
