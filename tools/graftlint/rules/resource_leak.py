"""resource-leak: an acquire whose release can be skipped.

PR 6's review found the traced server leaking request spans: every
exception between `start_trace()` and `root.end()` left an un-ended
span in the live-span table forever, polluting each watchdog stall
dump with phantom requests. The serving layer now releases on its
error paths (server.py's `except BaseException: ... .end(); raise`
blocks); this rule fences the class so the next acquire/release pair
added to the repo gets the same treatment mechanically.

Paired protocols are registered in ONE table (`PROTOCOLS`): tracer /
telemetry spans (`span()`/`start_span()`/`start_trace()` →
`.stop()`/`.end()`), thread lifecycles (`Thread()` + `.start()` →
`.join()`, daemon threads sanctioned), server/socket lifecycles
(`*Server()`/`socket()` + `.start()` → `.close()`/`.stop()`/...), the
submit/wait barrier discipline (an owned writer/executor's first
`.submit()` → `.wait()`/`.close()`/`.shutdown()`), and bare
`lock.acquire()` → `lock.release()`.

Two checks over the dataflow core's per-path state:

  - EXIT LEAK: a path reaches `return` / falls off the end while a
    tracked name is still held — acquire with no release on that path.
  - ERROR PATH (span protocols only — the PR-6 class): a statement
    that can raise (any call) executes while a span is held and no
    enclosing `try` releases it in a `finally` or an except handler —
    the success-path release exists but an exception skips it. Flagged
    at the release site's protocol, reported at the acquire.

Releases are credited generously (under-reach, dataflow.py policy): a
release under ANY branch counts, ownership transfers clear the fact
(the name returned / yielded / passed as an argument / stored into a
container or attribute — whoever received it owns the release), `with`
-managed names are never held, and only plain local names are tracked
(`self._writer.submit(...)` is the owning object's lifecycle, not this
function's). `test_*` functions are exempt from the ERROR-PATH check
only: a failing test already fails loudly and pytest owns teardown —
but a test that never releases at all still gets the exit-leak
finding.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterable, List, Optional, Set, Tuple

from tools.graftlint import dataflow as df
from tools.graftlint.core import (FileContext, Finding, Rule, call_name,
                                  register)

RULE = "resource-leak"


@dataclasses.dataclass(frozen=True)
class Protocol:
    key: str                 # short id used in messages
    kind: str                # "call" (result held) | "ctor" | "method"
    acquire: frozenset       # call names that acquire
    release: frozenset       # methods on the held name that release
    error_path: bool = False  # also run the PR-6 raise-window check
    gate: str = ""           # ctor: held only once this method is called
    # ctor kwargs that waive tracking entirely (daemon threads are
    # designed never to be joined)
    sanction_kwargs: frozenset = frozenset()
    # method-kind: acquire only arms on receivers CONSTRUCTED in this
    # function (a borrowed writer's lifecycle belongs to its owner);
    # False for lock.acquire — the acquire itself creates the
    # obligation regardless of who owns the lock object
    needs_owned: bool = True


PROTOCOLS: Tuple[Protocol, ...] = (
    Protocol("span", "call",
             frozenset({"span", "start_span", "start_trace"}),
             frozenset({"stop", "end", "close", "cancel"}),
             error_path=True),
    Protocol("thread", "ctor", frozenset({"Thread", "Timer"}),
             frozenset({"join"}), gate="start",
             sanction_kwargs=frozenset({"daemon"})),
    Protocol("server", "ctor", frozenset({"socket"}),
             frozenset({"close", "stop", "shutdown", "server_close",
                        "join"}), gate="start"),
    Protocol("submit-barrier", "method", frozenset({"submit"}),
             frozenset({"wait", "close", "join", "shutdown",
                        "drain_quiet", "stop", "result"})),
    Protocol("lock", "method", frozenset({"acquire"}),
             frozenset({"release"}), needs_owned=False),
)

# trailing call names that are protocol vocabulary: a statement whose
# calls are ALL acquires/releases is not a "risky" raise window (the
# shipped idiom opens two spans back-to-back before the try)
_PROTOCOL_CALL_NAMES = frozenset().union(
    *(p.acquire | p.release for p in PROTOCOLS),
    *({p.gate} for p in PROTOCOLS if p.gate))

# builtins/clock reads that do not realistically raise — span-attribute
# computation (`n=len(lines)`, `step=int(self.step_num)`) must not turn
# every acquire statement into its own "raise window"
_SAFE_CALL_NAMES = frozenset({
    "len", "int", "float", "bool", "str", "repr", "round", "min",
    "max", "abs", "isinstance", "issubclass", "hasattr", "getattr",
    "id", "type", "tuple", "list", "dict", "set", "sorted",
    "monotonic", "perf_counter", "time",
})

_BY_CALL: Dict[str, Protocol] = {}
for _p in PROTOCOLS:
    if _p.kind in ("call", "ctor"):
        for _a in _p.acquire:
            _BY_CALL[_a] = _p
_BY_METHOD: Dict[str, Protocol] = {}
for _p in PROTOCOLS:
    if _p.kind == "method":
        for _a in _p.acquire:
            _BY_METHOD[_a] = _p

# `span` is container vocabulary too (re.Match.span()); only credit it
# as an acquire when the receiver looks like a telemetry/trace object
_SPAN_RECEIVER_HINTS = ("tele", "trace", "obs", "span")


def _ctor_protocol(call: ast.Call) -> Optional[Protocol]:
    name = call_name(call)
    p = _BY_CALL.get(name)
    if p is not None and p.kind == "ctor":
        return p
    if name.endswith("Server"):
        return _BY_CALL["socket"]  # the server/socket lifecycle entry
    return None


def _call_protocol(call: ast.Call) -> Optional[Protocol]:
    name = call_name(call)
    p = _BY_CALL.get(name)
    if p is None or p.kind != "call":
        return None
    if name == "span" and isinstance(call.func, ast.Attribute):
        recv = df.dotted(call.func.value).lower()
        if recv and not any(h in recv for h in _SPAN_RECEIVER_HINTS):
            return None
    return p


# state fact per plain local name:
#   ("held", proto, line, desc)     acquired, release outstanding
#   ("pending", proto, line, desc)  ctor'd, not gate-started yet
#   ("owned", line)                 constructed here (method-kind arm)
#   ("cm",)                         with-managed — never tracked


class _Flow(df.FlowVisitor):
    def __init__(self, ctx: FileContext, fn: ast.AST, cls: str,
                 findings: List[Finding]):
        self.ctx = ctx
        self.fn = fn
        self.cls = cls
        self.findings = findings
        self.qualname = f"{cls}.{fn.name}" if cls else fn.name
        self.is_test = fn.name.startswith("test_")
        # names with a pending error-path candidate: name -> risky line
        self.candidates: Dict[str, int] = {}
        self.flagged: Set[Tuple[str, int, str]] = set()
        # stack of name-sets protected by an enclosing try whose
        # finally/handlers release them
        self.protection: List[Set[str]] = []
        self.in_finally = 0

    def join_states(self, a, b):
        # a name held on ONE side only was released (or never acquired)
        # on the other — credit the release, keep the intersection
        return {k: v for k, v in a.items()
                if k in b and (b[k] == v or b[k][0] == v[0])}

    # --- findings ---

    def _emit(self, kind: str, name: str, fact, line_hint: int) -> None:
        proto, aline, desc = fact[1], fact[2], fact[3]
        key = (name, aline, kind)
        if key in self.flagged:
            return
        self.flagged.add(key)
        rel = "/".join(sorted(proto.release))
        if kind == "exit":
            msg = (f"`{name}` (= {desc}) is not released on every "
                   f"path — the function can exit without "
                   f"`{name}.{rel}()`; release in a finally block or "
                   "use the context-manager form")
        else:
            msg = (f"`{name}` (= {desc}) is released only on the "
                   "success path — an exception in between leaks it "
                   "(the PR-6 leaked-span class); release in a "
                   "finally block, an except handler, or use the "
                   "context-manager form")
        self.findings.append(Finding(
            rule=RULE, path=self.ctx.rel, line=aline,
            symbol=self.qualname,
            detail=f"path exits near line {line_hint}" if kind == "exit"
            else f"can raise at line {line_hint}",
            message=msg))

    # --- acquire / release / escape ---

    def _acquire_from_assign(self, names: List[str], value: ast.AST,
                             state) -> bool:
        """Returns True when the RHS established a tracked fact."""
        if isinstance(value, ast.IfExp):
            return (self._acquire_from_assign(names, value.body, state)
                    or self._acquire_from_assign(names, value.orelse,
                                                 state))
        if not isinstance(value, ast.Call) or len(names) != 1:
            return False
        name = names[0]
        if "." in name:
            return False
        ctor = _ctor_protocol(value)
        if ctor is not None:
            for kw in value.keywords:
                if kw.arg in ctor.sanction_kwargs:
                    return False
            desc = f"{call_name(value)}(...)"
            if ctor.gate:
                state[name] = ("pending", ctor, value.lineno, desc)
            else:
                state[name] = ("held", ctor, value.lineno, desc)
            return True
        p = _call_protocol(value)
        if p is not None:
            desc = f"{df.dotted(value.func) or call_name(value)}(...)"
            state[name] = ("held", p, value.lineno, desc)
            return True
        cn = call_name(value)
        if cn and cn[0].isupper():
            # constructed (and therefore owned) here: arms the
            # method-kind protocols (an owned writer's .submit())
            state[name] = ("owned", value.lineno)
            return True
        return False

    def _method_call(self, name: str, method: str, call: ast.Call,
                     state) -> None:
        fact = state.get(name)
        if fact is None:
            proto = _BY_METHOD.get(method)
            if proto is not None and not proto.needs_owned:
                # lock.acquire(): the acquire itself creates the
                # release obligation, even on a borrowed object
                state[name] = ("held", proto, call.lineno,
                               f".{method}(...)")
            return
        if fact[0] == "cm":
            return
        if fact[0] in ("held", "pending"):
            proto = fact[1]
            if method in proto.release:
                cand = self.candidates.pop(name, None)
                if cand is not None and fact[0] == "held" \
                        and proto.error_path and not self.is_test:
                    self._emit("error", name, fact, cand)
                state.pop(name, None)
                return
            if fact[0] == "pending" and method == proto.gate:
                state[name] = ("held", proto, fact[2], fact[3])
            return
        if fact[0] == "owned":
            proto = _BY_METHOD.get(method)
            if proto is not None:
                state[name] = ("held", proto, call.lineno,
                               f".{method}(...)")

    def _escapes(self, node: ast.AST, state) -> None:
        """Ownership transfers: the name used as an argument, RHS
        alias, container element, returned/yielded value, or stored
        to an attribute/subscript."""

        def clear(expr: ast.AST) -> None:
            for d, _n in df.reads(expr):
                base = d.split(".", 1)[0]
                if base in state:
                    state.pop(base, None)
                    self.candidates.pop(base, None)

        for n in ast.walk(node):
            if isinstance(n, ast.Call):
                for a in n.args:
                    clear(a)
                for kw in n.keywords:
                    clear(kw.value)
            elif isinstance(n, (ast.Yield, ast.YieldFrom)) \
                    and n.value is not None:
                clear(n.value)  # the consumer owns the yielded value

    # --- engine hooks ---

    def on_bind(self, target, state, source, value=None):
        names = df.bound_names(target)
        for n in names:
            state.pop(n, None)
            self.candidates.pop(n, None)
        if source == "with":
            for n in names:
                if "." not in n:
                    state[n] = ("cm",)

    def on_with(self, stmt, state):
        # `with x:` — the context manager owns x's cleanup now
        for item in stmt.items:
            d = df.dotted(item.context_expr)
            if d and "." not in d:
                state.pop(d, None)
                self.candidates.pop(d, None)
        return None

    def on_try(self, stmt, state):
        protected: Set[str] = set()
        for region in ([stmt.finalbody]
                       + [h.body for h in stmt.handlers]):
            for n in region:
                for call in (c for c in ast.walk(n)
                             if isinstance(c, ast.Call)):
                    if isinstance(call.func, ast.Attribute):
                        base = df.dotted(call.func.value)
                        if base and "." not in base:
                            protected.add(base)
        self.protection.append(protected)
        return protected

    def after_try(self, token, state):
        self.protection.pop()

    def enter_finally(self):
        self.in_finally += 1

    def exit_finally(self):
        self.in_finally -= 1

    def _protected(self, name: str) -> bool:
        return any(name in s for s in self.protection)

    def on_expr(self, expr, state):
        for call in (n for n in ast.walk(expr)
                     if isinstance(n, ast.Call)):
            if isinstance(call.func, ast.Attribute):
                base = df.dotted(call.func.value)
                if base and "." not in base:
                    self._method_call(base, call.func.attr, call, state)

    def on_stmt(self, stmt, state):
        if isinstance(stmt, ast.Return):
            if stmt.value is not None:
                self._escapes(stmt, state)
                for d, _n in df.reads(stmt.value):
                    base = d.split(".", 1)[0]
                    state.pop(base, None)
                    self.candidates.pop(base, None)
            self._check_exit(stmt.lineno, state)
            return
        if isinstance(stmt, ast.Raise):
            # an explicit raise is a deliberate error path; the
            # enclosing caller's handler owns cleanup (under-reach)
            return

        # releases / gates / method-kind acquires, anywhere in the stmt
        self.on_expr(stmt, state)
        self._escapes(stmt, state)

        if isinstance(stmt, ast.Assign):
            names = [d for t in stmt.targets for d in df.bound_names(t)]
            for n in names:
                state.pop(n, None)
                self.candidates.pop(n, None)
            # ownership transfers through the RHS: a store THROUGH an
            # attribute/subscript, a plain alias (`handle = sp`), or a
            # container literal (`spans = [sp]`) — whoever can reach
            # the value now owns the release (under-reach)
            if any(df.mutated_bases(t) for t in stmt.targets) \
                    or isinstance(stmt.value,
                                  (ast.Name, ast.Attribute, ast.Dict,
                                   ast.List, ast.Tuple, ast.Set,
                                   ast.Starred, ast.IfExp)):
                for d, _n in df.reads(stmt.value):
                    base = d.split(".", 1)[0]
                    state.pop(base, None)
                    self.candidates.pop(base, None)
            self._acquire_from_assign(names, stmt.value, state)
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            names = df.bound_names(stmt.target)
            for n in names:
                state.pop(n, None)
            self._acquire_from_assign(names, stmt.value, state)

        # the PR-6 error-path window: something that can raise runs
        # while a span is held and no enclosing try releases it
        if not self.is_test and self.in_finally == 0 \
                and self._is_risky(stmt):
            for name, fact in list(state.items()):
                if fact[0] == "held" and fact[1].error_path \
                        and not self._protected(name) \
                        and name not in self.candidates \
                        and fact[2] != stmt.lineno:
                    self.candidates[name] = stmt.lineno

    def _is_risky(self, stmt: ast.AST) -> bool:
        """Can this statement realistically raise while spans are
        held? Calls that are themselves protocol vocabulary (opening a
        sibling span, starting a thread) don't count — the shipped
        idiom opens two spans back-to-back before its try block."""
        if not df.stmt_may_raise(stmt):
            return False
        saw_call = False
        for n in ast.walk(stmt):
            if isinstance(n, (ast.Raise, ast.Assert)):
                return True
            if isinstance(n, ast.Call):
                saw_call = True
                cn = call_name(n)
                if cn not in _PROTOCOL_CALL_NAMES \
                        and cn not in _SAFE_CALL_NAMES:
                    return True
        return not saw_call

    def _check_exit(self, line: int, state) -> None:
        for name, fact in state.items():
            if fact[0] == "held":
                self._emit("exit", name, fact, line)

    def at_exit(self, fn, state):
        self._check_exit(getattr(fn, "end_lineno", fn.lineno), state)


@register
class ResourceLeakRule(Rule):
    name = RULE
    description = ("paired acquire/release protocols (spans, threads, "
                   "servers, submit/wait, lock.acquire) where a path "
                   "exits without the release, or — for spans — an "
                   "exception window skips it (the PR-6 leaked-span "
                   "class); try/finally, except-handler and "
                   "context-manager releases are credited")

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        for fn, cls in df.iter_functions(ctx.tree):
            df.run_flow(fn, _Flow(ctx, fn, cls, findings))
        return findings
