"""retrace-hazard: jit call-site patterns that defeat the compile cache.

The serving layer's zero-compilations-under-load guarantee (PR 3:
warmed power-of-two predict buckets) and the train loop's
compile-once-per-shape contract both die quietly when a call site
re-traces: latency spikes of seconds under load, nothing fails. The
hazard patterns this rule catches, all statically visible:

  1. jit-in-loop / re-jit: `jax.jit(...)` evaluated inside a for/while
     body — every iteration builds a FRESH callable with an empty
     compile cache;
  2. immediate invocation: `jax.jit(f)(x)` — same storm, one-liner
     form;
  3. invalid statics: `static_argnums=` / `static_argnames=` values
     that are not int/str constants (or tuples/lists thereof) — a
     runtime-computed or unhashable static turns the cache key into a
     moving target (unhashable values raise, dynamic ones silently
     fragment the cache);
  4. Python scalar / dict literal passed positionally to a
     known-jitted callable — weak-typed scalars promote per call
     pattern and dict literals rebuild their pytree structure at every
     site; pass arrays, or mark the argument static;
  5. shape-derived branching around a jitted call: an `if` testing
     `.shape` in a function that calls a jitted callable compiles one
     variant per branch outcome — bucket shapes explicitly instead
     (the `predict_bucket_size` pow-2 pattern).

"Known-jitted" = names bound (locally or on self) from `jax.jit` /
`pmap` / `pjit` or from a `make_*step` factory (the repo idiom:
training/steps.py returns jitted steps).
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, List, Set

from tools.graftlint.core import (FileContext, Finding, Rule, call_name,
                                  is_self_attr, register, walk_body)

RULE = "retrace-hazard"

_JIT_NAMES = frozenset({"jit", "pmap", "pjit"})
_FACTORY_RE = re.compile(r"^make_\w*step$")


def _is_jit_call(node: ast.AST) -> bool:
    """`jax.jit(...)` / `jit(...)` / `functools.partial(jax.jit, ...)`."""
    if not isinstance(node, ast.Call):
        return False
    name = call_name(node)
    if name in _JIT_NAMES:
        return True
    if name == "partial" and node.args:
        return _is_jit_ref(node.args[0])
    return False


def _is_jit_ref(node: ast.AST) -> bool:
    return (isinstance(node, ast.Name) and node.id in _JIT_NAMES) or (
        isinstance(node, ast.Attribute) and node.attr in _JIT_NAMES)


def _static_kwarg_invalid(value: ast.AST, want) -> bool:
    """True when a static_argnums/static_argnames value is not a
    constant of the expected scalar type or a tuple/list of them."""
    def ok_scalar(n: ast.AST) -> bool:
        return isinstance(n, ast.Constant) and isinstance(n.value, want) \
            and not isinstance(n.value, bool)

    if ok_scalar(value):
        return False
    if isinstance(value, (ast.Tuple, ast.List)):
        return not all(ok_scalar(e) for e in value.elts)
    return True


def _is_jitted_value(node: ast.AST) -> bool:
    return isinstance(node, ast.Call) and (
        _is_jit_call(node) or bool(_FACTORY_RE.match(call_name(node))))


def _jitted_names_shallow(scope: ast.AST) -> Set[str]:
    """NAMES bound from jit calls / make_*step factories in exactly
    this scope (module or one function body) — walk_body stops at
    nested defs, so a jit binding in one function never leaks into an
    unrelated function that reuses the name."""
    out: Set[str] = set()
    for node in walk_body(scope):
        if isinstance(node, ast.Assign) and _is_jitted_value(node.value):
            out.update(t.id for t in node.targets
                       if isinstance(t, ast.Name))
    return out


def _jitted_self_attrs(cls: ast.ClassDef) -> Set[str]:
    """`self.x = jax.jit(...)` / `self.x = make_*step(...)` anywhere in
    the class — instance attributes are visible to every method (the
    `self._predict_step` idiom), unlike local names."""
    out: Set[str] = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and _is_jitted_value(node.value):
            for tgt in node.targets:
                attr = is_self_attr(tgt)
                if attr:
                    out.add(attr)
    return out


def _calls_jitted(node: ast.Call, jitted: Set[str]) -> bool:
    f = node.func
    if isinstance(f, ast.Name):
        return f.id in jitted
    attr = is_self_attr(f)
    return attr is not None and attr in jitted


def _mentions_shape(node: ast.AST) -> bool:
    return any(isinstance(n, ast.Attribute) and n.attr == "shape"
               for n in ast.walk(node))


@register
class RetraceRule(Rule):
    name = RULE
    description = ("jit/pmap/pjit usage that defeats the compile cache: "
                   "jit-in-loop, jit(f)(x), non-constant/unhashable "
                   "statics, scalar/dict literals as traced args, "
                   "shape-derived branching around jitted calls")

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        # TOP-LEVEL jitted bindings are visible everywhere in-file;
        # function-local ones are pushed/popped per scope below
        module_jitted = _jitted_names_shallow(ctx.tree)

        class V(ast.NodeVisitor):
            def __init__(self):
                self.loop_depth = 0
                self.fn_stack: List[str] = []
                self.jitted_stack: List[Set[str]] = [module_jitted]

            @property
            def symbol(self) -> str:
                return ".".join(self.fn_stack)

            @property
            def jitted(self) -> Set[str]:
                return set().union(*self.jitted_stack)

            def _finding(self, node: ast.AST, message: str) -> None:
                findings.append(Finding(
                    rule=RULE, path=ctx.rel, line=node.lineno,
                    symbol=self.symbol, message=message))

            def visit_FunctionDef(self, node):
                self.fn_stack.append(node.name)
                self.jitted_stack.append(_jitted_names_shallow(node))
                # loop state does not leak into a nested def's body
                # (defining a function in a loop is fine; CALLING jit
                # there is not — the call is what visit_Call sees)
                depth, self.loop_depth = self.loop_depth, 0
                self.generic_visit(node)
                self.loop_depth = depth
                self.jitted_stack.pop()
                self.fn_stack.pop()

            visit_AsyncFunctionDef = visit_FunctionDef

            def visit_ClassDef(self, node):
                self.fn_stack.append(node.name)
                self.jitted_stack.append(_jitted_self_attrs(node))
                self.generic_visit(node)
                self.jitted_stack.pop()
                self.fn_stack.pop()

            def _visit_loop(self, node):
                self.loop_depth += 1
                self.generic_visit(node)
                self.loop_depth -= 1

            visit_For = _visit_loop
            visit_While = _visit_loop

            def visit_If(self, node):
                if _mentions_shape(node.test):
                    jitted = self.jitted
                    for n in ast.walk(node):
                        if isinstance(n, ast.Call) and _calls_jitted(
                                n, jitted):
                            self._finding(
                                node,
                                "shape-derived branch around a jitted "
                                "call — each branch outcome compiles a "
                                "new variant under load; pad to "
                                "explicit shape buckets instead "
                                "(predict_bucket_size pattern)")
                            break
                self.generic_visit(node)

            def visit_Call(self, node):
                if _is_jit_call(node):
                    if self.loop_depth > 0:
                        self._finding(
                            node,
                            "jit/pmap/pjit evaluated inside a loop — "
                            "each iteration builds a fresh callable "
                            "with an empty compile cache; hoist it out")
                    for kw in node.keywords:
                        if kw.arg == "static_argnums" and \
                                _static_kwarg_invalid(kw.value, int):
                            self._finding(
                                kw.value,
                                "static_argnums must be a literal int "
                                "or tuple of ints — computed/unhashable "
                                "statics fragment (or break) the "
                                "compile cache")
                        if kw.arg == "static_argnames" and \
                                _static_kwarg_invalid(kw.value, str):
                            self._finding(
                                kw.value,
                                "static_argnames must be a literal str "
                                "or tuple of strs — computed statics "
                                "fragment the compile cache")
                if isinstance(node.func, ast.Call) and _is_jit_call(
                        node.func):
                    self._finding(
                        node,
                        "jit(f)(args) compiles on EVERY call (the "
                        "jitted callable is discarded immediately); "
                        "bind it once and reuse it")
                if _calls_jitted(node, self.jitted):
                    for arg in node.args:
                        if isinstance(arg, ast.Dict):
                            self._finding(
                                arg,
                                "dict literal passed to a jitted "
                                "callable — the pytree structure is "
                                "rebuilt at every call site; pass a "
                                "stable container built once")
                        elif isinstance(arg, ast.Constant) and \
                                isinstance(arg.value, (int, float)) \
                                and not isinstance(arg.value, bool):
                            self._finding(
                                arg,
                                "Python scalar literal passed as a "
                                "traced arg — weak-typed scalars risk "
                                "a retrace per call pattern; pass an "
                                "array or mark the argument static")
                self.generic_visit(node)

        V().visit(ctx.tree)
        return findings
