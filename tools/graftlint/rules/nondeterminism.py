"""nondeterminism: a nondeterministic value flowing into the
resume-parity surface.

PR 10/12's parity bars promise that resume is bit-exact and
topology-independent: a SIGKILLed-and-resumed run must equal an
uninterrupted one, at any cohort size. That only holds while
everything feeding the numerics is a function of (seed, step, data)
alone — the moment wall clock, the unseeded global `random`/
`np.random` streams, unsorted `os.listdir`/`glob` results, set
iteration order, or `id()`/`hash()` (PYTHONHASHSEED differs per
process) leaks into a tensor, an rng seam or checkpointed state, the
parity tests turn flaky in ways no single run can see.

Mechanics: the shared flow engine taints names assigned from
nondeterministic sources (dataflow.expr_nondet — ORDER kinds like
fs-order die at `sorted()`/`len()`-style order-insensitive consumers,
VALUE kinds like wall-clock survive any transform; reassignment
kills), plus the INTERPROCEDURAL hop: a call to a function whose
summary says it RETURNS nondeterminism (`compute_summaries`) is a
source too. A finding fires only when a tainted value reaches a sink:

  - tensor construction (`jnp.*`, `np.array/asarray/full`,
    `device_put`);
  - an rng/shuffle seam (`PRNGKey`/`key`/`fold_in`, `random.seed`,
    `np.random.seed`, any call's `seed=` keyword);
  - checkpointed state (`save_checkpoint` & friends, the async
    writer's `.submit`, any call whose summary carries a
    checkpoint-labelled collective effect — the one-hop sink).

Sanctioned seams (ISSUE 14): the step-keyed rng idiom
(`fold_in(rng, step)`) and the seeded retry jitter are clean BY
CONSTRUCTION — their inputs are never tainted (instance streams like
`self._rng.random()` are deliberately not sources; only the module-
global streams are). Telemetry timestamps never flag because
telemetry/event emission is not a sink — timestamps belong in event
logs, just not in tensors. `dither_from_index` is sanctioned BY NAME:
it is the deterministic counter-hash dither (ops/quant.py), and calls
to it are neither sources nor sinks regardless of what its bit-mixing
body looks like to the summary pass.

Per-host process-identity values are EXCLUDED here — `np.full(B,
process_index())` is the multihost row-tagging mechanism, not a bug;
divergence hazards are spmd-divergence's jurisdiction.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Tuple

from tools.graftlint import dataflow as df
from tools.graftlint.core import (FileContext, Finding, FnInfo, Rule,
                                  Scan, register)

RULE = "nondeterminism"

# kinds this rule reports (process-identity is spmd-divergence's)
_REPORTED = frozenset({"wall-clock", "global-rng", "fs-order",
                       "set-order", "object-identity"})

# calls that are neither sources nor sinks, whatever their bodies look
# like: the audited deterministic seams
_SANCTIONED_CALLS = frozenset({"dither_from_index"})

_RNG_SINKS = frozenset({"PRNGKey", "key", "fold_in"})
_SEED_KWARGS = frozenset({"seed", "rng_seed"})
_TENSOR_FNS = frozenset({"array", "asarray", "full", "device_put",
                         "full_like"})
_NP_ALIASES = frozenset({"np", "numpy", "onp", "jnp"})


class _Flow(df.FlowVisitor):
    def __init__(self, fn: FnInfo, scan: Scan, findings: List[Finding]):
        self.fn = fn
        self.ctx: FileContext = fn.ctx
        self.scan = scan
        self.findings = findings
        self.ckptrs = df.checkpointer_names(fn.node)
        self.flagged = set()  # (line, sink, kind)

    # --- state: name -> {kind: (line, desc)} ---

    def copy_state(self, state):
        return {k: dict(v) for k, v in state.items()}

    def join_states(self, a, b):
        out = {k: dict(v) for k, v in b.items()}
        for name, taint in a.items():
            df._merge(out.setdefault(name, {}), taint)
        return out

    # --- the interprocedural source hook ---

    def _call_kinds(self, call: ast.Call) -> df.Taint:
        name = df.call_trailing(call)
        if name in _SANCTIONED_CALLS:
            return {}
        target = self.scan.graph.resolve_call(self.fn, call)
        if target is None:
            return {}
        summ = self.scan.summaries.get(target.key)
        if summ is None:
            return {}
        return {kind: (call.lineno, f"returned by `{target.qualname}`")
                for kind in summ.returns_nondet if kind in _REPORTED}

    def _taint(self, expr: Optional[ast.AST], state) -> df.Taint:
        kinds = df.expr_nondet(expr, state, self._call_kinds)
        return {k: v for k, v in kinds.items() if k in _REPORTED}

    # --- sinks ---

    def _sink_label(self, call: ast.Call) -> Optional[str]:
        name = df.call_trailing(call)
        if name in _SANCTIONED_CALLS:
            return None
        base = df._call_base(call)
        base_root = base.split(".", 1)[0] if base else ""
        if base_root == "jnp" or base.startswith("jax.numpy"):
            return f"tensor construction (`{base}.{name}`)"
        if name in _TENSOR_FNS and (base_root in _NP_ALIASES
                                    or base_root == "jax"):
            return f"tensor construction (`{base}.{name}`)"
        if name in _RNG_SINKS:
            return f"the rng seam `{name}(...)`"
        if name == "seed" and (base == "random"
                               or base in df._NP_RANDOM_BASES):
            return f"the global rng seed (`{base}.seed`)"
        label = df.collective_effect_label(call, self.ckptrs)
        if label is not None and df.CHECKPOINT_LABEL in label:
            return "checkpointed state (the resume-parity surface)"
        target = self.scan.graph.resolve_call(self.fn, call)
        if target is not None:
            summ = self.scan.summaries.get(target.key)
            if summ is not None and any(
                    df.CHECKPOINT_LABEL in lbl
                    for lbl in summ.collective):
                return ("checkpointed state (the resume-parity "
                        f"surface, via `{target.qualname}`)")
        return None

    def _check_sinks(self, node: Optional[ast.AST], state) -> None:
        if node is None:
            return
        # pruned walk: a sink call inside a nested def/lambda executes
        # in its own frame at call time, not at the definition site
        stack = [node]
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef, ast.Lambda)):
                continue
            stack.extend(ast.iter_child_nodes(n))
            if not isinstance(n, ast.Call):
                continue
            sink = self._sink_label(n)
            if sink is not None:
                for arg in list(n.args) + [kw.value for kw in n.keywords]:
                    self._report(n, sink, self._taint(arg, state))
            for kw in n.keywords:
                if kw.arg in _SEED_KWARGS:
                    self._report(
                        n, f"the `{kw.arg}=` seam of `"
                           f"{df.call_trailing(n)}(...)`",
                        self._taint(kw.value, state))

    def _report(self, call: ast.Call, sink: str, kinds: df.Taint) -> None:
        for kind, (line, desc) in sorted(kinds.items()):
            key = (call.lineno, sink, kind)
            if key in self.flagged:
                continue
            self.flagged.add(key)
            self.findings.append(Finding(
                rule=RULE, path=self.ctx.rel, line=call.lineno,
                symbol=self.fn.qualname,
                detail=f"source: {desc} at line {line}",
                message=(f"{df.KIND_DESC[kind]} flows into {sink} — "
                         "the resume-parity bar (bit-exact, topology-"
                         "independent restarts) only holds for values "
                         "derived from (seed, step, data); thread the "
                         "seeded stream / sort the listing / key by "
                         "step instead")))

    # --- engine hooks ---

    def on_expr(self, expr, state):
        self._check_sinks(expr, state)
        # the engine evaluates a `for` iterable immediately before
        # binding its targets — remember it so on_bind can hand the
        # iterable's taint to the loop variable (`for n in
        # os.listdir(d):` makes `n` order-dependent)
        self._last_control_expr = expr

    def on_bind(self, target, state, source, value=None):
        kinds = {}
        if source == "for":
            kinds = self._taint(getattr(self, "_last_control_expr",
                                        None), state)
        elif source == "with" and value is not None:
            kinds = self._taint(value, state)
        for name in df.bound_names(target):
            state.pop(name, None)
            if kinds:
                state[name] = dict(kinds)

    def on_stmt(self, stmt, state):
        if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
            value = stmt.value
            targets = stmt.targets if isinstance(stmt, ast.Assign) \
                else [stmt.target]
            self._check_sinks(value, state)
            kinds = self._taint(value, state) if value is not None else {}
            for t in targets:
                for d in df.bound_names(t):
                    state.pop(d, None)
                    if kinds:
                        state[d] = dict(kinds)
                for base in df.mutated_bases(t):
                    if kinds:
                        df._merge(state.setdefault(base, {}), kinds)
            return
        if isinstance(stmt, ast.AugAssign):
            self._check_sinks(stmt.value, state)
            kinds = self._taint(stmt.value, state)
            for d in df.bound_names(stmt.target):
                if kinds:
                    df._merge(state.setdefault(d, {}), kinds)
            return
        if isinstance(stmt, ast.Delete):
            for t in stmt.targets:
                d = df.dotted(t)
                if d:
                    state.pop(d, None)
            return
        self._check_sinks(stmt, state)
        # `x.sort()` sorts in place: the name's ORDER taint dies here
        if (isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call)
                and isinstance(stmt.value.func, ast.Attribute)
                and stmt.value.func.attr == "sort"):
            d = df.dotted(stmt.value.func.value)
            if d and d in state:
                state[d] = {k: v for k, v in state[d].items()
                            if k not in df.ORDER_KINDS}


@register
class NondeterminismRule(Rule):
    name = RULE
    description = ("wall clock / global-rng / fs-order / set-order / "
                   "id()-hash() values flowing into tensor "
                   "construction, rng seams or checkpointed state "
                   "(summary-aware: sources and checkpoint sinks "
                   "resolve one call hop deep)")

    def check_scan(self, scan: Scan) -> Iterable[Finding]:
        findings: List[Finding] = []
        for fn in scan.functions:
            df.run_flow(fn.node, _Flow(fn, scan, findings))
        return findings
