"""Rule modules — importing this package registers every rule.

Adding a rule (README "Static analysis" has the user-facing steps):
  1. new module here with a `@register`-decorated `Rule` subclass
     (per-file `check_file`, repo-wide `check_repo`, or both);
  2. a true-positive AND a tricky false-positive fixture under
     tests/graftlint_fixtures/ + assertions in tests/test_graftlint.py;
  3. run `python -m tools.graftlint` — fix or baseline what the new
     rule surfaces (never baseline under serving/ or obs/).
"""

from tools.graftlint.rules import (config_drift, host_sync,  # noqa: F401
                                   lock_discipline, retrace,
                                   swallowed_error, test_markers)
# the dataflow rules (ISSUE 12) — built on tools/graftlint/dataflow.py
from tools.graftlint.rules import (donation_safety,  # noqa: F401
                                   resource_leak, thread_handoff)
# the interprocedural rules (ISSUE 14) — built on the call-summary
# layer (dataflow.compute_summaries over core.Scan)
from tools.graftlint.rules import (nondeterminism,  # noqa: F401
                                   spmd_divergence)
