"""host-sync-in-hot-path: host transfers inside the latency-critical
call graph.

The hot paths — the jitted train/eval/predict steps, the model's
`predict_device`, and the serving batcher's flush loop — must never
block on a host<->device transfer the author didn't budget for:
`.item()`, `float()/int()` on a device value, `np.asarray` /
`jax.device_get`, `print` of a device value, or a bare
`block_until_ready`. One stray sync serializes the dispatch pipeline
(BASELINE.md's timing methodology: ~60 ms per sync round-trip on the
tunneled platform) and is invisible to pytest because nothing is wrong,
only slow.

Mechanics: build a name-resolved static call graph over the scan set,
BFS from the hot roots, and scan every reachable function body. Roots:

  - any function carrying a jit/pmap/pjit decorator (the steps);
  - `Code2VecModel.predict_device` (the serving device phase);
  - `MicroBatcher._run` and `PredictionServer._run_batch` (the batcher
    flush path — `_batch_fn` is a constructor-injected indirection the
    static graph cannot see through, so both sides are roots).

Sanctioned sync points (not flagged, not traversed): `device_sync` and
`_Span.stop` — the obs helpers whose WHOLE JOB is the explicit,
telemetry-attributed sync (`span(...).stop(sync=tree)`) — and
`fetch_global` (parallel/distributed.py), the ONE named terminal
fetch that ends the predict/eval hot paths (single-process np.asarray
or multi-process allgather; its docstring owns the policy). The
round-11 inline suppressions inside fetch_global are gone with this
round-14 sanction: `code2vec_tpu/parallel/` joined
NO_BASELINE_PREFIXES, and a helper whose whole job is the deliberate
fetch is the same species as device_sync — an explicit, greppable
seam, not an accident this rule could catch. Accidental syncs
(.item(), float(), bare np.asarray) stay flagged everywhere; a NEW
deliberate fetch must either route through fetch_global or earn its
own entry here with a policy docstring.

Call resolution is heuristic by design (plain `ast`, no imports):
simple names resolve within the module then to a globally-unique def;
`self.x(...)` resolves within the class; other attribute calls resolve
only when the method name is defined exactly once repo-wide and is not
a common container-protocol name. Unresolvable calls end traversal —
the rule under-reaches rather than spraying false paths. The function
index and resolver live in core (`Scan.functions` / `Scan.graph`,
ISSUE 14) so the summary layer and the SPMD rules share them; this
rule keeps only its roots, sanctions and violation vocabulary.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Tuple

from tools.graftlint.core import (Finding, FnInfo, Rule, Scan,
                                  call_name, register, walk_body)

RULE = "host-sync-in-hot-path"

_JIT_NAMES = frozenset({"jit", "pmap", "pjit"})

# (class, function) hot roots the call graph cannot discover itself
_ROOT_METHODS = frozenset({
    ("Code2VecModel", "predict_device"),
    ("MicroBatcher", "_run"),
    ("PredictionServer", "_run_batch"),
})

# the explicit sync/fetch seams (module docstring has the policy):
# obs helpers + the parallel layer's one terminal result fetch
_SANCTIONED = frozenset({("", "device_sync"), ("_Span", "stop"),
                         ("", "fetch_global")})

# numpy module aliases whose `.asarray` is a device->host fetch when fed
# a jax array (jnp.asarray is host->device and is NOT flagged)
_NP_ALIASES = frozenset({"np", "numpy", "onp"})


def _has_jit_decorator(node: ast.AST) -> bool:
    for dec in getattr(node, "decorator_list", ()):
        for n in ast.walk(dec):
            if isinstance(n, ast.Name) and n.id in _JIT_NAMES:
                return True
            if isinstance(n, ast.Attribute) and n.attr in _JIT_NAMES:
                return True
    return False


def _mentions_shape_math(node: ast.AST) -> bool:
    """True when an expression is shape/dtype bookkeeping, not a device
    value: touching .shape/.ndim/.size/.dtype/len() or made purely of
    constants. float(loss) flags; int(x.shape[0]) does not."""
    all_const = True
    for n in ast.walk(node):
        if isinstance(n, ast.Attribute) and n.attr in (
                "shape", "ndim", "size", "dtype"):
            return True
        if isinstance(n, ast.Call) and call_name(n) == "len":
            return True
        if not isinstance(n, (ast.Constant, ast.BinOp, ast.UnaryOp,
                              ast.operator, ast.unaryop, ast.expr_context,
                              ast.Tuple, ast.List)):
            all_const = False
    return all_const


def _is_sanctioned(fn: FnInfo) -> bool:
    return ((fn.cls, fn.name) in _SANCTIONED
            or ("", fn.name) in _SANCTIONED)


def _scan_violations(fn: FnInfo, root_label: str) -> Iterable[Finding]:
    # which root reached us is BFS-order-dependent context -> `detail`
    # (outside the baseline identity), never part of the message
    via = f"hot path via {root_label}" if root_label != fn.qualname \
        else ""
    for node in walk_body(fn.node):
        if not isinstance(node, ast.Call):
            continue
        name = call_name(node)
        msg = None
        if name == "item" and isinstance(node.func, ast.Attribute) \
                and not node.args and not node.keywords:
            msg = ".item() forces a device->host sync"
        elif name in ("float", "int") and isinstance(node.func, ast.Name) \
                and len(node.args) == 1 \
                and not _mentions_shape_math(node.args[0]):
            msg = (f"{name}() on a runtime value blocks on the device "
                   "if it is a jax array")
        elif name == "asarray" and isinstance(node.func, ast.Attribute) \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id in _NP_ALIASES:
            msg = "np.asarray fetches device arrays to the host"
        elif name == "device_get":
            msg = "jax.device_get is an explicit device->host fetch"
        elif name == "print" and isinstance(node.func, ast.Name):
            msg = ("print in a hot function stalls the dispatch queue "
                   "(and syncs if handed a device value)")
        elif name == "block_until_ready":
            msg = ("bare block_until_ready in a hot function (and it "
                   "can return early on the tunneled platform — "
                   "BASELINE.md methodology)")
        if msg:
            yield Finding(
                rule=RULE, path=fn.ctx.rel, line=node.lineno,
                symbol=fn.qualname, detail=via,
                message=(f"{msg}; use the obs "
                         "span(...).stop(sync=...) helpers for a "
                         "deliberate sync, or move this off the hot "
                         "path"))


@register
class HostSyncRule(Rule):
    name = RULE
    description = ("host transfers (.item(), float()/int(), np.asarray, "
                   "print, bare block_until_ready) in functions "
                   "reachable from the jitted step / predict / "
                   "batcher-flush paths")

    def check_scan(self, scan: Scan) -> Iterable[Finding]:
        fns = scan.functions
        graph = scan.graph
        roots = [f for f in fns
                 if (_has_jit_decorator(f.node)
                     or (f.cls, f.name) in _ROOT_METHODS)
                 and not _is_sanctioned(f)]
        # BFS; remember which root first reached each function so the
        # message can say WHY it is considered hot (keys are
        # FnInfo.key 4-tuples: rel, cls, scope, name)
        reached: Dict[tuple, str] = {}
        queue: List[Tuple[FnInfo, str]] = [(f, f.qualname) for f in roots]
        for f, label in queue:
            reached.setdefault(f.key, label)
        i = 0
        while i < len(queue):
            fn, label = queue[i]
            i += 1
            for callee in graph.callees(fn):
                if _is_sanctioned(callee) or callee.key in reached:
                    continue
                reached[callee.key] = label
                queue.append((callee, label))
        findings: List[Finding] = []
        for fn in fns:
            label = reached.get(fn.key)
            if label is None or _is_sanctioned(fn):
                continue
            findings.extend(_scan_violations(fn, label))
        return findings
