"""test-marker-hygiene: the tier-1 budget is guarded by markers, so
markers must be real.

Tier-1 runs `-m 'not slow'` (ROADMAP.md). That deselection only works
when (a) the `slow` marker is REGISTERED in pytest.ini and (b) slow
tests actually CARRY it. test_requant_sweep.py / test_loadgen.py each
hand-rolled a guard for (a); this rule generalizes both directions over
every test file:

  - unknown marker: `@pytest.mark.X` (or `pytest.param(...,
    marks=...)`) where X is neither a pytest builtin nor registered in
    pytest.ini — a typo'd `slwo` would silently RUN in tier-1, the
    exact failure the hand-rolled guards exist to prevent;
  - unmarked long-runner: a test function without `@pytest.mark.slow`
    whose body (statically) commits to a long run — `time.sleep(C)`
    with a constant C >= 1.0 second, or driving a CLI with the
    `--duration` long-run flag. The sub-second sleeps the
    server/prefetch tests use for thread handoff stay below the
    threshold on purpose.

pytest.ini parsing is textual (the `markers =` block); registered
marker = the token before the first `:`.
"""

from __future__ import annotations

import ast
import configparser
import os
from typing import Iterable, List, Optional, Sequence, Set

from tools.graftlint.core import (FileContext, Finding, Rule, call_name,
                                  dotted_name, register)

RULE = "test-marker-hygiene"

_BUILTIN_MARKS = frozenset({
    "skip", "skipif", "xfail", "parametrize", "usefixtures",
    "filterwarnings", "tryfirst", "trylast",
})

_SLEEP_THRESHOLD_S = 1.0


def registered_markers(pytest_ini: str) -> Set[str]:
    if not os.path.exists(pytest_ini):
        return set()
    cp = configparser.ConfigParser()
    cp.read(pytest_ini)
    if not cp.has_option("pytest", "markers"):
        return set()
    out = set()
    for line in cp.get("pytest", "markers").splitlines():
        line = line.strip()
        if line:
            out.add(line.split(":", 1)[0].split("(", 1)[0].strip())
    return out


def _mark_names(node: ast.AST) -> Iterable[ast.Attribute]:
    """Every `pytest.mark.X` attribute under `node`."""
    for n in ast.walk(node):
        if isinstance(n, ast.Attribute) and dotted_name(n).startswith(
                "pytest.mark."):
            yield n


def _is_test_file(ctx: FileContext) -> bool:
    base = os.path.basename(ctx.rel)
    return (base.startswith("test_") or base == "conftest.py"
            or "/tests/" in f"/{ctx.rel}")


def _has_slow_mark(fn: ast.AST) -> bool:
    return any(m.attr == "slow"
               for dec in getattr(fn, "decorator_list", ())
               for m in _mark_names(dec))


def _long_run_reason(fn: ast.AST) -> Optional[ast.AST]:
    """First node that commits this test to a long run, else None."""
    for n in ast.walk(fn):
        if isinstance(n, ast.Call) and call_name(n) == "sleep" \
                and n.args and isinstance(n.args[0], ast.Constant) \
                and isinstance(n.args[0].value, (int, float)) \
                and n.args[0].value >= _SLEEP_THRESHOLD_S:
            return n
        if isinstance(n, ast.Constant) and n.value == "--duration":
            return n
    return None


@register
class TestMarkerRule(Rule):
    name = RULE
    description = ("unregistered pytest markers (typo'd `slow` runs in "
                   "tier-1) and long-running tests (sleep >= 1 s, "
                   "--duration CLI runs) missing @pytest.mark.slow")

    def check_ctx(self, ctx: FileContext,
                  registered: Set[str]) -> Iterable[Finding]:
        known = registered | _BUILTIN_MARKS
        findings: List[Finding] = []
        for mark in _mark_names(ctx.tree):
            if mark.attr not in known:
                findings.append(Finding(
                    rule=RULE, path=ctx.rel, line=mark.lineno,
                    symbol=f"pytest.mark.{mark.attr}",
                    message=(f"marker '{mark.attr}' is not registered "
                             "in pytest.ini (and is no pytest "
                             "builtin) — a typo here silently defeats "
                             "tier-1's `-m 'not slow'` deselection")))
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            if not node.name.startswith("test_") or _has_slow_mark(node):
                continue
            reason = _long_run_reason(node)
            if reason is not None:
                what = ("a constant sleep >= "
                        f"{_SLEEP_THRESHOLD_S:g} s"
                        if isinstance(reason, ast.Call)
                        else "a --duration long-run CLI invocation")
                findings.append(Finding(
                    rule=RULE, path=ctx.rel, line=reason.lineno,
                    symbol=node.name,
                    message=(f"test contains {what} but carries no "
                             "@pytest.mark.slow — tier-1 pays for it "
                             "on every run")))
        return findings

    def check_repo(self, ctxs: Sequence[FileContext],
                   root: str) -> Iterable[Finding]:
        registered = registered_markers(os.path.join(root, "pytest.ini"))
        findings: List[Finding] = []
        for ctx in ctxs:
            if _is_test_file(ctx):
                findings.extend(self.check_ctx(ctx, registered))
        return findings
