"""graftflow: the shared intraprocedural dataflow core (ISSUE 12).

graftlint's first six rules are per-node pattern matchers; the bug
classes the last five PRs kept fixing by hand — reads of donated
buffers, objects mutated after a thread handoff, acquire-without-
release on error paths — all require tracking a VALUE across
statements. This module owns that machinery once, so the three
dataflow rules (donation-safety, thread-handoff, resource-leak) are
just transfer functions:

  - a statement-ordered CFG walk per function: sequencing is program
    order; `if`/`try`/`match` branches are both executed on copies of
    the state and JOINED conservatively (a fact on either side
    survives); loops run ONE fixpoint pass (body executed twice with a
    join in between — enough to propagate loop-carried facts like "a
    name tainted at the bottom of the body is tainted at the top",
    without iterating to convergence);
  - per-name def-use facts: rules attach a fact to a dotted name
    (`params`, `self.opt_state`) when it is defined or flows somewhere
    interesting, and REASSIGNMENT KILLS it — `params, opt, loss =
    step(params, opt, ...)` launders the name on the same statement
    that donated it, which is why the normal train-loop idiom is clean
    by construction;
  - a lightweight escape lattice: LOCAL (the function owns the value)
    < ALIASED (another local name may refer to the same object) <
    ESCAPED (handed to another thread/queue/executor or stored where
    another thread can see it). Rules consult the lattice instead of
    re-deriving "who else can touch this".

Under-reach policy (the tool's documented design, ARCHITECTURE.md
"Dataflow: taint what escapes, kill on reassign"): whenever the
analysis cannot prove the hazardous flow — an unresolvable call, a
subscripted target, a name rebound through `exec`-level dynamism — it
drops the fact rather than guessing. A dataflow rule that sprays
plausible-but-wrong findings gets suppressed into uselessness; one
that only speaks when the chain is airtight gets fixed.

Everything here is pure `ast` + stdlib (the graftlint contract: parse,
never import).
"""

from __future__ import annotations

import ast
from typing import Any, Iterable, Iterator, List, Optional, Tuple

# ---- escape lattice ----

LOCAL = 0      # only this function's frame can reach the value
ALIASED = 1    # another local name may refer to the same object
ESCAPED = 2    # another thread/queue/executor/shared object can reach it

_LEAF_STMTS = (ast.Assign, ast.AugAssign, ast.AnnAssign, ast.Expr,
               ast.Assert, ast.Delete, ast.Import, ast.ImportFrom,
               ast.Global, ast.Nonlocal, ast.Pass)


# ---- name extraction helpers (the def/use vocabulary) ----

def dotted(node: ast.AST) -> str:
    """'a.b.c' for a Name/Attribute chain, '' otherwise."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def is_name_or_prefix(read: str, name: str) -> bool:
    """True when a read of `read` touches the value bound to `name`:
    the name itself or an attribute path under it (`params.shape`
    reads `params`; `self` does not read `self.params`)."""
    return read == name or read.startswith(name + ".")


def bound_names(target: ast.AST) -> List[str]:
    """Dotted names REBOUND by an assignment target (tuple/list/star
    unpacking flattened). Subscript targets (`x[k] = v`) mutate, they
    do not rebind — they are excluded here (see `mutated_bases`)."""
    out: List[str] = []
    stack = [target]
    while stack:
        t = stack.pop()
        if isinstance(t, (ast.Tuple, ast.List)):
            stack.extend(t.elts)
        elif isinstance(t, ast.Starred):
            stack.append(t.value)
        elif isinstance(t, (ast.Name, ast.Attribute)):
            d = dotted(t)
            if d:
                out.append(d)
    return out


def mutated_bases(target: ast.AST) -> List[str]:
    """Dotted base names MUTATED (not rebound) by an assignment
    target: `x[k] = v` and `x.a = v` mutate `x`; plain `x = v` does
    not. For `x.a = v` both the mutation of `x` and the rebind of
    `x.a` are real — callers pick the view they need."""
    out: List[str] = []
    stack = [target]
    while stack:
        t = stack.pop()
        if isinstance(t, (ast.Tuple, ast.List)):
            stack.extend(t.elts)
        elif isinstance(t, ast.Starred):
            stack.append(t.value)
        elif isinstance(t, ast.Subscript):
            d = dotted(t.value)
            if d:
                out.append(d)
        elif isinstance(t, ast.Attribute):
            d = dotted(t.value)
            if d:
                out.append(d)
    return out


def reads(expr: ast.AST) -> Iterator[Tuple[str, ast.AST]]:
    """Every dotted name READ inside an expression tree, as (name,
    node). An Attribute chain yields its full dotted path once (the
    rules prefix-match); Store/Del contexts are skipped. Descends into
    lambdas and comprehensions — a closure read is still a read."""
    stack = [expr]
    while stack:
        n = stack.pop()
        if isinstance(n, ast.Attribute):
            if isinstance(n.ctx, ast.Load):
                d = dotted(n)
                if d:
                    yield d, n
                    # the chain's names are covered by the prefix
                    # match; don't also yield the inner Name
                    stack.extend(a for a in ast.iter_child_nodes(n)
                                 if not isinstance(a, (ast.Name,
                                                       ast.Attribute)))
                    continue
            stack.append(n.value)
            continue
        if isinstance(n, ast.Name):
            if isinstance(n.ctx, ast.Load):
                yield n.id, n
            continue
        stack.extend(ast.iter_child_nodes(n))


def arg_names(call: ast.Call) -> List[Tuple[Optional[str], str, ast.AST]]:
    """(keyword-or-None, dotted-name, node) for every plain-name
    argument of a call. Complex argument expressions are skipped —
    their values are temporaries no later statement can read
    (under-reach)."""
    out: List[Tuple[Optional[str], str, ast.AST]] = []
    for a in call.args:
        node = a.value if isinstance(a, ast.Starred) else a
        d = dotted(node)
        if d:
            out.append((None, d, node))
    for kw in call.keywords:
        d = dotted(kw.value)
        if d:
            out.append((kw.arg, d, kw.value))
    return out


def stmt_may_raise(stmt: ast.AST) -> bool:
    """Heuristic: a statement containing any call (or an explicit
    raise/assert) can leave the function exceptionally. Attribute and
    subscript reads can too, but flagging those would make every
    statement 'risky' — calls are where the PR-6 leak class actually
    fired."""
    for n in ast.walk(stmt):
        if isinstance(n, (ast.Call, ast.Raise, ast.Assert, ast.Await)):
            return True
    return False


# every compound statement a def can hide inside — a function defined
# in a match-case arm or an async-with body is still a frame to analyze
_CONTAINER_STMTS = (ast.If, ast.Try, ast.With, ast.AsyncWith,
                    ast.For, ast.AsyncFor, ast.While,
                    ast.ExceptHandler) + tuple(
    getattr(ast, n) for n in ("Match", "match_case")
    if hasattr(ast, n))


def iter_functions(tree: ast.AST) -> Iterator[Tuple[ast.AST, str]]:
    """(function-node, enclosing-class-name) for every def in a module,
    including nested ones (each is analyzed as its own frame)."""
    stack: List[Tuple[ast.AST, str]] = [(tree, "")]
    while stack:
        node, cls = stack.pop()
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                stack.append((child, child.name))
            elif isinstance(child, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                yield child, cls
                stack.append((child, cls))
            elif isinstance(child, _CONTAINER_STMTS):
                stack.append((child, cls))
    return


# ---- the flow engine ----

class FlowVisitor:
    """Transfer-function interface a dataflow rule implements. The
    engine owns control flow (sequencing, branch copies + joins, the
    one-pass loop fixpoint, path death after return/raise/break); the
    visitor owns the state and the findings.

    State objects are opaque to the engine — it only ever calls
    `copy_state` and `join_states`. A `None` state is a dead path
    (after return/raise); `join_states` never sees one."""

    def initial_state(self, fn: ast.AST) -> Any:
        return {}

    def copy_state(self, state: Any) -> Any:
        return dict(state)

    def join_states(self, a: Any, b: Any) -> Any:
        """Conservative branch join: a fact surviving on EITHER side
        survives the join. Default: union, keeping `a`'s fact on
        conflict."""
        out = dict(b)
        out.update(a)
        return out

    # --- hooks the engine calls in execution order ---

    def on_stmt(self, stmt: ast.AST, state: Any) -> None:
        """A leaf statement (Assign/Expr/Return/Raise/...)."""

    def on_expr(self, expr: ast.AST, state: Any) -> None:
        """A control expression evaluated outside a leaf statement:
        an `if`/`while` test, a `for` iterable, a `with` item."""

    def on_bind(self, target: ast.AST, state: Any, source: str,
                value: Optional[ast.AST] = None) -> None:
        """A binding outside a leaf Assign: `for` targets
        (source='for'), `with ... as` (source='with', value=the
        context expr), `except ... as` (source='except'). Default:
        kill facts for the rebound names."""
        for name in bound_names(target):
            state.pop(name, None)

    def on_nested_def(self, node: ast.AST, state: Any) -> None:
        """A nested FunctionDef/AsyncFunctionDef/ClassDef — the engine
        does NOT descend (it runs at call time, in its own frame)."""

    def on_with(self, stmt: ast.AST, state: Any) -> Any:
        """Entering a with-block (after items were evaluated/bound).
        Returns a token passed back to `after_with`."""
        return None

    def after_with(self, token: Any, state: Optional[Any]) -> None:
        pass

    def on_try(self, stmt: ast.Try, state: Any) -> Any:
        """Entering a try. Returns a token passed to `after_try`;
        rules use it to register finally/handler protection."""
        return None

    def after_try(self, token: Any, state: Optional[Any]) -> None:
        pass

    def enter_finally(self) -> None:
        pass

    def exit_finally(self) -> None:
        pass

    def at_exit(self, fn: ast.AST, state: Any) -> None:
        """The implicit return at the end of the body (only reachable
        fall-off paths — a trailing `raise` never gets here)."""


class _LoopCtx:
    __slots__ = ("breaks", "continues")

    def __init__(self):
        self.breaks: List[Any] = []
        self.continues: List[Any] = []


def run_flow(fn: ast.AST, visitor: FlowVisitor) -> None:
    """Drive `visitor` over `fn`'s body in execution order with the
    CFG policy above."""
    state = visitor.initial_state(fn)
    state = _run_body(fn.body, visitor, state, [])
    if state is not None:
        visitor.at_exit(fn, state)


def _join(v: FlowVisitor, a: Any, b: Any) -> Any:
    if a is None:
        return b
    if b is None:
        return a
    return v.join_states(a, b)


def _run_body(body: Iterable[ast.AST], v: FlowVisitor, state: Any,
              loops: List[_LoopCtx]) -> Any:
    for stmt in body:
        if state is None:
            break  # unreachable code: under-reach, don't analyze
        state = _exec(stmt, v, state, loops)
    return state


def _exec(stmt: ast.AST, v: FlowVisitor, state: Any,
          loops: List[_LoopCtx]) -> Any:
    if isinstance(stmt, _LEAF_STMTS):
        v.on_stmt(stmt, state)
        return state

    if isinstance(stmt, ast.Return):
        v.on_stmt(stmt, state)
        return None
    if isinstance(stmt, ast.Raise):
        v.on_stmt(stmt, state)
        return None
    if isinstance(stmt, ast.Break):
        if loops:
            loops[-1].breaks.append(v.copy_state(state))
        return None
    if isinstance(stmt, ast.Continue):
        if loops:
            loops[-1].continues.append(v.copy_state(state))
        return None

    if isinstance(stmt, ast.If):
        v.on_expr(stmt.test, state)
        s_then = _run_body(stmt.body, v, v.copy_state(state), loops)
        s_else = _run_body(stmt.orelse, v, state, loops)
        return _join(v, s_then, s_else)

    if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
        loop = _LoopCtx()
        loops.append(loop)
        try:
            # one fixpoint pass: execute the body twice, joining with
            # the pre-loop state (zero iterations) and the first
            # pass's exit (loop-carried facts) in between
            for _ in range(2):
                if isinstance(stmt, ast.While):
                    v.on_expr(stmt.test, state)
                else:
                    v.on_expr(stmt.iter, state)
                    v.on_bind(stmt.target, state, "for")
                s_body = _run_body(stmt.body, v, v.copy_state(state),
                                   loops)
                for s_cont in loop.continues:
                    s_body = _join(v, s_body, s_cont)
                loop.continues.clear()
                state = _join(v, state, s_body)
        finally:
            loops.pop()
        for s_brk in loop.breaks:
            state = _join(v, state, s_brk)
        if stmt.orelse:
            state = _run_body(stmt.orelse, v, state, loops)
        return state

    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        for item in stmt.items:
            v.on_expr(item.context_expr, state)
            if item.optional_vars is not None:
                v.on_bind(item.optional_vars, state, "with",
                          value=item.context_expr)
        token = v.on_with(stmt, state)
        state = _run_body(stmt.body, v, state, loops)
        v.after_with(token, state)
        return state

    if isinstance(stmt, ast.Try):
        token = v.on_try(stmt, state)
        entry = v.copy_state(state)
        s_body = _run_body(stmt.body, v, state, loops)
        handler_states = []
        for h in stmt.handlers:
            # an exception can arrive from ANY point in the body: the
            # handler sees the entry state joined with the body-exit
            # state (facts born inside the body may or may not exist)
            hs = _join(v, v.copy_state(entry),
                       None if s_body is None else v.copy_state(s_body))
            if h.name:
                v.on_bind(ast.Name(id=h.name, ctx=ast.Store()), hs,
                          "except")
            handler_states.append(_run_body(h.body, v, hs, loops))
        out = s_body
        if stmt.orelse and out is not None:
            out = _run_body(stmt.orelse, v, out, loops)
        for hs in handler_states:
            out = _join(v, out, hs)
        if stmt.finalbody:
            fin_in = out if out is not None else entry
            v.enter_finally()
            try:
                out = _run_body(stmt.finalbody, v, fin_in, loops)
            finally:
                v.exit_finally()
        v.after_try(token, out)
        return out

    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.ClassDef)):
        v.on_nested_def(stmt, state)
        if isinstance(state, dict):
            state.pop(stmt.name, None)  # the def name is a rebind
        return state

    if hasattr(ast, "Match") and isinstance(stmt, ast.Match):
        v.on_expr(stmt.subject, state)
        out = v.copy_state(state)  # no-match path
        for case in stmt.cases:
            cs = _run_body(case.body, v, v.copy_state(state), loops)
            out = _join(v, out, cs)
        return out

    # anything else (future syntax): treat as an opaque leaf
    v.on_stmt(stmt, state)
    return state
